"""Benchmark: Filter-equivalent latency on the BASELINE north-star
snapshot — 10k nodes × 1k pending apps, whole-FIFO-queue gang solve
(the Pallas VMEM-resident queue kernel).

The measured operation is what a Filter request costs at steady state
with a 1k-deep driver queue: one whole-queue batched repack (FIFO
earlier-drivers pass + the current driver's gang decision).  Snapshot
tensors are maintained incrementally by the control plane, so
marshalling is off the hot path (reported separately).

Measurement method: this dev environment reaches the TPU through a
network relay whose round-trip (~67 ms) dwarfs device time and does not
exist on a co-located deployment (PCIe-attached host).  We therefore
chain CHAIN data-dependent solves on device (each consumes the previous
carry), fetch one scalar at the end, measure the relay RTT separately
with a null program, and report per-solve latency =
(chain_total − rtt) / CHAIN.  p99 is taken over repeated chain runs.

Prints ONE JSON line:
  {"metric": ..., "value": p99_ms, "unit": "ms", "vs_baseline": 50/p99}
vs_baseline > 1 means faster than the 50 ms north-star target.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_NODES = 10_000
N_APPS = 1_000
TARGET_MS = 50.0
CHAIN = 20
ROUNDS = 15


def build_problem():
    from k8s_spark_scheduler_tpu.ops.sparkapp import AppDemand
    from k8s_spark_scheduler_tpu.ops.tensorize import (
        scale_problem,
        tensorize_apps,
        tensorize_cluster,
    )
    from k8s_spark_scheduler_tpu.types.resources import (
        NodeSchedulingMetadata,
        Resources,
    )

    rng = np.random.RandomState(0)
    t0 = time.perf_counter()
    metadata = {}
    for i in range(N_NODES):
        metadata[f"node-{i:05d}"] = NodeSchedulingMetadata(
            available=Resources.of(
                str(int(rng.randint(4, 96))), f"{int(rng.randint(8, 256))}Gi"
            ),
            schedulable=Resources.of("96", "256Gi"),
            zone_label=f"z{i % 3}",
        )
    order = list(metadata)
    apps = [
        AppDemand(
            driver_resources=Resources.of("1", "2Gi"),
            executor_resources=Resources.of(
                str(int(rng.randint(1, 8))), f"{int(rng.randint(2, 16))}Gi"
            ),
            min_executor_count=int(rng.randint(1, 32)),
        )
        for _ in range(N_APPS)
    ]
    cluster = tensorize_cluster(metadata, order, order)
    app_tensor = tensorize_apps(apps)
    problem = scale_problem(cluster, app_tensor)
    marshal_s = time.perf_counter() - t0
    assert problem.ok, "bench snapshot must be exactly tensorizable"
    return problem, marshal_s


def _probe_tpu_backend(timeout_s: float = 180.0) -> bool:
    """The dev TPU sits behind a relay that can wedge; probing backend
    init in a subprocess keeps this process unblocked.  Returns True when
    the TPU backend is usable.  Skips the (multi-second) probe entirely
    when no non-CPU platform is configured."""
    from k8s_spark_scheduler_tpu.utils.tpuprobe import (
        live_platforms,
        probe_default_backend,
    )

    platforms = live_platforms()
    if not platforms or platforms.split(",")[0].strip() == "cpu":
        return False
    backend = probe_default_backend(timeout_s)
    return backend is not None and "tpu" in backend


def main() -> None:
    tpu_usable = _probe_tpu_backend()

    import jax

    if not tpu_usable:
        # tpuprobe prints the "relay wedged?" hint itself when the probe hangs
        print("# TPU backend unavailable; benching on CPU", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp

    on_tpu = jax.default_backend() == "tpu"
    from k8s_spark_scheduler_tpu.ops.batch_solver import solve_queue

    problem, marshal_s = build_problem()
    args = (
        jnp.asarray(problem.avail),
        jnp.asarray(problem.driver_rank),
        jnp.asarray(problem.exec_ok),
        jnp.asarray(problem.driver),
        jnp.asarray(problem.executor),
        jnp.asarray(problem.count),
        jnp.asarray(problem.app_valid),
    )


    if on_tpu:
        from k8s_spark_scheduler_tpu.ops.pallas_queue import pallas_solve_queue

        # grid batching knob for A/B on hardware (parity-validated for 1
        # and 8; see tests/test_pallas_queue.py)
        apps_per_step = int(os.environ.get("BENCH_APPS_PER_STEP", "1"))

        def one_solve(avail, rest):
            feas, didx, avail_after = pallas_solve_queue(
                avail, *rest, apps_per_step=apps_per_step
            )
            return feas, avail_after
    else:
        # note: sharding the scan across virtual CPU devices was measured
        # 18x SLOWER than single-device (per-step collective overhead);
        # the CPU fallback stays single-device on purpose

        def one_solve(avail, rest):
            out = solve_queue(avail, *rest, evenly=False, with_placements=False)
            return out.feasible, out.avail_after

    @functools.partial(jax.jit, static_argnames=("chain",))
    def chained(avail, *rest, chain=CHAIN):
        total = jnp.int32(0)
        for _ in range(chain):
            feas, avail_after = one_solve(avail, rest)
            total = total + jnp.sum(feas)
            avail = avail_after
        return total

    # relay/dispatch RTT baseline: a null program + scalar fetch
    null = jax.jit(lambda x: jnp.sum(x))
    tiny = jnp.ones((8, 128), jnp.int32)
    int(null(tiny))
    rtts = []
    for _ in range(10):
        t0 = time.perf_counter()
        int(null(tiny))
        rtts.append(time.perf_counter() - t0)
    rtt_s = float(np.median(rtts))

    # warmup/compile
    total = chained(*args)
    feasible_count = int(total) // CHAIN

    lat_ms = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        int(chained(*args))
        elapsed = time.perf_counter() - t0
        lat_ms.append(max(elapsed - rtt_s, 0.0) / CHAIN * 1000.0)

    lat = np.array(lat_ms)
    p99 = float(np.percentile(lat, 99))
    result = {
        "metric": "p99_filter_latency_10k_nodes_x_1k_apps_batched_repack",
        "value": round(p99, 3),
        "unit": "ms",
        "vs_baseline": round(TARGET_MS / p99, 3),
    }
    print(json.dumps(result))
    print(
        f"# p50={np.percentile(lat, 50):.2f}ms mean={lat.mean():.2f}ms "
        f"max={lat.max():.2f}ms relay_rtt={rtt_s * 1000:.1f}ms "
        f"feasible={feasible_count}/{N_APPS} marshal={marshal_s:.2f}s "
        f"platform={jax.devices()[0].platform} devices={len(jax.devices())} "
        f"backend={'pallas' if on_tpu else 'xla-scan'} chain={CHAIN}",
        file=sys.stderr,
    )
    _secondary_configs()


def _secondary_configs() -> None:
    """BASELINE.json configs (1), (2), (4) measured end-to-end through the
    extender harness (stderr diagnostics; the headline metric above is
    config (5))."""
    import logging

    h = None
    try:
        from k8s_spark_scheduler_tpu.testing.harness import Harness

        # synthetic old pods trip the slow-schedule warnings; keep the
        # diagnostics readable
        logging.disable(logging.WARNING)

        # (1) tightly-pack: 1 driver + 8 executors on a 32-node snapshot
        h = Harness(binpack_algo="tpu-batch", is_fifo=True)
        for i in range(32):
            h.new_node(f"n{i:02d}", cpu="16", memory="32Gi")
        nodes = [f"n{i:02d}" for i in range(32)]
        pods = Harness.static_allocation_spark_pods("warmup", 8)
        h.schedule(pods[0], nodes)
        t0 = time.perf_counter()
        pods = Harness.static_allocation_spark_pods("cfg1", 8)
        result = h.schedule(pods[0], nodes)
        assert result.node_names, result.failed_nodes
        cfg1_ms = (time.perf_counter() - t0) * 1000
        print(f"# config1 tightly-pack 1+8@32nodes: {cfg1_ms:.1f}ms e2e", file=sys.stderr)

        # (2) FIFO queue of 128 static apps drained in order
        drivers = []
        base = time.time()
        for i in range(128):
            d = Harness.static_allocation_spark_pods(
                f"q{i:03d}", 2, creation_timestamp=base - 1000 + i
            )[0]
            h.create_pod(d)
            drivers.append(d)
        t0 = time.perf_counter()
        granted = sum(1 for d in drivers if h.schedule(d, nodes).node_names)
        cfg2_ms = (time.perf_counter() - t0) * 1000
        print(
            f"# config2 FIFO 128 apps: {cfg2_ms:.0f}ms total "
            f"({cfg2_ms / 128:.1f}ms/app, {granted} granted)",
            file=sys.stderr,
        )

        # (4) dynamic allocation with soft reservations
        da = Harness.dynamic_allocation_spark_pods("cfg4", 2, 8)
        t0 = time.perf_counter()
        result = h.schedule(da[0], nodes)
        assert result.node_names, result.failed_nodes
        for p in da[1:]:
            h.schedule(p, nodes)
        cfg4_ms = (time.perf_counter() - t0) * 1000
        sr, _ = h.server.soft_reservation_store.get_soft_reservation("cfg4")
        print(
            f"# config4 DA min2/max8: {cfg4_ms:.0f}ms for driver+8 executors, "
            f"{len(sr.reservations)} soft reservations",
            file=sys.stderr,
        )
    except Exception as err:  # diagnostics must never break the bench
        print(f"# secondary configs failed: {err}", file=sys.stderr)
    finally:
        try:
            if h is not None:
                h.close()
        except Exception:
            pass
        logging.disable(logging.NOTSET)


if __name__ == "__main__":
    main()
