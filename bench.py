"""Benchmark: HTTP Filter latency on the BASELINE north-star snapshot —
10k nodes × 1k pending apps through the REAL extender server.

The HEADLINE is request-level (VERDICT r4 #2): the p99 of POST
/predicates round trips measured at the HTTP boundary (config5-e2e —
server/http.py → serde → Predicate → tensor mirror → queue lane →
reservation create), at steady state: every timed probe driver is
deleted (with its reservation) after its sample, so all ≥200 samples
measure the same 10k×1k problem with probe apps drawn from the same
1-32-executor distribution as the queue.  The solver-only lanes
(pallas / native C++ / XLA scan chained queue solves) are recorded as
diagnostics in the same artifact; when the e2e phase cannot run, the
headline falls back to the solver lane under the honest name
``p99_queue_solve_…`` so a solver microbench can never masquerade as
the Filter SLO.

Measurement method: this dev environment reaches the TPU through a
network relay whose round-trip (~67 ms) dwarfs device time and does not
exist on a co-located deployment (PCIe-attached host).  We therefore
chain CHAIN data-dependent solves on device (each consumes the previous
carry), fetch one scalar at the end, measure the relay RTT separately
with a null program, and report per-solve latency =
(chain_total − rtt) / CHAIN.  p99 is taken over repeated chain runs.

Wedge survival: the relay's backend init can block forever, and the
wedge outlives any single client process.  The TPU measurement therefore
runs in a FRESH worker subprocess per attempt (``--tpu-worker``), driven
by a bounded retry loop here — a hung worker is detached + killed and a
new one started, because a wedge can clear between attempts (grant
leases expire / the relay restarts).  Only after the whole retry budget
(``BENCH_TPU_BUDGET_S``, default 600 s) is spent does the bench fall
back to a truthful CPU number.  ``BENCH_RELAY_RESET_CMD``, when set, is
run between attempts as an operator-supplied relay reset hook.

On hardware the worker A/Bs the Pallas grid batching knob
(``apps_per_step`` in {1, 8}; override via ``BENCH_APPS_PER_STEP`` to
pin one) and reports the best; both numbers go to stderr diagnostics.

Prints ONE JSON line:
  {"metric": ..., "value": p99_ms, "unit": "ms", "vs_baseline": 50/p99}
vs_baseline > 1 means faster than the 50 ms north-star target.
"""

from __future__ import annotations

import functools
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

# canonical BASELINE config (5) shape; env overrides exist for smoke
# tests only — the driver runs with the defaults
N_NODES = int(os.environ.get("BENCH_NODES", "10000"))
N_APPS = int(os.environ.get("BENCH_APPS", "1000"))
TARGET_MS = 50.0
CHAIN = int(os.environ.get("BENCH_CHAIN", "20"))
ROUNDS = int(os.environ.get("BENCH_ROUNDS", "15"))

_RESULT_PREFIX = "BENCH_RESULT_JSON "
_LANES_PREFIX = "BENCH_LANES_JSON "
# worker exit code for "backend came up but is not a TPU" (no point
# retrying in that case — the platform config, not the relay, is wrong)
_EXIT_NOT_TPU = 3

# every measured lane lands here ({name: {p99_ms, p50_ms, ...}}) and is
# written to BENCH_RESULT.json at the end — the durable all-lane record
# (VERDICT r3 #1: the stdout tail is not the only copy of the evidence)
LANES: dict = {}
SECONDARY: dict = {}


def _machine_fingerprint() -> str:
    """Short hash of the executing host's CPU identity.  Keys the
    persistent XLA cache directory: a CPU AOT entry compiled on another
    machine's feature set can SIGILL (r3: cpu_aot_loader spew nulled the
    round artifact), so cache entries must never cross hosts."""
    import hashlib
    import platform

    bits = [platform.machine(), platform.system()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags")):
                    bits.append(line.strip())
                    if len(bits) >= 4:
                        break
    except OSError:
        pass
    try:
        import jaxlib

        bits.append(getattr(jaxlib, "__version__", ""))
    except Exception:
        pass
    return hashlib.sha1("|".join(bits).encode()).hexdigest()[:12]


def _host_info() -> dict:
    """Host context recorded with every artifact so cross-round numbers
    are comparable (the r1→r3 spread was load noise with no record)."""
    import platform

    info = {
        "fingerprint": _machine_fingerprint(),
        "platform": platform.platform(),
        "nproc": os.cpu_count(),
    }
    try:
        info["loadavg_1m"], info["loadavg_5m"], info["loadavg_15m"] = [
            round(v, 2) for v in os.getloadavg()
        ]
    except OSError:
        pass
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    info["cpu_model"] = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return info


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache shared across bench processes:
    fresh-subprocess TPU attempts (and re-runs after a relay wedge) hit
    the cache instead of paying the 3-20s compile every time.  The dir
    is keyed by machine fingerprint — entries never load cross-host."""
    try:
        import jax

        # axon remote-compile sessions produce CPU AOT code targeted at
        # the RELAY host's features; keyed separately so a TPU worker
        # can never poison the local-CPU cache (the r3 cpu_aot_loader
        # spew came back through exactly this path in r5)
        suffix = ""
        if (os.environ.get("JAX_PLATFORMS", "") or "").strip() not in ("", "cpu"):
            suffix = "-axon"
        cache_dir = os.environ.get("BENCH_JAX_CACHE") or os.path.join(
            os.path.dirname(__file__), ".jax_cache", _machine_fingerprint() + suffix
        )
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as err:  # cache is an optimization, never a failure
        print(f"# compile cache unavailable: {err}", file=sys.stderr)


def build_problem():
    from k8s_spark_scheduler_tpu.ops.sparkapp import AppDemand
    from k8s_spark_scheduler_tpu.ops.tensorize import (
        scale_problem,
        tensorize_apps,
        tensorize_cluster,
    )
    from k8s_spark_scheduler_tpu.types.resources import (
        NodeSchedulingMetadata,
        Resources,
    )

    rng = np.random.RandomState(0)
    t0 = time.perf_counter()
    metadata = {}
    for i in range(N_NODES):
        metadata[f"node-{i:05d}"] = NodeSchedulingMetadata(
            available=Resources.of(
                str(int(rng.randint(4, 96))), f"{int(rng.randint(8, 256))}Gi"
            ),
            schedulable=Resources.of("96", "256Gi"),
            zone_label=f"z{i % 3}",
        )
    order = list(metadata)
    apps = [
        AppDemand(
            driver_resources=Resources.of("1", "2Gi"),
            executor_resources=Resources.of(
                str(int(rng.randint(1, 8))), f"{int(rng.randint(2, 16))}Gi"
            ),
            min_executor_count=int(rng.randint(1, 32)),
        )
        for _ in range(N_APPS)
    ]
    cluster = tensorize_cluster(metadata, order, order)
    app_tensor = tensorize_apps(apps)
    problem = scale_problem(cluster, app_tensor)
    marshal_s = time.perf_counter() - t0
    assert problem.ok, "bench snapshot must be exactly tensorizable"
    return problem, marshal_s


def _device_args(problem):
    import jax.numpy as jnp

    return (
        jnp.asarray(problem.avail),
        jnp.asarray(problem.driver_rank),
        jnp.asarray(problem.exec_ok),
        jnp.asarray(problem.driver),
        jnp.asarray(problem.executor),
        jnp.asarray(problem.count),
        jnp.asarray(problem.app_valid),
    )


def _measure_chained(one_solve, args, label: str):
    """Compile + run the chained measurement; returns (lat_ms array,
    feasible_count, rtt_s)."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("chain",))
    def chained(avail, *rest, chain=CHAIN):
        total = jnp.int32(0)
        for _ in range(chain):
            feas, avail_after = one_solve(avail, rest)
            total = total + jnp.sum(feas)
            avail = avail_after
        return total

    # relay/dispatch RTT baseline: a null program + scalar fetch
    null = jax.jit(lambda x: jnp.sum(x))
    tiny = jnp.ones((8, 128), jnp.int32)
    int(null(tiny))
    rtts = []
    for _ in range(10):
        t0 = time.perf_counter()
        int(null(tiny))
        rtts.append(time.perf_counter() - t0)
    rtt_s = float(np.median(rtts))

    t0 = time.perf_counter()
    total = chained(*args)  # warmup/compile
    feasible_count = int(total) // CHAIN
    compile_s = time.perf_counter() - t0

    lat_ms = []
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        int(chained(*args))
        elapsed = time.perf_counter() - t0
        lat_ms.append(max(elapsed - rtt_s, 0.0) / CHAIN * 1000.0)
    lat = np.array(lat_ms)
    LANES[label] = _lane_stats(lat, feasible_count, rtt_s=rtt_s, compile_s=compile_s)
    print(
        f"# [{label}] p99={np.percentile(lat, 99):.2f}ms "
        f"p50={np.percentile(lat, 50):.2f}ms mean={lat.mean():.2f}ms "
        f"max={lat.max():.2f}ms compile={compile_s:.1f}s "
        f"rtt={rtt_s * 1000:.1f}ms feasible={feasible_count}/{N_APPS}",
        file=sys.stderr,
    )
    return lat, feasible_count, rtt_s


def _lane_stats(lat, feasible_count, rtt_s=None, compile_s=None) -> dict:
    stats = {
        "p99_ms": round(float(np.percentile(lat, 99)), 3),
        "p50_ms": round(float(np.percentile(lat, 50)), 3),
        "mean_ms": round(float(lat.mean()), 3),
        "max_ms": round(float(lat.max()), 3),
        "rounds": int(lat.size),
        "feasible": int(feasible_count),
    }
    if rtt_s is not None:
        stats["rtt_ms"] = round(rtt_s * 1000.0, 3)
    if compile_s is not None:
        stats["compile_s"] = round(compile_s, 2)
    return stats


def _emit(
    lat, feasible_count, rtt_s, marshal_s, backend: str, extra: str = "",
    as_worker: bool = False,
) -> dict:
    import jax

    p99 = float(np.percentile(lat, 99))
    result = {
        # solver-lane metric: a chained whole-queue solve on prebuilt
        # tensors.  Deliberately NOT named "filter latency" — the Filter
        # is the HTTP request, measured by config5-e2e (VERDICT r4 #2);
        # main() promotes that request-level number to the headline.
        "metric": "p99_queue_solve_10k_nodes_x_1k_apps_batched_repack",
        "value": round(p99, 3),
        "unit": "ms",
        # the floor only guards the division (tiny smoke shapes can
        # measure 0.0 after RTT subtraction); the reported value is raw
        "vs_baseline": round(TARGET_MS / max(p99, 1e-3), 3),
        # which lane produced the headline — consumers (the sentinel)
        # key off this, never off stderr diagnostics
        "backend": backend,
    }
    if as_worker:
        # the worker's stdout is parsed by the parent (prefixed lines);
        # the parent re-emits the one bare JSON line the driver parses
        print(_RESULT_PREFIX + json.dumps(result))
        print(_LANES_PREFIX + json.dumps(LANES))
    print(
        f"# p50={np.percentile(lat, 50):.2f}ms mean={lat.mean():.2f}ms "
        f"max={lat.max():.2f}ms relay_rtt={rtt_s * 1000:.1f}ms "
        f"feasible={feasible_count}/{N_APPS} marshal={marshal_s:.2f}s "
        f"platform={jax.devices()[0].platform} devices={len(jax.devices())} "
        f"backend={backend} chain={CHAIN}{extra}",
        file=sys.stderr,
    )
    return result


def tpu_worker() -> int:
    """One fresh-process TPU measurement attempt.  Exits nonzero (or
    hangs, to be reaped by the parent) on any failure; on success prints
    the result line with a machine-readable prefix."""
    import jax
    import jax.numpy as jnp

    _enable_compile_cache()
    backend = jax.default_backend()  # ← the call that wedges on a bad relay
    if "tpu" not in backend:
        print(f"# worker: default backend is {backend!r}, not tpu", file=sys.stderr)
        return _EXIT_NOT_TPU

    from k8s_spark_scheduler_tpu.ops.batch_solver import solve_app
    from k8s_spark_scheduler_tpu.ops.pallas_queue import pallas_solve_queue

    problem, marshal_s = build_problem()
    # production semantics (TpuFifoSolver): the current driver (the last
    # real app) is EXCLUDED from the queue pass and decoded separately
    # against the post-queue availability
    problem.app_valid[N_APPS - 1] = False
    args = _device_args(problem)

    pinned = os.environ.get("BENCH_APPS_PER_STEP")
    candidates = [int(pinned)] if pinned else [1, 8]

    best = None
    for aps in candidates:

        def one_solve(avail, rest, _aps=aps):
            # the production Filter cost: the queue pass PLUS the current
            # driver's placement decode (TpuFifoSolver runs solve_single
            # on the post-queue availability to produce the executor
            # list) — fold the decode outputs into the carry so the
            # decode is actually materialized every solve
            rank, exec_ok, drivers, executors, counts, valid = rest
            feas, didx, avail_after = pallas_solve_queue(
                avail, *rest, apps_per_step=_aps
            )
            # the current driver's decode (excluded from the queue above,
            # exactly as TpuFifoSolver runs it); feasible ⟹ placements
            # sum to k, so the conjunction preserves the feasibility
            # count while making the placement compute non-dead code
            last = N_APPS - 1
            decode = solve_app(
                avail_after, rank, exec_ok, drivers[last], executors[last], counts[last]
            )
            feas = feas.at[last].set(
                decode.feasible & (jnp.sum(decode.exec_counts) == counts[last])
            )
            return feas, avail_after

        lat, feasible_count, rtt_s = _measure_chained(
            one_solve, args, label=f"pallas apps_per_step={aps}"
        )
        p99 = float(np.percentile(lat, 99))
        if best is None or p99 < best[0]:
            best = (p99, aps, lat, feasible_count, rtt_s)

    _, aps, lat, feasible_count, rtt_s = best
    # result lines print BEFORE the diagnostics: the diags run fresh TPU
    # programs through the wedge-prone relay, and a wedge there must not
    # cost the completed measurement (the parent parses partial output
    # of a killed worker)
    _emit(
        lat,
        feasible_count,
        rtt_s,
        marshal_s,
        backend="pallas",
        extra=f" apps_per_step={aps}",
        as_worker=True,
    )
    sys.stdout.flush()
    _single_az_diag(problem, rtt_s)
    _min_frag_diag(problem, rtt_s)
    # request-level lane on the device backend (VERDICT r4 #4): the HTTP
    # Filter driven by the pallas queue lane.  Runs LAST — the solver
    # evidence above is already on stdout, so a relay wedge here cannot
    # cost it.  Per-request latency through the dev relay includes the
    # ~67ms tunnel RTT a co-located deployment doesn't pay; the lane
    # records rtt_ms context for exactly that.
    os.environ.setdefault("BENCH_E2E_PROBES", "25")
    e2e = _config5_e2e(force_cpu=False)
    if e2e is not None:
        e2e["relay_rtt_ms"] = round(rtt_s * 1000.0, 1)
        print(_LANES_PREFIX + json.dumps({"config5-e2e http (tpu)": e2e}))
        sys.stdout.flush()
    return 0


def _min_frag_diag(problem, rtt_s: float) -> None:
    """Secondary diagnostic: the minimal-fragmentation whole-queue pass —
    the pallas VMEM kernel (the production TPU lane,
    pallas_solve_queue_min_frag) and the fused XLA scan
    (solve_queue_min_frag, the comparison point: 123ms/queue in r02) on
    the same snapshot (stderr only)."""
    try:
        import jax
        import jax.numpy as jnp

        from k8s_spark_scheduler_tpu.ops.batch_solver import solve_queue_min_frag
        from k8s_spark_scheduler_tpu.ops.pallas_queue import (
            pallas_solve_queue_min_frag,
        )

        rest = (
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver),
            jnp.asarray(problem.executor),
            jnp.asarray(problem.count),
            jnp.asarray(problem.app_valid),
        )
        a0 = jnp.asarray(problem.avail)

        def measure(label, one, chain):
            @functools.partial(jax.jit, static_argnames=("c",))
            def chained(a, c=chain):
                tot = jnp.int32(0)
                for _ in range(c):
                    feas, a = one(a)
                    tot = tot + jnp.sum(feas)
                return tot

            t0 = time.perf_counter()
            int(chained(a0))  # compile
            compile_s = time.perf_counter() - t0
            lat = []
            for _ in range(6):
                t0 = time.perf_counter()
                int(chained(a0))
                lat.append(
                    max(time.perf_counter() - t0 - rtt_s, 0.0) / chain * 1000.0
                )
            print(
                f"# min-frag whole-queue ({label}): "
                f"median={float(np.median(lat)):.1f}ms/queue "
                f"compile={compile_s:.1f}s",
                file=sys.stderr,
            )

        def pallas_one(a):
            feas, _, a2 = pallas_solve_queue_min_frag(a, *rest)
            return feas, a2

        def xla_one(a):
            out = solve_queue_min_frag(a, *rest, with_placements=False)
            return out.feasible, out.avail_after

        measure("pallas kernel", pallas_one, chain=4)
        measure("fused scan", xla_one, chain=2)
    except Exception as err:
        print(f"# min-frag diagnostic failed: {err}", file=sys.stderr)


def _single_az_diag(problem, rtt_s: float) -> None:
    """Secondary diagnostic: the single-AZ whole-queue kernel
    (pallas_solve_queue_single_az) on the same snapshot with a synthetic
    3-zone split — the single-AZ policies' FIFO cost (stderr only)."""
    try:
        import jax
        import jax.numpy as jnp

        from k8s_spark_scheduler_tpu.ops.pallas_queue import (
            pallas_solve_queue_single_az,
        )

        nb = problem.avail.shape[0]
        zone_vec = (np.arange(nb) % 3).astype(np.int32)
        sched = np.full(nb, 96000, np.int32)  # uniform synthetic schedulables
        no_gpu = np.zeros(nb, np.int32)
        inv_m = np.full(nb, 1.0 / 256.0, np.float32)
        th_m = np.full(nb, 256, np.int32)
        rest = (
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(zone_vec),
            jnp.asarray(problem.driver),
            jnp.asarray(problem.executor),
            jnp.asarray(problem.count),
            jnp.asarray(problem.app_valid),
            jnp.asarray(sched),
            jnp.asarray(no_gpu),
            jnp.asarray(inv_m),
            jnp.asarray(th_m),
            jnp.asarray(np.array([1000], np.int32)),
            jnp.asarray(np.array([1000], np.int32)),
        )

        diag_chain = 4

        @functools.partial(jax.jit, static_argnames=("chain",))
        def chained(a, chain=diag_chain):
            tot = jnp.int32(0)
            for _ in range(chain):
                feas, _z, _d, unc, a2 = pallas_solve_queue_single_az(
                    a, *rest, n_zones=3, az_aware=True
                )
                tot = tot + jnp.sum(feas) + jnp.sum(unc)
                a = a2
            return tot
        a0 = jnp.asarray(problem.avail)
        int(chained(a0))  # compile
        lat = []
        for _ in range(6):
            t0 = time.perf_counter()
            int(chained(a0))
            lat.append(max(time.perf_counter() - t0 - rtt_s, 0.0) / diag_chain * 1000.0)
        print(
            f"# single-az az-aware whole-queue (pallas, 3 zones): "
            f"median={float(np.median(lat)):.1f}ms/queue",
            file=sys.stderr,
        )

        # the single-az minimal-fragmentation pass: pallas kernel (the
        # production TPU lane) vs the fused XLA scan
        mf_chain = 2

        @functools.partial(jax.jit, static_argnames=("chain",))
        def mf_pallas_chained(a, chain=mf_chain):
            tot = jnp.int32(0)
            for _ in range(chain):
                feas, _z, _d, unc, a = pallas_solve_queue_single_az(
                    a, *rest, n_zones=3, az_aware=False, minfrag=True, strict=True
                )
                tot = tot + jnp.sum(feas) + jnp.sum(unc)
            return tot

        int(mf_pallas_chained(a0))  # compile
        lat = []
        for _ in range(6):
            t0 = time.perf_counter()
            int(mf_pallas_chained(a0))
            lat.append(max(time.perf_counter() - t0 - rtt_s, 0.0) / mf_chain * 1000.0)
        print(
            f"# single-az min-frag whole-queue (pallas, 3 zones): "
            f"median={float(np.median(lat)):.1f}ms/queue",
            file=sys.stderr,
        )

        from k8s_spark_scheduler_tpu.ops.batch_solver import solve_queue_single_az

        nb = problem.avail.shape[0]
        zone_masks = np.stack([(np.arange(nb) % 3) == z for z in range(3)])
        mf_rest = (
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(zone_masks),
            jnp.asarray(problem.driver),
            jnp.asarray(problem.executor),
            jnp.asarray(problem.count),
            jnp.asarray(problem.app_valid),
            *rest[7:11],  # s_cpu, s_gpu, inv_m, th_m planes
            jnp.int32(1000),
            jnp.int32(1000),
        )

        @functools.partial(jax.jit, static_argnames=("chain",))
        def mf_chained(a, chain=mf_chain):
            tot = jnp.int32(0)
            for _ in range(chain):
                out = solve_queue_single_az(
                    a, *mf_rest, az_aware=False, minfrag=True, strict=True
                )
                tot = tot + jnp.sum(out.feasible)
                a = out.avail_after
            return tot

        int(mf_chained(a0))  # compile
        lat = []
        for _ in range(6):
            t0 = time.perf_counter()
            int(mf_chained(a0))
            lat.append(max(time.perf_counter() - t0 - rtt_s, 0.0) / mf_chain * 1000.0)
        print(
            f"# single-az min-frag whole-queue (fused scan, 3 zones): "
            f"median={float(np.median(lat)):.1f}ms/queue",
            file=sys.stderr,
        )
    except Exception as err:
        print(f"# single-az diagnostic failed: {err}", file=sys.stderr)


def _run_tpu_worker_attempt(timeout_s: float) -> dict | None | str:
    """Spawn a fresh worker; returns the parsed result dict, None on
    failure/hang, or "not-tpu" when retrying is pointless.  Hang safety
    (detached Popen + poll loop, kill without a blocking wait) lives in
    tpuprobe.run_detached."""
    from k8s_spark_scheduler_tpu.utils.tpuprobe import run_detached

    with tempfile.TemporaryFile() as outf:
        code = run_detached(
            [sys.executable, os.path.abspath(__file__), "--tpu-worker"],
            timeout_s,
            outf,
            sys.stderr,  # stream worker diagnostics through
        )
        if code == _EXIT_NOT_TPU:
            return "not-tpu"
        if code is None:
            print(
                f"# TPU worker hung past {timeout_s:.0f}s (relay wedged?); "
                "killed (parsing partial output)",
                file=sys.stderr,
            )
        elif code != 0:
            print(
                f"# TPU worker exited rc={code} (parsing partial output)",
                file=sys.stderr,
            )
        # parse whatever reached stdout even on a hang/crash: the result
        # prints before the diagnostics, so a measurement that completed
        # and then wedged in a diag is still evidence
        outf.seek(0)
        result = None
        for raw in outf.read().decode(errors="replace").splitlines():
            if raw.startswith(_RESULT_PREFIX):
                try:
                    result = json.loads(raw[len(_RESULT_PREFIX):])
                except json.JSONDecodeError:
                    continue
            elif raw.startswith(_LANES_PREFIX):
                try:
                    LANES.update(json.loads(raw[len(_LANES_PREFIX):]))
                except json.JSONDecodeError:
                    pass
        if result is None and code == 0:
            print("# TPU worker exited 0 but printed no result", file=sys.stderr)
        return result


def try_tpu(budget_s: float, attempt_s: float) -> dict | None:
    """Bounded retry loop around fresh-process TPU attempts."""
    from k8s_spark_scheduler_tpu.utils.tpuprobe import live_platforms

    platforms = live_platforms()
    if not platforms or platforms.split(",")[0].strip() == "cpu":
        print("# no accelerator platform configured; skipping TPU", file=sys.stderr)
        return None

    reset_cmd = os.environ.get("BENCH_RELAY_RESET_CMD")
    deadline = time.monotonic() + budget_s
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if attempt > 0 and remaining <= 30.0:
            break
        attempt += 1
        # every attempt (including the first) stays inside the budget
        timeout_s = min(attempt_s, max(remaining, 10.0))
        print(
            f"# TPU attempt {attempt} (timeout {timeout_s:.0f}s, "
            f"budget left {max(remaining, 0):.0f}s)",
            file=sys.stderr,
        )
        result = _run_tpu_worker_attempt(timeout_s)
        if isinstance(result, dict):
            return result
        if result == "not-tpu":
            return None
        if reset_cmd:
            print(f"# running relay reset hook: {reset_cmd}", file=sys.stderr)
            try:
                subprocess.run(reset_cmd, shell=True, timeout=60)
            except Exception as err:
                print(f"# reset hook failed: {err}", file=sys.stderr)
        time.sleep(min(5.0, max(deadline - time.monotonic(), 0.0)))
    print(
        f"# TPU retry budget ({budget_s:.0f}s) exhausted after "
        f"{attempt} attempts; falling back to CPU",
        file=sys.stderr,
    )
    return None


def cpu_fallback() -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()

    from k8s_spark_scheduler_tpu.ops.batch_solver import solve_app, solve_queue

    problem, marshal_s = build_problem()
    # same operation as the TPU worker: queue over the earlier apps,
    # separate decode for the current driver
    problem.app_valid[N_APPS - 1] = False

    # the production CPU lane (TpuFifoSolver backend="auto" on a
    # CPU-only host) is the native C++ queue solver — decision-identical
    # to the device scan (tests/test_native_fifo.py); it is the honest
    # fallback headline, with the XLA scan kept as a diagnostic
    native = _native_cpu_measure(problem)
    _deltasolve_measure(problem)
    _provenance_measure(problem)
    _capacity_probe_measure(problem)
    _preemption_whatif_measure(problem)
    _class_compressed_measure()

    args = _device_args(problem)

    # note: sharding the scan across virtual CPU devices was measured
    # 18x SLOWER than single-device (per-step collective overhead);
    # the CPU fallback stays single-device on purpose
    def one_solve(avail, rest):
        import jax.numpy as jnp

        rank, exec_ok, drivers, executors, counts, valid = rest
        out = solve_queue(avail, *rest, evenly=False, with_placements=False)
        last = N_APPS - 1
        decode = solve_app(
            out.avail_after, rank, exec_ok, drivers[last], executors[last], counts[last]
        )
        feas = out.feasible.at[last].set(
            decode.feasible & (jnp.sum(decode.exec_counts) == counts[last])
        )
        return feas, out.avail_after

    lat, feasible_count, rtt_s = _measure_chained(one_solve, args, label="xla-scan cpu")
    _native_policy_diag(problem)
    if native is not None:
        nat_lat, nat_feasible = native
        return _emit(nat_lat, nat_feasible, 0.0, marshal_s, backend="native-cpp")
    return _emit(lat, feasible_count, rtt_s, marshal_s, backend="xla-scan")


def _native_policy_diag(problem) -> None:
    """Native C++ lanes for the remaining policies on the same snapshot:
    whole-queue minimal-fragmentation (vs the 123ms/queue XLA scan) and
    the single-AZ zone-choice pass (3 synthetic zones) — the CPU-host
    story for every policy, not just tightly/evenly (VERDICT r3 #4)."""
    try:
        from k8s_spark_scheduler_tpu.native.fifo import (
            native_fifo_available,
            solve_queue_min_frag_native,
            solve_queue_native,
            solve_queue_single_az_native,
        )

        if not native_fifo_available():
            return
        nb = problem.avail.shape[0]

        def measure(label, one, reps=8):
            one()  # warm
            lat_ms = []
            for _ in range(reps):
                t0 = time.perf_counter()
                feasible = one()
                lat_ms.append((time.perf_counter() - t0) * 1000.0)
            lat = np.array(lat_ms)
            LANES[label] = _lane_stats(lat, feasible)
            print(
                f"# [{label}] p99={np.percentile(lat, 99):.2f}ms "
                f"p50={np.percentile(lat, 50):.2f}ms feasible={feasible}/{N_APPS}",
                file=sys.stderr,
            )

        measure(
            "native-cpp evenly cpu",
            lambda: int(
                solve_queue_native(
                    problem.avail, problem.driver_rank, problem.exec_ok,
                    problem.driver, problem.executor, problem.count,
                    problem.app_valid, evenly=True,
                )[0].sum()
            ),
        )
        measure(
            "native-cpp minfrag cpu",
            lambda: int(
                solve_queue_min_frag_native(
                    problem.avail, problem.driver_rank, problem.exec_ok,
                    problem.driver, problem.executor, problem.count,
                    problem.app_valid,
                )[0].sum()
            ),
        )

        zone_vec = (np.arange(nb) % 3).astype(np.int32)
        sched = np.abs(problem.avail.astype(np.int64)) * 2 + 1000
        scale = np.array([100, 2**20, 1000], np.int64)
        sched *= scale[None, :]
        measure(
            "native-cpp single-az cpu",
            lambda: int(
                solve_queue_single_az_native(
                    problem.avail, problem.driver_rank, problem.exec_ok,
                    zone_vec, problem.driver, problem.executor, problem.count,
                    problem.app_valid, sched, scale, n_zones=3,
                )[0].sum()
            ),
        )
    except Exception as err:
        print(f"# native policy diagnostics failed: {err}", file=sys.stderr)


def _native_cpu_measure(problem):
    """Measure the native C++ queue solver (queue pass + current-driver
    decode, the TpuFifoSolver CPU-lane program).  Returns (lat_ms array,
    feasible_count) or None when the toolchain is unavailable."""
    try:
        from k8s_spark_scheduler_tpu.native.fifo import (
            native_fifo_available,
            solve_app_native,
            solve_queue_native,
        )

        if not native_fifo_available():
            return None
        last = N_APPS - 1

        def one():
            feas, _, avail_after = solve_queue_native(
                problem.avail, problem.driver_rank, problem.exec_ok,
                problem.driver, problem.executor, problem.count,
                problem.app_valid, evenly=False,
            )
            fb, _db, cb, _caps = solve_app_native(
                avail_after, problem.driver_rank, problem.exec_ok,
                problem.driver[last], problem.executor[last],
                int(problem.count[last]),
            )
            return int(feas.sum()) + int(fb and cb.sum() == problem.count[last])

        feasible_count = one()  # warm the code path
        lat_ms = []
        for _ in range(max(ROUNDS, 15)):
            t0 = time.perf_counter()
            one()
            lat_ms.append((time.perf_counter() - t0) * 1000.0)
        lat = np.array(lat_ms)
        LANES["native-cpp cpu"] = _lane_stats(lat, feasible_count)
        print(
            f"# [native-cpp cpu] p99={np.percentile(lat, 99):.2f}ms "
            f"p50={np.percentile(lat, 50):.2f}ms mean={lat.mean():.2f}ms "
            f"feasible={feasible_count}/{N_APPS}",
            file=sys.stderr,
        )
        return lat, feasible_count
    except Exception as err:
        print(f"# native CPU lane unavailable: {err}", file=sys.stderr)
        return None


def _deltasolve_measure(problem) -> None:
    """Delta-solve session lane: cold full solve (basis load + whole
    queue) vs warm full-prefix resume on the SAME session at the bench
    shape.  Records both distributions so the acceptance bound — warm
    p50 at least 3x below the cold full-solve p50 — is durable in the
    artifact (the perf guard pins the same bound in CI)."""
    try:
        from k8s_spark_scheduler_tpu.native.fifo import (
            NativeFifoSession,
            native_session_available,
        )

        if not native_session_available():
            return
        packed = np.hstack(
            [
                problem.driver, problem.executor,
                problem.count[:, None],
                problem.app_valid.astype(np.int32)[:, None],
            ]
        ).astype(np.int32)
        sess = NativeFifoSession()
        try:
            def cold():
                sess.load(
                    problem.avail, problem.driver_rank, problem.exec_ok, 0
                )
                return sess.solve(packed)

            def warm():
                return sess.solve(packed)

            _, feas_cold, _, after_cold = cold()
            resume, feas_warm, _, after_warm = warm()
            assert resume == packed.shape[0]
            assert np.array_equal(feas_warm, feas_cold)
            assert np.array_equal(after_warm, after_cold)
            reps = max(ROUNDS, 15)
            cold_ms, warm_ms = [], []
            for _ in range(reps):
                t0 = time.perf_counter()
                cold()
                cold_ms.append((time.perf_counter() - t0) * 1000.0)
            for _ in range(reps):
                t0 = time.perf_counter()
                warm()
                warm_ms.append((time.perf_counter() - t0) * 1000.0)
            cold_lat, warm_lat = np.array(cold_ms), np.array(warm_ms)
            feasible = int(feas_cold.sum())
            stats = _lane_stats(warm_lat, feasible)
            stats["cold_p50_ms"] = round(float(np.percentile(cold_lat, 50)), 3)
            stats["warm_p50_ms"] = round(float(np.percentile(warm_lat, 50)), 3)
            stats["warm_speedup_p50"] = round(
                float(np.percentile(cold_lat, 50))
                / max(float(np.percentile(warm_lat, 50)), 1e-6),
                1,
            )
            LANES["deltasolve-session cpu"] = stats
            SECONDARY["deltasolve_cold_p50_ms"] = stats["cold_p50_ms"]
            SECONDARY["deltasolve_warm_p50_ms"] = stats["warm_p50_ms"]
            print(
                f"# [deltasolve-session cpu] cold_p50={stats['cold_p50_ms']}ms "
                f"warm_p50={stats['warm_p50_ms']}ms "
                f"speedup={stats['warm_speedup_p50']}x",
                file=sys.stderr,
            )
        finally:
            sess.close()
    except Exception as err:
        print(f"# deltasolve lane unavailable: {err}", file=sys.stderr)


def _provenance_measure(problem) -> None:
    """Provenance overhead contract (PR 6): the explain path (shortfall
    + blocker replay at the bench shape) and the flight-recorder
    note+persist cost, as their own diagnostic lane.  Explain is
    on-demand (a refusal or an /explain request), so its budget is
    'about one cold solve', not microseconds — the lane pins that it
    stays in that regime; the perf guard separately pins the capture
    cost on the request path at < 5% (enabled) / zero (disabled)."""
    try:
        from k8s_spark_scheduler_tpu.native.fifo import (
            explain_queue_native,
            native_explain_available,
        )
        from k8s_spark_scheduler_tpu.provenance.recorder import FlightRecorder
        from k8s_spark_scheduler_tpu.provenance.tracker import SolveArtifacts

        if not native_explain_available():
            return
        packed = np.hstack(
            [
                problem.driver, problem.executor,
                problem.count[:, None],
                problem.app_valid.astype(np.int32)[:, None],
            ]
        ).astype(np.int32)
        target = int(packed.shape[0] - 1)
        reps = max(ROUNDS, 10)
        explain_ms = []
        for _ in range(reps):
            t0 = time.perf_counter()
            explain_queue_native(
                problem.avail, problem.driver_rank, problem.exec_ok,
                packed, 0, target,
            )
            explain_ms.append((time.perf_counter() - t0) * 1000.0)
        n_earlier = target
        art = SolveArtifacts(
            policy_code=0,
            lane="bench",
            basis=problem.avail,
            driver_rank=problem.driver_rank,
            exec_ok=problem.exec_ok,
            packed=packed,
            n_earlier=n_earlier,
            feasible=np.ones(n_earlier, dtype=bool),
            didx=np.zeros(n_earlier, dtype=np.int32),
            resume=0,
            avail_after=problem.avail,
        )
        note_ms = []
        with tempfile.TemporaryDirectory() as tmp:
            rec = FlightRecorder(
                capacity=8, out_dir=tmp, max_nodes=problem.avail.shape[0]
            )
            for _ in range(reps):
                t0 = time.perf_counter()
                rec.note(art, "bench-pod", "failure-fit")
                note_ms.append((time.perf_counter() - t0) * 1000.0)
            t0 = time.perf_counter()
            path = rec.persist("bench")
            persist_ms = (time.perf_counter() - t0) * 1000.0
            bundle_bytes = os.path.getsize(path) if path else 0
        lat = np.array(explain_ms)
        stats = _lane_stats(lat, 0)
        stats["explain_p50_ms"] = round(float(np.percentile(lat, 50)), 3)
        stats["recorder_note_p50_ms"] = round(
            float(np.percentile(np.array(note_ms), 50)), 3
        )
        stats["persist_ms"] = round(persist_ms, 3)
        stats["bundle_file_bytes"] = int(bundle_bytes)
        LANES["provenance-explain cpu"] = stats
        SECONDARY["provenance_explain_p50_ms"] = stats["explain_p50_ms"]
        print(
            f"# [provenance-explain cpu] explain_p50={stats['explain_p50_ms']}ms "
            f"note_p50={stats['recorder_note_p50_ms']}ms "
            f"persist={stats['persist_ms']}ms bundle={bundle_bytes}B",
            file=sys.stderr,
        )
    except Exception as err:
        print(f"# provenance lane unavailable: {err}", file=sys.stderr)


def _capacity_probe_measure(problem) -> None:
    """Capacity-observatory contract (PR 7): the batched what-if
    headroom probe at the bench node shape × 16 gang shapes, as its own
    diagnostic lane.  The probe is the sampler's unit of work (one per
    (group, zone) combo per state change), so its latency budget is
    'milliseconds at 10k nodes', and the bisection depth (solves per
    shape) should stay a handful — both are pinned by the bench
    contract."""
    try:
        from k8s_spark_scheduler_tpu.native.fifo import (
            native_probe_available,
            probe_headroom_native,
        )

        if not native_probe_available():
            return
        n_shapes = 16
        take = min(n_shapes, problem.driver.shape[0])
        shapes = np.zeros((n_shapes, 6), dtype=np.int32)
        shapes[:take, 0:3] = problem.driver[:take]
        shapes[:take, 3:6] = problem.executor[:take]
        if take < n_shapes:  # pad by cycling (smoke shapes have few apps)
            for i in range(take, n_shapes):
                shapes[i] = shapes[i % max(take, 1)]
        reps = max(ROUNDS, 10)
        probe_ms = []
        solves = 0
        for _ in range(reps):
            t0 = time.perf_counter()
            out = probe_headroom_native(
                problem.avail, problem.driver_rank, problem.exec_ok,
                shapes, 1_000_000,
            )
            probe_ms.append((time.perf_counter() - t0) * 1000.0)
            solves = int(out[2].sum())
        lat = np.array(probe_ms)
        stats = _lane_stats(lat, int((out[0] > 0).sum()))
        stats["probe_p50_ms"] = round(float(np.percentile(lat, 50)), 3)
        stats["shapes"] = n_shapes
        stats["solves_per_probe"] = solves
        stats["solves_per_shape_p50"] = round(
            float(np.percentile(out[2], 50)), 1
        )
        LANES["capacity-probe cpu"] = stats
        SECONDARY["capacity_probe_p50_ms"] = stats["probe_p50_ms"]
        print(
            f"# [capacity-probe cpu] probe_p50={stats['probe_p50_ms']}ms "
            f"({n_shapes} shapes, {solves} feasibility solves/probe)",
            file=sys.stderr,
        )
    except Exception as err:
        print(f"# capacity-probe lane unavailable: {err}", file=sys.stderr)


def _preemption_whatif_measure(problem) -> None:
    """Policy-engine contract (ISSUE 14): the preemption what-if solve
    at the bench node shape × 16 preemptor gangs, as its own lane.  A
    what-if validates one candidate victim set — ``gang_feasible`` on
    ``avail + freed`` — and the selector runs up to ``max_victims`` of
    them per refused driver, so its per-call latency bounds the cost a
    preemption attempt adds to a Filter round.  Pure numpy (the
    fallback when no warm delta-solve session exists), so the lane is
    unconditional."""
    try:
        from k8s_spark_scheduler_tpu.policy.victims import whatif_fits

        n_nodes = problem.avail.shape[0]
        n_gangs = 16
        take = max(min(n_gangs, problem.driver.shape[0]), 1)
        gangs = [
            (
                problem.driver[i % take],
                problem.executor[i % take],
                int(problem.count[i % take]),
            )
            for i in range(n_gangs)
        ]
        # a victim set's freed capacity: a few whole applications'
        # worth of executors returned across a handful of nodes
        # (deterministic; the verdict itself is irrelevant to latency)
        rng = np.random.default_rng(7)
        freed = np.zeros((n_nodes, 3), dtype=problem.avail.dtype)
        victim_nodes = rng.choice(n_nodes, size=min(8, n_nodes), replace=False)
        for i, node in enumerate(victim_nodes):
            freed[node] = problem.executor[i % take] * 3
        reps = max(ROUNDS, 10)
        whatif_ms = []
        fits = 0
        for _ in range(reps):
            fits = 0
            for gang in gangs:
                t0 = time.perf_counter()
                ok = whatif_fits(
                    problem.avail, problem.exec_ok, problem.driver_rank,
                    freed, gang,
                )
                whatif_ms.append((time.perf_counter() - t0) * 1000.0)
                fits += int(ok)
        lat = np.array(whatif_ms)
        stats = _lane_stats(lat, fits)
        stats["whatif_p50_ms"] = round(float(np.percentile(lat, 50)), 3)
        stats["gangs"] = n_gangs
        LANES["preemption-whatif cpu"] = stats
        SECONDARY["preemption_whatif_p50_ms"] = stats["whatif_p50_ms"]
        print(
            f"# [preemption-whatif cpu] whatif_p50={stats['whatif_p50_ms']}ms "
            f"p99={stats['p99_ms']}ms ({n_gangs} gangs, {n_nodes} nodes)",
            file=sys.stderr,
        )
    except Exception as err:
        print(f"# preemption-whatif lane unavailable: {err}", file=sys.stderr)


def _class_compressed_measure() -> None:
    """Equivalence-class lane (ROADMAP 2): the class-compressed native
    solver at 100k nodes × 10k apps — the scale where per-app O(nodes)
    row sweeps stop fitting in a Filter budget and O(classes + diverged
    overlay) keeps working.  Runs at its OWN shape (``BENCH_CLASS_NODES``
    × ``BENCH_CLASS_APPS``; 10× the main shape when unset so smoke runs
    scale down honestly), proves byte-identical verdicts against a
    row-level cold solve of the same inputs every run, and records the
    compression evidence (class count, ratio, rebuilds) alongside the
    latencies — the speedup claim is only as good as the parity + the
    partition it rode on."""
    try:
        from k8s_spark_scheduler_tpu.native.fifo import (
            NativeFifoSession,
            native_classes_available,
            solve_packed_classes,
            solve_packed_cold,
        )

        if not native_classes_available():
            return
        cn = int(os.environ.get("BENCH_CLASS_NODES", str(N_NODES * 10)))
        ca = int(os.environ.get("BENCH_CLASS_APPS", str(N_APPS * 10)))
        rng = np.random.RandomState(20)
        # fleet-shaped: ~24 machine shapes, salted with near-duplicates
        # (one unit off) so the partition is earned, not gifted
        shapes = rng.randint(20, 200, size=(24, 3)).astype(np.int32)
        avail = shapes[rng.randint(0, 24, size=cn)].copy()
        near = rng.choice(cn, size=max(1, cn // 50), replace=False)
        avail[near, rng.randint(0, 3, size=len(near))] += 1
        rank = np.arange(cn, dtype=np.int32)
        rng.shuffle(rank)
        eok = rng.rand(cn) > 0.05
        drv = rng.randint(0, 3, size=(ca, 3)).astype(np.int32)
        exe = rng.randint(1, 5, size=(ca, 3)).astype(np.int32)
        cnt = rng.randint(1, 8, size=ca).astype(np.int32)
        packed = np.hstack(
            [drv, exe, cnt[:, None], np.ones((ca, 1), np.int32)]
        ).astype(np.int32)

        # parity first: the speedup only counts if the bits agree
        feas, didx, after, evidence = solve_packed_classes(
            0, avail, rank, eok, packed
        )
        ref_f, ref_d, ref_a = solve_packed_cold(0, avail, rank, eok, packed)
        assert np.array_equal(feas, ref_f)
        assert np.array_equal(didx, ref_d)
        assert np.array_equal(after, ref_a)

        # the row-level reference is seconds per solve at this shape:
        # a few reps give a stable p50 without eating the bench budget
        row_reps = max(3, min(ROUNDS, 5))
        row_ms = []
        for _ in range(row_reps):
            t0 = time.perf_counter()
            solve_packed_cold(0, avail, rank, eok, packed)
            row_ms.append((time.perf_counter() - t0) * 1000.0)
        cls_reps = max(ROUNDS, 10)
        cold_ms = []
        for _ in range(cls_reps):
            t0 = time.perf_counter()
            solve_packed_classes(0, avail, rank, eok, packed)
            cold_ms.append((time.perf_counter() - t0) * 1000.0)

        # warm lane: a persistent class-mode session resolving the same
        # queue (full-prefix resume — the steady Filter retry path)
        warm_ms = []
        sess = NativeFifoSession()
        try:
            if sess.set_classes(True):
                sess.load(avail, rank, eok, 0)
                sess.solve(packed)
                for _ in range(cls_reps):
                    t0 = time.perf_counter()
                    sess.solve(packed)
                    warm_ms.append((time.perf_counter() - t0) * 1000.0)
        finally:
            sess.close()

        row_lat, cold_lat = np.array(row_ms), np.array(cold_ms)
        stats = _lane_stats(cold_lat, int(feas.sum()))
        stats["nodes"] = cn
        stats["apps"] = ca
        stats["row_p50_ms"] = round(float(np.percentile(row_lat, 50)), 3)
        stats["speedup_p50"] = round(
            float(np.percentile(row_lat, 50))
            / max(float(np.percentile(cold_lat, 50)), 1e-6),
            1,
        )
        stats["classes_initial"] = int(evidence["classes_initial"])
        stats["classes_last"] = int(evidence["classes_last"])
        stats["rebuilds"] = int(evidence["rebuilds"])
        stats["overlay_peak"] = int(evidence["overlay_peak"])
        stats["compression_ratio"] = round(
            cn / max(int(evidence["classes_initial"]), 1), 1
        )
        stats["parity"] = "byte-identical"
        LANES["class-compressed cold"] = stats
        if warm_ms:
            warm_lat = np.array(warm_ms)
            wstats = _lane_stats(warm_lat, int(feas.sum()))
            wstats["nodes"] = cn
            wstats["apps"] = ca
            LANES["class-compressed warm"] = wstats
        SECONDARY["class_cold_p50_ms"] = stats["p50_ms"]
        SECONDARY["class_row_p50_ms"] = stats["row_p50_ms"]
        SECONDARY["class_speedup_p50"] = stats["speedup_p50"]
        print(
            f"# [class-compressed cold] {cn}x{ca} p50={stats['p50_ms']}ms "
            f"row_p50={stats['row_p50_ms']}ms "
            f"speedup={stats['speedup_p50']}x "
            f"classes={stats['classes_initial']} "
            f"ratio={stats['compression_ratio']}x "
            f"rebuilds={stats['rebuilds']}",
            file=sys.stderr,
        )
    except Exception as err:
        print(f"# class-compressed lane unavailable: {err}", file=sys.stderr)


def _check_load() -> bool:
    """VERDICT r4 #8: annotate the artifact loudly when another heavy
    process owns the core at run start, so cross-round deltas mean
    something.  Threshold: on this nproc-core host a 1-minute load
    above 0.5·nproc means the bench shares its core(s)."""
    try:
        load1 = os.getloadavg()[0]
    except OSError:
        return True
    ok = load1 <= 0.5 * (os.cpu_count() or 1)
    if not ok:
        print(
            f"# WARNING: loadavg_1m={load1:.2f} at bench start — another "
            "process is using the core; latencies are NOT comparable "
            "across rounds (artifact carries load_ok=false)",
            file=sys.stderr,
        )
    return ok


def main() -> None:
    budget_s = float(os.environ.get("BENCH_TPU_BUDGET_S", "600"))
    attempt_s = float(os.environ.get("BENCH_TPU_ATTEMPT_S", "240"))
    load_ok = _check_load()

    solver = try_tpu(budget_s, attempt_s) if budget_s > 0 else None
    if solver is None:
        print("# TPU backend unavailable; benching on CPU", file=sys.stderr)
        solver = cpu_fallback()
    solver["load_ok"] = load_ok
    # write the durable artifact BEFORE the secondary configs: a kill
    # during those (they are unbounded harness runs) must not cost the
    # solver-lane evidence; rewritten afterwards with SECONDARY + the
    # request-level headline filled in
    _write_bench_result(solver, commit=False)
    _secondary_configs()
    e2e = _config5_e2e()
    if e2e is not None:
        # the headline is the request-level number measured at the HTTP
        # boundary (VERDICT r4 #2); the solver lane rides along so the
        # two can never be confused
        p99 = e2e["p99_ms"]
        headline = {
            "metric": "p99_filter_latency_10k_nodes_x_1k_apps_batched_repack",
            "value": round(p99, 3),
            "unit": "ms",
            "vs_baseline": round(TARGET_MS / max(p99, 1e-3), 3),
            "backend": e2e["backend"],
            "samples": e2e["rounds"],
            "p50_ms": e2e["p50_ms"],
            "p95_ms": e2e.get("p95_ms"),
            "measured_at": "http",
            "solver_p99_ms": solver.get("value"),
            "solver_backend": solver.get("backend"),
            "load_ok": load_ok,
        }
        # delta-solve evidence rides on the headline: steady-state warm
        # hit rate + resume depth from the e2e phase, warm/cold solver
        # p50s from the session lane (contract-pinned)
        if "warm_hit_rate" in e2e:
            headline["warm_hit_rate"] = e2e["warm_hit_rate"]
            headline["resume_depth_p50"] = e2e.get("resume_depth_p50")
        ds = LANES.get("deltasolve-session cpu")
        if ds is not None:
            headline["warm_solve_p50_ms"] = ds["warm_p50_ms"]
            headline["cold_solve_p50_ms"] = ds["cold_p50_ms"]
        # contention-observatory evidence: how much of the request the
        # decomposition explains, and which segment dominates
        if "criticalpath_coverage_p50" in e2e:
            headline["criticalpath_coverage_p50"] = e2e["criticalpath_coverage_p50"]
            headline["criticalpath_dominant"] = e2e.get("criticalpath_dominant")
    else:
        # no request-level measurement: the solver lane stands, under
        # its own honest p99_queue_solve_… name
        headline = solver
    _write_bench_result(headline)
    # the headline is the FINAL stdout line, emitted after everything
    # that could possibly crash or spew — a tail-window capture (the
    # driver's) can never lose it to later output (VERDICT r3 #1)
    print(json.dumps(headline))


def _write_bench_result(headline: dict, commit: bool = True) -> None:
    """Durable all-lane artifact: BENCH_RESULT.json on disk, committed
    best-effort — the round's evidence survives even when the driver's
    stdout capture doesn't.  Non-canonical (smoke) shapes write to a
    side path so they can never clobber canonical evidence."""
    canonical = (N_NODES, N_APPS) == (10000, 1000)
    artifact = {
        "timestamp_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "headline": headline,
        "lanes": LANES,
        "secondary_configs": SECONDARY,
        "host": _host_info(),
        "shape": {"nodes": N_NODES, "apps": N_APPS, "chain": CHAIN, "rounds": ROUNDS},
        "target_ms": TARGET_MS,
    }
    name = "BENCH_RESULT.json" if canonical else "BENCH_RESULT_smoke.json"
    repo = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(repo, name)
    try:
        with open(path, "w") as f:
            json.dump(artifact, f, indent=2)
            f.write("\n")
    except OSError as err:
        print(f"# could not write {name}: {err}", file=sys.stderr)
        return
    # only canonical-shape runs are evidence worth a commit
    if not commit or not canonical or os.environ.get("BENCH_NO_COMMIT"):
        return
    # a rebase/merge in flight means a HUMAN owns the index right now —
    # an automatic evidence commit would land mid-operation (ADVICE r4
    # #2); the artifact stays on disk for them to commit
    for marker in ("MERGE_HEAD", "rebase-merge", "rebase-apply", "CHERRY_PICK_HEAD"):
        if os.path.exists(os.path.join(repo, ".git", marker)):
            print(
                f"# skipping evidence commit: .git/{marker} present "
                "(rebase/merge in progress)",
                file=sys.stderr,
            )
            return
    msg = (
        f"bench evidence: {headline.get('backend')} p99 {headline.get('value')}ms"
    )
    try:
        for attempt in range(5):
            add = subprocess.run(
                ["git", "-C", repo, "add", "--", name],
                capture_output=True, text=True, timeout=30,
            )
            if add.returncode == 0:
                done = subprocess.run(
                    ["git", "-C", repo, "commit", "-m", msg, "--", name],
                    capture_output=True, text=True, timeout=30,
                )
                if done.returncode == 0:
                    print(f"# committed {name}", file=sys.stderr)
                    return
                err_txt = done.stderr.strip() or done.stdout.strip()
            else:
                err_txt = add.stderr.strip()
            # a busy index (sentinel/driver committing concurrently)
            # clears quickly; anything else will fail all 5 attempts
            print(
                f"# {name} commit attempt {attempt} failed: {err_txt[-200:]}",
                file=sys.stderr,
            )
            time.sleep(2.0)
    except Exception as err:  # evidence-commit is best-effort
        print(f"# {name} commit failed: {err}", file=sys.stderr)


def _secondary_configs() -> None:
    """BASELINE.json configs (1), (2), (3), (4) measured end-to-end
    through the extender harness on CPU (stderr diagnostics; the headline
    metric above is config (5))."""
    import logging

    import jax

    jax.config.update("jax_platforms", "cpu")

    h = None
    try:
        from k8s_spark_scheduler_tpu.testing.harness import Harness

        # synthetic old pods trip the slow-schedule warnings; keep the
        # diagnostics readable
        logging.disable(logging.WARNING)

        # (1) tightly-pack: 1 driver + 8 executors on a 32-node snapshot
        h = Harness(binpack_algo="tpu-batch", is_fifo=True)
        for i in range(32):
            h.new_node(f"n{i:02d}", cpu="16", memory="32Gi")
        nodes = [f"n{i:02d}" for i in range(32)]
        pods = Harness.static_allocation_spark_pods("warmup", 8)
        h.schedule(pods[0], nodes)
        t0 = time.perf_counter()
        pods = Harness.static_allocation_spark_pods("cfg1", 8)
        result = h.schedule(pods[0], nodes)
        assert result.node_names, result.failed_nodes
        cfg1_ms = (time.perf_counter() - t0) * 1000
        SECONDARY["config1_tightly_pack_e2e_ms"] = round(cfg1_ms, 1)
        print(f"# config1 tightly-pack 1+8@32nodes: {cfg1_ms:.1f}ms e2e", file=sys.stderr)

        # (2) FIFO queue of 128 static apps drained in order
        drivers = []
        base = time.time()
        for i in range(128):
            d = Harness.static_allocation_spark_pods(
                f"q{i:03d}", 2, creation_timestamp=base - 1000 + i
            )[0]
            h.create_pod(d)
            drivers.append(d)
        t0 = time.perf_counter()
        granted = sum(1 for d in drivers if h.schedule(d, nodes).node_names)
        cfg2_ms = (time.perf_counter() - t0) * 1000
        SECONDARY["config2_fifo128_ms_per_app"] = round(cfg2_ms / 128, 2)
        SECONDARY["config2_fifo128_granted"] = granted
        print(
            f"# config2 FIFO 128 apps: {cfg2_ms:.0f}ms total "
            f"({cfg2_ms / 128:.1f}ms/app, {granted} granted)",
            file=sys.stderr,
        )

        # (4) dynamic allocation with soft reservations
        da = Harness.dynamic_allocation_spark_pods("cfg4", 2, 8)
        t0 = time.perf_counter()
        result = h.schedule(da[0], nodes)
        assert result.node_names, result.failed_nodes
        for p in da[1:]:
            h.schedule(p, nodes)
        cfg4_ms = (time.perf_counter() - t0) * 1000
        SECONDARY["config4_da_e2e_ms"] = round(cfg4_ms, 1)
        sr, _ = h.server.soft_reservation_store.get_soft_reservation("cfg4")
        print(
            f"# config4 DA min2/max8: {cfg4_ms:.0f}ms for driver+8 executors, "
            f"{len(sr.reservations)} soft reservations",
            file=sys.stderr,
        )
        h.close()
        h = None

        # (3) heterogeneous multi-instance-group nodes with label-priority
        # sort (exercises the label-aware fast path)
        _config3(nodes_per_group=16)
    except Exception as err:  # diagnostics must never break the bench
        print(f"# secondary configs failed: {err}", file=sys.stderr)
    finally:
        try:
            if h is not None:
                h.close()
        except Exception:
            pass
        logging.disable(logging.NOTSET)


def _config5_e2e(force_cpu: bool = True) -> dict | None:
    """(5) end-to-end, the HEADLINE phase: the north-star snapshot
    through the REAL HTTP extender — N_NODES nodes, N_APPS pending FIFO
    drivers, Filter latency measured at the request level
    (server/http.py → serde → Predicate → tensor mirror → native/device
    queue lane; reference path resource.go:128-183 +
    cmd/endpoints.go:29-41).

    Sampling (VERDICT r4 #3): ≥200 timed probes drawn from the SAME
    1-32-executor / 1-8-cpu / 2-16Gi distribution as the queue.  After
    each sample the probe pod is deleted and its reservation collected
    (the app-finished flow), and the next probe waits for that settling
    — so every sample measures the identical steady-state 10k×1k
    problem instead of a growing queue.  Returns the lane stats dict
    (with `backend` = the queue lane that actually served) or None."""
    import json as _json
    import logging
    import urllib.request

    if force_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    probes = int(os.environ.get("BENCH_E2E_PROBES", "200"))
    http = scheduler = None
    try:
        from k8s_spark_scheduler_tpu.config import Install
        from k8s_spark_scheduler_tpu.kube.apiserver import APIServer
        from k8s_spark_scheduler_tpu.kube.crd import (
            DEMAND_CRD_NAME,
            demand_crd_spec,
        )
        from k8s_spark_scheduler_tpu.server.http import ExtenderHTTPServer
        from k8s_spark_scheduler_tpu.server.wiring import init_server_with_clients
        from k8s_spark_scheduler_tpu.testing.harness import Harness
        from k8s_spark_scheduler_tpu.types import serde
        from k8s_spark_scheduler_tpu.types.objects import Node, ObjectMeta
        from k8s_spark_scheduler_tpu.types.resources import ZONE_LABEL, Resources

        logging.disable(logging.WARNING)
        t_setup = time.perf_counter()
        api = APIServer()
        api.create_crd(DEMAND_CRD_NAME, demand_crd_spec())
        scheduler = init_server_with_clients(
            api, Install(binpack_algo="tpu-batch", fifo=True),
            demand_poll_interval=0.5,
        )
        rng = np.random.RandomState(5)
        names = []
        for i in range(N_NODES):
            name = f"n{i:05d}"
            names.append(name)
            api.create(
                Node(
                    meta=ObjectMeta(
                        name=name,
                        labels={
                            ZONE_LABEL: f"z{i % 3}",
                            "resource_channel": "batch-medium-priority",
                        },
                    ),
                    allocatable=Resources.of(
                        str(int(rng.randint(4, 96))),
                        f"{int(rng.randint(8, 256))}Gi",
                    ),
                )
            )
        base = time.time() - 10_000.0
        for i in range(N_APPS):
            d = Harness.static_allocation_spark_pods(
                f"queue-{i:04d}",
                int(rng.randint(1, 32)),
                executor_cpu=str(int(rng.randint(1, 8))),
                executor_mem=f"{int(rng.randint(2, 16))}Gi",
                creation_timestamp=base + i,
            )[0]
            api.create(d)
        http = ExtenderHTTPServer(scheduler, port=0)
        http.start()
        # the readiness condition a deployment gates traffic on: caches
        # synced AND solver warmup done (its compiler threads would
        # otherwise contend with the timed probes for the core)
        scheduler.wait_ready(timeout=600.0)
        setup_s = time.perf_counter() - t_setup

        def post_filter(pod):
            payload = {
                "Pod": serde.pod_to_dict(pod),
                "NodeNames": names,
            }
            req = urllib.request.Request(
                f"http://127.0.0.1:{http.port}/predicates",
                data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=120) as resp:
                body = _json.loads(resp.read())
            return (time.perf_counter() - t0) * 1000.0, body

        rr_cache = scheduler.resource_reservation_cache

        def retire_probe(pod, app_id):
            """The app-finished flow: delete the probe pod (owner GC
            collects its reservation — or the dangling-owner check does,
            if the async create lands later) and wait until the
            reservation cache has dropped the app, so the next sample
            sees the exact steady-state shape again."""
            api.delete("Pod", pod.namespace, pod.name)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if rr_cache.get(pod.namespace, app_id) is None:
                    return True
                time.sleep(0.002)
            return False

        def one_probe(i):
            d = Harness.static_allocation_spark_pods(
                f"probe-{i:04d}",
                int(rng.randint(1, 32)),
                executor_cpu=str(int(rng.randint(1, 8))),
                executor_mem=f"{int(rng.randint(2, 16))}Gi",
                creation_timestamp=base + N_APPS + i,
            )[0]
            pod = api.create(d)
            ms, body = post_filter(pod)
            ok = bool(body.get("NodeNames") or body.get("nodeNames"))
            settled = retire_probe(pod, pod.labels.get("spark-app-id", ""))
            return ms, ok, settled

        # warmups absorb compile / tensor-mirror build / cache priming
        warm_ms, _, _ = one_probe(0)
        one_probe(1)
        lat_ms = []
        granted = 0
        unsettled = 0
        for i in range(2, probes + 2):
            ms, ok, settled = one_probe(i)
            lat_ms.append(ms)
            granted += ok
            unsettled += not settled
        lat = np.array(lat_ms)
        p99 = float(np.percentile(lat, 99))
        stats = _lane_stats(lat, granted)
        stats["p95_ms"] = round(float(np.percentile(lat, 95)), 3)
        stats["setup_s"] = round(setup_s, 1)
        stats["warmup_ms"] = round(warm_ms, 1)
        stats["unsettled"] = unsettled
        solver = getattr(scheduler.extender.binpacker, "queue_solver", None)
        lane = getattr(solver, "last_queue_lane", None)
        stats["backend"] = {
            "native": "native-cpp", "native-minfrag": "native-cpp",
            "native-session": "native-cpp",
            "pallas": "pallas", "pallas-minfrag": "pallas",
            "xla": "xla-scan", "minfrag-xla": "xla-scan",
        }.get(lane, lane or "unknown")
        # delta-solve engine evidence for the steady-state phase: how
        # often the persistent session served warm, and how deep into
        # the queue the prefix cache resumed (contract-pinned by
        # tests/test_bench_contract.py)
        engine = getattr(scheduler.extender, "delta_engine", None)
        if engine is not None:
            es = engine.stats()
            stats["warm_hit_rate"] = round(float(es["warm_hit_rate"]), 4)
            stats["resume_depth_p50"] = es["resume_depth_p50"]
            stats["deltasolve_sessions"] = es["sessions"]
            stats["deltasolve_misses"] = es["misses"]
        # contention-observatory scrape: the critical-path decomposition
        # of the probes just measured (acceptance: named segments must
        # reconstruct the server-side request) plus the predicate lock's
        # wait/hold picture — one more lane in the durable artifact
        try:
            def get_json(path):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{http.port}{path}", timeout=30
                ) as resp:
                    return _json.loads(resp.read())

            cp = get_json("/debug/criticalpath")
            con = get_json("/debug/contention?lock=extender.predicate")
            seg = cp.get("segments", {})
            lane = {
                "window": cp.get("window", 0),
                "total_p99_ms": cp.get("totalMs", {}).get("p99", 0.0),
                "coverage_p50": cp.get("coverage", {}).get("p50", 0.0),
                "gate_queue_p99_ms": seg.get("gate-queue", {}).get("p99Ms", 0.0),
                "lock_wait_p99_ms": seg.get("lock-wait", {}).get("p99Ms", 0.0),
                "serde_p99_ms": seg.get("serde", {}).get("p99Ms", 0.0),
                "solve_p99_ms": seg.get("solve", {}).get("p99Ms", 0.0),
                "write_back_p99_ms": seg.get("write-back", {}).get("p99Ms", 0.0),
                "other_p99_ms": seg.get("other", {}).get("p99Ms", 0.0),
            }
            locks = {l["name"]: l for l in con.get("locks", [])}
            plock = locks.get("extender.predicate")
            if plock is not None:
                lane["lock_acquisitions"] = plock["acquisitions"]
                lane["lock_contended"] = plock["contended"]
                lane["lock_wait_ms_p95"] = plock["waitMs"]["p95"]
                lane["lock_hold_ms_p95"] = plock["holdMs"]["p95"]
                lane["lock_hold_ms_p99"] = plock["holdMs"]["p99"]
            LANES["contention http"] = lane
            stats["criticalpath_coverage_p50"] = lane["coverage_p50"]
            stats["criticalpath_dominant"] = max(
                cp.get("dominant", {}) or {"": 0},
                key=lambda k: cp["dominant"].get(k, 0),
            )
            print(
                f"# contention: coverage p50={lane['coverage_p50']} "
                f"solve p99={lane['solve_p99_ms']:.1f}ms "
                f"serde p99={lane['serde_p99_ms']:.1f}ms "
                f"write-back p99={lane['write_back_p99_ms']:.1f}ms "
                f"lock hold p95={lane.get('lock_hold_ms_p95', 0.0)}ms",
                file=sys.stderr,
            )
        except Exception as err:
            print(f"# contention scrape failed: {err}", file=sys.stderr)
        try:
            _concurrent_admission_measure(scheduler, api, names, base)
        except Exception as err:
            print(f"# concurrent-admission lane failed: {err}", file=sys.stderr)
        LANES["config5-e2e http"] = stats
        SECONDARY["config5_e2e_p99_ms"] = round(p99, 1)
        SECONDARY["config5_e2e_p50_ms"] = round(float(np.percentile(lat, 50)), 1)
        SECONDARY["config5_e2e_granted"] = granted
        print(
            f"# config5-e2e HTTP Filter {N_NODES}x{N_APPS}: "
            f"p99={p99:.1f}ms p95={stats['p95_ms']:.1f}ms "
            f"p50={np.percentile(lat, 50):.1f}ms n={len(lat_ms)} "
            f"granted={granted}/{len(lat_ms)} lane={stats['backend']} "
            f"unsettled={unsettled} warmup={warm_ms:.0f}ms "
            f"setup={setup_s:.0f}s",
            file=sys.stderr,
        )
        return stats
    except Exception as err:
        print(f"# config5-e2e failed: {err}", file=sys.stderr)
        return None
    finally:
        try:
            if http is not None:
                http.stop()
            if scheduler is not None:
                scheduler.stop()
        except Exception:
            pass
        logging.disable(logging.NOTSET)


def _concurrent_admission_measure(scheduler, api, names, base_ts) -> None:
    """(ISSUE 18) Concurrent admission throughput on the live e2e
    server: the same probe workload pushed through the serial extender
    and then through the speculate→FIFO-commit engine at 1/2/4/8 client
    threads, decisions/sec per lane, with byte-identity asserted every
    round (the engine's contract: commits ARE the serial extender in
    ticket order, so the decision stream never changes — only the
    wall-clock does).  The per-round commit_results record how the
    speculative verdicts fared (seq/memcmp hits vs conflicts vs serial
    declines) — the conflict rate is the operator's tuning signal.
    ``p99_ms`` (request latency at 8 clients, gate wait included) rides
    in the lane so tools/perf_regression.py band-gates it like every
    other lane."""
    import threading

    from k8s_spark_scheduler_tpu.concurrent import ConcurrentAdmissionEngine
    from k8s_spark_scheduler_tpu.config import ConcurrentConfig
    from k8s_spark_scheduler_tpu.testing.harness import Harness
    from k8s_spark_scheduler_tpu.types.extenderapi import ExtenderArgs

    probes = int(os.environ.get("BENCH_CONCURRENT_PROBES", "48"))
    if probes <= 0:
        return
    rr_cache = scheduler.resource_reservation_cache
    rng = np.random.RandomState(18)
    specs = [
        (
            f"cprobe-{i:04d}",
            int(rng.randint(1, 32)),
            str(int(rng.randint(1, 8))),
            f"{int(rng.randint(2, 16))}Gi",
        )
        for i in range(probes)
    ]

    def create_batch():
        pods = []
        for i, (app, execs, cpu, mem) in enumerate(specs):
            d = Harness.static_allocation_spark_pods(
                app,
                execs,
                executor_cpu=cpu,
                executor_mem=mem,
                creation_timestamp=base_ts + 50_000 + i,
            )[0]
            pods.append(api.create(d))
        return pods

    def retire_batch(pods):
        """The app-finished flow for the whole batch: every probe pod
        deleted and its reservation collected, so the next round sees
        the identical steady-state problem."""
        for pod in pods:
            try:
                api.delete("Pod", pod.namespace, pod.name)
            except Exception:
                pass
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(
                rr_cache.get(p.namespace, p.labels.get("spark-app-id", ""))
                is None
                for p in pods
            ):
                return
            time.sleep(0.005)

    def decision_of(pod, result):
        return (
            pod.name,
            tuple(result.node_names or ()),
            tuple(sorted((result.failed_nodes or {}).items())),
        )

    def serial_round():
        pods = create_batch()
        ext = scheduler.extender
        out = [None] * len(pods)
        lat = [0.0] * len(pods)
        t0 = time.perf_counter()
        for i, pod in enumerate(pods):
            t1 = time.perf_counter()
            res = ext.predicate(ExtenderArgs(pod=pod, node_names=names))
            lat[i] = (time.perf_counter() - t1) * 1000.0
            out[i] = decision_of(pod, res)
        wall = time.perf_counter() - t0
        retire_batch(pods)
        return out, wall, lat

    def concurrent_round(n_clients):
        engine = ConcurrentAdmissionEngine(
            scheduler.extender,
            ConcurrentConfig(enabled=True),
            metrics=scheduler.metrics,
        )
        pods = create_batch()
        # tickets preassigned in workload order: the FIFO commit order
        # is the serial order regardless of thread interleaving
        tickets = [engine.gate.ticket() for _ in pods]
        out = [None] * len(pods)
        lat = [0.0] * len(pods)
        errs = []

        def worker(idx):
            try:
                for j in range(idx, len(pods), n_clients):
                    t1 = time.perf_counter()
                    res = engine.predicate(
                        ExtenderArgs(pod=pods[j], node_names=names),
                        ticket=tickets[j],
                    )
                    lat[j] = (time.perf_counter() - t1) * 1000.0
                    out[j] = decision_of(pods[j], res)
            except BaseException as err:  # noqa: BLE001 - reraised below
                errs.append(err)

        workers = [
            threading.Thread(target=worker, args=(i,), daemon=True)
            for i in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in workers:
            t.start()
        for t in workers:
            t.join(600)
        wall = time.perf_counter() - t0
        retire_batch(pods)
        if errs:
            raise errs[0]
        return out, wall, lat, engine.stats()

    serial_dec, serial_wall, serial_lat = serial_round()
    serial_dps = probes / max(serial_wall, 1e-9)
    lane = {
        "probes": probes,
        "serial_dps": round(serial_dps, 1),
        "serial_wall_s": round(serial_wall, 3),
        # serial per-decision p50 is solve-dominated at this shape: the
        # acceptance comparison partner for the commit lock hold below
        "solve_p50_ms": round(float(np.percentile(np.array(serial_lat), 50)), 3),
        "clients": {},
        "identical": True,
    }
    for c in (1, 2, 4, 8):
        dec, wall, lat, stats = concurrent_round(c)
        identical = dec == serial_dec
        lane["identical"] = lane["identical"] and identical
        results = stats["commit_results"]
        arr = np.array(lat)
        lane["clients"][str(c)] = {
            "dps": round(probes / max(wall, 1e-9), 1),
            "wall_s": round(wall, 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
            "commit_results": results,
            "conflicts": sum(
                v
                for k, v in results.items()
                if k in ("conflict", "queue-drift", "skip-drift", "candidate-drift")
            ),
            "identical": identical,
        }
    eight = lane["clients"]["8"]
    lane["dps_8clients"] = eight["dps"]
    lane["speedup_8clients"] = round(eight["dps"] / max(serial_dps, 1e-9), 2)
    lane["p99_ms"] = eight["p99_ms"]
    # the commit critical section replaces solver tenure under the
    # predicate lock: its hold p95 must sit below the serial solve p50
    # (ISSUE 18 acceptance) — read from the lock's own timekeeper
    try:
        lane["lock_hold_ms_p95"] = scheduler.extender._predicate_lock.snapshot()[
            "holdMs"
        ]["p95"]
    except Exception:
        pass
    LANES["concurrent-admission cpu"] = lane
    SECONDARY["concurrent_admission_speedup_8"] = lane["speedup_8clients"]
    SECONDARY["concurrent_admission_identical"] = lane["identical"]
    print(
        f"# concurrent-admission {probes} probes: serial {serial_dps:.1f}/s, "
        + ", ".join(
            f"{c}cl {lane['clients'][c]['dps']:.1f}/s" for c in ("1", "2", "4", "8")
        )
        + f", speedup(8)={lane['speedup_8clients']}x "
        f"identical={lane['identical']} "
        f"conflicts(8)={eight['conflicts']}",
        file=sys.stderr,
    )


def _config3(nodes_per_group: int) -> None:
    from k8s_spark_scheduler_tpu.ops.nodesort import LabelPriorityOrder
    from k8s_spark_scheduler_tpu.testing.harness import Harness

    h = Harness(
        binpack_algo="tpu-batch",
        is_fifo=True,
        driver_prioritized_node_label=LabelPriorityOrder("pool", ["reserved", "spot"]),
        executor_prioritized_node_label=LabelPriorityOrder("pool", ["spot", "reserved"]),
    )
    try:
        nodes = []
        for g, (ig, pool) in enumerate(
            [("batch", "reserved"), ("batch", "spot"), ("ml", "reserved")]
        ):
            for i in range(nodes_per_group):
                name = f"g{g}-n{i:02d}"
                h.new_node(
                    name,
                    cpu="16",
                    memory="32Gi",
                    instance_group=ig,
                    labels={"pool": pool},
                )
                nodes.append(name)
        batch_nodes = [n for n in nodes if not n.startswith("g2-")]
        warm = Harness.static_allocation_spark_pods("warm3", 4, instance_group="batch")
        res = h.schedule(warm[0], batch_nodes)
        assert res.node_names, res.failed_nodes
        t0 = time.perf_counter()
        pods = Harness.static_allocation_spark_pods("cfg3", 8, instance_group="batch")
        result = h.schedule(pods[0], batch_nodes)
        assert result.node_names, result.failed_nodes
        cfg3_ms = (time.perf_counter() - t0) * 1000
        SECONDARY["config3_label_priority_e2e_ms"] = round(cfg3_ms, 1)
        print(
            f"# config3 heterogeneous 3-group label-priority: {cfg3_ms:.1f}ms e2e "
            f"(driver on {result.node_names[0]})",
            file=sys.stderr,
        )
    finally:
        h.close()


if __name__ == "__main__":
    if "--tpu-worker" in sys.argv:
        sys.exit(tpu_worker())
    main()
