#!/usr/bin/env python
"""Self-contained local demo: extender server + fake autoscaler + a
simulated kube-scheduler submitting Spark apps over HTTP.

    python examples/run-local-demo.py

Shows the full loop from SURVEY §1's diagram: Filter calls, gang
admission, reservation objects, a demand when capacity runs out, the
autoscaler fulfilling it, and the retried app landing on scaled nodes.
"""

import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# default to CPU so the demo never blocks on TPU-tunnel availability;
# set DEMO_TPU=1 to run the solver on the chip
if os.environ.get("DEMO_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")

import logging

logging.basicConfig(level=logging.WARNING)

from k8s_spark_scheduler_tpu.config import Install
from k8s_spark_scheduler_tpu.kube.apiserver import APIServer
from k8s_spark_scheduler_tpu.kube.crd import DEMAND_CRD_NAME, demand_crd_spec
from k8s_spark_scheduler_tpu.server.http import ExtenderHTTPServer
from k8s_spark_scheduler_tpu.server.wiring import init_server_with_clients
from k8s_spark_scheduler_tpu.testing.fake_autoscaler import FakeAutoscaler
from k8s_spark_scheduler_tpu.testing.harness import Harness
from k8s_spark_scheduler_tpu.types import serde
from k8s_spark_scheduler_tpu.types.objects import Node, ObjectMeta
from k8s_spark_scheduler_tpu.types.resources import Resources, ZONE_LABEL


def post(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predicates",
        data=json.dumps(payload).encode(),
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def main():
    api = APIServer()
    api.create_crd(DEMAND_CRD_NAME, demand_crd_spec())
    scheduler = init_server_with_clients(
        api, Install(fifo=True, binpack_algo="tpu-batch"), demand_poll_interval=0.05
    )
    scheduler.lazy_demand_informer.wait_ready(10)
    http = ExtenderHTTPServer(scheduler, port=0)
    http.start()
    print(f"extender on :{http.port} (binpack=tpu-batch, fifo=on)")

    for i in range(3):
        api.create(
            Node(
                meta=ObjectMeta(
                    name=f"node-{i}",
                    labels={ZONE_LABEL: "zone1", "resource_channel": "batch-medium-priority"},
                ),
                allocatable=Resources.of("8", "16Gi"),
            )
        )
    print("cluster: 3 nodes x 8cpu/16Gi")

    autoscaler = FakeAutoscaler(api, scheduler.lazy_demand_informer.informer())

    def submit(app_id, executors, driver_exists=False):
        pods = Harness.static_allocation_spark_pods(app_id, executors)
        if not driver_exists:
            api.create(pods[0])
        node_names = [n.name for n in api.list("Node")]
        result = post(http.port, {"Pod": serde.pod_to_dict(pods[0]), "NodeNames": node_names})
        if result.get("NodeNames"):
            driver_node = result["NodeNames"][0]
            bound = api.get("Pod", "default", pods[0].name)
            bound.node_name = driver_node
            bound.phase = "Running"
            api.update(bound)
            placed = [driver_node]
            for p in pods[1:]:
                api.create(p)
                r = post(http.port, {"Pod": serde.pod_to_dict(p), "NodeNames": node_names})
                if r.get("NodeNames"):
                    b = api.get("Pod", "default", p.name)
                    b.node_name = r["NodeNames"][0]
                    b.phase = "Running"
                    api.update(b)
                    placed.append(r["NodeNames"][0])
            print(f"  {app_id}: GANG ADMITTED driver@{driver_node}, executors@{placed[1:]}")
            return True
        reason = next(iter(result.get("FailedNodes", {"?": "?"}).values()))
        print(f"  {app_id}: rejected — {reason}")
        return False

    print("\n[1] small app (1 driver + 3 executors):")
    submit("etl-small", 3)

    print("\n[2] big app that does NOT fit (1 + 40):")
    ok = submit("ml-big", 40)
    if not ok:
        demands = api.list("Demand")
        print(f"  demand created: {demands[0].name if demands else 'none'} "
              f"(units: {[(u.count, u.resources.cpu.serialize()) for u in demands[0].spec.units] if demands else []})")

    deadline = time.time() + 10
    while time.time() < deadline and not autoscaler.fulfilled:
        time.sleep(0.05)
    scaled = [n.name for n in api.list("Node") if n.name.startswith("scaled-")]
    print(f"\n[3] fake autoscaler fulfilled the demand: +{len(scaled)} nodes")

    print("\n[4] kube-scheduler retries the big app (driver + all executors):")
    submit("ml-big", 40, driver_exists=True)
    scaled_used = {
        r.node
        for rr in api.list("ResourceReservation")
        if rr.name == "ml-big"
        for r in rr.spec.reservations.values()
        if r.node.startswith("scaled-")
    }
    print(f"  reservations on scaled nodes: {sorted(scaled_used) or 'none'}")

    rrs = api.list("ResourceReservation")
    print(f"\nreservation objects at the API server: {[rr.name for rr in rrs]}")
    snap = scheduler.metrics.snapshot()
    requests = {k: v for k, v in snap["counters"].items() if k.startswith("foundry.spark.scheduler.requests")}
    print(f"request counters: {json.dumps(requests, indent=2)[:400]}")

    http.stop()
    scheduler.stop()
    print("\ndemo complete")


if __name__ == "__main__":
    main()
