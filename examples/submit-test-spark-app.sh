#!/usr/bin/env bash
# Submit a fake Spark application (annotated pods) against the extender,
# mirroring the reference's examples/submit-test-spark-app.sh.
set -euo pipefail
APP_ID="${1:-test-app-$RANDOM}"
EXECUTORS="${2:-2}"
HOST="${3:-localhost:8080}"

driver_payload() {
cat <<JSON
{"Pod": {"metadata": {"name": "${APP_ID}-driver",
  "labels": {"spark-role": "driver", "spark-app-id": "${APP_ID}"},
  "annotations": {"spark-driver-cpu": "1", "spark-driver-mem": "1Gi",
                  "spark-executor-cpu": "1", "spark-executor-mem": "1Gi",
                  "spark-executor-count": "${EXECUTORS}"}},
 "spec": {"schedulerName": "spark-scheduler",
  "affinity": {"nodeAffinity": {"requiredDuringSchedulingIgnoredDuringExecution":
    {"nodeSelectorTerms": [{"matchExpressions":
      [{"key": "resource_channel", "operator": "In", "values": ["batch-medium-priority"]}]}]}}}}},
 "NodeNames": $(kubectl get nodes -o json | python3 -c 'import json,sys; print(json.dumps([n["metadata"]["name"] for n in json.load(sys.stdin)["items"]]))')}
JSON
}
curl -s -X POST "http://${HOST}/predicates" -d "$(driver_payload)"
