// A/B harness: cap_pass variants at 10k nodes, 3 dims nonzero.
//   v0: committed fused divpd (3 dims in one loop)
//   v1: dim-at-a-time reciprocal-multiply with exact int correction
//   v2: dim-at-a-time divpd
//   v3: fused reciprocal-multiply (r4's rejected shape, as control)
// Build: g++ -O3 -march=native -o /tmp/ab_cappass /tmp/ab_cappass.cpp
#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

constexpr int32_t kBig = 2147483647;

// v0: the committed shape
int64_t v0(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, double de0, double de1,
           double de2, int32_t k, int32_t* cap) {
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = k;
    c = std::min(c, static_cast<int32_t>(a0[i] / de0));
    c = std::min(c, static_cast<int32_t>(a1[i] / de1));
    c = std::min(c, static_cast<int32_t>(a2[i] / de2));
    c = exec_ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

// one dim of v1: cap[i] = min(cap[i], floor(a[i]/e)) for a[i] >= 0;
// negative a gives negative q -> min keeps it (clamped at the end).
// q = (int)(a * inv) may be off by 1 either way; correct with two
// integer multiply-compares (int64 to dodge overflow).
static inline void dim_pass_recip(const int32_t* a, int64_t nb, int32_t e,
                                  int32_t* cap) {
  const double inv = 1.0 / static_cast<double>(e);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += (static_cast<int64_t>(q + 1) * e <= a[i]);
    q -= (static_cast<int64_t>(q) * e > a[i]);
    cap[i] = std::min(cap[i], q);
  }
}

static inline void dim_pass_div(const int32_t* a, int64_t nb, double de,
                                int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i) {
    cap[i] = std::min(cap[i], static_cast<int32_t>(a[i] / de));
  }
}

int64_t v1(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, int32_t e0, int32_t e1,
           int32_t e2, int32_t k, int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i) cap[i] = k;
  dim_pass_recip(a0, nb, e0, cap);
  dim_pass_recip(a1, nb, e1, cap);
  dim_pass_recip(a2, nb, e2, cap);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = exec_ok[i] ? cap[i] : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int64_t v2(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, double de0, double de1,
           double de2, int32_t k, int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i) cap[i] = k;
  dim_pass_div(a0, nb, de0, cap);
  dim_pass_div(a1, nb, de1, cap);
  dim_pass_div(a2, nb, de2, cap);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = exec_ok[i] ? cap[i] : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int64_t v3(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, int32_t e0, int32_t e1,
           int32_t e2, int32_t k, int32_t* cap) {
  const double i0 = 1.0 / e0, i1 = 1.0 / e1, i2 = 1.0 / e2;
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q0 = static_cast<int32_t>(static_cast<double>(a0[i]) * i0);
    q0 += (static_cast<int64_t>(q0 + 1) * e0 <= a0[i]);
    q0 -= (static_cast<int64_t>(q0) * e0 > a0[i]);
    int32_t q1 = static_cast<int32_t>(static_cast<double>(a1[i]) * i1);
    q1 += (static_cast<int64_t>(q1 + 1) * e1 <= a1[i]);
    q1 -= (static_cast<int64_t>(q1) * e1 > a1[i]);
    int32_t q2 = static_cast<int32_t>(static_cast<double>(a2[i]) * i2);
    q2 += (static_cast<int64_t>(q2 + 1) * e2 <= a2[i]);
    q2 -= (static_cast<int64_t>(q2) * e2 > a2[i]);
    int32_t c = std::min(std::min(q0, q1), std::min(q2, k));
    c = exec_ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int main(int argc, char** argv) {
  const int64_t nb = argc > 1 ? atoll(argv[1]) : 10000;
  const int reps = argc > 2 ? atoi(argv[2]) : 2000;
  std::mt19937 rng(7);
  std::vector<int32_t> a0(nb), a1(nb), a2(nb), cap(nb), ref(nb);
  std::vector<uint8_t> ok(nb);
  for (int64_t i = 0; i < nb; ++i) {
    a0[i] = static_cast<int32_t>(rng() % 96000) - 2000;
    a1[i] = static_cast<int32_t>(rng() % (256u << 20)) - 4096;
    a2[i] = static_cast<int32_t>(rng() % 8000) - 1000;
    ok[i] = (rng() % 100) < 97;
  }
  const int32_t e0 = 4500, e1 = 9 << 20, e2 = 1000, k = 17;
  const double de0 = e0, de1 = e1, de2 = e2;

  // correctness: all variants must agree
  int64_t t0s = v0(a0.data(), a1.data(), a2.data(), ok.data(), nb, de0, de1,
                   de2, k, ref.data());
  int64_t t1s = v1(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1,
                   e2, k, cap.data());
  for (int64_t i = 0; i < nb; ++i)
    if (cap[i] != ref[i]) { printf("v1 MISMATCH at %lld\n", (long long)i); return 1; }
  int64_t t2s = v2(a0.data(), a1.data(), a2.data(), ok.data(), nb, de0, de1,
                   de2, k, cap.data());
  for (int64_t i = 0; i < nb; ++i)
    if (cap[i] != ref[i]) { printf("v2 MISMATCH at %lld\n", (long long)i); return 1; }
  int64_t t3s = v3(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1,
                   e2, k, cap.data());
  for (int64_t i = 0; i < nb; ++i)
    if (cap[i] != ref[i]) { printf("v3 MISMATCH at %lld\n", (long long)i); return 1; }
  if (t0s != t1s || t0s != t2s || t0s != t3s) { printf("total mismatch\n"); return 1; }

  auto bench = [&](const char* name, auto fn) {
    volatile int64_t sink = 0;
    auto t0 = std::chrono::steady_clock::now();
    for (int r = 0; r < reps; ++r) sink += fn();
    auto t1 = std::chrono::steady_clock::now();
    double us =
        std::chrono::duration<double, std::micro>(t1 - t0).count() / reps;
    printf("%s: %.2f us/pass (%lld)\n", name, us, (long long)sink);
  };
  bench("v0 fused-divpd   ", [&] {
    return v0(a0.data(), a1.data(), a2.data(), ok.data(), nb, de0, de1, de2,
              k, cap.data());
  });
  bench("v1 dim-recip     ", [&] {
    return v1(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, e2, k,
              cap.data());
  });
  bench("v2 dim-divpd     ", [&] {
    return v2(a0.data(), a1.data(), a2.data(), ok.data(), nb, de0, de1, de2,
              k, cap.data());
  });
  bench("v3 fused-recip   ", [&] {
    return v3(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, e2, k,
              cap.data());
  });
  return 0;
}
