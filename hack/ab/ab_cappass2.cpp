// Round 2: tighten the dim-at-a-time shape.
//   v1: 5 passes (init, 3 dims, finalize)   [winner of round 1]
//   v4: 3 passes (dim0 folds init, dim2 folds finalize)
//   v5: v4 with divpd instead of recip
#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

static inline void dim_pass_recip(const int32_t* a, int64_t nb, int32_t e,
                                  int32_t* cap) {
  const double inv = 1.0 / static_cast<double>(e);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += (static_cast<int64_t>(q + 1) * e <= a[i]);
    q -= (static_cast<int64_t>(q) * e > a[i]);
    cap[i] = std::min(cap[i], q);
  }
}

int64_t v1(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, int32_t e0, int32_t e1,
           int32_t e2, int32_t k, int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i) cap[i] = k;
  dim_pass_recip(a0, nb, e0, cap);
  dim_pass_recip(a1, nb, e1, cap);
  dim_pass_recip(a2, nb, e2, cap);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = exec_ok[i] ? cap[i] : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

static inline void dim_first_recip(const int32_t* a, int64_t nb, int32_t e,
                                   int32_t k, int32_t* cap) {
  const double inv = 1.0 / static_cast<double>(e);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += (static_cast<int64_t>(q + 1) * e <= a[i]);
    q -= (static_cast<int64_t>(q) * e > a[i]);
    cap[i] = std::min(k, q);
  }
}

static inline int64_t dim_last_recip(const int32_t* a, int64_t nb, int32_t e,
                                     const uint8_t* exec_ok, int32_t* cap) {
  const double inv = 1.0 / static_cast<double>(e);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += (static_cast<int64_t>(q + 1) * e <= a[i]);
    q -= (static_cast<int64_t>(q) * e > a[i]);
    int32_t c = std::min(cap[i], q);
    c = exec_ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int64_t v4(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, int32_t e0, int32_t e1,
           int32_t e2, int32_t k, int32_t* cap) {
  dim_first_recip(a0, nb, e0, k, cap);
  dim_pass_recip(a1, nb, e1, cap);
  return dim_last_recip(a2, nb, e2, exec_ok, cap);
}

static inline void dim_first_div(const int32_t* a, int64_t nb, double de,
                                 int32_t k, int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i)
    cap[i] = std::min(k, static_cast<int32_t>(a[i] / de));
}
static inline void dim_pass_div(const int32_t* a, int64_t nb, double de,
                                int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i)
    cap[i] = std::min(cap[i], static_cast<int32_t>(a[i] / de));
}
static inline int64_t dim_last_div(const int32_t* a, int64_t nb, double de,
                                   const uint8_t* exec_ok, int32_t* cap) {
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = std::min(cap[i], static_cast<int32_t>(a[i] / de));
    c = exec_ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}
int64_t v5(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* exec_ok, int64_t nb, double de0, double de1,
           double de2, int32_t k, int32_t* cap) {
  dim_first_div(a0, nb, de0, k, cap);
  dim_pass_div(a1, nb, de1, cap);
  return dim_last_div(a2, nb, de2, exec_ok, cap);
}

int main(int argc, char** argv) {
  const int64_t nb = argc > 1 ? atoll(argv[1]) : 10000;
  const int reps = argc > 2 ? atoi(argv[2]) : 3000;
  std::mt19937 rng(7);
  std::vector<int32_t> a0(nb), a1(nb), a2(nb), cap(nb), ref(nb);
  std::vector<uint8_t> ok(nb);
  for (int64_t i = 0; i < nb; ++i) {
    a0[i] = static_cast<int32_t>(rng() % 96000) - 2000;
    a1[i] = static_cast<int32_t>(rng() % (256u << 20)) - 4096;
    a2[i] = static_cast<int32_t>(rng() % 8000) - 1000;
    ok[i] = (rng() % 100) < 97;
  }
  const int32_t e0 = 4500, e1 = 9 << 20, e2 = 1000, k = 17;

  int64_t t1 = v1(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, e2, k, ref.data());
  int64_t t4 = v4(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, e2, k, cap.data());
  for (int64_t i = 0; i < nb; ++i) if (cap[i] != ref[i]) { printf("v4 MISMATCH\n"); return 1; }
  int64_t t5 = v5(a0.data(), a1.data(), a2.data(), ok.data(), nb, (double)e0, (double)e1, (double)e2, k, cap.data());
  for (int64_t i = 0; i < nb; ++i) if (cap[i] != ref[i]) { printf("v5 MISMATCH\n"); return 1; }
  if (t1 != t4 || t1 != t5) { printf("total mismatch\n"); return 1; }

  auto bench = [&](const char* name, auto fn) {
    volatile int64_t sink = 0;
    auto s = std::chrono::steady_clock::now();
    for (int r = 0; r < reps; ++r) sink += fn();
    auto e = std::chrono::steady_clock::now();
    printf("%s: %.2f us/pass (%lld)\n", name,
           std::chrono::duration<double, std::micro>(e - s).count() / reps,
           (long long)sink);
  };
  bench("v1 5-pass recip", [&] { return v1(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, e2, k, cap.data()); });
  bench("v4 3-pass recip", [&] { return v4(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, e2, k, cap.data()); });
  bench("v5 3-pass divpd", [&] { return v5(a0.data(), a1.data(), a2.data(), ok.data(), nb, (double)e0, (double)e1, (double)e2, k, cap.data()); });
  return 0;
}
