// Round 3: common-case fusion for 2 division dims + 1 zero dim.
//   vA: current shape: dim_first(a0), dim_next(a1), zero_mask(a2), finalize  (4 passes)
//   vB: dim_first(a0), fused[dim_next(a1) + zero_mask(a2) + exec_ok + clamp + total]  (2 passes)
//   vC: fully fused single pass (control; expect register-pressure loss)
#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

static inline void dim_first(const int32_t* a, int64_t nb, int32_t e,
                             int32_t init, int32_t* cap) {
  const int32_t d = std::max(e, 1);
  const double inv = 1.0 / static_cast<double>(d);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += ((static_cast<int64_t>(q) + 1) * d <= a[i]);
    q -= (static_cast<int64_t>(q) * d > a[i]);
    cap[i] = std::min(init, q);
  }
}
static inline void dim_next(const int32_t* a, int64_t nb, int32_t e, int32_t* cap) {
  const int32_t d = std::max(e, 1);
  const double inv = 1.0 / static_cast<double>(d);
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a[i]) * inv);
    q += ((static_cast<int64_t>(q) + 1) * d <= a[i]);
    q -= (static_cast<int64_t>(q) * d > a[i]);
    cap[i] = std::min(cap[i], q);
  }
}
static inline void zero_mask(const int32_t* a, int64_t nb, int32_t* cap) {
  for (int64_t i = 0; i < nb; ++i) cap[i] = a[i] >= 0 ? cap[i] : int32_t{-1};
}
static inline int64_t finalize(const uint8_t* ok, int64_t nb, int32_t* cap) {
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t c = ok[i] ? cap[i] : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int64_t vA(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* ok, int64_t nb, int32_t e0, int32_t e1, int32_t k,
           int32_t* cap) {
  dim_first(a0, nb, e0, k, cap);
  dim_next(a1, nb, e1, cap);
  zero_mask(a2, nb, cap);
  return finalize(ok, nb, cap);
}

int64_t vB(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* ok, int64_t nb, int32_t e0, int32_t e1, int32_t k,
           int32_t* cap) {
  dim_first(a0, nb, e0, k, cap);
  const int32_t d = std::max(e1, 1);
  const double inv = 1.0 / static_cast<double>(d);
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q = static_cast<int32_t>(static_cast<double>(a1[i]) * inv);
    q += ((static_cast<int64_t>(q) + 1) * d <= a1[i]);
    q -= (static_cast<int64_t>(q) * d > a1[i]);
    int32_t c = std::min(cap[i], q);
    c = a2[i] >= 0 ? c : int32_t{-1};
    c = ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int64_t vC(const int32_t* a0, const int32_t* a1, const int32_t* a2,
           const uint8_t* ok, int64_t nb, int32_t e0, int32_t e1, int32_t k,
           int32_t* cap) {
  const int32_t d0 = std::max(e0, 1), d1 = std::max(e1, 1);
  const double i0 = 1.0 / d0, i1 = 1.0 / d1;
  int64_t total = 0;
  for (int64_t i = 0; i < nb; ++i) {
    int32_t q0 = static_cast<int32_t>(static_cast<double>(a0[i]) * i0);
    q0 += ((static_cast<int64_t>(q0) + 1) * d0 <= a0[i]);
    q0 -= (static_cast<int64_t>(q0) * d0 > a0[i]);
    int32_t q1 = static_cast<int32_t>(static_cast<double>(a1[i]) * i1);
    q1 += ((static_cast<int64_t>(q1) + 1) * d1 <= a1[i]);
    q1 -= (static_cast<int64_t>(q1) * d1 > a1[i]);
    int32_t c = std::min(std::min(q0, q1), k);
    c = a2[i] >= 0 ? c : int32_t{-1};
    c = ok[i] ? c : 0;
    c = std::max(c, 0);
    cap[i] = c;
    total += c;
  }
  return total;
}

int main(int argc, char** argv) {
  const int64_t nb = argc > 1 ? atoll(argv[1]) : 10000;
  const int reps = argc > 2 ? atoi(argv[2]) : 4000;
  std::mt19937 rng(7);
  std::vector<int32_t> a0(nb), a1(nb), a2(nb), cap(nb), ref(nb);
  std::vector<uint8_t> ok(nb);
  for (int64_t i = 0; i < nb; ++i) {
    a0[i] = static_cast<int32_t>(rng() % 96000) - 2000;
    a1[i] = static_cast<int32_t>(rng() % (256u << 20)) - 4096;
    a2[i] = static_cast<int32_t>(rng() % 100) - 5;
    ok[i] = (rng() % 100) < 97;
  }
  const int32_t e0 = 4500, e1 = 9 << 20, k = 17;
  int64_t tA = vA(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, k, ref.data());
  int64_t tB = vB(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, k, cap.data());
  for (int64_t i = 0; i < nb; ++i) if (cap[i] != ref[i]) { printf("vB MISMATCH\n"); return 1; }
  int64_t tC = vC(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, k, cap.data());
  for (int64_t i = 0; i < nb; ++i) if (cap[i] != ref[i]) { printf("vC MISMATCH\n"); return 1; }
  if (tA != tB || tA != tC) { printf("total mismatch\n"); return 1; }
  auto bench = [&](const char* name, auto fn) {
    volatile int64_t sink = 0;
    auto s = std::chrono::steady_clock::now();
    for (int r = 0; r < reps; ++r) sink += fn();
    auto e = std::chrono::steady_clock::now();
    printf("%s: %.2f us/pass (%lld)\n", name,
           std::chrono::duration<double, std::micro>(e - s).count() / reps,
           (long long)sink);
  };
  bench("vA 4-pass        ", [&]{ return vA(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, k, cap.data()); });
  bench("vB 2-pass fused  ", [&]{ return vB(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, k, cap.data()); });
  bench("vC 1-pass fused  ", [&]{ return vC(a0.data(), a1.data(), a2.data(), ok.data(), nb, e0, e1, k, cap.data()); });
  return 0;
}
