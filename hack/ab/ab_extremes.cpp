#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <random>
#include <vector>
constexpr int32_t kBig = 2147483647;
struct MfExtremes { int32_t maxc = 0, min_ge = kBig, min_pos = kBig; };

MfExtremes v0(const std::vector<int32_t>& caps, int32_t k) {
  MfExtremes ext;
  for (const int32_t c : caps) {
    ext.maxc = std::max(ext.maxc, c);
    ext.min_ge = std::min(ext.min_ge, c >= k ? c : kBig);
    ext.min_pos = std::min(ext.min_pos, c > 0 ? c : kBig);
  }
  return ext;
}
MfExtremes v1(const std::vector<int32_t>& caps, int32_t k) {
  MfExtremes ext;
  const int32_t* p = caps.data();
  const int64_t n = caps.size();
  int32_t maxc = 0;
  for (int64_t i = 0; i < n; ++i) maxc = std::max(maxc, p[i]);
  int32_t mge = kBig;
  for (int64_t i = 0; i < n; ++i) mge = std::min(mge, p[i] >= k ? p[i] : kBig);
  int32_t mpos = kBig;
  for (int64_t i = 0; i < n; ++i) mpos = std::min(mpos, p[i] > 0 ? p[i] : kBig);
  ext.maxc = maxc; ext.min_ge = mge; ext.min_pos = mpos;
  return ext;
}
int main() {
  const int64_t nb = 10240;
  std::mt19937 rng(7);
  std::vector<int32_t> caps(nb);
  for (auto& c : caps) c = (int32_t)(rng() % 120) - 10;
  MfExtremes a = v0(caps, 17), b = v1(caps, 17);
  if (a.maxc != b.maxc || a.min_ge != b.min_ge || a.min_pos != b.min_pos) { printf("MISMATCH\n"); return 1; }
  for (int which = 0; which < 2; ++which) {
    volatile int64_t sink = 0;
    auto t0 = std::chrono::steady_clock::now();
    for (int r = 0; r < 20000; ++r) {
      MfExtremes e = which ? v1(caps, 17) : v0(caps, 17);
      sink += e.maxc + e.min_ge + e.min_pos;
    }
    auto t1 = std::chrono::steady_clock::now();
    printf("v%d: %.2f us/pass (%lld)\n", which,
           std::chrono::duration<double, std::micro>(t1 - t0).count() / 20000,
           (long long)sink);
  }
  return 0;
}
