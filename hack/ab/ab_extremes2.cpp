#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <random>
#include <vector>
constexpr int32_t kBig = 2147483647;
struct MfExtremes { int32_t maxc = 0, min_ge = kBig, min_pos = kBig; };

__attribute__((noinline)) int32_t pure_max(const int32_t* p, int64_t n) {
  int32_t m = 0;
  for (int64_t i = 0; i < n; ++i) m = std::max(m, p[i]);
  return m;
}
__attribute__((noinline)) int32_t pure_min(const int32_t* p, int64_t n) {
  int32_t m = kBig;
  for (int64_t i = 0; i < n; ++i) m = std::min(m, p[i]);
  return m;
}
// map+reduce: select into scratch (vectorizes), then pure min
MfExtremes v2(const std::vector<int32_t>& caps, int32_t k,
              std::vector<int32_t>& scratch) {
  MfExtremes ext;
  const int32_t* p = caps.data();
  const int64_t n = caps.size();
  ext.maxc = pure_max(p, n);
  int32_t* s = scratch.data();
  for (int64_t i = 0; i < n; ++i) s[i] = p[i] >= k ? p[i] : kBig;
  ext.min_ge = pure_min(s, n);
  for (int64_t i = 0; i < n; ++i) s[i] = p[i] > 0 ? p[i] : kBig;
  ext.min_pos = pure_min(s, n);
  return ext;
}
// omp simd reductions
MfExtremes v3(const std::vector<int32_t>& caps, int32_t k) {
  MfExtremes ext;
  const int32_t* p = caps.data();
  const int64_t n = caps.size();
  int32_t maxc = 0, mge = kBig, mpos = kBig;
  #pragma omp simd reduction(max:maxc) reduction(min:mge) reduction(min:mpos)
  for (int64_t i = 0; i < n; ++i) {
    maxc = std::max(maxc, p[i]);
    mge = std::min(mge, p[i] >= k ? p[i] : kBig);
    mpos = std::min(mpos, p[i] > 0 ? p[i] : kBig);
  }
  ext.maxc = maxc; ext.min_ge = mge; ext.min_pos = mpos;
  return ext;
}
MfExtremes v0(const std::vector<int32_t>& caps, int32_t k) {
  MfExtremes ext;
  for (const int32_t c : caps) {
    ext.maxc = std::max(ext.maxc, c);
    ext.min_ge = std::min(ext.min_ge, c >= k ? c : kBig);
    ext.min_pos = std::min(ext.min_pos, c > 0 ? c : kBig);
  }
  return ext;
}
int main() {
  const int64_t nb = 10240;
  std::mt19937 rng(7);
  std::vector<int32_t> caps(nb), scratch(nb);
  for (auto& c : caps) c = (int32_t)(rng() % 120) - 10;
  MfExtremes a = v0(caps, 17);
  MfExtremes b2 = v2(caps, 17, scratch);
  MfExtremes b3 = v3(caps, 17);
  for (auto* x : {&b2, &b3})
    if (a.maxc != x->maxc || a.min_ge != x->min_ge || a.min_pos != x->min_pos) { printf("MISMATCH\n"); return 1; }
  auto bench = [&](const char* nm, auto fn) {
    volatile int64_t sink = 0;
    auto t0 = std::chrono::steady_clock::now();
    for (int r = 0; r < 20000; ++r) { MfExtremes e = fn(); sink += e.maxc + e.min_ge + e.min_pos; }
    auto t1 = std::chrono::steady_clock::now();
    printf("%s: %.2f us/pass (%lld)\n", nm,
           std::chrono::duration<double, std::micro>(t1 - t0).count() / 20000,
           (long long)sink);
  };
  bench("v0 fused   ", [&]{ return v0(caps, 17); });
  bench("v2 map+red ", [&]{ return v2(caps, 17, scratch); });
  bench("v3 omp simd", [&]{ return v3(caps, 17); });
  return 0;
}
