// Instrumented copy of the min-frag queue loop: loads the real bench
// arrays, times each phase, counts attempt outcomes.
// Build: g++ -O3 -march=native -funroll-loops -fno-math-errno \
//   -fno-trapping-math -I/root/repo/native -DMF_HARNESS \
//   -o /tmp/mf_harness /tmp/mf_harness.cpp
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

// pull in the real implementation (anonymous-namespace helpers included)
#include "fifo_solver.cpp"

static std::vector<char> slurp(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) { perror(path); exit(1); }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::vector<char> v(n);
  if (fread(v.data(), 1, n, f) != (size_t)n) { perror("fread"); exit(1); }
  fclose(f);
  return v;
}

using Clock = std::chrono::steady_clock;
static double us(Clock::time_point a, Clock::time_point b) {
  return std::chrono::duration<double, std::micro>(b - a).count();
}

int main() {
  auto avail_raw = slurp("/tmp/mfdump/avail.bin");
  auto rank_raw = slurp("/tmp/mfdump/driver_rank.bin");
  auto eok_raw = slurp("/tmp/mfdump/exec_ok.bin");
  auto drv_raw = slurp("/tmp/mfdump/driver.bin");
  auto exe_raw = slurp("/tmp/mfdump/executor.bin");
  auto cnt_raw = slurp("/tmp/mfdump/count.bin");
  auto val_raw = slurp("/tmp/mfdump/app_valid.bin");
  const int64_t nb = rank_raw.size() / 4;
  const int64_t na = cnt_raw.size() / 4;
  printf("nb=%lld na=%lld\n", (long long)nb, (long long)na);
  const int32_t* driver_rank = (const int32_t*)rank_raw.data();
  const uint8_t* exec_ok = (const uint8_t*)eok_raw.data();
  const int32_t* drivers = (const int32_t*)drv_raw.data();
  const int32_t* executors = (const int32_t*)exe_raw.data();
  const int32_t* counts = (const int32_t*)cnt_raw.data();
  const uint8_t* app_valid = (const uint8_t*)val_raw.data();

  std::vector<uint8_t> feas(na);
  std::vector<int32_t> didx(na);

  // whole-solve baseline timing via the real entry points
  for (int what = 0; what < 2; ++what) {
    double best = 1e18;
    for (int rep = 0; rep < 8; ++rep) {
      std::vector<char> avail_copy = avail_raw;
      auto t0 = Clock::now();
      if (what == 0)
        fifo_solve_queue(nb, na, (int32_t*)avail_copy.data(), driver_rank,
                         exec_ok, drivers, executors, counts, app_valid, 0,
                         feas.data(), didx.data());
      else
        fifo_solve_queue_minfrag(nb, na, (int32_t*)avail_copy.data(),
                                 driver_rank, exec_ok, drivers, executors,
                                 counts, app_valid, feas.data(), didx.data());
      auto t1 = Clock::now();
      double ms = us(t0, t1) / 1000.0;
      if (ms < best) best = ms;
    }
    int fcount = 0;
    for (int64_t i = 0; i < na; ++i) fcount += feas[i];
    printf("%s: best %.1f ms (feasible %d)\n",
           what == 0 ? "tightly" : "minfrag", best, fcount);
  }

  // phase-instrumented replica of fifo_solve_queue_minfrag
  {
    std::vector<char> avail_copy = avail_raw;
    int32_t* avail_io = (int32_t*)avail_copy.data();
    std::vector<int32_t> cand;
    cand.reserve(nb);
    for (int64_t i = 0; i < nb; ++i)
      if (driver_rank[i] < kBig) cand.push_back((int32_t)i);
    std::sort(cand.begin(), cand.end(), [&](int32_t x, int32_t y) {
      return driver_rank[x] < driver_rank[y];
    });
    std::vector<int32_t> a0(nb), a1(nb), a2(nb);
    for (int64_t i = 0; i < nb; ++i) {
      a0[i] = avail_io[i * 3 + 0];
      a1[i] = avail_io[i * 3 + 1];
      a2[i] = avail_io[i * 3 + 2];
    }
    std::vector<int32_t> mf_caps(nb);
    MfScratch ws;
    MfSegs segs;
    double t_pass = 0, t_ext = 0, t_drv = 0, t_assign = 0, t_sub = 0;
    long n_instant = 0, n_drain = 0, n_subset_drain = 0;
    for (int64_t ai = 0; ai < na; ++ai) {
      const int32_t* d = drivers + ai * 3;
      const int32_t* e = executors + ai * 3;
      const int32_t k = counts[ai];
      if (!app_valid[ai]) continue;
      auto p0 = Clock::now();
      int64_t total = mf_cap_pass_all(a0.data(), a1.data(), a2.data(),
                                      exec_ok, nb, e, k, mf_caps.data());
      auto p1 = Clock::now();
      t_pass += us(p0, p1);
      int32_t dd = -1;
      if (total >= k) {
        for (int32_t i : cand) {
          int32_t a[3] = {a0[i], a1[i], a2[i]};
          if (a[0] < d[0] || a[1] < d[1] || a[2] < d[2]) continue;
          int32_t am[3];
          for (int j = 0; j < 3; ++j) am[j] = wrap_sub(a[j], d[j]);
          int32_t cwd = exec_ok[i] ? clamped_cap(am, e, k) : 0;
          if (total - std::clamp<int32_t>(mf_caps[i], 0, k) + cwd >= k) {
            dd = i;
            break;
          }
        }
      }
      auto p2 = Clock::now();
      t_drv += us(p1, p2);
      if (dd < 0) continue;
      if (exec_ok[dd]) {
        int32_t av[3];
        for (int j = 0; j < 3; ++j)
          av[j] = wrap_sub((j == 0 ? a0 : j == 1 ? a1 : a2)[dd], d[j]);
        mf_caps[dd] = mf_cap_one(av[0], av[1], av[2], e);
      }
      auto p3 = Clock::now();
      MfExtremes ext = mf_extremes(mf_caps, k, ws.copy);
      auto p4 = Clock::now();
      t_ext += us(p3, p4);
      // inline mf_assign with outcome counting
      segs.clear();
      bool placed = false;
      {
        const bool has_sent = ext.maxc == kMfSent;
        const bool attempt_subset = has_sent || k < ext.maxc;
        const int64_t target =
            has_sent ? (int64_t)kMfSent
                     : (attempt_subset ? (k + (int64_t)ext.maxc) / 2 : 0);
        const bool have_ge = ext.min_ge != kBig && ext.min_ge >= k;
        if (attempt_subset && have_ge && ext.min_ge < target) {
          ++n_instant;
        } else if (attempt_subset && ext.min_pos != kBig &&
                   ext.min_pos < target) {
          ++n_subset_drain;
        } else {
          ++n_drain;
        }
        placed = k > 0 && mf_assign(mf_caps, k, ext, ws, segs);
      }
      auto p5 = Clock::now();
      t_assign += us(p4, p5);
      bool dhe = false;
      if (placed) {
        for (const auto& seg : segs) {
          const int32_t i = seg.first;
          if (i == dd) dhe = true;
          a0[i] = wrap_sub(a0[i], e[0]);
          a1[i] = wrap_sub(a1[i], e[1]);
          a2[i] = wrap_sub(a2[i], e[2]);
        }
      }
      if (!dhe) {
        a0[dd] = wrap_sub(a0[dd], d[0]);
        a1[dd] = wrap_sub(a1[dd], d[1]);
        a2[dd] = wrap_sub(a2[dd], d[2]);
      }
      auto p6 = Clock::now();
      t_sub += us(p5, p6);
    }
    printf("phases (ms/queue): cap_pass=%.1f driver=%.1f extremes=%.1f "
           "assign=%.1f subtract=%.1f\n",
           t_pass / 1000, t_drv / 1000, t_ext / 1000, t_assign / 1000,
           t_sub / 1000);
    printf("attempts: instant=%ld subset_drain=%ld full=%ld\n", n_instant,
           n_subset_drain, n_drain);
  }
  return 0;
}
