#!/usr/bin/env bash
# Generate a CA + server certificate for the extender / conversion
# webhook (the apiserver only dials conversion webhooks over HTTPS with
# a trusted caBundle).  Analog of the reference's dev cert tooling,
# written for this framework's install shape:
#
#   hack/generate-certs.sh [OUTDIR] [SERVICE_NAME] [SERVICE_NAMESPACE]
#
# Produces in OUTDIR (default ./certs):
#   ca.crt ca.key      — the CA; base64 of ca.crt goes in the CRD's
#                        conversion clientConfig caBundle (or point the
#                        install's conversion-webhook.ca-bundle-file at
#                        ca.crt and the server does it for you)
#   server.crt server.key — serve with --tls-cert/--tls-key
#
# SANs cover the in-cluster service DNS names plus localhost for local
# runs.
set -euo pipefail

OUTDIR="${1:-certs}"
SERVICE="${2:-spark-scheduler}"
NAMESPACE="${3:-spark}"
DAYS="${DAYS:-3650}"

mkdir -p "$OUTDIR"
cd "$OUTDIR"

openssl genrsa -out ca.key 2048 >/dev/null 2>&1
openssl req -x509 -new -nodes -key ca.key -subj "/CN=${SERVICE}-ca" \
  -days "$DAYS" -out ca.crt

cat > server.conf <<EOF
[req]
distinguished_name = dn
req_extensions = ext
prompt = no
[dn]
CN = ${SERVICE}.${NAMESPACE}.svc
[ext]
subjectAltName = @alt_names
[alt_names]
DNS.1 = ${SERVICE}
DNS.2 = ${SERVICE}.${NAMESPACE}
DNS.3 = ${SERVICE}.${NAMESPACE}.svc
DNS.4 = ${SERVICE}.${NAMESPACE}.svc.cluster.local
DNS.5 = localhost
IP.1 = 127.0.0.1
EOF

openssl genrsa -out server.key 2048 >/dev/null 2>&1
openssl req -new -key server.key -out server.csr -config server.conf
openssl x509 -req -in server.csr -CA ca.crt -CAkey ca.key -CAcreateserial \
  -days "$DAYS" -extensions ext -extfile server.conf -out server.crt >/dev/null 2>&1
rm -f server.csr server.conf ca.srl

echo "wrote $OUTDIR/{ca.crt,ca.key,server.crt,server.key}"
echo "caBundle (for a hand-written CRD): $(openssl base64 -A < ca.crt | head -c 48)..."
