#!/usr/bin/env bash
# Local mirror of the CI lint job: schedlint (always — it ships with the
# package) plus ruff and mypy when installed (pip install -e ".[lint]").
# Exit nonzero on any finding so it can gate a pre-push hook.
set -uo pipefail

cd "$(dirname "$0")/.."
rc=0

echo "==> schedlint (python -m k8s_spark_scheduler_tpu.analysis --strict)"
python -m k8s_spark_scheduler_tpu.analysis --strict || rc=1

if command -v ruff >/dev/null 2>&1; then
    echo "==> ruff check"
    ruff check k8s_spark_scheduler_tpu || rc=1
else
    echo "==> ruff not installed — skipping (pip install -e '.[lint]')"
fi

if command -v mypy >/dev/null 2>&1; then
    echo "==> mypy"
    mypy || rc=1
else
    echo "==> mypy not installed — skipping (pip install -e '.[lint]')"
fi

exit $rc
