#!/usr/bin/env bash
# Local mirror of the CI lint job: schedlint (always — it ships with the
# package) plus ruff and mypy when installed (pip install -e ".[lint]").
# Exit nonzero on any finding so it can gate a pre-push hook.
set -uo pipefail

cd "$(dirname "$0")/.."
rc=0

echo "==> schedlint (python -m k8s_spark_scheduler_tpu.analysis --strict)"
python -m k8s_spark_scheduler_tpu.analysis --strict || rc=1

echo "==> schedlint native-boundary + lock-coverage audit (--select LK004,NA --strict)"
# redundant with the full run but named separately, mirroring CI: a
# Python↔C++ boundary regression should say so, not "lint failed"
python -m k8s_spark_scheduler_tpu.analysis --strict --select LK004,NA || rc=1

echo "==> schedlint protocol verifier (--select PC --strict: tickets, fencing, journal, spans, deadlines)"
# also covered by the full run; named so a typestate regression reads
# as "protocol discipline broken", not generic lint noise
python -m k8s_spark_scheduler_tpu.analysis --strict --select PC || rc=1

echo "==> schedlint suppression baseline (no new pragmas/allowlist entries)"
# zero findings is only meaningful if nothing new was silenced; a
# justified new suppression regenerates the baseline in the same PR
# (python tools/schedlint_diff.py --write-baseline)
python tools/schedlint_diff.py --diff-baseline || rc=1

echo "==> native build (native/*.cpp compile + load, incl. the delta-solve session)"
python - <<'PY' || rc=1
from k8s_spark_scheduler_tpu.native import native_available
from k8s_spark_scheduler_tpu.native.fifo import (
    native_fifo_available,
    native_session_available,
)

assert native_available(), "native/snapshot.cpp failed to build/load"
assert native_fifo_available(), "native/fifo_solver.cpp failed to build/load"
assert native_session_available(), "fifo session API missing from the built library"
print("native libraries build and load (session API present)")
PY

if command -v ruff >/dev/null 2>&1; then
    echo "==> ruff check"
    ruff check k8s_spark_scheduler_tpu || rc=1
else
    echo "==> ruff not installed — skipping (pip install -e '.[lint]')"
fi

if command -v mypy >/dev/null 2>&1; then
    echo "==> mypy"
    mypy || rc=1
else
    echo "==> mypy not installed — skipping (pip install -e '.[lint]')"
fi

exit $rc
