#!/usr/bin/env bash
# Rebuild the extender image and restart the running deployment, then
# tail the new pod's logs — the edit/compile/run loop for dev clusters
# (analog of the reference's pod-restart reload script, but through a
# rollout so the HA pair restarts cleanly one replica at a time).
set -euo pipefail

SCRIPT_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
NAMESPACE=kube-system
NAME=tpu-gang-scheduler

if [ "${SKIP_BUILD:-}" != "1" ]; then
  eval "$(minikube docker-env)"
  docker build -t "${NAME}:latest" -f "${SCRIPT_ROOT}/docker/Dockerfile" "${SCRIPT_ROOT}"
fi

kubectl -n "${NAMESPACE}" rollout restart "deploy/${NAME}"
kubectl -n "${NAMESPACE}" rollout status "deploy/${NAME}" --timeout=180s

# logs via the deployment so we always follow a CURRENT replica — a
# pod selected by phase=Running right after rollout can still be the
# terminating old one
echo "tailing logs (ctrl-c to stop)"
exec kubectl -n "${NAMESPACE}" logs -f "deploy/${NAME}"
