#!/usr/bin/env bash
# Build, deploy and run the extender on a running minikube cluster —
# the dev-cluster e2e loop (analog of the reference's minikube tooling,
# adapted to this framework's image/manifest shape).
#
#   minikube start            # once
#   hack/run-in-minikube.sh   # build image in-cluster, certs, deploy
#
# Afterwards:
#   examples/submit-test-spark-app.sh   # submit an annotated test app
#   hack/live-reload.sh                 # rebuild + restart + tail logs
set -euo pipefail

SCRIPT_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
NAMESPACE=kube-system
NAME=tpu-gang-scheduler
CERT_DIR="${SCRIPT_ROOT}/out/certs"

# 1. build the image inside minikube's docker daemon so the deployment
#    can pull it without a registry (imagePullPolicy: IfNotPresent)
eval "$(minikube docker-env)"
docker build -t "${NAME}:latest" -f "${SCRIPT_ROOT}/docker/Dockerfile" "${SCRIPT_ROOT}"

# 2. TLS: the apiserver only dials the CRD conversion webhook over HTTPS
#    with a trusted caBundle; the kube-scheduler extender config also
#    talks HTTPS
"${SCRIPT_ROOT}/hack/generate-certs.sh" "${CERT_DIR}" "${NAME}" "${NAMESPACE}"

kubectl -n "${NAMESPACE}" delete secret "${NAME}-tls" --ignore-not-found
kubectl -n "${NAMESPACE}" create secret tls "${NAME}-tls" \
  --cert="${CERT_DIR}/server.crt" --key="${CERT_DIR}/server.key"

# 3. install config: ship examples/install.json (the file the
#    deployment's --config points at) plus the CA so the server can
#    stamp the conversion webhook's caBundle itself
kubectl -n "${NAMESPACE}" delete configmap "${NAME}-config" --ignore-not-found
kubectl -n "${NAMESPACE}" create configmap "${NAME}-config" \
  --from-file=install.json="${SCRIPT_ROOT}/examples/install.json" \
  --from-file=ca.crt="${CERT_DIR}/ca.crt"

# 4. RBAC + service + deployment, then the spark-scheduler
#    kube-scheduler pair that calls the extender for Filter
kubectl apply -f "${SCRIPT_ROOT}/examples/extender-deployment.yaml"
kubectl -n "${NAMESPACE}" rollout status "deploy/${NAME}" --timeout=180s
kubectl apply -f "${SCRIPT_ROOT}/examples/spark-kube-scheduler.yaml"
kubectl -n "${NAMESPACE}" rollout status deploy/spark-kube-scheduler --timeout=180s

echo
echo "extender is up:"
kubectl -n "${NAMESPACE}" get pods -l app="${NAME}"
echo
echo "next: examples/submit-test-spark-app.sh to drive a gang decision,"
echo "      hack/live-reload.sh after code changes"
