#!/usr/bin/env bash
# Native sanitizer lanes: build native/tests/concurrency_smoke.cpp
# together with the two extension translation units under TSan or
# ASan+UBSan and run it.  CI's tsan-native / asan-ubsan-native jobs call
# this; run it locally before touching native/*.cpp.
#
#   hack/sanitize.sh tsan   # -fsanitize=thread (SweepPool / session churn)
#   hack/sanitize.sh asan   # -fsanitize=address,undefined (full API walk)
#   hack/sanitize.sh tidy   # clang-tidy bugprone-*/concurrency-* static pass
#   hack/sanitize.sh        # all of the above
#
# Suppressions live in native/tests/tsan.supp (dynamic lanes) and
# native/tests/clang-tidy.supp (static lane) — both empty by policy
# unless every entry is justified (see the headers there).  The tidy
# lane is skipped with a notice when clang-tidy is not installed, so
# `hack/sanitize.sh` stays runnable on a bare toolchain; CI installs it.
set -euo pipefail

cd "$(dirname "$0")/.."
mkdir -p native/_build

SRCS="native/fifo_solver.cpp native/snapshot.cpp native/tests/concurrency_smoke.cpp"
# -O1: enough to exercise the vectorized loops without optimizing the
# races away; frame pointers keep sanitizer stacks readable
COMMON="-std=c++17 -O1 -g -fno-omit-frame-pointer -pthread"

run_tsan() {
    echo "==> tsan build"
    g++ $COMMON -fsanitize=thread $SRCS -o native/_build/smoke_tsan
    echo "==> tsan run (SweepPool + session churn under -fsanitize=thread)"
    TSAN_OPTIONS="suppressions=native/tests/tsan.supp halt_on_error=1 exitcode=66" \
        ./native/_build/smoke_tsan
}

run_asan() {
    echo "==> asan+ubsan build"
    g++ $COMMON -fsanitize=address,undefined -fno-sanitize-recover=undefined \
        $SRCS -o native/_build/smoke_asan
    echo "==> asan+ubsan run (full native API walk)"
    ASAN_OPTIONS="detect_leaks=1" ./native/_build/smoke_asan
}

run_tidy() {
    if ! command -v clang-tidy >/dev/null 2>&1; then
        echo "==> clang-tidy not installed — skipping static lane (apt install clang-tidy)"
        return 0
    fi
    echo "==> clang-tidy (bugprone-*, concurrency-* over native/*.cpp)"
    # no compile_commands.json in this build (the extension is compiled
    # ad hoc by the ctypes loader), so pass the flags after --
    local out rc=0
    out="$(clang-tidy --quiet $SRCS -- $COMMON 2>/dev/null)" || rc=$?
    # filter diagnostics through the justified-suppression file; any
    # remaining warning fails the lane
    local remaining
    remaining="$(printf '%s\n' "$out" | grep -E 'warning:|error:' | \
        grep -v -F -f <(grep -vE '^\s*(#|$)' native/tests/clang-tidy.supp; echo '\x01never-matches') \
        || true)"
    if [ -n "$remaining" ]; then
        printf '%s\n' "$out"
        echo "clang-tidy: unsuppressed diagnostics (justify in native/tests/clang-tidy.supp or fix):" >&2
        printf '%s\n' "$remaining" >&2
        return 1
    fi
    echo "clang-tidy: clean"
}

case "${1:-all}" in
    tsan) run_tsan ;;
    asan) run_asan ;;
    tidy) run_tidy ;;
    all)  run_tsan; run_asan; run_tidy ;;
    *) echo "usage: hack/sanitize.sh [tsan|asan|tidy|all]" >&2; exit 2 ;;
esac
echo "sanitize: clean"
