#!/usr/bin/env bash
# Native sanitizer lanes: build native/tests/concurrency_smoke.cpp
# together with the two extension translation units under TSan or
# ASan+UBSan and run it.  CI's tsan-native / asan-ubsan-native jobs call
# this; run it locally before touching native/*.cpp.
#
#   hack/sanitize.sh tsan   # -fsanitize=thread (SweepPool / session churn)
#   hack/sanitize.sh asan   # -fsanitize=address,undefined (full API walk)
#   hack/sanitize.sh        # both
#
# Suppressions live in native/tests/tsan.supp — empty by policy unless
# every entry is justified (see the header there).
set -euo pipefail

cd "$(dirname "$0")/.."
mkdir -p native/_build

SRCS="native/fifo_solver.cpp native/snapshot.cpp native/tests/concurrency_smoke.cpp"
# -O1: enough to exercise the vectorized loops without optimizing the
# races away; frame pointers keep sanitizer stacks readable
COMMON="-std=c++17 -O1 -g -fno-omit-frame-pointer -pthread"

run_tsan() {
    echo "==> tsan build"
    g++ $COMMON -fsanitize=thread $SRCS -o native/_build/smoke_tsan
    echo "==> tsan run (SweepPool + session churn under -fsanitize=thread)"
    TSAN_OPTIONS="suppressions=native/tests/tsan.supp halt_on_error=1 exitcode=66" \
        ./native/_build/smoke_tsan
}

run_asan() {
    echo "==> asan+ubsan build"
    g++ $COMMON -fsanitize=address,undefined -fno-sanitize-recover=undefined \
        $SRCS -o native/_build/smoke_asan
    echo "==> asan+ubsan run (full native API walk)"
    ASAN_OPTIONS="detect_leaks=1" ./native/_build/smoke_asan
}

case "${1:-all}" in
    tsan) run_tsan ;;
    asan) run_asan ;;
    all)  run_tsan; run_asan ;;
    *) echo "usage: hack/sanitize.sh [tsan|asan|all]" >&2; exit 2 ;;
esac
echo "sanitize: clean"
