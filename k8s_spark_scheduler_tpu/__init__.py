"""tpu-gang-scheduler: a TPU-native gang-scheduling framework.

A ground-up rebuild of the capabilities of palantir/k8s-spark-scheduler
(reference mounted read-only at /root/reference): a Kubernetes scheduler
extender that admits a Spark driver only when the whole application
(driver + executors) fits, with reservation objects, FIFO ordering,
dynamic-allocation soft reservations, autoscaler demand signaling, and
failover reconciliation.  The packing math runs as a JAX/XLA batch solver
with the node axis sharded over the TPU mesh (`binpack: tpu-batch`),
validated decision-for-decision against exact CPU oracles.
"""

__version__ = "0.1.0"
