"""schedlint: project-specific static analysis + runtime race detection.

The scheduler's correctness rests on three families of invariants that
ordinary linters cannot see:

- **TS/DT determinism** — every *semantic* clock read goes through
  :mod:`..timesource` (the simulator swaps in a virtual clock), and
  every random stream is explicitly seeded.  A stray ``time.time()`` or
  unseeded ``random.random()`` silently breaks sim reproducibility.
- **LK lock discipline** — the mutable state behind the extender lock
  (write-back stores, soft reservations, resilience components) is
  declared with :func:`guarded_by`; mutations outside the declared
  ``with lock:`` scope are flagged at lint time and observed at runtime
  by the lockset race detector (:mod:`.racecheck`).
- **JX tracer safety** — the ``ops/`` JAX kernels must not branch on
  traced values, concretize tracers, or close over mutable state: each
  of those is a silent-retrace (or outright crash) hazard on the
  binpack hot path.
- **PC protocol discipline** — flow-sensitive typestate over a real CFG
  (:mod:`.flow`): commit-gate tickets retire on *every* path including
  exceptional ones, kube mutations are dominated by a fencing check
  from their entry points, journal intents are never acked before their
  execute, spans/locks close path-completely, and the extender's phase
  ladder re-arms its deadline at each boundary (:mod:`.rules_protocol`).

Run it::

    python -m k8s_spark_scheduler_tpu.analysis --strict

Suppressions are inline pragmas with a mandatory justification in
strict mode::

    deadline = time.monotonic() + t  # schedlint: disable=TS002 -- bounded infra wait, must not freeze with the sim clock

See docs/development.md for the rule catalogue.
"""

from __future__ import annotations

from .core import (
    DEFAULT_ALLOWLIST,
    AnalysisConfig,
    AnalysisResult,
    Finding,
    SuppressedFinding,
    analyze_package,
    analyze_paths,
    analyze_paths_detailed,
    load_allowlist,
    package_root,
)
from .guarded import guarded_by, guarded_fields
from .reporters import render_json, render_text

__all__ = [
    "AnalysisConfig",
    "AnalysisResult",
    "DEFAULT_ALLOWLIST",
    "Finding",
    "SuppressedFinding",
    "analyze_package",
    "analyze_paths",
    "analyze_paths_detailed",
    "guarded_by",
    "guarded_fields",
    "load_allowlist",
    "package_root",
    "render_json",
    "render_text",
]
