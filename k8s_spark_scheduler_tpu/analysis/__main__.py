"""CLI: ``python -m k8s_spark_scheduler_tpu.analysis [--strict] [paths]``.

Exit codes: 0 clean, 1 findings, 2 usage/config error (including a
``--select`` token that matches no known rule family — a typo must not
silently select nothing and report "clean").
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List, Optional, Sequence, Tuple

from .core import (
    AnalysisConfig,
    analyze_paths_detailed,
    load_allowlist,
    package_root,
)
from .reporters import render_json, render_text

# The rule registry, grouped by family prefix.  ``--select`` tokens are
# validated against this: every token must be a prefix of at least one
# rule id listed here, so adding a rule means adding it to its family
# (test_cli_list_rules_covers_all_families enforces the catalogue stays
# in sync with the implemented rule set).
RULE_FAMILIES: Tuple[Tuple[str, str, Tuple[Tuple[str, str], ...]], ...] = (
    (
        "TS",
        "determinism / time",
        (
            ("TS001", "direct time.time() — semantic timestamps must use timesource.now()"),
            ("TS002", "direct time.monotonic() — infra-only (allowlist or justified pragma)"),
            ("TS003", "datetime.now()/utcnow()/today() bypasses the timesource"),
        ),
    ),
    (
        "DT",
        "determinism / randomness",
        (
            ("DT001", "unseeded randomness (global random.* or random.Random())"),
            ("DT002", "legacy NumPy global RNG (numpy.random.*)"),
        ),
    ),
    (
        "LK",
        "locking",
        (
            ("LK001", "mutation of a @guarded_by attribute outside 'with self.<lock>:'"),
            ("LK002", "bare .acquire() without try/finally release"),
            ("LK003", "@guarded_by declaration whose lock attr is never assigned in __init__"),
            ("LK004", "threading.Lock attribute + mutating methods but no @guarded_by"),
        ),
    ),
    (
        "NA",
        "native boundary (Python<->C++ via ctypes)",
        (
            ("NA001", "native call while holding a @guarded_by lock (not on the GIL-safe list)"),
            ("NA002", "raw native ._handle referenced outside the native/ binding package"),
        ),
    ),
    (
        "JX",
        "tracer-safety (JAX kernels)",
        (
            ("JX001", "Python if/while on a traced value inside a jitted function"),
            ("JX002", "bool()/int()/float()/.item() concretizes a traced value under jit"),
            ("JX003", "jitted function closes over mutable module state or self attributes"),
            ("JX004", "unhashable static argument (mutable default or literal at call site)"),
        ),
    ),
    (
        "PC",
        "protocol (flow-sensitive typestate over the CFG)",
        (
            ("PC001", "CommitGate ticket can leak: a path reaches an exit without retire"),
            ("PC002", "double retire: a retire may run on an already-retired ticket"),
            ("PC003", "kube-mutating call not dominated by a FencedWriter.check from its entry point"),
            ("PC004", "journal intent acked on a path where the execute may not have happened"),
            ("PC005", "manually opened span/lock not closed on every path"),
            ("PC006", "phase boundary crossed without re-arming the deadline check"),
        ),
    ),
    (
        "PR",
        "pragma hygiene",
        (
            ("PR000", "file does not parse"),
            ("PR001", "(--strict) pragma without a '-- justification'"),
        ),
    ),
)

ALL_RULE_IDS: Tuple[str, ...] = tuple(
    rule_id for _, _, rules in RULE_FAMILIES for rule_id, _ in rules
)


def render_rule_catalogue() -> str:
    lines = ["schedlint rules (see docs/development.md for worked examples):"]
    for family, title, rules in RULE_FAMILIES:
        lines.append("")
        lines.append(f"{family}  {title}")
        for rule_id, desc in rules:
            lines.append(f"  {rule_id}  {desc}")
    return "\n".join(lines) + "\n"


def validate_select(tokens: Sequence[str]) -> List[str]:
    """Return the select tokens that match no known rule id prefix."""
    return [t for t in tokens if not any(r.startswith(t) for r in ALL_RULE_IDS)]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_spark_scheduler_tpu.analysis",
        description="schedlint: determinism, lock-discipline, protocol "
        "and JAX tracer-safety analysis for the gang scheduler",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to analyze (default: the installed package)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="require a justification on every pragma (PR001)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule-id prefixes to run (e.g. TS,DT or LK001); "
        "unknown prefixes are an error (exit 2), not an empty selection",
    )
    parser.add_argument(
        "--allowlist", default=None, metavar="FILE",
        help="JSON allowlist merged over the built-in one",
    )
    parser.add_argument(
        "--no-default-allowlist", action="store_true",
        help="ignore the built-in allowlist (audit mode)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue grouped by family",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(render_rule_catalogue(), end="")
        return 0

    select: Optional[Tuple[str, ...]] = None
    if args.select:
        select = tuple(s.strip() for s in args.select.split(",") if s.strip())
        unknown = validate_select(select)
        if unknown:
            known = ", ".join(family for family, _, _ in RULE_FAMILIES)
            print(
                "schedlint: unknown rule selector(s): "
                f"{', '.join(unknown)} (known families: {known}; "
                "see --list-rules)",
                file=sys.stderr,
            )
            return 2

    extra_allowlist = {}
    if args.allowlist:
        try:
            extra_allowlist = load_allowlist(args.allowlist)
        except (OSError, ValueError) as exc:
            print(f"schedlint: bad allowlist: {exc}", file=sys.stderr)
            return 2

    config = AnalysisConfig(
        select=select,
        allowlist=extra_allowlist,
        use_default_allowlist=not args.no_default_allowlist,
        strict=args.strict,
    )
    root = package_root()
    paths = args.paths or [root]
    result = analyze_paths_detailed(paths, config=config, root=root)

    if args.fmt == "json":
        sys.stdout.write(
            render_json(
                result.findings, strict=args.strict, suppressed=result.suppressed
            )
        )
    else:
        sys.stdout.write(render_text(result.findings))
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
