"""CLI: ``python -m k8s_spark_scheduler_tpu.analysis [--strict] [paths]``.

Exit codes: 0 clean, 1 findings, 2 usage/config error.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .core import (
    AnalysisConfig,
    analyze_paths,
    load_allowlist,
    package_root,
)
from .reporters import render_json, render_text

_RULE_CATALOGUE = """\
schedlint rules (see docs/development.md for worked examples):

determinism
  TS001  direct time.time() — semantic timestamps must use timesource.now()
  TS002  direct time.monotonic() — infra-only (allowlist or justified pragma)
  TS003  datetime.now()/utcnow()/today() bypasses the timesource
  DT001  unseeded randomness (global random.* or random.Random())
  DT002  legacy NumPy global RNG (numpy.random.*)

locking
  LK001  mutation of a @guarded_by attribute outside 'with self.<lock>:'
  LK002  bare .acquire() without try/finally release
  LK003  @guarded_by declaration whose lock attr is never assigned in __init__
  LK004  threading.Lock attribute + mutating methods but no @guarded_by

native boundary (Python↔C++ via ctypes)
  NA001  native call while holding a @guarded_by lock (not on the GIL-safe list)
  NA002  raw native ._handle referenced outside the native/ binding package

tracer-safety (JAX kernels)
  JX001  Python if/while on a traced value inside a jitted function
  JX002  bool()/int()/float()/.item() concretizes a traced value under jit
  JX003  jitted function closes over mutable module state or self attributes
  JX004  unhashable static argument (mutable default or literal at call site)

pragma
  PR000  file does not parse
  PR001  (--strict) pragma without a '-- justification'
"""


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_spark_scheduler_tpu.analysis",
        description="schedlint: determinism, lock-discipline and JAX "
        "tracer-safety analysis for the gang scheduler",
    )
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories to analyze (default: the installed package)",
    )
    parser.add_argument(
        "--strict", action="store_true",
        help="require a justification on every pragma (PR001)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt",
    )
    parser.add_argument(
        "--select", default=None,
        help="comma-separated rule-id prefixes to run (e.g. TS,DT or LK001)",
    )
    parser.add_argument(
        "--allowlist", default=None, metavar="FILE",
        help="JSON allowlist merged over the built-in one",
    )
    parser.add_argument(
        "--no-default-allowlist", action="store_true",
        help="ignore the built-in allowlist (audit mode)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_RULE_CATALOGUE, end="")
        return 0

    extra_allowlist = {}
    if args.allowlist:
        try:
            extra_allowlist = load_allowlist(args.allowlist)
        except (OSError, ValueError) as exc:
            print(f"schedlint: bad allowlist: {exc}", file=sys.stderr)
            return 2

    config = AnalysisConfig(
        select=tuple(s.strip() for s in args.select.split(",")) if args.select else None,
        allowlist=extra_allowlist,
        use_default_allowlist=not args.no_default_allowlist,
        strict=args.strict,
    )
    root = package_root()
    paths = args.paths or [root]
    findings = analyze_paths(paths, config=config, root=root)

    if args.fmt == "json":
        sys.stdout.write(render_json(findings, strict=args.strict))
    else:
        sys.stdout.write(render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
