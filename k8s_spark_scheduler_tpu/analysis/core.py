"""schedlint core: findings, pragmas, allowlists, and the analysis driver.

A *finding* is one rule violation at one source location.  Suppression
is layered:

1. **inline pragma** — ``# schedlint: disable=TS002 -- justification``
   on the offending line (or alone on the line directly above it).
   Multiple rules separate with commas; ``disable=all`` suppresses every
   rule on that line.  In ``--strict`` mode a pragma *must* carry a
   justification after ``--``; a bare pragma is itself a finding
   (``PR001``), so nothing is ever silenced without a recorded reason.
2. **per-rule allowlist** — a mapping of rule id → package-relative
   path prefixes where the rule does not apply (e.g. ``TS002`` in
   ``testing/``: harness deadlines intentionally read the real
   monotonic clock).  The built-in allowlist is
   :data:`DEFAULT_ALLOWLIST`; ``--allowlist file.json`` merges a
   user-supplied one on top, and each entry carries a ``why`` string so
   the exemption is as justified as a pragma.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PACKAGE_NAME = "k8s_spark_scheduler_tpu"

_PRAGMA_RE = re.compile(
    r"#\s*schedlint:\s*disable=(?P<rules>[A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(?P<why>.*\S))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    category: str        # determinism | locking | tracer-safety | pragma
    file: str            # package-relative, forward slashes
    line: int
    col: int
    message: str
    symbol: str = ""     # enclosing function/class, when known

    def sort_key(self):
        return (self.file, self.line, self.col, self.rule)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "category": self.category,
            "file": self.file,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "symbol": self.symbol,
        }


@dataclass
class Pragma:
    line: int            # line the pragma suppresses
    rules: Tuple[str, ...]
    why: Optional[str]
    pragma_line: int     # line the comment physically sits on

    def covers(self, rule: str) -> bool:
        return "all" in self.rules or rule in self.rules


# Rule-id → list of {"path": <prefix>, "why": <reason>}.  Paths are
# package-relative prefixes (a file matches when it equals the prefix or
# lives under it).  Keep every entry justified — this list is reviewed
# in docs/development.md.
DEFAULT_ALLOWLIST: Dict[str, List[dict]] = {
    "TS001": [
        {"path": "timesource.py", "why": "the timesource IS the wall-clock abstraction"},
        {"path": "sim/clock.py", "why": "the virtual clock replaces the timesource in sims"},
    ],
    "TS002": [
        {"path": "testing/", "why": "harness waits bound REAL time; a frozen virtual clock must never make them infinite"},
        {"path": "resilience/deadline.py", "why": "request deadlines bound wall latency for a live HTTP caller"},
        {"path": "resilience/gate.py", "why": "shed-recently window is an operator-facing wall-clock signal"},
        {"path": "kube/restclient.py", "why": "idle-connection reconnect tracks real socket age"},
        {"path": "kube/ratelimit.py", "why": "token-bucket refill meters real API-server wall time"},
        {"path": "utils/tpuprobe.py", "why": "subprocess probe timeout bounds real wall time"},
        {"path": "ha/crashmatrix.py", "why": "matrix cells run live servers with wall-clock lease TTLs; waits must bound real time"},
        {"path": "tracing/", "why": "latency measurement wants real durations even in sims"},
    ],
    "DT001": [],
    "LK002": [],
}


def load_allowlist(path: str) -> Dict[str, List[dict]]:
    """Load a user allowlist JSON file: ``{"RULE": [{"path":..,"why":..},..]}``.
    Entries missing ``why`` are rejected — exemptions carry reasons."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"{path}: allowlist must be a JSON object keyed by rule id")
    out: Dict[str, List[dict]] = {}
    for rule, entries in data.items():
        if not isinstance(entries, list):
            raise ValueError(f"{path}: allowlist[{rule!r}] must be a list")
        for e in entries:
            if not isinstance(e, dict) or "path" not in e:
                raise ValueError(f"{path}: allowlist[{rule!r}] entries need a 'path'")
            if not str(e.get("why", "")).strip():
                raise ValueError(
                    f"{path}: allowlist[{rule!r}] entry for {e['path']!r} "
                    f"needs a 'why' justification"
                )
        out[rule] = list(entries)
    return out


def merge_allowlists(*lists: Dict[str, List[dict]]) -> Dict[str, List[dict]]:
    merged: Dict[str, List[dict]] = {}
    for al in lists:
        for rule, entries in al.items():
            merged.setdefault(rule, []).extend(entries)
    return merged


def allowlisted(allowlist: Dict[str, List[dict]], rule: str, relpath: str) -> bool:
    for entry in allowlist.get(rule, ()):
        prefix = entry["path"]
        if relpath == prefix or relpath.startswith(prefix.rstrip("/") + "/") or (
            prefix.endswith("/") and relpath.startswith(prefix)
        ):
            return True
    return False


@dataclass
class AnalysisConfig:
    select: Optional[Sequence[str]] = None      # rule-id prefixes, e.g. ("TS", "LK001")
    allowlist: Dict[str, List[dict]] = field(default_factory=dict)
    use_default_allowlist: bool = True
    strict: bool = False                        # pragmas must carry justifications

    def effective_allowlist(self) -> Dict[str, List[dict]]:
        if self.use_default_allowlist:
            return merge_allowlists(DEFAULT_ALLOWLIST, self.allowlist)
        return dict(self.allowlist)

    def rule_selected(self, rule: str) -> bool:
        if not self.select:
            return True
        return any(rule.startswith(prefix) for prefix in self.select)


def extract_pragmas(source: str) -> List[Pragma]:
    """Pragmas by suppressed line.  A pragma trailing code suppresses
    its own line; a pragma alone on a line suppresses the next line."""
    pragmas: List[Pragma] = []
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _PRAGMA_RE.search(text)
        if not m:
            continue
        rules = tuple(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        why = m.group("why")
        own_line = text[: m.start()].strip() != ""
        pragmas.append(
            Pragma(
                line=lineno if own_line else lineno + 1,
                rules=rules,
                why=why.strip() if why else None,
                pragma_line=lineno,
            )
        )
    return pragmas


class FileContext:
    """Everything the rule visitors need about one source file."""

    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.pragmas = extract_pragmas(source)

    def pragma_for(self, rule: str, line: int) -> Optional[Pragma]:
        for p in self.pragmas:
            if p.line == line and p.covers(rule):
                return p
        return None


def _iter_py_files(root: str) -> Iterable[str]:
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@dataclass(frozen=True)
class SuppressedFinding:
    """A finding that a pragma or allowlist entry silenced — kept so
    tooling (``tools/schedlint_diff.py``) can tell pre-existing
    justified suppressions apart from *new* ones."""

    finding: Finding
    via: str             # "pragma" | "allowlist"
    why: str

    def to_dict(self) -> dict:
        d = self.finding.to_dict()
        d["suppressed_via"] = self.via
        d["why"] = self.why
        return d


@dataclass
class AnalysisResult:
    findings: List[Finding]
    suppressed: List[SuppressedFinding]


def analyze_paths_detailed(
    paths: Sequence[str],
    config: Optional[AnalysisConfig] = None,
    root: Optional[str] = None,
) -> AnalysisResult:
    """Analyze the given files/directories.  ``root`` anchors the
    package-relative paths used by pragmas/allowlists (defaults to the
    installed package directory).

    Two passes: the per-file rule modules run on each file as it is
    parsed, then the protocol rules (:mod:`.rules_protocol`) run once
    over the whole file set — PC003's fence-dominance is
    interprocedural, so it needs every function in scope at once."""
    from . import rules_jax, rules_locks, rules_native, rules_protocol, rules_time

    config = config or AnalysisConfig()
    root = os.path.abspath(root or package_root())
    allowlist = config.effective_allowlist()

    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isdir(p):
            files.extend(_iter_py_files(p))
        else:
            files.append(p)

    findings: List[Finding] = []
    suppressed: List[SuppressedFinding] = []
    contexts: List[FileContext] = []
    raw: List[Finding] = []
    for path in files:
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(
                Finding(
                    rule="PR000",
                    category="pragma",
                    file=relpath,
                    line=exc.lineno or 1,
                    col=exc.offset or 0,
                    message=f"file does not parse: {exc.msg}",
                )
            )
            continue
        ctx = FileContext(relpath, source, tree)
        contexts.append(ctx)
        raw.extend(rules_time.check(ctx))
        raw.extend(rules_locks.check(ctx))
        raw.extend(rules_jax.check(ctx))
        raw.extend(rules_native.check(ctx))
        raw.extend(rules_protocol.check(ctx))

        if config.strict:
            # every pragma in the file — used or not — must carry a
            # justification: nothing gets silenced without a reason
            for pragma in ctx.pragmas:
                if not pragma.why:
                    findings.append(
                        Finding(
                            rule="PR001",
                            category="pragma",
                            file=relpath,
                            line=pragma.pragma_line,
                            col=0,
                            message=(
                                "pragma suppresses "
                                + ",".join(pragma.rules)
                                + " without a justification "
                                "(append: -- <one-line reason>)"
                            ),
                        )
                    )

    # package-wide pass (interprocedural rules)
    raw.extend(rules_protocol.check_package(contexts))

    ctx_by_relpath = {c.relpath: c for c in contexts}
    for finding in raw:
        if not config.rule_selected(finding.rule):
            continue
        if allowlisted(allowlist, finding.rule, finding.file):
            for entry in allowlist.get(finding.rule, ()):
                prefix = entry["path"]
                if finding.file == prefix or finding.file.startswith(
                    prefix.rstrip("/") + "/"
                ) or (prefix.endswith("/") and finding.file.startswith(prefix)):
                    suppressed.append(
                        SuppressedFinding(finding, "allowlist", str(entry.get("why", "")))
                    )
                    break
            continue
        ctx = ctx_by_relpath.get(finding.file)
        pragma = ctx.pragma_for(finding.rule, finding.line) if ctx else None
        if pragma is not None:
            suppressed.append(
                SuppressedFinding(finding, "pragma", pragma.why or "")
            )
            continue
        findings.append(finding)

    findings.sort(key=Finding.sort_key)
    suppressed.sort(key=lambda s: s.finding.sort_key())
    return AnalysisResult(findings=findings, suppressed=suppressed)


def analyze_paths(
    paths: Sequence[str],
    config: Optional[AnalysisConfig] = None,
    root: Optional[str] = None,
) -> List[Finding]:
    """Backward-compatible wrapper: just the surviving findings."""
    return analyze_paths_detailed(paths, config=config, root=root).findings


def analyze_package(config: Optional[AnalysisConfig] = None) -> List[Finding]:
    """Analyze the whole installed ``k8s_spark_scheduler_tpu`` package."""
    root = package_root()
    return analyze_paths([root], config=config, root=root)
