"""Flow-sensitive analysis substrate for schedlint protocol rules.

Three layers, each usable on its own:

1. :class:`CFG` — a statement-level control-flow graph per function,
   built from the ``ast`` module with *explicit* exception, ``finally``
   and ``with`` edges.  Synthetic nodes model entry, normal exit,
   raise-exit (the "function unwinds" sink), except-handler dispatch,
   shared ``finally`` bodies and the implicit ``__exit__`` of a
   ``with`` block.

2. Dominance (:meth:`CFG.dominators`, :meth:`CFG.dominates`) and a
   generic forward worklist dataflow engine (:func:`forward_dataflow`)
   over caller-supplied transfer/join functions.  Exception edges carry
   a separately computed state (``transfer_exc``) so typestate rules
   can model "the call raised before/after the effect took hold".

3. :class:`PackageIndex` — a lightweight intra-package call graph:
   every function/method in the analyzed file set keyed by
   ``relpath::qualname``, with resolution for ``self.method(...)``,
   same-module ``name(...)`` and ``imported_module.name(...)`` calls.
   Attribute-typed receivers (``self._client.create``) are *not*
   resolved — by design they participate only as lexical patterns in
   the rules, never as call-graph edges.

Modelling decisions (documented imprecision)
--------------------------------------------
* Exception edges are added only from statements whose own expressions
  contain a ``Call``, ``Raise``, ``Assert``, ``Await`` or ``Yield`` —
  plain assignments and constant returns are assumed not to raise.
  ``yield`` gets a raise edge because a generator can be abandoned
  (``GeneratorExit``) or ``throw``-injected at any suspension point.
* ``finally`` bodies are built ONCE and shared by every path that
  crosses them (normal fall-through, every ``return``/``break``/
  ``continue``, and exception propagation).  Continuations are merged:
  after the shared finally body the CFG branches to every continuation
  any path requested.  This over-approximates paths (a ``return`` may
  appear to "fall through") but never hides one.
* ``with`` blocks are modelled like ``try/finally`` whose cleanup is a
  single synthetic ``with-exit`` node (the ``__exit__`` call) — rules
  treat it as the close event for the context object.
* Handler lists never swallow propagation: even a bare ``except:``
  keeps an edge from the protected body to the outer exception target,
  because the repo deliberately injects ``BaseException``-derived
  crashes (:mod:`..ha.crashpoint`) that bypass ``except Exception``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "CFG",
    "Node",
    "build_cfg",
    "forward_dataflow",
    "may_raise",
    "FunctionUnit",
    "PackageIndex",
]

# Edge kinds
NORMAL = "normal"
EXC = "exc"

# Node kinds
ENTRY = "entry"
EXIT = "exit"
RAISE_EXIT = "raise-exit"
STMT = "stmt"
TEST = "test"
EXCEPT = "except"
FINALLY = "finally"
WITH_EXIT = "with-exit"
JOIN = "join"


class Node:
    """One CFG node.  ``stmt`` is the owning ast node (None for the
    synthetic entry/exit/join nodes); ``kind`` distinguishes synthetic
    roles so rules can pattern-match on them."""

    __slots__ = ("idx", "stmt", "kind", "line")

    def __init__(self, idx: int, stmt: Optional[ast.AST], kind: str):
        self.idx = idx
        self.stmt = stmt
        self.kind = kind
        self.line = getattr(stmt, "lineno", 0) if stmt is not None else 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Node {self.idx} {self.kind} L{self.line}>"


class _MayRaiseScan(ast.NodeVisitor):
    """Does this expression tree contain anything that can raise?

    Deliberately narrow: calls, raises, asserts, awaits and yields.
    Attribute access / arithmetic can raise too, but flagging them
    would drown typestate rules in impossible paths."""

    def __init__(self) -> None:
        self.found = False

    def generic_visit(self, node: ast.AST) -> None:
        if self.found:
            return
        if isinstance(
            node, (ast.Call, ast.Raise, ast.Assert, ast.Await, ast.Yield, ast.YieldFrom)
        ):
            self.found = True
            return
        # do not descend into nested function/class bodies
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)
        ):
            return
        super().generic_visit(node)


def may_raise(node: ast.AST) -> bool:
    """True when the statement's own expressions may raise (see
    :class:`_MayRaiseScan` for the deliberate narrowness)."""
    scan = _MayRaiseScan()
    if isinstance(node, (ast.If, ast.While)):
        scan.visit(node.test)
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        scan.visit(node.iter)
        # iteration itself (StopIteration handling aside) calls __next__
        return True
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        return True
    elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return False
    else:
        scan.visit(node)
    return scan.found


@dataclass
class _Cleanup:
    """A shared cleanup region (finally body or with-exit node).

    ``head`` is wired as the target of every path that crosses the
    cleanup; ``out`` (the cleanup subgraph's exit frontier) gets edges
    to every requested continuation once the function is built."""

    head: int
    out: List[int] = field(default_factory=list)
    requests: Set[int] = field(default_factory=set)


@dataclass
class _Loop:
    continue_target: int
    break_join: int
    cleanup_depth: int


class CFG:
    """Statement-level control-flow graph for one function body."""

    def __init__(self, func: Optional[ast.AST] = None):
        self.func = func
        self.nodes: List[Node] = []
        self.succs: List[List[Tuple[int, str]]] = []
        self.preds: List[List[Tuple[int, str]]] = []
        self._dom: Optional[List[int]] = None  # bitsets, lazily computed

    # -- construction helpers (used by _Builder) --------------------------

    def new_node(self, stmt: Optional[ast.AST], kind: str) -> int:
        idx = len(self.nodes)
        self.nodes.append(Node(idx, stmt, kind))
        self.succs.append([])
        self.preds.append([])
        return idx

    def add_edge(self, src: int, dst: int, kind: str = NORMAL) -> None:
        if (dst, kind) not in self.succs[src]:
            self.succs[src].append((dst, kind))
            self.preds[dst].append((src, kind))
        self._dom = None

    # -- queries -----------------------------------------------------------

    @property
    def entry(self) -> int:
        return 0

    @property
    def exit(self) -> int:
        return 1

    @property
    def raise_exit(self) -> int:
        return 2

    def reachable(self) -> List[int]:
        """Nodes reachable from entry, in reverse post-order."""
        seen: Set[int] = set()
        order: List[int] = []

        def dfs(n: int) -> None:
            stack = [(n, iter(self.succs[n]))]
            seen.add(n)
            while stack:
                node, it = stack[-1]
                advanced = False
                for dst, _kind in it:
                    if dst not in seen:
                        seen.add(dst)
                        stack.append((dst, iter(self.succs[dst])))
                        advanced = True
                        break
                if not advanced:
                    order.append(node)
                    stack.pop()

        dfs(self.entry)
        order.reverse()
        return order

    def dominators(self) -> Dict[int, Set[int]]:
        """dom(n) = nodes on *every* path entry→n (classic iterative
        dataflow over bitsets; functions are small so this is cheap)."""
        if self._dom is None:
            order = self.reachable()
            n_nodes = len(self.nodes)
            full = (1 << n_nodes) - 1
            dom = [full] * n_nodes
            dom[self.entry] = 1 << self.entry
            changed = True
            reach = set(order)
            while changed:
                changed = False
                for n in order:
                    if n == self.entry:
                        continue
                    new = full
                    for p, _k in self.preds[n]:
                        if p in reach:
                            new &= dom[p]
                    new |= 1 << n
                    if new != dom[n]:
                        dom[n] = new
                        changed = True
            self._dom = dom
        out: Dict[int, Set[int]] = {}
        for n in self.reachable():
            bits = self._dom[n]
            out[n] = {i for i in range(len(self.nodes)) if bits >> i & 1}
        return out

    def dominates(self, a: int, b: int) -> bool:
        """True when every path from entry to ``b`` passes through ``a``."""
        if self._dom is None:
            self.dominators()
        assert self._dom is not None
        return bool(self._dom[b] >> a & 1)

    def stmt_nodes(self) -> Iterable[Node]:
        for node in self.nodes:
            if node.stmt is not None:
                yield node


class _Builder:
    """Recursive-descent CFG construction.

    ``frontier`` holds the node indices whose normal-completion edge
    flows into whatever comes next.  ``exc_stack`` holds, innermost
    last, the *flattened* list of exception targets active for the
    region being built (handler dispatch nodes, cleanup heads, and
    ultimately the function's raise-exit)."""

    def __init__(self, func: ast.AST):
        self.cfg = CFG(func)
        self.cfg.new_node(None, ENTRY)  # 0
        self.cfg.new_node(None, EXIT)  # 1
        self.cfg.new_node(None, RAISE_EXIT)  # 2
        self.exc_stack: List[List[int]] = [[self.cfg.raise_exit]]
        self.cleanups: List[_Cleanup] = []
        self.loops: List[_Loop] = []
        self.frontier: List[int] = [self.cfg.entry]

    # -- plumbing ----------------------------------------------------------

    def _flow_to(self, idx: int) -> None:
        for src in self.frontier:
            self.cfg.add_edge(src, idx, NORMAL)
        self.frontier = [idx]

    def _exc_edges(self, idx: int) -> None:
        for target in self.exc_stack[-1]:
            self.cfg.add_edge(idx, target, EXC)

    def _stmt_node(self, stmt: ast.AST, kind: str = STMT) -> int:
        idx = self.cfg.new_node(stmt, kind)
        self._flow_to(idx)
        if may_raise(stmt):
            self._exc_edges(idx)
        return idx

    def _route_abrupt(self, src: int, final_target: int, down_to: int) -> None:
        """Route an abrupt jump (return/break/continue) from ``src``
        through every cleanup region inner to ``down_to`` (a cleanup
        stack depth), landing at ``final_target``."""
        chain = self.cleanups[down_to:]
        if not chain:
            self.cfg.add_edge(src, final_target, NORMAL)
            return
        # innermost first when crossing outward
        chain = list(reversed(chain))
        self.cfg.add_edge(src, chain[0].head, NORMAL)
        for inner, outer in zip(chain, chain[1:]):
            inner.requests.add(outer.head)
        chain[-1].requests.add(final_target)

    # -- statement dispatch -------------------------------------------------

    def build(self) -> CFG:
        body = self.cfg.func.body  # type: ignore[union-attr]
        self._block(body)
        for src in self.frontier:
            self.cfg.add_edge(src, self.cfg.exit, NORMAL)
        # flush cleanup continuation requests
        for cleanup in self.cleanups:
            for target in sorted(cleanup.requests):
                for out in cleanup.out:
                    self.cfg.add_edge(out, target, NORMAL)
        return self.cfg

    def _block(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            if not self.frontier:
                # dead code after return/raise/break — still build nodes
                # so rules can see them, but leave them unreachable
                pass
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.If):
            self._if(stmt)
        elif isinstance(stmt, (ast.While,)):
            self._while(stmt)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._for(stmt)
        elif isinstance(stmt, ast.Try):
            self._try(stmt)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._with(stmt)
        elif isinstance(stmt, ast.Return):
            idx = self._stmt_node(stmt)
            self.frontier = []
            self._route_abrupt(idx, self.cfg.exit, 0)
        elif isinstance(stmt, ast.Raise):
            idx = self.cfg.new_node(stmt, STMT)
            self._flow_to(idx)
            self._exc_edges(idx)
            self.frontier = []
        elif isinstance(stmt, ast.Break):
            idx = self._stmt_node(stmt)
            self.frontier = []
            if self.loops:
                loop = self.loops[-1]
                self._route_abrupt(idx, loop.break_join, loop.cleanup_depth)
        elif isinstance(stmt, ast.Continue):
            idx = self._stmt_node(stmt)
            self.frontier = []
            if self.loops:
                loop = self.loops[-1]
                self._route_abrupt(idx, loop.continue_target, loop.cleanup_depth)
        elif isinstance(stmt, ast.Match):
            self._match(stmt)
        else:
            # simple statement (incl. nested def/class, which are opaque)
            self._stmt_node(stmt)

    def _if(self, stmt: ast.If) -> None:
        test = self._stmt_node(stmt, TEST)
        self.frontier = [test]
        self._block(stmt.body)
        body_frontier = self.frontier
        if stmt.orelse:
            self.frontier = [test]
            self._block(stmt.orelse)
            self.frontier = body_frontier + self.frontier
        else:
            self.frontier = body_frontier + [test]

    @staticmethod
    def _const_true(expr: ast.expr) -> bool:
        return isinstance(expr, ast.Constant) and bool(expr.value)

    def _while(self, stmt: ast.While) -> None:
        test = self._stmt_node(stmt, TEST)
        break_join = self.cfg.new_node(None, JOIN)
        self.loops.append(_Loop(test, break_join, len(self.cleanups)))
        self.frontier = [test]
        self._block(stmt.body)
        for src in self.frontier:
            self.cfg.add_edge(src, test, NORMAL)  # back edge
        self.loops.pop()
        exits: List[int] = [break_join]
        if not self._const_true(stmt.test):
            exits.append(test)
        if stmt.orelse:
            self.frontier = [test] if not self._const_true(stmt.test) else []
            self._block(stmt.orelse)
            exits = [break_join] + self.frontier
        self.frontier = exits

    def _for(self, stmt) -> None:
        head = self._stmt_node(stmt, TEST)
        break_join = self.cfg.new_node(None, JOIN)
        self.loops.append(_Loop(head, break_join, len(self.cleanups)))
        self.frontier = [head]
        self._block(stmt.body)
        for src in self.frontier:
            self.cfg.add_edge(src, head, NORMAL)
        self.loops.pop()
        if stmt.orelse:
            self.frontier = [head]
            self._block(stmt.orelse)
            self.frontier = [break_join] + self.frontier
        else:
            self.frontier = [break_join, head]

    def _try(self, stmt: ast.Try) -> None:
        handlers = [self.cfg.new_node(h, EXCEPT) for h in stmt.handlers]
        cleanup: Optional[_Cleanup] = None
        if stmt.finalbody:
            head = self.cfg.new_node(None, FINALLY)
            cleanup = _Cleanup(head=head)
            self.cleanups.append(cleanup)
            outer_exc = self.exc_stack[-1]
            # uncaught exceptions run the finally, then propagate
            cleanup.requests.update(outer_exc)
            body_exc = handlers + [head]
            handler_exc = [head]
        else:
            body_exc = handlers + list(self.exc_stack[-1])
            handler_exc = list(self.exc_stack[-1])

        # protected body (+ else clause, same protection minus handlers)
        self.exc_stack.append(body_exc)
        entry_frontier = list(self.frontier)
        self._block(stmt.body)
        self.exc_stack.pop()
        if stmt.orelse:
            self.exc_stack.append(
                [cleanup.head] if cleanup else list(self.exc_stack[-1])
            )
            self._block(stmt.orelse)
            self.exc_stack.pop()
        normal_out = list(self.frontier)

        # handlers
        handler_outs: List[int] = []
        for h_node, handler in zip(handlers, stmt.handlers):
            self.exc_stack.append(handler_exc)
            self.frontier = [h_node]
            self._block(handler.body)
            handler_outs.extend(self.frontier)
            self.exc_stack.pop()
        del entry_frontier

        if cleanup is not None:
            # all normal completions funnel through the shared finally
            for src in normal_out + handler_outs:
                self.cfg.add_edge(src, cleanup.head, NORMAL)
            self.exc_stack.append(list(self.exc_stack[-1]))
            self.frontier = [cleanup.head]
            self._block(stmt.finalbody)
            self.exc_stack.pop()
            cleanup.out = list(self.frontier)
            # the cleanup is now sealed: subsequent abrupt routing in
            # enclosing code no longer crosses it
            self.cleanups.remove(cleanup)
            self.cleanups_done_append(cleanup)
            # fall-through continues after the finally body
            self.frontier = list(cleanup.out)
        else:
            self.frontier = normal_out + handler_outs

    # sealed cleanups kept so build() can flush their requests
    def cleanups_done_append(self, cleanup: _Cleanup) -> None:
        if not hasattr(self, "_sealed"):
            self._sealed: List[_Cleanup] = []
        self._sealed.append(cleanup)

    def _with(self, stmt) -> None:
        head = self._stmt_node(stmt, STMT)  # context-expr evaluation
        exit_node = self.cfg.new_node(stmt, WITH_EXIT)
        cleanup = _Cleanup(head=exit_node, out=[exit_node])
        self.cleanups.append(cleanup)
        # body exceptions run __exit__, then propagate outward
        cleanup.requests.update(self.exc_stack[-1])
        self.exc_stack.append([exit_node])
        self.frontier = [head]
        self._block(stmt.body)
        self.exc_stack.pop()
        for src in self.frontier:
            self.cfg.add_edge(src, exit_node, NORMAL)
        # __exit__ itself may raise
        for target in self.exc_stack[-1]:
            self.cfg.add_edge(exit_node, target, EXC)
        self.cleanups.remove(cleanup)
        self.cleanups_done_append(cleanup)
        self.frontier = [exit_node]

    def _match(self, stmt: ast.Match) -> None:
        subject = self._stmt_node(stmt, TEST)
        outs: List[int] = []
        for case in stmt.cases:
            self.frontier = [subject]
            self._block(case.body)
            outs.extend(self.frontier)
        self.frontier = outs + [subject]


def build_cfg(func: ast.AST) -> CFG:
    """Build the CFG for a FunctionDef/AsyncFunctionDef body."""
    builder = _Builder(func)
    cfg = builder.build()
    # flush sealed cleanup continuations (finally / with-exit regions)
    for cleanup in getattr(builder, "_sealed", []):
        for target in sorted(cleanup.requests):
            for out in cleanup.out:
                cfg.add_edge(out, target, NORMAL)
    return cfg


# ---------------------------------------------------------------------------
# forward dataflow
# ---------------------------------------------------------------------------


def forward_dataflow(
    cfg: CFG,
    init: Any,
    transfer: Callable[[Node, Any], Any],
    join: Callable[[Any, Any], Any],
    transfer_exc: Optional[Callable[[Node, Any], Any]] = None,
    max_iter: int = 10000,
) -> Dict[int, Any]:
    """Worklist forward dataflow.  Returns IN-state per node index.

    ``transfer(node, in_state) -> out_state`` is applied along normal
    edges; ``transfer_exc`` (default: same as ``transfer``) along
    exception edges — typestate rules use it to model effects that do
    or don't take hold when the statement raises.  ``join`` must be
    monotone and idempotent; ``None`` is the implicit bottom (absent
    state) and is never passed to ``join``/``transfer``."""
    if transfer_exc is None:
        transfer_exc = transfer
    in_state: Dict[int, Any] = {cfg.entry: init}
    order = cfg.reachable()
    pos = {n: i for i, n in enumerate(order)}
    work = list(order)
    in_work = set(work)
    iters = 0
    while work:
        iters += 1
        if iters > max_iter:  # pragma: no cover - safety valve
            break
        n = work.pop(0)
        in_work.discard(n)
        if n not in in_state:
            continue
        node = cfg.nodes[n]
        state = in_state[n]
        out_normal = transfer(node, state)
        out_exc = transfer_exc(node, state)
        for dst, kind in cfg.succs[n]:
            out = out_exc if kind == EXC else out_normal
            if dst in in_state:
                merged = join(in_state[dst], out)
            else:
                merged = out
            if dst not in in_state or merged != in_state[dst]:
                in_state[dst] = merged
                if dst not in in_work and dst in pos:
                    in_work.add(dst)
                    work.append(dst)
                    work.sort(key=lambda x: pos.get(x, 0))
    return in_state


# ---------------------------------------------------------------------------
# package index / call graph
# ---------------------------------------------------------------------------


@dataclass
class FunctionUnit:
    """One function or method in the analyzed file set."""

    relpath: str
    qualname: str  # "Class.method", "func", "outer.<locals>.inner"
    name: str
    class_name: Optional[str]
    node: ast.AST
    ctx: Any  # analysis.core.FileContext
    _cfg: Optional[CFG] = None

    @property
    def key(self) -> Tuple[str, str]:
        return (self.relpath, self.qualname)

    def cfg(self) -> CFG:
        if self._cfg is None:
            self._cfg = build_cfg(self.node)
        return self._cfg


class PackageIndex:
    """Function units + import maps + call resolution for one analysis
    run.  ``contexts`` is the list of per-file FileContext objects the
    schedlint driver parsed."""

    def __init__(self, contexts: Sequence[Any]):
        self.contexts = list(contexts)
        self.units: Dict[Tuple[str, str], FunctionUnit] = {}
        # relpath -> {name -> qualname} for module-level functions
        self.module_funcs: Dict[str, Dict[str, str]] = {}
        # relpath -> {alias -> imported module relpath}
        self.module_aliases: Dict[str, Dict[str, str]] = {}
        # relpath -> {alias -> (module relpath, symbol name)}
        self.symbol_imports: Dict[str, Dict[str, Tuple[str, str]]] = {}
        by_relpath = {c.relpath: c for c in self.contexts}
        for ctx in self.contexts:
            self._collect_units(ctx)
            self._collect_imports(ctx, by_relpath)

    # -- construction ------------------------------------------------------

    def _collect_units(self, ctx: Any) -> None:
        module_funcs: Dict[str, str] = {}

        def walk(node: ast.AST, class_name: Optional[str], prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = prefix + child.name
                    unit = FunctionUnit(
                        relpath=ctx.relpath,
                        qualname=qual,
                        name=child.name,
                        class_name=class_name,
                        node=child,
                        ctx=ctx,
                    )
                    self.units[unit.key] = unit
                    if not prefix:
                        module_funcs[child.name] = qual
                    walk(child, None, qual + ".<locals>.")
                elif isinstance(child, ast.ClassDef):
                    walk(child, child.name, prefix + child.name + ".")
        walk(ctx.tree, None, "")
        self.module_funcs[ctx.relpath] = module_funcs

    def _collect_imports(self, ctx: Any, by_relpath: Dict[str, Any]) -> None:
        aliases: Dict[str, str] = {}
        symbols: Dict[str, Tuple[str, str]] = {}
        pkg_dir = ctx.relpath.rsplit("/", 1)[0] if "/" in ctx.relpath else ""
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                base = self._resolve_from_base(node, pkg_dir)
                if base is None:
                    continue
                for alias in node.names:
                    bound = alias.asname or alias.name
                    as_module = (base + "/" if base else "") + alias.name + ".py"
                    if as_module in by_relpath:
                        aliases[bound] = as_module
                    else:
                        mod_file = (base + ".py") if base else ""
                        if mod_file in by_relpath:
                            symbols[bound] = (mod_file, alias.name)
        self.module_aliases[ctx.relpath] = aliases
        self.symbol_imports[ctx.relpath] = symbols

    @staticmethod
    def _resolve_from_base(node: ast.ImportFrom, pkg_dir: str) -> Optional[str]:
        """Map an ImportFrom to a package-relative directory/module path
        ("" means the package root).  Returns None when the import is
        outside the analyzed package."""
        if node.level:
            parts = pkg_dir.split("/") if pkg_dir else []
            up = node.level - 1
            if up > len(parts):
                return None
            base_parts = parts[: len(parts) - up]
            if node.module:
                base_parts.extend(node.module.split("."))
            return "/".join(base_parts)
        if node.module and node.module.startswith("k8s_spark_scheduler_tpu"):
            rest = node.module.split(".")[1:]
            return "/".join(rest)
        return None

    # -- resolution --------------------------------------------------------

    def resolve_call(
        self, call: ast.Call, unit: FunctionUnit
    ) -> Optional[FunctionUnit]:
        func = call.func
        if isinstance(func, ast.Name):
            # same-module function
            qual = self.module_funcs.get(unit.relpath, {}).get(func.id)
            if qual is not None:
                return self.units.get((unit.relpath, qual))
            # imported symbol
            target = self.symbol_imports.get(unit.relpath, {}).get(func.id)
            if target is not None:
                relpath, name = target
                qual = self.module_funcs.get(relpath, {}).get(name)
                if qual is not None:
                    return self.units.get((relpath, qual))
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            recv, attr = func.value.id, func.attr
            if recv == "self" and unit.class_name is not None:
                return self.units.get(
                    (unit.relpath, f"{unit.class_name}.{attr}")
                )
            mod = self.module_aliases.get(unit.relpath, {}).get(recv)
            if mod is not None:
                qual = self.module_funcs.get(mod, {}).get(attr)
                if qual is not None:
                    return self.units.get((mod, qual))
        return None

    def calls_in(self, unit: FunctionUnit) -> List[ast.Call]:
        """Every Call expression lexically inside the unit's body,
        excluding nested function bodies (those are separate units)."""
        out: List[ast.Call] = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                walk(child)

        for stmt in unit.node.body:  # type: ignore[union-attr]
            walk(stmt)
            if isinstance(stmt, ast.Call):  # pragma: no cover - stmts aren't Calls
                out.append(stmt)
        return out
