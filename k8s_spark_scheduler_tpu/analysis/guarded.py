"""``@guarded_by`` — declared lock discipline for mutable shared state.

A class decorator that records, per class, which attributes are guarded
by which lock attribute::

    @guarded_by("_lock", "_store", "_observers")
    class ObjectStore: ...

The declaration is consumed twice:

- **statically** by the LK rule family (``analysis/rules_locks.py``):
  any mutation of a declared attribute outside a lexical
  ``with self._lock:`` scope (``__init__`` excepted — construction
  happens-before publication) is a finding;
- **at runtime** by the lockset race detector
  (:mod:`.racecheck`): when the detector is active, instances created
  by a decorated class get their lock attribute wrapped in a tracked
  proxy so the detector knows exactly which locks each thread holds at
  every instrumented mutation;
- **at runtime** by the contention observatory
  (:mod:`..contention.locktime`): every instance's lock is wrapped in
  a ``TimedLock`` at construction (the always-on timing layer; it
  records wait/hold stats only while the process-wide timekeeper is
  enabled, and its disabled path costs one module-attribute read).

In production the decorator therefore adds only the ``TimedLock``
shim: metadata registration plus a thin ``__init__`` wrapper.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple, Type

# class → (lock attribute name, tuple of guarded attribute names).
# Keyed by the class object itself so subclasses don't alias.
_REGISTRY: Dict[Type, Tuple[str, Tuple[str, ...]]] = {}


def guarded_by(lock_attr: str, *fields: str):
    """Declare that ``fields`` of the decorated class are only mutated
    while ``self.<lock_attr>`` is held.  ``lock_attr`` must be assigned
    in ``__init__``."""

    def decorate(cls):
        _REGISTRY[cls] = (lock_attr, tuple(fields))
        original_init = cls.__init__

        @functools.wraps(original_init)
        def init(self, *args, **kwargs):
            original_init(self, *args, **kwargs)
            # contention timing wraps the raw lock FIRST (always-on;
            # recording gates on the locktime switchboard), so when the
            # race detector is also active its TrackedLock proxy ends
            # up outermost and the timing layer measures the real lock
            from ..contention import locktime

            locktime.wrap_instance(self, cls, lock_attr)
            # late import: racecheck imports nothing heavy, but keeping
            # the hot (disabled) path to one module-attribute read
            from . import racecheck

            if racecheck.active():
                racecheck.instrument_instance(self, cls, lock_attr)

        cls.__init__ = init
        return cls

    return decorate


def guarded_fields(cls: Type) -> Tuple[str, Tuple[str, ...]]:
    """(lock_attr, fields) declared for ``cls`` (or the nearest
    decorated base), or ``("", ())`` when undeclared."""
    for klass in cls.__mro__:
        if klass in _REGISTRY:
            return _REGISTRY[klass]
    return "", ()


def registry() -> Dict[Type, Tuple[str, Tuple[str, ...]]]:
    return dict(_REGISTRY)
