"""Model-check scenario corpus over the scheduler's guarded components.

These are the components the ROADMAP-1 parallel-admission work will put
under real concurrency: the tensor mirror's :class:`~..state.store.ChangeFeed`
(warm-path invalidation truth), the
:class:`~..ops.deltasolve.DeltaSolveEngine` session map (eviction vs.
in-flight solves), the :class:`~..resilience.journal.IntentJournal`
(divert → replay exactly-once), the
:class:`~..resilience.gate.AdmissionGate` (bounded in-flight
accounting), and the :class:`~..capacity.observatory.CapacitySampler`
(background sampling vs. HTTP freshen).  Each scenario is small — two
to four threads, a handful of operations — because the model checker
pays per interleaving; the point is *exhaustiveness over schedules*,
not volume.

Every scenario asserts its component's core invariant on every explored
schedule AND runs under a fresh race detector (lockset + happens-before
+ lock-order), so a pass means: on every interleaving within the
preemption bound, the invariant held and no access pair was unordered.

``python -m k8s_spark_scheduler_tpu.analysis.modelcheck`` runs this
corpus; ``tests/test_modelcheck.py`` runs it at a reduced budget in
tier 1.  When adding a scenario, keep every thread body deterministic
(no wall clock, no unseeded randomness — schedlint enforces this) and
synchronize only through tracked locks, ``note_access`` checkpoints,
or the cooperative primitives in :mod:`.modelcheck`.
"""

from __future__ import annotations

import threading
from typing import List

import numpy as np

from . import racecheck
from .guarded import guarded_by
from .modelcheck import CoopEvent, Scenario, checkpoint

# ---------------------------------------------------------------------------
# 1. ChangeFeed: publish → wakeup ordering + sequence monotonicity
# ---------------------------------------------------------------------------


def _changefeed_scenario() -> Scenario:
    from ..state.store import DELTA_NODE, DELTA_RESERVATION, ChangeFeed

    class State:
        def __init__(self):
            self.feed = ChangeFeed(capacity=64)
            self.wakeup = CoopEvent()
            self.feed.attach_wakeup(self.wakeup)
            self.observed: List[int] = []

    def setup():
        return State()

    def threads(st: State):
        def publisher_a():
            st.feed.publish(DELTA_RESERVATION, "app-a")
            st.feed.publish(DELTA_NODE, "node-1")

        def publisher_b():
            st.feed.publish(DELTA_RESERVATION, "app-b")

        def waiter():
            st.wakeup.wait()
            # publish happens-before the wakeup: at least one delta must
            # be visible once the event fires
            seq = st.feed.seq
            assert seq >= 1, "woke before any publish was visible"
            kinds = st.feed.kinds_since(0)
            assert kinds is not None and len(kinds) >= 1

        def reader():
            last = 0
            for _ in range(3):
                seq = st.feed.seq
                assert seq >= last, f"feed seq went backwards {last}→{seq}"
                st.observed.append(seq)
                last = seq
                checkpoint("between-reads")

        return [
            ("pub-a", publisher_a),
            ("pub-b", publisher_b),
            ("waiter", waiter),
            ("reader", reader),
        ]

    def final(st: State):
        assert st.feed.seq == 3, f"lost publishes: seq={st.feed.seq}"
        assert st.observed == sorted(st.observed)

    return Scenario(
        name="changefeed-publish-wakeup",
        setup=setup,
        threads=threads,
        final=final,
        description="feed sequence is monotone, no publish is lost, and "
        "the wakeup event never fires before its publish is visible",
    )


# ---------------------------------------------------------------------------
# 2. Mirror lockstep: the delta-solve warm check's O(1) truth
# ---------------------------------------------------------------------------


def _mirror_warm_check_scenario() -> Scenario:
    """The engine's warm path rests on one property of the tensor
    mirror: the content sequence and the content move in lockstep under
    the mirror lock, so *unchanged seq ⟹ unchanged world*.  Model the
    mirror as (data, feed) mutated under one lock — exactly
    TensorSnapshotCache's discipline — and a warm-checking reader that
    caches (seq, data) and later revalidates."""
    from ..state.store import DELTA_RESERVATION, ChangeFeed

    @guarded_by("_lock", "data")
    class Mirror:
        def __init__(self):
            self._lock = threading.RLock()
            self.feed = ChangeFeed(capacity=64)
            self.data = 0

        def mutate(self):
            with self._lock:
                racecheck.note_access(self, "data")
                self.data += 1
                self.feed.publish(DELTA_RESERVATION, "r")

        def read(self):
            with self._lock:
                return self.data, self.feed.seq

    class State:
        def __init__(self):
            self.mirror = Mirror()

    def setup():
        return State()

    def threads(st: State):
        def writer():
            for _ in range(2):
                st.mirror.mutate()

        def warm_reader():
            data1, seq1 = st.mirror.read()
            assert data1 == seq1, "content and sequence out of lockstep"
            checkpoint("warm-window")
            # the O(1) warm check: an unchanged sequence proves the
            # content is unchanged — (data, seq) must be read as one
            # consistent pair (the engine compares the seq inside the
            # snapshot's content_key, never a separately-read one)
            data2, seq2 = st.mirror.read()
            if seq2 == seq1:
                assert data2 == data1, (
                    f"seq unchanged ({seq1}) but content moved "
                    f"{data1}→{data2}: warm check unsound"
                )

        return [
            ("writer", writer),
            ("warm-a", warm_reader),
            ("warm-b", warm_reader),
        ]

    def invariant(st: State):
        data, seq = st.mirror.read()
        assert data == seq, f"lockstep broken: data={data} seq={seq}"

    return Scenario(
        name="mirror-seq-warm-check",
        setup=setup,
        threads=threads,
        invariant=invariant,
        description="unchanged ChangeFeed seq implies unchanged mirror "
        "content on every interleaving (the delta-solve warm-path axiom)",
    )


# ---------------------------------------------------------------------------
# 3. IntentJournal: divert vs. replay, no lost intents
# ---------------------------------------------------------------------------


def _journal_scenario() -> Scenario:
    from ..resilience.journal import IntentJournal

    class State:
        def __init__(self):
            self.journal = IntentJournal(path=None)
            self.recorded: List[str] = []
            self.acked: List[str] = []

    def setup():
        return State()

    def threads(st: State):
        def divert():
            for name in ("app-a", "app-b"):
                st.journal.record("create", "rr", "ns", name, {"n": name})
                st.recorded.append(name)

        def divert_deletes():
            st.journal.record("delete", "rr", "ns", "app-c", None)
            st.recorded.append("app-c")

        def replay():
            # the recovery loop's shape: read pending, replay each, ack
            for rec in st.journal.pending():
                if st.journal.ack(rec["op"], rec["ns"], rec["name"]):
                    st.acked.append(rec["name"])

        return [
            ("divert", divert),
            ("divert-del", divert_deletes),
            ("replay", replay),
        ]

    def invariant(st: State):
        # an intent is never both acked and still pending
        pending = {name for _, name in st.journal.pending_keys()}
        for name in st.acked:
            assert name not in pending, f"{name} acked but still pending"

    def final(st: State):
        pending = {name for _, name in st.journal.pending_keys()}
        for name in st.recorded:
            assert name in pending or name in st.acked, (
                f"lost intent: {name} neither pending nor acked"
            )

    return Scenario(
        name="journal-divert-replay",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="every diverted intent is exactly-once: still "
        "pending or acked, never lost, on every interleaving",
    )


# ---------------------------------------------------------------------------
# 4. AdmissionGate: bounded in-flight accounting
# ---------------------------------------------------------------------------


def _gate_scenario() -> Scenario:
    from ..resilience.gate import AdmissionGate

    class State:
        def __init__(self):
            self.gate = AdmissionGate(max_waiters=2)
            self.admitted = 0
            self.shed = 0

    def setup():
        return State()

    def threads(st: State):
        def request():
            if st.gate.try_enter():
                st.admitted += 1
                checkpoint("holding-admission")
                st.gate.leave()
            else:
                st.shed += 1

        return [(f"req-{i}", request) for i in range(3)]

    def invariant(st: State):
        inflight = st.gate.in_flight
        assert 0 <= inflight <= st.gate.max_waiters, (
            f"in_flight {inflight} outside [0, {st.gate.max_waiters}]"
        )

    def final(st: State):
        assert st.gate.in_flight == 0, "gate leaked an admission"
        assert st.admitted + st.shed == 3
        assert st.gate.shed_total == st.shed

    return Scenario(
        name="admission-gate",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="in-flight count stays within [0, max] and every "
        "request is exactly one of admitted/shed on every interleaving",
    )


# ---------------------------------------------------------------------------
# 5. DeltaSolveEngine: session eviction vs. bookkeeping vs. invalidate
# ---------------------------------------------------------------------------


class _FakeNativeSession:
    """Stands in for NativeFifoSession: the engine only calls
    mem_bytes() under its lock, and eviction must tolerate another
    thread still holding a reference (refcount semantics)."""

    def __init__(self):
        self.closed = False

    def mem_bytes(self) -> int:
        assert not self.closed, "mem_bytes on a closed session"
        return 1024


def _engine_scenario() -> Scenario:
    from ..ops.deltasolve import DeltaSolveEngine, _Session

    def _fake_session() -> "_Session":
        zero = np.zeros((1, 3), dtype=np.int64)
        return _Session(
            native=_FakeNativeSession(), policy_code=0, avail64=zero,
            sched64=zero, cluster=None, zones={},
            scale=np.ones(3, dtype=np.int64),
            scaled_avail=np.zeros((1, 3), dtype=np.int32),
            driver_rank=np.zeros(1, dtype=np.int32),
            exec_ok=np.zeros(1, dtype=bool), nb=1, content_key=(0, 0),
        )

    class State:
        def __init__(self):
            self.engine = DeltaSolveEngine(metrics=None, threads=0)

        def insert(self, key):
            """_cold_build's session-map update, verbatim idiom: pop the
            stale entry, rebuild off-lock, insert + evict over the cap."""
            eng = self.engine
            with eng._lock:
                racecheck.note_access(eng, "_sessions")
                eng._sessions.pop(key, None)
            sess = _fake_session()  # the off-lock rebuild window
            checkpoint("rebuild-window")
            with eng._lock:
                racecheck.note_access(eng, "_sessions")
                eng._sessions[key] = sess
                while len(eng._sessions) > eng.MAX_SESSIONS:
                    eng._sessions.popitem(last=False)

    def setup():
        return State()

    def threads(st: State):
        def builder_a():
            for key in ("k0", "k1", "k2"):
                st.insert(key)

        def builder_b():
            for key in ("k2", "k3", "k4"):
                st.insert(key)

        def bookkeeper():
            st.engine._miss("content")
            st.engine._record_warm(resume=3)
            stats = st.engine.stats()
            assert stats["warm_hits"] >= 1
            assert stats["misses"].get("content", 0) >= 1

        def invalidator():
            st.engine.invalidate()
            # builders may re-insert immediately after the clear, so the
            # post-state is only bounded, never exactly empty
            stats = st.engine.stats()
            assert 0 <= stats["sessions"] <= st.engine.MAX_SESSIONS

        return [
            ("builder-a", builder_a),
            ("builder-b", builder_b),
            ("bookkeeper", bookkeeper),
            ("invalidate", invalidator),
        ]

    def invariant(st: State):
        stats = st.engine.stats()
        assert stats["sessions"] <= st.engine.MAX_SESSIONS, (
            f"LRU cap breached: {stats['sessions']}"
        )
        assert stats["session_bytes"] == stats["sessions"] * 1024

    return Scenario(
        name="deltasolve-eviction",
        setup=setup,
        threads=threads,
        invariant=invariant,
        description="concurrent session rebuilds, eviction, stats and "
        "invalidate keep the session map bounded and consistent",
    )


# ---------------------------------------------------------------------------
# 6. CapacitySampler: background sampling vs. HTTP freshen
# ---------------------------------------------------------------------------


def _sampler_scenario() -> Scenario:
    from ..capacity.observatory import CapacitySampler
    from ..state.store import DELTA_RESERVATION, ChangeFeed
    from ..state.tensor_snapshot import TensorSnapshot

    class FakeCache:  # schedlint: disable=LK004 -- scenario fixture: the lock is tracked via racecheck.track_extra_lock in setup
        """Two-node snapshot source with the mirror's (data, seq)
        lockstep discipline."""

        def __init__(self):
            self._lock = threading.Lock()
            self.feed = ChangeFeed(capacity=64)
            self._usage = 0

        def mutate(self):
            with self._lock:
                self._usage += 1
                self.feed.publish(DELTA_RESERVATION, "r")

        def snapshot(self) -> TensorSnapshot:
            with self._lock:
                usage = self._usage
                seq = self.feed.seq
            alloc = np.full((2, 3), 4_000, dtype=np.int64)
            used = np.zeros((2, 3), dtype=np.int64)
            used[0, 0] = usage
            return TensorSnapshot(
                names=["node-0", "node-1"],
                allocatable=alloc,
                usage=used,
                overhead=np.zeros((2, 3), dtype=np.int64),
                zone_names=["az-a"],
                zone_id=np.zeros(2, dtype=np.int32),
                ready=np.ones(2, dtype=bool),
                unschedulable=np.zeros(2, dtype=bool),
                labels=[{}, {}],
                exact=True,
                res_entries=np.zeros(2, dtype=bool),
                name_rank=np.arange(2, dtype=np.int64),
                structure_key=(0, 0),
                content_key=(0, seq),
            )

    class State:
        def __init__(self):
            self.cache = FakeCache()
            self.sampler = CapacitySampler(
                self.cache, debounce_seconds=0.0, k_max=4,
            )
            # the sample mutex is the freshen-vs-background serializer
            # and the fake cache's lock guards its (data, seq) lockstep;
            # track both so the scheduler can interleave across them
            # instead of deadlocking on raw locks
            racecheck.track_extra_lock(self.sampler, "_sample_mutex")
            racecheck.track_extra_lock(self.cache, "_lock")

    def setup():
        return State()

    def threads(st: State):
        def publisher():
            st.cache.mutate()
            st.cache.mutate()

        def background():
            st.sampler.maybe_sample(trigger="feed")

        def http_freshen():
            st.sampler.sample_now(trigger="manual")

        return [
            ("publisher", publisher),
            ("background", background),
            ("freshen", http_freshen),
        ]

    def invariant(st: State):
        timeline = st.sampler.timeline()
        seqs = [s.seq for s in timeline]
        assert seqs == sorted(seqs), f"timeline seqs out of order: {seqs}"
        assert len(seqs) == len(set(seqs)), f"duplicate timeline key: {seqs}"

    def final(st: State):
        stats = st.sampler.stats()
        assert stats["lock_violations"] == 0
        # an unchanged-seq re-sample REPLACES its timeline entry rather
        # than appending, so samples may exceed distinct timeline keys —
        # but never the other way around
        assert stats["samples"] >= len(st.sampler.timeline())
        assert stats["samples"] >= 1

    return Scenario(
        name="capacity-sampler-freshen",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="background sampling, HTTP freshen and feed "
        "publishes keep the timeline ordered and duplicate-free",
    )


# ---------------------------------------------------------------------------
# 7. PreemptionCoordinator: admission vs. commit vs. failover replay
# ---------------------------------------------------------------------------


def _preemption_scenario() -> Scenario:
    """Concurrent admission, a preemption commit, and a failover
    recover() replaying a predecessor's pending evict intent.  The
    exactly-once contract under every interleaving: no lost eviction
    (every intent executed and acked — journal drains), no double-evict
    (no pod is ever successfully deleted twice), and admission of an
    uninvolved app is never disturbed."""
    from ..kube.errors import NotFoundError
    from ..policy.preempt import EVICT_KIND, PreemptionCoordinator
    from ..policy.victims import VictimCandidate, VictimPlan

    @guarded_by("_lock", "pods", "rrs", "pod_deletes")
    class Cluster:
        """Pod + RR state shared by the fake api and rr_cache views;
        counts SUCCESSFUL deletes per pod — the double-evict witness."""

        def __init__(self):
            self._lock = threading.Lock()
            self.pods = {"app-a-driver", "app-a-exec-1", "app-b-driver", "app-b-exec-1"}
            self.rrs = {"app-a", "app-b"}
            self.pod_deletes: dict = {}

        def delete_pod(self, name: str) -> None:
            with self._lock:
                racecheck.note_access(self, "pods")
                racecheck.note_access(self, "pod_deletes")
                if name not in self.pods:
                    raise NotFoundError(f"pod {name}")
                self.pods.remove(name)
                self.pod_deletes[name] = self.pod_deletes.get(name, 0) + 1

        def delete_rr(self, name: str) -> None:
            with self._lock:
                racecheck.note_access(self, "rrs")
                if name not in self.rrs:
                    raise NotFoundError(f"rr {name}")
                self.rrs.remove(name)

        def add_rr(self, name: str) -> None:
            with self._lock:
                racecheck.note_access(self, "rrs")
                self.rrs.add(name)

    class FakeAPI:
        def __init__(self, cluster):
            self._cluster = cluster

        def delete(self, kind, ns, name):
            self._cluster.delete_pod(name)

    class FakeRRCache:
        def __init__(self, cluster):
            self._cluster = cluster

        def delete(self, ns, name):
            self._cluster.delete_rr(name)

    def _plan(app: str) -> VictimPlan:
        return VictimPlan(
            preemptor_app="storm-001",
            preemptor_band="high",
            victims=[
                VictimCandidate(
                    namespace="ns", app_id=app, band="low", band_rank=0,
                    tenant="t", created=1.0,
                    freed=np.zeros((1, 3), dtype=np.int64),
                    pods=[f"{app}-driver", f"{app}-exec-1"],
                )
            ],
            whatif_ms=0.0,
            lane="numpy",
        )

    class State:
        def __init__(self):
            self.cluster = Cluster()
            self.coordinator = PreemptionCoordinator(
                api=FakeAPI(self.cluster), rr_cache=FakeRRCache(self.cluster)
            )
            # the predecessor instance journaled app-a's eviction and
            # crashed before executing it: a pending intent recover()
            # must replay exactly once
            self.coordinator._journal.record(
                "delete", EVICT_KIND, "ns", "app-a",
                {"pods": ["app-a-driver", "app-a-exec-1"], "reason": "crashed",
                 "preemptor": "storm-000", "band": "low", "tenant": "t"},
            )

    def setup():
        return State()

    def threads(st: State):
        def active_commit():
            st.coordinator.commit(_plan("app-b"))

        def standby_recover():
            st.coordinator.recover()

        def admitter():
            st.cluster.add_rr("app-c")
            checkpoint("post-admission")
            snap = st.coordinator.state()
            assert snap["evictionsTotal"] >= 0

        return [
            ("commit", active_commit),
            ("recover", standby_recover),
            ("admitter", admitter),
        ]

    def invariant(st: State):
        with st.cluster._lock:
            deletes = dict(st.cluster.pod_deletes)
        for pod, n in deletes.items():
            assert n <= 1, f"double-evict: pod {pod} successfully deleted {n}x"

    def final(st: State):
        # no lost eviction: every intent executed and acked
        assert st.coordinator.journal_depth() == 0, "evict intent left pending"
        with st.cluster._lock:
            pods, rrs = set(st.cluster.pods), set(st.cluster.rrs)
        assert not pods, f"victim pods survived eviction: {sorted(pods)}"
        assert rrs == {"app-c"}, f"expected only the admitted app's RR, got {sorted(rrs)}"
        evicted = {e["app"] for e in st.coordinator.state()["recent"]}
        assert evicted == {"app-a", "app-b"}, f"evicted set wrong: {sorted(evicted)}"

    return Scenario(
        name="preemption-commit-vs-recover",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="concurrent admission, preemption commit and failover "
        "replay: no lost eviction, no double-evict, journal drains on "
        "every interleaving",
    )


# ---------------------------------------------------------------------------
# 8. Fencing: lease steal vs. renewal observation vs. in-flight write-back
# ---------------------------------------------------------------------------


def _fencing_scenario() -> Scenario:
    """The split-brain triangle (ha/fencing.py): a rival CAS-steals the
    lease at epoch 2 while the resident leader (epoch 1) has write-backs
    in flight and its renewal loop is racing to observe the steal.  The
    contract under every interleaving: a write whose read-through peek
    already saw epoch 2 refuses deterministically; a commit may straddle
    the steal only when the lease moved *between* its peek and its
    commit (the irreducible in-flight window), and the fence's
    stale-commit witness counts at most those straddlers — it never
    invents one.  Once deposition is observed, every later check
    refuses."""
    from ..ha.fencing import FencedWriter, FenceState, StaleEpochError

    @guarded_by("_lock", "epoch")
    class LeaseView:
        """The coordination lease as the read-through sees it."""

        def __init__(self):
            self._lock = threading.Lock()
            self.epoch = 1

        def peek(self):
            with self._lock:
                racecheck.note_access(self, "epoch")
                view = LeaseView.__new__(LeaseView)
                view.epoch = self.epoch
                return view

        def steal(self, epoch: int):
            with self._lock:
                racecheck.note_access(self, "epoch")
                self.epoch = epoch

    class State:
        def __init__(self):
            self.lease = LeaseView()
            self.fence = FenceState()
            self.fence.grant(1)
            self.writer = FencedWriter(self.fence, lease_reader=self.lease.peek)
            self._lock = threading.Lock()
            self.committed: List[int] = []
            self.refused = 0
            # commits whose peek→commit window straddled the steal
            self.straddled = 0

    def setup():
        return State()

    def threads(st: State):
        def write(op: str):
            try:
                epoch = st.writer.check(op)
            except StaleEpochError:
                with st._lock:
                    st.refused += 1
                return
            checkpoint("pre-commit")  # the in-flight window
            st.writer.commit()
            # deposition is monotone (no re-grant in this scenario), so
            # "deposed now" is a sound upper bound for "deposed when
            # note_commit ran" — every fence-counted straddler is
            # counted here too, never the reverse
            deposed = st.fence.deposed()
            with st._lock:
                st.committed.append(epoch)
                if deposed:
                    st.straddled += 1

        def rival():
            # the rival's CAS lands on the lease object first; the
            # resident only learns of it via a peek or a renewal
            st.lease.steal(2)

        def renewer():
            # the renewal round observing whatever the lease holds now
            st.fence.observe(st.lease.peek().epoch)

        return [
            ("write-a", lambda: write("writeback.create")),
            ("write-b", lambda: write("writeback.update")),
            ("rival", rival),
            ("renewer", renewer),
        ]

    def invariant(st: State):
        with st._lock:
            committed = list(st.committed)
        for epoch in committed:
            assert epoch == 1, f"write committed at unheld epoch {epoch}"

    def final(st: State):
        with st._lock:
            decided = len(st.committed) + st.refused
            straddled = st.straddled
        # the witness only counts commits that really straddled the
        # steal (asserted post-quiesce: mid-flight the bookkeeping and
        # the fence counter are updated at different instants)
        assert st.fence.stale_commits() <= straddled, (
            f"fence counted {st.fence.stale_commits()} stale commits but "
            f"only {straddled} straddled the steal"
        )
        assert decided == 2, f"a write was neither committed nor refused ({decided}/2)"
        # the steal always lands; once anything has observed it, every
        # subsequent check must refuse — probe it
        assert st.fence.observe(st.lease.peek().epoch), "deposition not visible"
        try:
            st.writer.check("writeback.probe")
        except StaleEpochError:
            pass
        else:
            raise AssertionError("check passed after deposition was observed")

    return Scenario(
        name="fencing-steal-vs-writeback",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="lease steal vs. renewal observation vs. in-flight "
        "write-back: commits only at the held epoch, refusals are "
        "deterministic once deposition is visible, and the stale-commit "
        "witness never over-counts, on every interleaving",
    )


# ---------------------------------------------------------------------------
# 9. CommitGate: linearizable FIFO under speculate/conflict/abort/recommit
# ---------------------------------------------------------------------------


def _concurrent_commit_scenario() -> Scenario:
    """The concurrent admission engine's ordering contract
    (concurrent/commitgate.py): requests speculate against a
    seq-stamped basis in parallel, then commit strictly in ticket
    order.  Aborts (deadline expiry before the turn) must not stall the
    queue; a commit whose speculative basis moved must observe that at
    revalidation (conflict → re-solve) — never consume the stale
    verdict.  FIFO among committed requests is the linearizability the
    scenario proves over every explored interleaving."""
    from ..concurrent.commitgate import CommitGate
    from .modelcheck import CoopEvent

    @guarded_by("_lock", "basis_seq", "commit_log", "aborted")
    class State:
        def __init__(self):
            # CoopEvent so a parked turn stays visible to the
            # cooperative scheduler (a raw Event.wait would read as a
            # stuck schedule)
            self.gate = CommitGate(event_factory=CoopEvent)
            self._lock = threading.Lock()
            # the shared basis, stood in by its ChangeFeed sequence:
            # success-shaped commits bump it (the reservation
            # write-back); refusals leave it alone
            self.basis_seq = 0
            self.commit_log: List[tuple] = []  # (ticket, reason), commit order
            self.aborted: List[int] = []

    def setup():
        return State()

    def threads(st: State):
        def request(abort: bool, mutates: bool):
            ticket = st.gate.ticket()
            committed = False
            try:
                # the speculative solve: an off-lock snapshot read of
                # the basis, concurrent with every other request's
                with st._lock:
                    racecheck.note_access(st, "basis_seq")
                    spec_seq = st.basis_seq
                checkpoint("speculated")
                if abort:
                    # deadline expired before the turn: retire without
                    # committing — later tickets must skip this one
                    with st._lock:
                        racecheck.note_access(st, "aborted")
                        st.aborted.append(ticket)
                    return
                st.gate.await_turn(ticket)
                # the commit: revalidate the speculation against the
                # then-current basis — O(1) seq check, conflict → re-solve
                with st._lock:
                    racecheck.note_access(st, "basis_seq")
                    racecheck.note_access(st, "commit_log")
                    reason = "seq-hit" if st.basis_seq == spec_seq else "conflict"
                    st.commit_log.append((ticket, reason))
                    if mutates:
                        st.basis_seq += 1
                committed = True
            finally:
                st.gate.retire(ticket, committed)

        return [
            ("commit-a", lambda: request(False, True)),
            ("commit-b", lambda: request(False, False)),
            ("abort-c", lambda: request(True, False)),
            ("commit-d", lambda: request(False, True)),
        ]

    def invariant(st: State):
        with st._lock:
            log = list(st.commit_log)
        tickets = [t for t, _ in log]
        assert tickets == sorted(tickets), (
            f"commits out of FIFO ticket order: {tickets}"
        )

    def final(st: State):
        with st._lock:
            log = list(st.commit_log)
            aborted = list(st.aborted)
        tickets = [t for t, _ in log]
        assert tickets == sorted(tickets), f"final order not FIFO: {tickets}"
        assert len(log) == 3, f"expected 3 commits, got {log}"
        assert len(aborted) == 1, f"expected 1 abort, got {aborted}"
        assert not set(tickets) & set(aborted), "a ticket both committed and aborted"
        stats = st.gate.stats()
        assert stats["committed"] == 3 and stats["aborted"] == 1
        assert stats["head"] == stats["issued"] == 4, (
            f"gate head did not drain: {stats}"
        )

    return Scenario(
        name="concurrent-commit-fifo",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="speculate/conflict/abort/recommit through the "
        "commit gate: commits land strictly in ticket order, aborts "
        "never stall the queue, and the gate drains to head==issued on "
        "every interleaving",
    )


# ---------------------------------------------------------------------------
# 10. ClassIndex: concurrent class rebuild vs. the digest warm check
# ---------------------------------------------------------------------------


def _class_rebuild_scenario() -> Scenario:
    """The class-digest warm tier rests on two ClassIndex properties
    under concurrency: *unchanged class revision ⟹ unchanged class
    multiset* (the delta-solve invalidation key never lies), and the
    incrementally maintained index equals a from-scratch rebuild of the
    authoritative rows at every instant — so a rebuild racing a
    warm-checking reader can never expose a divergent partition.  Both
    (rev, content) reads happen under the mirror lock, exactly
    TensorSnapshotCache.snapshot()'s discipline."""
    from ..state.classindex import ClassIndex

    big = np.array([8000, 16 << 30, 0], dtype=np.int64)
    small = np.array([4000, 8 << 30, 0], dtype=np.int64)
    zero = np.zeros(3, dtype=np.int64)

    @guarded_by("_lock", "rows")
    class Holder:
        """Authoritative rows + the incremental index, one lock — the
        tensor mirror's discipline in miniature."""

        def __init__(self):
            self._lock = threading.RLock()
            self.classes = ClassIndex()
            # slot -> (alloc, usage)
            self.rows = {}
            for slot, alloc in ((0, big), (1, big), (2, small)):
                self.note(slot, alloc, zero)

        def note(self, slot, alloc, usage):
            with self._lock:
                racecheck.note_access(self, "rows")
                self.rows[slot] = (alloc, usage)
                self.classes.note_node(
                    slot, f"n{slot}", alloc, usage, zero, 0, True, False,
                    labels={},
                )

        def snap(self):
            """(rev, digest, class multiset) as ONE consistent triple."""
            with self._lock:
                return (
                    self.classes.class_rev,
                    self.classes.digest,
                    self.classes.class_sizes(),
                )

        def rebuild(self):
            """From-scratch partition of the current authoritative rows
            (what a cold class rebuild computes), plus the incremental
            index's answer at the same instant."""
            with self._lock:
                racecheck.note_access(self, "rows")
                fresh = ClassIndex()
                for slot, (alloc, usage) in self.rows.items():
                    fresh.note_node(
                        slot, f"n{slot}", alloc, usage, zero, 0, True,
                        False, labels={},
                    )
                return fresh.class_sizes(), self.classes.class_sizes()

    class State:
        def __init__(self):
            self.holder = Holder()

    def setup():
        return State()

    def threads(st: State):
        def key_churner():
            # slot 1 migrates between classes: every move MUST bump rev
            st.holder.note(1, small, zero)
            st.holder.note(1, big, zero)

        def usage_churner():
            # content-only churn on slot 2: digest flips and cancels,
            # rev must never move on its account
            used = zero.copy()
            used[0] = 100
            st.holder.note(2, small, used)
            st.holder.note(2, small, zero)

        def warm_reader():
            rev1, dig1, sizes1 = st.holder.snap()
            checkpoint("warm-window")
            rev2, dig2, sizes2 = st.holder.snap()
            if rev2 == rev1:
                assert sizes2 == sizes1, (
                    f"rev unchanged ({rev1}) but the class multiset "
                    f"moved {sizes1} → {sizes2}: warm tier unsound"
                )
            if dig2 == dig1:
                # digest covers a superset of the multiset: equal digest
                # must come with an equal partition too
                assert sizes2 == sizes1, (
                    f"digest unchanged but multiset moved "
                    f"{sizes1} → {sizes2}"
                )

        def rebuilder():
            fresh, incremental = st.holder.rebuild()
            assert fresh == incremental, (
                f"incremental index diverged from a cold rebuild: "
                f"{incremental} vs {fresh}"
            )

        return [
            ("key-churn", key_churner),
            ("usage-churn", usage_churner),
            ("warm-a", warm_reader),
            ("rebuild", rebuilder),
        ]

    def invariant(st: State):
        fresh, incremental = st.holder.rebuild()
        assert fresh == incremental, (
            f"incremental {incremental} != rebuilt {fresh}"
        )

    def final(st: State):
        rev, _, sizes = st.holder.snap()
        # both churners restored their slots: back to the initial
        # partition {big: 2, small: 1}, with the rev recording that the
        # multiset was disturbed along the way
        assert sorted(sizes.values()) == [1, 2], sizes
        assert rev >= 2, f"key churn never bumped the revision: {rev}"

    return Scenario(
        name="class-rebuild-warm-check",
        setup=setup,
        threads=threads,
        invariant=invariant,
        final=final,
        description="unchanged class revision implies an unchanged class "
        "multiset on every interleaving of key churn, usage churn, and a "
        "concurrent from-scratch rebuild (the class-digest warm-tier "
        "axiom)",
    )


def corpus() -> List[Scenario]:
    return [
        _changefeed_scenario(),
        _mirror_warm_check_scenario(),
        _journal_scenario(),
        _gate_scenario(),
        _engine_scenario(),
        _sampler_scenario(),
        _preemption_scenario(),
        _fencing_scenario(),
        _concurrent_commit_scenario(),
        _class_rebuild_scenario(),
    ]
