"""Systematic interleaving model checker for small concurrency scenarios.

The race detectors (:mod:`.racecheck`) observe whatever interleaving a
test happens to produce; this module *controls* the interleaving.  A
scenario's threads run under a cooperative scheduler: exactly one
thread executes at a time, and at every preemption point — each
:class:`~.racecheck.TrackedLock` acquire/release, each
:func:`~.racecheck.note_access` checkpoint, each explicit
:func:`checkpoint` — control returns to the scheduler, which decides
whether to continue the current thread or preempt it.  Schedules are
explored systematically (iterative DFS over untried decisions with a
bounded preemption count, the CHESS discipline) and then randomly from
a seed, so the same budget is spent first on the "few preemptions"
schedules that find most bugs and then on diversity.

Every explored schedule checks:

- the scenario's ``invariant`` (called at every scheduling point, while
  all threads are parked) and ``final`` (after quiescence);
- freedom from deadlock (no runnable thread, not all done — this is how
  a lost wakeup manifests);
- the race detectors: each schedule runs under a fresh
  :class:`~.racecheck.RaceDetector`, so a lockset/happens-before race or
  a lock-order cycle on ANY explored schedule fails the scenario.

A violation is returned as a :class:`Counterexample` carrying the exact
decision sequence and a formatted trace; :func:`replay` re-runs it
deterministically (same scenario + same schedule ⇒ same execution,
because only one thread ever runs at a time and scenario code is
required to be deterministic — no wall clock, no unseeded randomness;
schedlint's TS/DT rules enforce exactly this).

Scenario code synchronizing through anything other than a tracked lock
uses the cooperative primitives here: :class:`CoopEvent` (sticky, like
``threading.Event``) and :class:`CoopPulse` (memoryless notify — the
primitive whose misuse IS the classic lost wakeup).  Blocking on a raw
``threading.Event``/``queue.Queue`` inside a controlled thread would
hang the schedule; the run guard turns that into a loud
``stuck schedule`` failure rather than a silent CI timeout.

The scenario corpus over the scheduler's own guarded components lives
in :mod:`.mcscenarios`; ``python -m k8s_spark_scheduler_tpu.analysis.modelcheck``
runs it (CI's model-check lane).
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from . import racecheck

# Per-run guard rails.  A schedule that exceeds them is reported as a
# failure (livelock / uncontrolled blocking), never silently dropped.
# The park timeout is WALL time and exists only to catch scenarios that
# block on untracked primitives — keep it generous: on a small shared
# host a concurrent test suite's compile storm can starve this process
# for tens of seconds, and a false "stuck schedule" is worse than a
# slow loud failure (livelock is caught by the step cap and deadlock by
# the blocked-thread check, neither of which is wall-time based).
DEFAULT_MAX_STEPS = 20_000
_PARK_TIMEOUT_S = 120.0


class _Abort(BaseException):
    """Raised inside controlled threads to unwind an abandoned run.
    BaseException so scenario code's ``except Exception`` can't eat it."""


class StuckSchedule(RuntimeError):
    """A controlled thread failed to reach a preemption point — almost
    always a blocking call on an untracked primitive inside a scenario."""


# ---------------------------------------------------------------------------
# Scenario definition
# ---------------------------------------------------------------------------


@dataclass
class Scenario:
    """One model-checked concurrency scenario.

    ``setup()`` builds fresh state per schedule; ``threads(state)``
    returns ``[(name, zero-arg callable), ...]``; ``invariant(state)``
    (optional) raises ``AssertionError`` on violation and is called at
    every scheduling point; ``final(state)`` (optional) runs after all
    threads finish."""

    name: str
    setup: Callable[[], object]
    threads: Callable[[object], Sequence[Tuple[str, Callable[[], None]]]]
    invariant: Optional[Callable[[object], None]] = None
    final: Optional[Callable[[object], None]] = None
    description: str = ""


@dataclass
class Counterexample:
    reason: str
    schedule: Tuple[int, ...]     # chosen runnable-index at each decision
    trace: Tuple[str, ...]        # one line per scheduling step
    schedule_index: int           # which explored schedule failed

    def __str__(self) -> str:
        lines = [f"counterexample ({self.reason})",
                 f"schedule: {list(self.schedule)}"]
        lines += [f"  {line}" for line in self.trace]
        return "\n".join(lines)


@dataclass
class ExploreResult:
    scenario: str
    schedules: int                # schedules fully executed
    decisions: int                # total scheduling decisions taken
    max_preemptions: int
    violation: Optional[Counterexample] = None

    @property
    def ok(self) -> bool:
        return self.violation is None


# ---------------------------------------------------------------------------
# Cooperative synchronization primitives for scenario code
# ---------------------------------------------------------------------------


class CoopEvent:
    """Sticky event (``threading.Event`` semantics) that parks under the
    cooperative scheduler instead of blocking the OS thread.  Outside a
    model-check run it degrades to a real Event."""

    def __init__(self):
        self._flag = False
        self._real = threading.Event()

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._real.set()
        run = _current_run()
        if run is not None:
            run.object_signaled(self)

    def wait(self) -> None:
        run = _current_run()
        if run is not None and run.controls_current_thread():
            while not self._flag:
                run.wait_for_object(self, "event")
            return
        self._real.wait()


class CoopPulse:
    """Memoryless notify: ``notify()`` wakes the threads waiting *right
    now* and is lost otherwise — the condition-variable pulse whose
    check-then-wait misuse is the textbook lost wakeup.  Only usable
    under the scheduler (a real memoryless wait cannot be emulated
    portably outside it)."""

    def notify(self) -> None:
        run = _current_run()
        if run is not None:
            run.object_signaled(self)

    def wait(self) -> None:
        run = _current_run()
        if run is None or not run.controls_current_thread():
            raise RuntimeError("CoopPulse.wait outside a model-check run")
        run.wait_for_object(self, "pulse")


def checkpoint(label: str = "checkpoint") -> None:
    """Explicit preemption point for scenario code between synchronized
    regions (tracked locks and note_access checkpoints yield already)."""
    run = _current_run()
    if run is not None and run.controls_current_thread():
        run.preempt(label)


# ---------------------------------------------------------------------------
# One schedule execution
# ---------------------------------------------------------------------------

_READY = "ready"
_RUNNING = "running"
_BLOCKED = "blocked"
_DONE = "done"


class _Cell:
    __slots__ = ("index", "name", "fn", "thread", "state", "waiting",
                 "label", "error", "locks_held")

    def __init__(self, index: int, name: str, fn: Callable[[], None]):
        self.index = index
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.state = _READY
        self.waiting: Optional[object] = None   # lock/object blocked on
        self.label = "start"
        self.error: Optional[BaseException] = None
        self.locks_held = 0


# The per-thread run registry lives on the racecheck module, NOT here:
# under ``python -m …analysis.modelcheck`` THIS module is loaded twice
# (once as __main__, once canonically via mcscenarios' import), and two
# private ``threading.local()``s would split the registry — the _Run
# registers in one copy while CoopEvent.wait consults the other, gets
# None, and falls back to a REAL blocking wait that never yields (a
# phantom "stuck schedule" on correct code).  racecheck is imported by
# both copies as the same canonical module, so its attribute is shared.
_run_tls = racecheck._modelcheck_run_tls


def _current_run() -> Optional["_Run"]:
    return getattr(_run_tls, "run", None)


class _Run:
    """Executes one scenario under one schedule.  Doubles as the
    racecheck scheduler hook (set for the run's duration)."""

    def __init__(self, scenario: Scenario, forced: Sequence[int],
                 rng: Optional[random.Random], max_steps: int):
        self.scenario = scenario
        self.forced = list(forced)
        self.rng = rng                    # None ⇒ deterministic default policy
        self.max_steps = max_steps
        self._cv = threading.Condition()
        self._cells: List[_Cell] = []
        self._current: Optional[_Cell] = None
        self._abort = False
        self._last: Optional[_Cell] = None
        # per-decision record: (chosen index into runnable, runnable size,
        # default index — what the continue-current policy would pick —
        # and whether the previously-running cell was among the runnable,
        # i.e. whether a different choice costs a preemption)
        self.decisions: List[Tuple[int, int, int, bool]] = []
        self.trace: List[str] = []
        self.failure: Optional[str] = None
        self.detector: Optional[racecheck.RaceDetector] = None

    # -- hook protocol (called from controlled threads) -----------------------

    def controls_current_thread(self) -> bool:
        return getattr(_run_tls, "cell", None) is not None

    def preempt(self, label: str) -> None:
        cell: _Cell = _run_tls.cell
        self._park(cell, _READY, None, label)

    def wait_for_lock(self, lock) -> None:
        cell: _Cell = _run_tls.cell
        self._park(cell, _BLOCKED, lock, f"lock-wait:{lock.name}")

    def lock_acquired(self, lock) -> None:
        _run_tls.cell.locks_held += 1

    def lock_released(self, lock) -> None:
        cell: _Cell = _run_tls.cell
        if cell.locks_held > 0:
            cell.locks_held -= 1
        with self._cv:
            for c in self._cells:
                if c.state == _BLOCKED and c.waiting is lock:
                    c.state = _READY
                    c.waiting = None

    def wait_for_object(self, obj: object, kind: str) -> None:
        cell: _Cell = _run_tls.cell
        self._park(cell, _BLOCKED, obj, f"{kind}-wait")

    def object_signaled(self, obj: object) -> None:
        with self._cv:
            for c in self._cells:
                if c.state == _BLOCKED and c.waiting is obj:
                    c.state = _READY
                    c.waiting = None

    def _park(self, cell: _Cell, state: str, waiting: Optional[object],
              label: str) -> None:
        with self._cv:
            if self._abort:
                raise _Abort()
            cell.state = state
            cell.waiting = waiting
            cell.label = label
            self._current = None
            self._cv.notify_all()
            while self._current is not cell:
                if not self._cv.wait(timeout=_PARK_TIMEOUT_S):
                    raise StuckSchedule(
                        f"{self.scenario.name}: thread {cell.name} parked "
                        f"at {label} was never rescheduled"
                    )
            if self._abort:
                raise _Abort()
            cell.state = _RUNNING
            cell.waiting = None

    # -- thread bodies --------------------------------------------------------

    def _runner(self, cell: _Cell) -> None:
        _run_tls.run = self
        _run_tls.cell = cell
        try:
            # park until first scheduled
            with self._cv:
                while self._current is not cell:
                    if not self._cv.wait(timeout=_PARK_TIMEOUT_S):
                        raise StuckSchedule(
                            f"{self.scenario.name}: thread {cell.name} "
                            "never received its first slot"
                        )
                if self._abort:
                    raise _Abort()
                cell.state = _RUNNING
            cell.fn()
        except _Abort:
            pass
        except BaseException as exc:  # reported as the run's failure
            cell.error = exc
        finally:
            _run_tls.cell = None
            _run_tls.run = None
            with self._cv:
                cell.state = _DONE
                if self._current is cell:
                    self._current = None
                self._cv.notify_all()

    # -- scheduling -----------------------------------------------------------

    def _choose(self, runnable: List[_Cell]) -> _Cell:
        step = len(self.decisions)
        had_last = self._last is not None and self._last in runnable
        default_idx = runnable.index(self._last) if had_last else 0
        if step < len(self.forced):
            idx = self.forced[step] % len(runnable)
        elif self.rng is not None:
            # hybrid phase: bias toward staying on the current thread so
            # random schedules still resemble real executions
            if had_last and self.rng.random() < 0.6:
                idx = default_idx
            else:
                idx = self.rng.randrange(len(runnable))
        else:
            # deterministic default: keep running the same thread
            idx = default_idx
        self.decisions.append((idx, len(runnable), default_idx, had_last))
        return runnable[idx]

    def execute(self) -> None:
        """Run the schedule to completion; failures land in
        ``self.failure`` (+ ``self.trace``)."""
        prev_detector = racecheck.disable()
        self.detector = racecheck.enable(racecheck.RaceDetector())
        racecheck.set_sched_hook(self)
        try:
            state = self.scenario.setup()
            specs = list(self.scenario.threads(state))
            self._cells = [
                _Cell(i, name, fn) for i, (name, fn) in enumerate(specs)
            ]
            for cell in self._cells:
                cell.thread = threading.Thread(
                    target=self._runner, args=(cell,),
                    name=f"mc-{self.scenario.name}-{cell.name}", daemon=True,
                )
                cell.thread.start()
            self._orchestrate(state)
        finally:
            self._shutdown()
            racecheck.set_sched_hook(None)
            racecheck.disable()
            if prev_detector is not None:
                racecheck.enable(prev_detector)

    def _orchestrate(self, state: object) -> None:
        while True:
            with self._cv:
                while self._current is not None:
                    if not self._cv.wait(timeout=_PARK_TIMEOUT_S):
                        self.failure = (
                            "stuck schedule: running thread never yielded "
                            "(blocking call on an untracked primitive?)"
                        )
                        return
                runnable = [c for c in self._cells if c.state == _READY]
                done = all(c.state == _DONE for c in self._cells)
                # invariants may take component locks, so only check at
                # lock-quiescent points (no parked thread mid-critical-
                # section — otherwise the orchestrator would block on a
                # lock whose holder is parked)
                locks_quiescent = all(c.locks_held == 0 for c in self._cells)
            if done:
                break
            if (
                self.failure is None
                and locks_quiescent
                and self.scenario.invariant is not None
            ):
                try:
                    self._observe(self.scenario.invariant, state)
                except AssertionError as exc:
                    self.failure = f"invariant violated: {exc}"
                    return
            if not runnable:
                blocked = [
                    f"{c.name}({c.label})"
                    for c in self._cells
                    if c.state == _BLOCKED
                ]
                self.failure = (
                    "deadlock: no runnable thread; blocked: "
                    + (", ".join(blocked) or "<none>")
                )
                return
            if len(self.decisions) >= self.max_steps:
                self.failure = (
                    f"schedule exceeded {self.max_steps} steps (livelock?)"
                )
                return
            chosen = self._choose(runnable)
            self.trace.append(
                f"step {len(self.decisions) - 1}: run {chosen.name} "
                f"(at {chosen.label}; runnable "
                f"{[c.name for c in runnable]})"
            )
            with self._cv:
                self._last = chosen
                self._current = chosen
                self._cv.notify_all()
        # quiesced: thread errors, final check, then the race detectors
        for cell in self._cells:
            if cell.error is not None:
                self.failure = (
                    f"thread {cell.name} raised: {cell.error!r}"
                )
                return
        if self.scenario.final is not None:
            try:
                self._observe(self.scenario.final, state)
            except AssertionError as exc:
                self.failure = f"final check failed: {exc}"
                return
        det = self.detector
        if det is not None and not det.clean():
            self.failure = "race detected: " + "; ".join(det.report_lines())

    def _observe(self, check: Callable[[object], None], state: object) -> None:
        """Run an invariant/final check on the orchestrator thread with
        its detector bookkeeping QUARANTINED: the check may take
        component locks, and without the quarantine the orchestrator's
        cumulative vector clock would flow through every lock it
        touches, fabricating happens-before (and acquisition-graph)
        edges between scenario threads that mask real races."""
        det = self.detector
        if det is not None:
            det.quarantine_current_thread(True)
        try:
            check(state)
        finally:
            if det is not None:
                det.quarantine_current_thread(False)

    def _shutdown(self) -> None:
        """Unwind any still-live controlled threads (abandoned run)."""
        with self._cv:
            self._abort = True
            for c in self._cells:
                if c.state in (_READY, _BLOCKED):
                    c.state = _READY
            self._cv.notify_all()
        deadline_tries = 0
        for cell in self._cells:
            while cell.thread is not None and cell.thread.is_alive():
                with self._cv:
                    if cell.state == _DONE:
                        break
                    self._current = cell
                    self._cv.notify_all()
                cell.thread.join(timeout=0.05)
                deadline_tries += 1
                if deadline_tries > 200:
                    return  # daemon threads; give up rather than hang


# ---------------------------------------------------------------------------
# Exploration
# ---------------------------------------------------------------------------


def _preemption_count(decisions, upto: int,
                      alt: Optional[Tuple[int, int]] = None) -> int:
    """Preemptions in ``decisions[:upto]`` (+ one hypothetical ``alt`` =
    (step, idx)): a preemption is choosing a thread other than the one
    that was running while that one was still runnable."""
    count = 0
    for step, (idx, _n, default_idx, had_last) in enumerate(decisions[:upto]):
        if alt is not None and step == alt[0]:
            idx = alt[1]
        if had_last and idx != default_idx:
            count += 1
    return count


def explore(
    scenario: Scenario,
    max_schedules: int = 200,
    max_preemptions: int = 2,
    seed: int = 0,
    max_steps: int = DEFAULT_MAX_STEPS,
) -> ExploreResult:
    """Systematically explore interleavings of ``scenario``.

    Phase 1 (DFS): starting from the default schedule, branch on every
    untried decision whose preemption count stays within
    ``max_preemptions``.  Phase 2 (random): spend any remaining budget
    on seeded random schedules.  Stops at the first violation."""
    result = ExploreResult(
        scenario=scenario.name, schedules=0, decisions=0,
        max_preemptions=max_preemptions,
    )
    stack: List[List[int]] = [[]]
    visited = {()}
    rng_master = random.Random(seed)
    schedule_index = 0
    while schedule_index < max_schedules:
        if stack:
            forced = stack.pop()
            rng = None
        else:
            # hybrid tail: seeded random walks
            forced = []
            rng = random.Random(rng_master.randrange(2**63))
        run = _Run(scenario, forced, rng, max_steps)
        run.execute()
        result.schedules += 1
        result.decisions += len(run.decisions)
        if run.failure is not None:
            result.violation = Counterexample(
                reason=run.failure,
                schedule=tuple(d[0] for d in run.decisions),
                trace=tuple(run.trace),
                schedule_index=schedule_index,
            )
            return result
        if rng is None:
            # enqueue untried siblings along this run, deepest-first so
            # the DFS stays DFS-shaped
            for step in range(len(run.decisions) - 1, len(forced) - 1, -1):
                idx, n, _default_idx, had_last = run.decisions[step]
                for alt in range(n):
                    if alt == idx:
                        continue
                    if had_last and _preemption_count(
                        run.decisions, step + 1, (step, alt)
                    ) > max_preemptions:
                        continue
                    prefix = [d[0] for d in run.decisions[:step]]
                    prefix.append(alt)
                    key = tuple(prefix)
                    if key not in visited:
                        visited.add(key)
                        stack.append(prefix)
        schedule_index += 1
    return result


def replay(
    scenario: Scenario,
    schedule: Sequence[int],
    max_steps: int = DEFAULT_MAX_STEPS,
) -> Optional[Counterexample]:
    """Deterministically re-run one schedule (e.g. a counterexample's);
    returns the reproduced Counterexample, or None if it runs clean."""
    run = _Run(scenario, list(schedule), None, max_steps)
    run.execute()
    if run.failure is None:
        return None
    return Counterexample(
        reason=run.failure,
        schedule=tuple(d[0] for d in run.decisions),
        trace=tuple(run.trace),
        schedule_index=0,
    )


# ---------------------------------------------------------------------------
# CLI: run the scenario corpus (CI's model-check lane)
# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import json as _json
    import sys as _sys

    parser = argparse.ArgumentParser(
        prog="python -m k8s_spark_scheduler_tpu.analysis.modelcheck",
        description="explore thread interleavings of the scheduler's "
        "guarded components and fail on any invariant violation, "
        "deadlock, or race on any schedule",
    )
    parser.add_argument("--schedules", type=int, default=1000,
                        help="schedules to explore per scenario")
    parser.add_argument("--preemptions", type=int, default=2,
                        help="DFS preemption bound")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--scenario", default=None,
                        help="run one scenario by name (default: all)")
    parser.add_argument("--json", default=None, metavar="FILE",
                        help="write a machine-readable summary")
    args = parser.parse_args(argv)

    from .mcscenarios import corpus

    scenarios = corpus()
    if args.scenario is not None:
        scenarios = [s for s in scenarios if s.name == args.scenario]
        if not scenarios:
            print(f"unknown scenario: {args.scenario}", file=_sys.stderr)
            return 2

    summaries = []
    failed = False
    for sc in scenarios:
        res = explore(
            sc, max_schedules=args.schedules,
            max_preemptions=args.preemptions, seed=args.seed,
        )
        status = "ok" if res.ok else "VIOLATION"
        print(
            f"{sc.name:32s} {status:10s} "
            f"schedules={res.schedules} decisions={res.decisions}"
        )
        if not res.ok:
            failed = True
            print(str(res.violation))
        summaries.append({
            "scenario": sc.name,
            "ok": res.ok,
            "schedules": res.schedules,
            "decisions": res.decisions,
            "violation": (
                None if res.ok else {
                    "reason": res.violation.reason,
                    "schedule": list(res.violation.schedule),
                    "trace": list(res.violation.trace),
                }
            ),
        })
    if args.json:
        with open(args.json, "w") as f:
            _json.dump(
                {"seed": args.seed, "schedules": args.schedules,
                 "preemptions": args.preemptions, "results": summaries},
                f, indent=2, sort_keys=True,
            )
    return 1 if failed else 0


if __name__ == "__main__":
    import sys as _sys

    # dispatch through the CANONICAL module so every class/TLS the
    # scenarios touch is the same object this run uses (python -m loads
    # this file as __main__ AND as the package module)
    from k8s_spark_scheduler_tpu.analysis.modelcheck import main as _canonical_main

    _sys.exit(_canonical_main())
