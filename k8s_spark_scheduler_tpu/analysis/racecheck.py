"""Runtime race detection: Eraser lockset + FastTrack-style vector
clocks + a lock-order recorder, over the ``@guarded_by`` registry.

Static lock-discipline rules (LK*) catch mutations that are *lexically*
outside the declared ``with lock:`` scope; this module catches what the
AST cannot: a mutation reached on a path where the lock genuinely is
not held, accesses on two threads with no *ordering* between them, and
lock acquisition orders that could deadlock.

Two detectors run over the same instrumentation checkpoints:

- **Lockset** (Savage et al., "Eraser", SOSP '97): every
  :func:`guarded_by <.guarded.guarded_by>`-decorated class built while
  the detector is active gets its lock attribute wrapped in a
  :class:`TrackedLock` proxy that maintains a per-thread held-lock set;
  mutation sites call :func:`note_access`, which intersects the
  candidate lockset for ``(instance, field)`` with the locks currently
  held.  A field written by two or more threads with an empty candidate
  lockset is reported (state machine: virgin → exclusive(first thread)
  → shared → shared-modified, exactly Eraser's refinement so
  single-threaded init and read-sharing don't false-positive).
- **Happens-before** (Flanagan & Freund, "FastTrack", PLDI '09 —
  adapted to full vector clocks, which are cheap at checkpoint
  granularity): each thread carries a vector clock; release/acquire on
  any :class:`TrackedLock` creates an edge, as do thread start/join
  (hooked on ``threading.Thread``) and the explicit channel edges
  (:func:`hb_publish` / :func:`hb_observe`) that cover synchronization
  the lockset cannot express — ``ChangeFeed`` publish → sampler wakeup,
  ``IntentJournal`` persist → replay, ``ShardedUniqueQueue`` handoff.
  Two accesses to the same field, at least one a write, on different
  threads with neither ordered before the other is a **data race**, and
  the report carries *both* access sites.  The two detectors disagree in
  exactly the documented directions: a channel-synchronized handoff is
  lockset noise but HB-clean; an unsynchronized write→read pair is
  lockset-silent (Eraser only reports on shared-*modified*) but an HB
  race.

The acquisition-order graph is unchanged from PR 4: acquiring lock B
while holding lock A adds edge A→B; a pre-existing path B⇝A means a
lock-order cycle (potential deadlock), recorded with both lock names.

Enablement: ``SCHEDLINT_RACECHECK=1`` in the environment makes the test
harness and the sim runner call :func:`enable` before any guarded
instance is constructed; tests may also call :func:`enable` /
:func:`disable` directly.  When inactive, :func:`note_access` is a
single module-attribute read and a ``None`` check — cheap enough to
leave in the hot paths permanently (the perf guard pins this).

Instances constructed *before* the detector was enabled carry untracked
raw locks; their accesses are skipped (``_schedlint_tracked`` marker)
rather than misreported as lock-free.

The model checker (:mod:`.modelcheck`) reuses this instrumentation as
its preemption points: a cooperative scheduler hook installed via
:func:`set_sched_hook` is consulted at every tracked acquire/release
and every :func:`note_access` checkpoint, which is how small scenarios
get systematically interleaved without touching the code under test.
"""

from __future__ import annotations

import os
import sys
import threading
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

ENV_FLAG = "SCHEDLINT_RACECHECK"

# Eraser field states
_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MODIFIED = 3


def enabled_via_env() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0", "false")


@dataclass
class RaceReport:
    owner: str           # ClassName#n
    field: str
    threads: Tuple[str, ...]
    note: str

    def __str__(self) -> str:
        return (
            f"unprotected shared write: {self.owner}.{self.field} "
            f"written by {', '.join(self.threads)} with empty lockset ({self.note})"
        )


# (filename, lineno, function) of an instrumented access — the frame
# that called note_access, i.e. the mutation site itself
Site = Tuple[str, int, str]


def _fmt_site(site: Optional[Site]) -> str:
    if site is None:
        return "<unknown>"
    fn, line, func = site
    return f"{os.path.basename(fn)}:{line} in {func}"


@dataclass
class HbRaceReport:
    """A happens-before data race: two accesses to the same field, at
    least one a write, with no ordering edge between them.  Both access
    sites are carried so the report is actionable without a debugger."""

    owner: str
    field: str
    first_thread: str
    first_site: Optional[Site]
    first_write: bool
    second_thread: str
    second_site: Optional[Site]
    second_write: bool

    def __str__(self) -> str:
        def rw(w: bool) -> str:
            return "write" if w else "read"

        return (
            f"happens-before race: {self.owner}.{self.field} — "
            f"{rw(self.first_write)} by {self.first_thread} at "
            f"{_fmt_site(self.first_site)} unordered with "
            f"{rw(self.second_write)} by {self.second_thread} at "
            f"{_fmt_site(self.second_site)}"
        )


@dataclass
class LockOrderReport:
    edge: Tuple[str, str]      # the acquisition that closed the cycle
    cycle: Tuple[str, ...]     # lock names along the pre-existing path

    def __str__(self) -> str:
        a, b = self.edge
        return (
            f"lock-order cycle: acquiring {b} while holding {a}, but "
            f"{' -> '.join(self.cycle)} already recorded"
        )


class TrackedLock:
    """Proxy over a real ``Lock``/``RLock`` that maintains the calling
    thread's held-lock set, the global acquisition-order graph, and the
    release/acquire vector-clock edges.  Reentrant acquisitions (RLock)
    are counted so the held set stays accurate."""

    def __init__(self, inner, name: str, detector: "RaceDetector"):
        self._inner = inner
        self.name = name
        self._detector = detector
        self._counts = threading.local()

    # -- lock protocol --------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        hook = _sched_hook
        if hook is not None and hook.controls_current_thread():
            # cooperative mode: a preemption point, then spin-yield until
            # the non-blocking acquire succeeds (only one thread runs at
            # a time, so a real blocking acquire would deadlock the run)
            hook.preempt(f"acquire:{self.name}")
            while not self._inner.acquire(False):
                if not blocking:
                    return False
                hook.wait_for_lock(self)
            self._on_acquired()
            if self._depth() == 1:
                hook.lock_acquired(self)
            return True
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._on_acquired()
        return got

    def release(self) -> None:
        fully = self._on_release()
        self._inner.release()
        if fully:
            hook = _sched_hook
            if hook is not None and hook.controls_current_thread():
                hook.lock_released(self)
                hook.preempt(f"release:{self.name}")

    def __enter__(self):
        self.acquire()  # schedlint: disable=LK002 -- lock proxy: __exit__ is the paired release
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return inner_locked()
        # RLock grows .locked() only in Python 3.14; approximate: held by
        # this thread, else a non-blocking probe (net-zero, untracked)
        if self._depth() > 0:
            return True
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    # -- tracking -------------------------------------------------------------

    def _depth(self) -> int:
        return getattr(self._counts, "n", 0)

    def _on_acquired(self) -> None:
        n = self._depth()
        self._counts.n = n + 1
        if n == 0:  # outermost acquisition only
            self._detector._lock_acquired(self)

    def _on_release(self) -> bool:
        """True when this release drops the outermost hold."""
        n = self._depth()
        if n <= 1:
            self._counts.n = 0
            self._detector._lock_released(self)
            return True
        self._counts.n = n - 1
        return False


@dataclass
class _FieldState:
    state: int = _VIRGIN
    first_thread: Optional[int] = None
    lockset: Optional[FrozenSet[str]] = None   # None = universe (virgin)
    threads: Set[str] = field(default_factory=set)
    reported: bool = False


@dataclass
class _HbFieldState:
    """Per-field happens-before access history: the last write and the
    last read per thread token, each with its epoch and source site."""

    writes: Dict[int, Tuple[int, Optional[Site], str]] = field(default_factory=dict)
    reads: Dict[int, Tuple[int, Optional[Site], str]] = field(default_factory=dict)
    reported: bool = False


class _ThreadState:
    """Per-thread detector state (token, vector clock, held stack)."""

    __slots__ = ("token", "vc", "stack")

    def __init__(self, token: int):
        self.token = token
        self.vc: Dict[int, int] = {token: 1}
        self.stack: List[TrackedLock] = []


def _vc_join(dst: Dict[int, int], src: Dict[int, int]) -> None:
    for tok, epoch in src.items():
        if epoch > dst.get(tok, 0):
            dst[tok] = epoch


class RaceDetector:  # schedlint: disable=LK004 -- the detector cannot instrument itself: _mu guards its own bookkeeping
    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()           # .state → _ThreadState
        self._thread_seq = 0
        self._instances: Dict[int, str] = {}    # id(owner) → display name
        self._by_class_seq: Dict[str, int] = {}
        self._fields: Dict[Tuple[int, str], _FieldState] = {}
        self._hb_fields: Dict[Tuple[int, str], _HbFieldState] = {}
        self._edges: Dict[str, Set[str]] = {}   # lock name → successors
        # vector-clock state shared across threads (all under _mu).
        # _vc_by_token grows one small dict per thread that TOUCHED the
        # detector (threads that never do create no entry); lock VCs are
        # keyed WEAKLY by the TrackedLock itself so a churned guarded
        # instance's freed lock cannot hand its clock to an unrelated
        # new lock via id reuse (same rationale as the fork bookkeeping)
        self._vc_by_token: Dict[int, Dict[int, int]] = {}
        self._lock_vcs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._channel_vcs: Dict[object, Dict[int, int]] = {}
        # fork/join bookkeeping is keyed WEAKLY by the Thread object:
        # a started-but-never-joined thread that never touches the
        # detector (one per HTTP connection under ThreadingHTTPServer)
        # must not pin a vector-clock copy forever, and id()-keying
        # would let a recycled id hand a dead thread's parent clock to
        # an unrelated new thread, fabricating ordering edges
        self._fork_vcs: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self._token_by_thread: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        self.races: List[RaceReport] = []
        self.hb_races: List[HbRaceReport] = []
        self.lock_order_violations: List[LockOrderReport] = []
        _install_thread_hooks()

    # -- per-thread state -----------------------------------------------------

    def _thread_state(self) -> _ThreadState:
        """The calling thread's state; created on first use.  Tokens are
        unique and never recycled (OS thread idents from
        ``threading.get_ident()`` ARE recycled once a thread exits — a
        fast first writer's ident can be reused by the second, making a
        two-thread race look single-threaded).  Creation consumes any
        pending fork edge recorded by the ``Thread.start`` hook, so a
        child's first access is ordered after everything its parent did
        before starting it."""
        st = getattr(self._tls, "state", None)
        if st is None:
            cur = threading.current_thread()
            with self._mu:
                self._thread_seq += 1
                st = _ThreadState(self._thread_seq)
                parent_vc = self._fork_vcs.pop(cur, None)
                if parent_vc is not None:
                    _vc_join(st.vc, parent_vc)
                self._vc_by_token[st.token] = st.vc
                self._token_by_thread[cur] = st.token
            self._tls.state = st
        return st

    def _held_stack(self) -> List[TrackedLock]:
        return self._thread_state().stack

    def held_lock_names(self) -> FrozenSet[str]:
        return frozenset(lk.name for lk in self._held_stack())

    def _thread_token(self) -> int:
        return self._thread_state().token

    # -- thread start/join edges ----------------------------------------------

    def _on_thread_start(self, thread: threading.Thread) -> None:
        st = self._thread_state()
        with self._mu:
            # child inherits everything the parent has done so far …
            self._fork_vcs[thread] = dict(st.vc)
            # … and the parent's subsequent work is NOT ordered before it
            st.vc[st.token] += 1

    def _on_thread_join(self, thread: threading.Thread) -> None:
        st = self._thread_state()
        with self._mu:
            token = self._token_by_thread.get(thread)
            if token is not None:
                child_vc = self._vc_by_token.get(token)
                if child_vc is not None:
                    _vc_join(st.vc, child_vc)
            # joining a thread that never touched the detector: no edge
            # needed — it has no recorded accesses to order against
            self._fork_vcs.pop(thread, None)

    # -- lock bookkeeping -----------------------------------------------------

    def _quarantined(self) -> bool:
        """True while the calling thread's detector bookkeeping is
        suspended.  The model checker quarantines its ORCHESTRATOR
        thread around scenario invariant/final calls: those may take
        component locks, and without the quarantine the orchestrator's
        cumulative clock would flow through every lock it touches,
        fabricating happens-before edges BETWEEN scenario threads (and
        acquisition-graph edges the scenario never forms) that mask the
        very races the run exists to find."""
        return getattr(self._tls, "quarantined", False)

    def quarantine_current_thread(self, flag: bool) -> None:
        self._tls.quarantined = flag

    def _lock_acquired(self, lock: TrackedLock) -> None:
        st = self._thread_state()
        if st.stack and not self._quarantined():
            self._record_edge(st.stack[-1].name, lock.name)
        st.stack.append(lock)
        with self._mu:
            if self._quarantined():
                return
            lock_vc = self._lock_vcs.get(lock)
            if lock_vc is not None:
                _vc_join(st.vc, lock_vc)

    def _lock_released(self, lock: TrackedLock) -> None:
        st = self._thread_state()
        # locks are almost always released LIFO; tolerate out-of-order
        for i in range(len(st.stack) - 1, -1, -1):
            if st.stack[i] is lock:
                del st.stack[i]
                break
        with self._mu:
            if self._quarantined():
                return
            lock_vc = self._lock_vcs.setdefault(lock, {})
            _vc_join(lock_vc, st.vc)
            st.vc[st.token] += 1

    def _record_edge(self, held: str, acquiring: str) -> None:
        if held == acquiring:
            return
        with self._mu:
            succs = self._edges.setdefault(held, set())
            if acquiring in succs:
                return
            # does a path acquiring ⇝ held already exist?  Then this
            # acquisition closes a cycle.
            path = self._find_path(acquiring, held)
            succs.add(acquiring)
            if path is not None:
                self.lock_order_violations.append(
                    LockOrderReport(edge=(held, acquiring), cycle=tuple(path))
                )

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        # iterative DFS over the (small) acquisition graph; caller holds _mu
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- explicit happens-before channels -------------------------------------

    def channel_publish(self, channel) -> None:
        """Order everything this thread has done so far before any
        subsequent :meth:`channel_observe` of the same channel."""
        if self._quarantined():
            return
        st = self._thread_state()
        with self._mu:
            ch = self._channel_vcs.setdefault(channel, {})
            _vc_join(ch, st.vc)
            st.vc[st.token] += 1

    def channel_observe(self, channel) -> None:
        """Join every prior publish of ``channel`` into this thread."""
        if self._quarantined():
            return
        st = self._thread_state()
        with self._mu:
            ch = self._channel_vcs.get(channel)
            if ch is not None:
                _vc_join(st.vc, ch)

    def channel_snapshot(self) -> Tuple["RaceDetector", Dict[int, int]]:
        """Capture the calling thread's clock as a detector-tagged
        snapshot.  Carried inside a handed-off item (a queue closure)
        and joined by the consumer via :meth:`join_snapshot`, the edge
        exists exactly iff the handoff happened — a failed non-blocking
        put simply drops the snapshot, so it can never order (and
        thereby hide) a genuinely racing access pair, and a successful
        one is visible to the consumer the instant the item is."""
        st = self._thread_state()
        with self._mu:
            snap = dict(st.vc)
            st.vc[st.token] += 1
        return (self, snap)

    def join_snapshot(self, snapshot: Tuple["RaceDetector", Dict[int, int]]) -> None:
        origin, snap = snapshot
        if origin is not self:
            # produced under a different detector: its tokens are
            # meaningless (and may collide) here — no edge
            return
        st = self._thread_state()
        with self._mu:
            _vc_join(st.vc, snap)

    # -- instance registration ------------------------------------------------

    def track_extra_lock(self, owner: object, lock_attr: str) -> None:
        """Wrap an auxiliary lock attribute (one beyond the class's
        ``@guarded_by`` declaration, e.g. a sample mutex) in a
        TrackedLock so it participates in HB edges, the lock-order
        graph, and — critically — the model checker's cooperative
        scheduling.  Used by model-check scenarios; production code
        never needs it."""
        inner = getattr(owner, lock_attr, None)
        if inner is None or isinstance(inner, TrackedLock):
            return
        name = f"{type(owner).__name__}.{lock_attr}"
        object.__setattr__(owner, lock_attr, TrackedLock(inner, name, self))

    def register_instance(self, owner: object, cls: type, lock_attr: str) -> None:
        """Wrap ``owner.<lock_attr>`` in a TrackedLock (once) and mark
        the instance as instrumented."""
        inner = getattr(owner, lock_attr, None)
        if inner is None or isinstance(inner, TrackedLock):
            return
        with self._mu:
            seq = self._by_class_seq.get(cls.__name__, 0)
            self._by_class_seq[cls.__name__] = seq + 1
        name = f"{cls.__name__}.{lock_attr}#{seq}"
        object.__setattr__(owner, lock_attr, TrackedLock(inner, name, self))
        self._instances[id(owner)] = f"{cls.__name__}#{seq}"
        object.__setattr__(owner, "_schedlint_tracked", True)

    # -- the access checkpoint -----------------------------------------------

    @staticmethod
    def _caller_site() -> Optional[Site]:
        """The first frame outside this module — the mutation site."""
        try:
            fr = sys._getframe(2)
            while fr is not None and fr.f_code.co_filename == __file__:
                fr = fr.f_back
            if fr is None:
                return None
            return (fr.f_code.co_filename, fr.f_lineno, fr.f_code.co_name)
        except Exception:
            return None

    def record_access(self, owner: object, fieldname: str, write: bool) -> None:
        if not getattr(owner, "_schedlint_tracked", False):
            return
        if id(owner) not in self._instances:
            # instrumented by a DIFFERENT detector instance: its lock
            # reports to that detector's held stacks, so judging it
            # against this one's (empty) stacks would fabricate races
            return
        if self._quarantined():
            return
        st = self._thread_state()
        held = frozenset(lk.name for lk in st.stack)
        tid = st.token
        tname = threading.current_thread().name
        site = self._caller_site()
        key = (id(owner), fieldname)
        with self._mu:
            self._lockset_check(key, tid, tname, held, write, owner)
            self._hb_check(key, st, tname, write, site, owner)
        hook = _sched_hook
        if hook is not None and hook.controls_current_thread():
            hook.preempt(f"access:{fieldname}")

    def _lockset_check(self, key, tid, tname, held, write, owner) -> None:
        # caller holds _mu
        st = self._fields.setdefault(key, _FieldState())
        st.threads.add(tname)
        if st.state == _VIRGIN:
            st.state = _EXCLUSIVE
            st.first_thread = tid
            st.lockset = held
            return
        st.lockset = (st.lockset & held) if st.lockset is not None else held
        if st.state == _EXCLUSIVE:
            if tid == st.first_thread:
                return
            st.state = _SHARED_MODIFIED if write else _SHARED
        elif st.state == _SHARED and write:
            st.state = _SHARED_MODIFIED
        if st.state == _SHARED_MODIFIED and not st.lockset and not st.reported:
            st.reported = True
            self.races.append(
                RaceReport(
                    owner=self._instances.get(id(owner), type(owner).__name__),
                    field=key[1],
                    threads=tuple(sorted(st.threads)),
                    note="candidate lockset became empty",
                )
            )

    def _hb_check(self, key, st: _ThreadState, tname, write, site, owner) -> None:
        # caller holds _mu.  Race iff a prior conflicting access by
        # another thread is NOT ordered before this one: its epoch
        # exceeds this thread's vector-clock entry for that thread.
        hb = self._hb_fields.setdefault(key, _HbFieldState())
        tid = st.token
        epoch = st.vc[tid]
        if not hb.reported:
            conflicting = [(u, e, True) for u, e in hb.writes.items()]
            if write:
                conflicting += [(u, e, False) for u, e in hb.reads.items()]
            for utok, (uepoch, usite, uname), is_prior_write in conflicting:
                if utok == tid or uepoch <= st.vc.get(utok, 0):
                    continue
                hb.reported = True
                self.hb_races.append(
                    HbRaceReport(
                        owner=self._instances.get(id(owner), type(owner).__name__),
                        field=key[1],
                        first_thread=uname,
                        first_site=usite,
                        first_write=is_prior_write,
                        second_thread=tname,
                        second_site=site,
                        second_write=write,
                    )
                )
                break
        if write:
            hb.writes[tid] = (epoch, site, tname)
            # a write supersedes this thread's read entry (the write
            # conflicts with strictly more than the read did)
            hb.reads.pop(tid, None)
        else:
            hb.reads[tid] = (epoch, site, tname)

    # -- reporting ------------------------------------------------------------

    def clean(self) -> bool:
        return (
            not self.races
            and not self.hb_races
            and not self.lock_order_violations
        )

    def report_lines(self) -> List[str]:
        return (
            [str(r) for r in self.races]
            + [str(r) for r in self.hb_races]
            + [str(v) for v in self.lock_order_violations]
        )


# -- module-level switchboard -------------------------------------------------

_active: Optional[RaceDetector] = None

# cooperative-scheduler hook (the model checker).  The contract is tiny:
#   controls_current_thread() -> bool   — is this thread under control?
#   preempt(label)                      — a scheduling point
#   wait_for_lock(tracked_lock)         — yield until the lock may be free
#   lock_acquired(tracked_lock)         — a controlled thread now holds it
#   lock_released(tracked_lock)         — a controlled thread released it
_sched_hook: Optional[Any] = None


def set_sched_hook(hook) -> None:
    """Install (or clear, with ``None``) the cooperative scheduler hook
    consulted at every tracked acquire/release and access checkpoint.
    Only the model checker should ever set this."""
    global _sched_hook
    _sched_hook = hook


def active() -> bool:
    return _active is not None


def get() -> Optional[RaceDetector]:
    return _active


def enable(detector: Optional[RaceDetector] = None) -> RaceDetector:
    """Install ``detector`` (or a fresh one) as the process-wide race
    detector.  Idempotent: enabling while active keeps the existing
    detector unless a new one is passed explicitly."""
    global _active
    if detector is not None:
        _active = detector
    elif _active is None:
        _active = RaceDetector()
    return _active


def disable() -> Optional[RaceDetector]:
    """Deactivate and return the detector (for post-run assertions)."""
    global _active
    d, _active = _active, None
    return d


def enable_if_env() -> Optional[RaceDetector]:
    """Harness/sim hook: enable when ``SCHEDLINT_RACECHECK`` is set."""
    return enable() if enabled_via_env() else None


def instrument_instance(owner: object, cls: type, lock_attr: str) -> None:
    d = _active
    if d is not None:
        d.register_instance(owner, cls, lock_attr)


def note_access(owner: object, fieldname: str, write: bool = True) -> None:
    """Instrumentation checkpoint placed inside shared-state mutators.
    Near-zero cost while the detector is inactive."""
    d = _active
    if d is not None:
        d.record_access(owner, fieldname, write)


def track_extra_lock(owner: object, lock_attr: str) -> None:
    """Module-level convenience for :meth:`RaceDetector.track_extra_lock`."""
    d = _active
    if d is not None:
        d.track_extra_lock(owner, lock_attr)


# the model checker's per-thread run registry: hosted HERE (a module
# that is only ever loaded once) so ``python -m …analysis.modelcheck``
# — which loads modelcheck.py twice, as __main__ and canonically — has
# one registry, not two (see modelcheck._run_tls)
_modelcheck_run_tls = threading.local()

_channel_seq = __import__("itertools").count(1)


def channel_token() -> int:
    """Process-unique id for building happens-before channel keys.
    Prefer ``("kind", channel_token())`` captured at ``__init__`` over
    ``("kind", id(self))``: object ids are recycled, and a recycled id
    would hand a dead channel's clock to an unrelated new object,
    fabricating ordering edges."""
    return next(_channel_seq)


def hb_publish(channel) -> None:
    """Record a happens-before *publish* on ``channel`` (any hashable):
    everything the calling thread did so far is ordered before any
    subsequent :func:`hb_observe` of the same channel.  Place this at
    the sending side of synchronization the lock tracker cannot see —
    an ``Event.set``, a queue put, a durable-file append."""
    d = _active
    if d is not None:
        d.channel_publish(channel)


def hb_observe(channel) -> None:
    """The receiving side of :func:`hb_publish`: joins every prior
    publish of ``channel`` into the calling thread's clock."""
    d = _active
    if d is not None:
        d.channel_observe(channel)


def hb_snapshot():
    """Capture the calling thread's clock for an item-carried handoff
    edge: stash the result inside whatever is handed to the consumer (a
    queue closure), and have the consumer call :func:`hb_join` on it.
    Unlike a channel publish, the edge exists exactly iff the handoff
    happened — a failed non-blocking put just drops the snapshot."""
    d = _active
    if d is not None:
        return d.channel_snapshot()
    return None


def hb_join(snapshot) -> None:
    """Consumer side of :func:`hb_snapshot`: join the producer's
    captured clock into the calling thread."""
    d = _active
    if d is not None and snapshot is not None:
        d.join_snapshot(snapshot)


# -- threading.Thread start/join hooks ---------------------------------------
#
# Installed once, on first detector construction; the wrappers cost one
# module-attribute read when no detector is active, mirroring
# note_access's disabled cost.  They give the HB detector its fork/join
# edges without requiring scenarios to call anything.

_thread_hooks_installed = False


def _install_thread_hooks() -> None:
    global _thread_hooks_installed
    if _thread_hooks_installed:
        return
    _thread_hooks_installed = True
    orig_start = threading.Thread.start
    orig_join = threading.Thread.join

    def start(self, *args, **kwargs):
        d = _active
        if d is not None:
            d._on_thread_start(self)
        return orig_start(self, *args, **kwargs)

    def join(self, *args, **kwargs):
        result = orig_join(self, *args, **kwargs)
        d = _active
        if d is not None and not self.is_alive():
            d._on_thread_join(self)
        return result

    start.__wrapped__ = orig_start  # type: ignore[attr-defined]
    join.__wrapped__ = orig_join    # type: ignore[attr-defined]
    threading.Thread.start = start  # type: ignore[method-assign]
    threading.Thread.join = join    # type: ignore[method-assign]
