"""Eraser-style runtime lockset race detector + lock-order recorder.

Static lock-discipline rules (LK*) catch mutations that are *lexically*
outside the declared ``with lock:`` scope; this module catches what the
AST cannot: a mutation reached on a path where the lock genuinely is
not held, and lock acquisition orders that could deadlock.

The classic lockset algorithm (Savage et al., "Eraser", SOSP '97),
adapted to instrumented checkpoints instead of binary instrumentation:

- every :func:`guarded_by <.guarded.guarded_by>`-decorated class built
  while the detector is active gets its lock attribute wrapped in a
  :class:`TrackedLock` proxy that maintains a per-thread held-lock set
  and feeds the lock-order graph;
- mutation sites in the shared-state hot paths call
  :func:`note_access`, which intersects the candidate lockset for
  ``(instance, field)`` with the locks currently held;
- a field that has been written by two or more threads with an empty
  candidate lockset is reported as a race (state machine:
  virgin → exclusive(first thread) → shared → shared-modified, exactly
  Eraser's refinement so single-threaded init and read-sharing don't
  false-positive);
- acquiring lock B while holding lock A adds edge A→B to a global
  acquisition graph; a path B⇝A already present means a lock-order
  cycle (potential deadlock) and is recorded with both stacks' lock
  names.

Enablement: ``SCHEDLINT_RACECHECK=1`` in the environment makes the test
harness and the sim runner call :func:`enable` before any guarded
instance is constructed; tests may also call :func:`enable` /
:func:`disable` directly.  When inactive, :func:`note_access` is a
single module-attribute read and a ``None`` check — cheap enough to
leave in the hot paths permanently.

Instances constructed *before* the detector was enabled carry untracked
raw locks; their accesses are skipped (``_schedlint_tracked`` marker)
rather than misreported as lock-free.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

ENV_FLAG = "SCHEDLINT_RACECHECK"

# Eraser field states
_VIRGIN = 0
_EXCLUSIVE = 1
_SHARED = 2
_SHARED_MODIFIED = 3


def enabled_via_env() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0", "false")


@dataclass
class RaceReport:
    owner: str           # ClassName#n
    field: str
    threads: Tuple[str, ...]
    note: str

    def __str__(self) -> str:
        return (
            f"unprotected shared write: {self.owner}.{self.field} "
            f"written by {', '.join(self.threads)} with empty lockset ({self.note})"
        )


@dataclass
class LockOrderReport:
    edge: Tuple[str, str]      # the acquisition that closed the cycle
    cycle: Tuple[str, ...]     # lock names along the pre-existing path

    def __str__(self) -> str:
        a, b = self.edge
        return (
            f"lock-order cycle: acquiring {b} while holding {a}, but "
            f"{' -> '.join(self.cycle)} already recorded"
        )


class TrackedLock:
    """Proxy over a real ``Lock``/``RLock`` that maintains the calling
    thread's held-lock set and the global acquisition-order graph.
    Reentrant acquisitions (RLock) are counted so the held set stays
    accurate."""

    def __init__(self, inner, name: str, detector: "RaceDetector"):
        self._inner = inner
        self.name = name
        self._detector = detector
        self._counts = threading.local()

    # -- lock protocol --------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._on_acquired()
        return got

    def release(self) -> None:
        self._on_release()
        self._inner.release()

    def __enter__(self):
        self.acquire()  # schedlint: disable=LK002 -- lock proxy: __exit__ is the paired release
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return inner_locked()
        # RLock grows .locked() only in Python 3.14; approximate: held by
        # this thread, else a non-blocking probe (net-zero, untracked)
        if self._depth() > 0:
            return True
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    # -- tracking -------------------------------------------------------------

    def _depth(self) -> int:
        return getattr(self._counts, "n", 0)

    def _on_acquired(self) -> None:
        n = self._depth()
        self._counts.n = n + 1
        if n == 0:  # outermost acquisition only
            self._detector._lock_acquired(self)

    def _on_release(self) -> None:
        n = self._depth()
        if n <= 1:
            self._counts.n = 0
            self._detector._lock_released(self)
        else:
            self._counts.n = n - 1


@dataclass
class _FieldState:
    state: int = _VIRGIN
    first_thread: Optional[int] = None
    lockset: Optional[FrozenSet[str]] = None   # None = universe (virgin)
    threads: Set[str] = field(default_factory=set)
    reported: bool = False


class RaceDetector:
    def __init__(self):
        self._mu = threading.Lock()
        self._held = threading.local()          # per-thread list of TrackedLock
        self._thread_seq = 0
        self._instances: Dict[int, str] = {}    # id(owner) → display name
        self._by_class_seq: Dict[str, int] = {}
        self._fields: Dict[Tuple[int, str], _FieldState] = {}
        self._edges: Dict[str, Set[str]] = {}   # lock name → successors
        self.races: List[RaceReport] = []
        self.lock_order_violations: List[LockOrderReport] = []

    # -- lock bookkeeping -----------------------------------------------------

    def _held_stack(self) -> List[TrackedLock]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def held_lock_names(self) -> FrozenSet[str]:
        return frozenset(lk.name for lk in self._held_stack())

    def _thread_token(self) -> int:
        """Unique, never-recycled id for the calling thread.  (OS thread
        idents from ``threading.get_ident()`` ARE recycled once a thread
        exits — a fast first writer's ident can be reused by the second,
        making a two-thread race look single-threaded.)"""
        token = getattr(self._held, "token", None)
        if token is None:
            with self._mu:
                self._thread_seq += 1
                token = self._thread_seq
            self._held.token = token
        return token

    def _lock_acquired(self, lock: TrackedLock) -> None:
        stack = self._held_stack()
        if stack:
            self._record_edge(stack[-1].name, lock.name)
        stack.append(lock)

    def _lock_released(self, lock: TrackedLock) -> None:
        stack = self._held_stack()
        # locks are almost always released LIFO; tolerate out-of-order
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    def _record_edge(self, held: str, acquiring: str) -> None:
        if held == acquiring:
            return
        with self._mu:
            succs = self._edges.setdefault(held, set())
            if acquiring in succs:
                return
            # does a path acquiring ⇝ held already exist?  Then this
            # acquisition closes a cycle.
            path = self._find_path(acquiring, held)
            succs.add(acquiring)
            if path is not None:
                self.lock_order_violations.append(
                    LockOrderReport(edge=(held, acquiring), cycle=tuple(path))
                )

    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        # iterative DFS over the (small) acquisition graph; caller holds _mu
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._edges.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- instance registration ------------------------------------------------

    def register_instance(self, owner: object, cls: type, lock_attr: str) -> None:
        """Wrap ``owner.<lock_attr>`` in a TrackedLock (once) and mark
        the instance as instrumented."""
        inner = getattr(owner, lock_attr, None)
        if inner is None or isinstance(inner, TrackedLock):
            return
        with self._mu:
            seq = self._by_class_seq.get(cls.__name__, 0)
            self._by_class_seq[cls.__name__] = seq + 1
        name = f"{cls.__name__}.{lock_attr}#{seq}"
        object.__setattr__(owner, lock_attr, TrackedLock(inner, name, self))
        self._instances[id(owner)] = f"{cls.__name__}#{seq}"
        object.__setattr__(owner, "_schedlint_tracked", True)

    # -- the lockset algorithm ------------------------------------------------

    def record_access(self, owner: object, fieldname: str, write: bool) -> None:
        if not getattr(owner, "_schedlint_tracked", False):
            return
        if id(owner) not in self._instances:
            # instrumented by a DIFFERENT detector instance: its lock
            # reports to that detector's held stacks, so judging it
            # against this one's (empty) stacks would fabricate races
            return
        held = self.held_lock_names()
        tid = self._thread_token()
        tname = threading.current_thread().name
        key = (id(owner), fieldname)
        with self._mu:
            st = self._fields.setdefault(key, _FieldState())
            st.threads.add(tname)
            if st.state == _VIRGIN:
                st.state = _EXCLUSIVE
                st.first_thread = tid
                st.lockset = held
                return
            st.lockset = (st.lockset & held) if st.lockset is not None else held
            if st.state == _EXCLUSIVE:
                if tid == st.first_thread:
                    return
                st.state = _SHARED_MODIFIED if write else _SHARED
            elif st.state == _SHARED and write:
                st.state = _SHARED_MODIFIED
            if st.state == _SHARED_MODIFIED and not st.lockset and not st.reported:
                st.reported = True
                self.races.append(
                    RaceReport(
                        owner=self._instances.get(id(owner), type(owner).__name__),
                        field=fieldname,
                        threads=tuple(sorted(st.threads)),
                        note="candidate lockset became empty",
                    )
                )

    # -- reporting ------------------------------------------------------------

    def clean(self) -> bool:
        return not self.races and not self.lock_order_violations

    def report_lines(self) -> List[str]:
        return [str(r) for r in self.races] + [
            str(v) for v in self.lock_order_violations
        ]


# -- module-level switchboard -------------------------------------------------

_active: Optional[RaceDetector] = None


def active() -> bool:
    return _active is not None


def get() -> Optional[RaceDetector]:
    return _active


def enable(detector: Optional[RaceDetector] = None) -> RaceDetector:
    """Install ``detector`` (or a fresh one) as the process-wide race
    detector.  Idempotent: enabling while active keeps the existing
    detector unless a new one is passed explicitly."""
    global _active
    if detector is not None:
        _active = detector
    elif _active is None:
        _active = RaceDetector()
    return _active


def disable() -> Optional[RaceDetector]:
    """Deactivate and return the detector (for post-run assertions)."""
    global _active
    d, _active = _active, None
    return d


def enable_if_env() -> Optional[RaceDetector]:
    """Harness/sim hook: enable when ``SCHEDLINT_RACECHECK`` is set."""
    return enable() if enabled_via_env() else None


def instrument_instance(owner: object, cls: type, lock_attr: str) -> None:
    d = _active
    if d is not None:
        d.register_instance(owner, cls, lock_attr)


def note_access(owner: object, fieldname: str, write: bool = True) -> None:
    """Instrumentation checkpoint placed inside shared-state mutators.
    Near-zero cost while the detector is inactive."""
    d = _active
    if d is not None:
        d.record_access(owner, fieldname, write)
