"""schedlint output: human text and machine JSON.

The JSON schema is **stable** — CI diffs findings between runs, so keys
are never renamed, only added (bump ``schema_version`` when they are).

.. code-block:: json

    {
      "schema_version": 1,
      "tool": "schedlint",
      "strict": false,
      "findings": [
        {"rule": "TS001", "category": "determinism", "file": "tracing/spans.py",
         "line": 118, "col": 8, "message": "...", "symbol": "Span.__enter__"}
      ],
      "counts": {"total": 1, "by_rule": {"TS001": 1}, "by_category": {"determinism": 1}},
      "suppressed": [
        {"rule": "TS002", "file": "util/locktime.py", "line": 40, "col": 8,
         "category": "determinism", "message": "...", "symbol": "...",
         "suppressed_via": "allowlist", "why": "monotonic deadline arithmetic"}
      ]
    }

``suppressed`` (added, schema unchanged: keys are only ever added) lists
every finding that an allowlist entry or pragma silenced, with the
justification.  CI diffs it against a committed baseline so a *new*
suppression — someone pragma-ing their way past a fresh finding — fails
review even though the findings list stays empty.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .core import Finding, SuppressedFinding

SCHEMA_VERSION = 1


def render_text(findings: List[Finding]) -> str:
    if not findings:
        return "schedlint: clean (0 findings)\n"
    lines = []
    for f in findings:
        where = f"{f.file}:{f.line}:{f.col}"
        sym = f" [{f.symbol}]" if f.symbol else ""
        lines.append(f"{where}: {f.rule} {f.message}{sym}")
    by_rule = _count_by(findings, "rule")
    summary = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
    lines.append(f"schedlint: {len(findings)} finding(s) ({summary})")
    return "\n".join(lines) + "\n"


def render_json(
    findings: List[Finding],
    strict: bool = False,
    suppressed: Optional[List[SuppressedFinding]] = None,
) -> str:
    doc = {
        "schema_version": SCHEMA_VERSION,
        "tool": "schedlint",
        "strict": strict,
        "findings": [f.to_dict() for f in findings],
        "counts": {
            "total": len(findings),
            "by_rule": _count_by(findings, "rule"),
            "by_category": _count_by(findings, "category"),
        },
        "suppressed": [s.to_dict() for s in (suppressed or [])],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def _count_by(findings: List[Finding], attr: str) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        key = getattr(f, attr)
        counts[key] = counts.get(key, 0) + 1
    return counts
