"""JX — tracer-safety rules for the JAX kernels in ``ops/``.

The binpack hot path is a set of ``@jax.jit`` kernels whose contracts
only surface as perf-guard regressions after the fact: a Python branch
on a traced value raises ``TracerBoolConversionError`` at runtime (or,
worse, silently retraces per call when the branched value happens to be
weakly-typed), a non-hashable static argument raises at dispatch, and a
closure over mutable module state bakes a stale snapshot into the
compiled executable.  These rules catch the known hazards at lint time.

A function is *jitted* when it is decorated with ``jax.jit`` /
``functools.partial(jax.jit, ...)`` or wrapped by a module-level
``name = jax.jit(fn)`` assignment.  Parameters named in
``static_argnames`` / positioned in ``static_argnums`` are *static*
(concrete at trace time) — branching on them is the supported idiom and
is never flagged.  Attribute reads that stay static under tracing
(``x.shape``, ``x.ndim``, ``x.dtype``, ``x.size``) are excluded.

Rules:

- **JX001** — ``if``/``while`` whose test reads a traced (non-static)
  parameter: concretizes the tracer; use ``jnp.where`` / ``lax.cond`` /
  ``lax.while_loop``.
- **JX002** — explicit concretization of a traced parameter:
  ``bool(x)``, ``int(x)``, ``float(x)``, or ``x.item()``.
- **JX003** — a jitted function reads module-level *mutable* state (a
  list/dict/set binding) or ``self`` attributes: the value is captured
  at trace time and silently goes stale — pass it as an argument.
- **JX004** — a static argument that cannot be hashed: a
  ``static_argnames`` parameter with a mutable default, or a same-module
  call site passing a list/dict/set literal for a static parameter.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import FileContext, Finding

_STATIC_SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}
_CONCRETIZERS = {"bool", "int", "float"}
_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "deque", "defaultdict", "OrderedDict"}


def _finding(ctx: FileContext, rule: str, node: ast.AST, message: str, symbol: str) -> Finding:
    return Finding(
        rule=rule,
        category="tracer-safety",
        file=ctx.relpath,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
        symbol=symbol,
    )


def _is_jax_jit(node: ast.AST) -> bool:
    """``jax.jit`` or bare ``jit`` (from-imported)."""
    if isinstance(node, ast.Attribute) and node.attr == "jit":
        return isinstance(node.value, ast.Name) and node.value.id == "jax"
    return isinstance(node, ast.Name) and node.id == "jit"


def _jit_decoration(deco: ast.AST) -> Optional[Tuple[Set[str], Set[int]]]:
    """(static_argnames, static_argnums) when ``deco`` is a jit
    decorator, else None."""
    if _is_jax_jit(deco):
        return set(), set()
    if isinstance(deco, ast.Call):
        # functools.partial(jax.jit, static_argnames=(...)) or jax.jit(...)
        target = None
        fn = deco.func
        if isinstance(fn, ast.Attribute) and fn.attr == "partial" or (
            isinstance(fn, ast.Name) and fn.id == "partial"
        ):
            if deco.args and _is_jax_jit(deco.args[0]):
                target = deco
        elif _is_jax_jit(fn):
            target = deco
        if target is None:
            return None
        names: Set[str] = set()
        nums: Set[int] = set()
        for kw in target.keywords:
            if kw.arg == "static_argnames":
                names |= _string_elements(kw.value)
            elif kw.arg == "static_argnums":
                nums |= _int_elements(kw.value)
        return names, nums
    return None


def _string_elements(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def _int_elements(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        return name in _MUTABLE_CTORS
    return False


class _ModuleIndex:
    """Module-level bindings + which function defs are jitted and how."""

    def __init__(self, tree: ast.Module):
        self.mutable_globals: Set[str] = set()
        self.functions: Dict[str, ast.FunctionDef] = {}
        # fn name → (static names, static nums); may be registered via a
        # decorator or a module-level `x = jax.jit(fn, ...)` wrapper
        self.jitted: Dict[str, Tuple[Set[str], Set[int]]] = {}
        # wrapper alias → wrapped fn name (solve_zones_jit = jax.jit(solve_zones))
        self.jit_aliases: Dict[str, str] = {}

        for stmt in tree.body:
            if isinstance(stmt, ast.FunctionDef):
                self.functions[stmt.name] = stmt
                for deco in stmt.decorator_list:
                    statics = _jit_decoration(deco)
                    if statics is not None:
                        self.jitted[stmt.name] = statics
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                value = stmt.value
                if value is None:
                    continue
                for t in targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if _is_mutable_literal(value):
                        self.mutable_globals.add(t.id)
                    if (
                        isinstance(value, ast.Call)
                        and _is_jax_jit(value.func)
                        and value.args
                        and isinstance(value.args[0], ast.Name)
                    ):
                        wrapped = value.args[0].id
                        self.jit_aliases[t.id] = wrapped
                        names: Set[str] = set()
                        nums: Set[int] = set()
                        for kw in value.keywords:
                            if kw.arg == "static_argnames":
                                names |= _string_elements(kw.value)
                            elif kw.arg == "static_argnums":
                                nums |= _int_elements(kw.value)
                        self.jitted.setdefault(wrapped, (names, nums))


def _param_names(fn: ast.FunctionDef) -> List[str]:
    args = fn.args
    return [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]


def _static_params(fn: ast.FunctionDef, statics: Tuple[Set[str], Set[int]]) -> Set[str]:
    names, nums = statics
    params = _param_names(fn)
    out = set(names)
    for i in nums:
        if 0 <= i < len(params):
            out.add(params[i])
    return out


class _JitBodyChecker(ast.NodeVisitor):
    """Checks one jitted function body for JX001/JX002/JX003."""

    def __init__(self, ctx: FileContext, fn: ast.FunctionDef, statics: Tuple[Set[str], Set[int]], index: _ModuleIndex):
        self.ctx = ctx
        self.fn = fn
        self.index = index
        self.static = _static_params(fn, statics)
        self.traced = set(_param_names(fn)) - self.static
        self.findings: List[Finding] = []
        self._locals: Set[str] = set(_param_names(fn))
        # pre-collect every name assigned anywhere in the body: reads of
        # those are locals (possibly defined later in a loop), not
        # closure captures
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                self._locals.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node is not fn:
                    self._locals.add(node.name)

    def symbol(self) -> str:
        return self.fn.name

    # -- JX001: control flow on traced values ---------------------------------

    def _traced_names_in_test(self, test: ast.AST) -> List[ast.Name]:
        hits: List[ast.Name] = []
        parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(test):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and node.id in self.traced:
                parent = parents.get(node)
                if (
                    isinstance(parent, ast.Attribute)
                    and parent.value is node
                    and parent.attr in _STATIC_SAFE_ATTRS
                ):
                    continue  # x.shape etc. — static under tracing
                hits.append(node)
        return hits

    def visit_If(self, node: ast.If) -> None:  # noqa: N802 (ast API)
        for name in self._traced_names_in_test(node.test):
            self.findings.append(_finding(
                self.ctx, "JX001", node,
                f"Python 'if' on traced value {name.id!r} inside jitted "
                f"{self.fn.name}() — use jnp.where/lax.cond or declare it "
                "static",
                self.symbol(),
            ))
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:  # noqa: N802
        for name in self._traced_names_in_test(node.test):
            self.findings.append(_finding(
                self.ctx, "JX001", node,
                f"Python 'while' on traced value {name.id!r} inside jitted "
                f"{self.fn.name}() — use lax.while_loop or declare it static",
                self.symbol(),
            ))
        self.generic_visit(node)

    # -- JX002: concretization calls ------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        fn = node.func
        if (
            isinstance(fn, ast.Name)
            and fn.id in _CONCRETIZERS
            and len(node.args) == 1
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in self.traced
        ):
            self.findings.append(_finding(
                self.ctx, "JX002", node,
                f"{fn.id}({node.args[0].id}) concretizes a traced value "
                f"inside jitted {self.fn.name}()",
                self.symbol(),
            ))
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr == "item"
            and isinstance(fn.value, ast.Name)
            and fn.value.id in self.traced
        ):
            self.findings.append(_finding(
                self.ctx, "JX002", node,
                f"{fn.value.id}.item() concretizes a traced value inside "
                f"jitted {self.fn.name}()",
                self.symbol(),
            ))
        self.generic_visit(node)

    # -- JX003: mutable closure capture ---------------------------------------

    def visit_Name(self, node: ast.Name) -> None:  # noqa: N802
        if (
            isinstance(node.ctx, ast.Load)
            and node.id not in self._locals
            and node.id in self.index.mutable_globals
        ):
            self.findings.append(_finding(
                self.ctx, "JX003", node,
                f"jitted {self.fn.name}() reads mutable module state "
                f"{node.id!r} — captured at trace time and silently stale "
                "afterwards; pass it as an argument",
                self.symbol(),
            ))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:  # noqa: N802
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and isinstance(node.ctx, ast.Load)
        ):
            self.findings.append(_finding(
                self.ctx, "JX003", node,
                f"jitted {self.fn.name}() reads self.{node.attr} — instance "
                "state is captured at trace time; pass it as an argument or "
                "mark the method static over a hashable self",
                self.symbol(),
            ))
        self.generic_visit(node)


def _check_static_defaults(ctx: FileContext, fn: ast.FunctionDef, statics: Tuple[Set[str], Set[int]]) -> List[Finding]:
    findings: List[Finding] = []
    static_names = _static_params(fn, statics)
    args = fn.args
    positional = args.posonlyargs + args.args
    defaults: List[Tuple[str, ast.AST]] = []
    for arg, default in zip(positional[len(positional) - len(args.defaults):], args.defaults):
        defaults.append((arg.arg, default))
    for arg, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is not None:
            defaults.append((arg.arg, default))
    for name, default in defaults:
        if name in static_names and _is_mutable_literal(default):
            findings.append(_finding(
                ctx, "JX004", default,
                f"static argument {name!r} of jitted {fn.name}() has a "
                "mutable (unhashable) default — jit dispatch will raise",
                fn.name,
            ))
    return findings


class _CallSiteChecker(ast.NodeVisitor):
    """JX004 at call sites: list/dict/set literals passed for static
    params of same-module jitted functions."""

    def __init__(self, ctx: FileContext, index: _ModuleIndex):
        self.ctx = ctx
        self.index = index
        self.findings: List[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:  # noqa: N802
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else None
        if name in self.index.jit_aliases:
            name = self.index.jit_aliases[name]
        if name in self.index.jitted and name in self.index.functions:
            fndef = self.index.functions[name]
            static_names = _static_params(fndef, self.index.jitted[name])
            for kw in node.keywords:
                if kw.arg in static_names and _is_mutable_literal(kw.value):
                    self.findings.append(_finding(
                        self.ctx, "JX004", kw.value,
                        f"unhashable literal passed for static argument "
                        f"{kw.arg!r} of jitted {name}()",
                        name,
                    ))
        self.generic_visit(node)


def check(ctx: FileContext) -> List[Finding]:
    index = _ModuleIndex(ctx.tree)
    findings: List[Finding] = []
    for name, statics in index.jitted.items():
        fn = index.functions.get(name)
        if fn is None:
            continue
        checker = _JitBodyChecker(ctx, fn, statics, index)
        checker.visit(fn)
        findings.extend(checker.findings)
        findings.extend(_check_static_defaults(ctx, fn, statics))
    call_sites = _CallSiteChecker(ctx, index)
    call_sites.visit(ctx.tree)
    findings.extend(call_sites.findings)
    return findings
