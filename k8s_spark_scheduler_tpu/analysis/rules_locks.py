"""LK — lock-discipline rules over ``@guarded_by`` declarations.

PR 3 multiplied the threads touching the scheduler's shared caches
(async write-back workers, journal replay, lane-health probes, the
admission gate, informer callbacks).  The locking convention is simple —
one lock per component, every mutation inside ``with self._lock:`` —
but nothing enforced it.  These rules read the
:func:`~.guarded.guarded_by` declarations and verify the convention
*lexically*; the runtime lockset detector (:mod:`.racecheck`) covers
the paths the AST cannot see.

Rules:

- **LK001** — a method of a ``@guarded_by``-decorated class mutates a
  declared attribute (assignment, augmented assignment, subscript
  write/delete, or a known mutating method call such as ``.append`` /
  ``.pop`` / ``.update``) outside a lexical ``with self.<lock>:`` block.
  ``__init__`` is exempt (construction happens-before publication).
  Helper methods that run with the lock already held by the caller
  carry a justified pragma.
- **LK002** — statement-level ``<lock>.acquire()`` with no enclosing or
  immediately-following ``try/finally`` that calls ``.release()``: an
  exception between acquire and release leaks the lock forever.  Prefer
  ``with lock:``.
- **LK003** — a ``@guarded_by`` declaration whose lock attribute is
  never assigned in ``__init__``: the declaration is dead and the rule
  family silently stops protecting the class.
- **LK004** — a class assigns a ``threading.Lock``/``RLock`` attribute
  in ``__init__`` and has mutating methods, but declares no
  ``@guarded_by``: the lock exists, yet neither the LK001 lexical check
  nor the runtime race detectors can see what it guards.  Either
  declare the guarded fields or carry a justified pragma on the class
  line (e.g. a lock that guards no *fields* — a pure serializer).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .core import FileContext, Finding

_MUTATING_METHODS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "appendleft", "popleft",
    "sort", "reverse",
}


def _finding(ctx: FileContext, rule: str, node: ast.AST, message: str, symbol: str) -> Finding:
    return Finding(
        rule=rule,
        category="locking",
        file=ctx.relpath,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
        symbol=symbol,
    )


def _guarded_decl(cls: ast.ClassDef) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """Parse ``@guarded_by("lock", "f1", ...)`` off a class, if present."""
    for deco in cls.decorator_list:
        if not isinstance(deco, ast.Call):
            continue
        fn = deco.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if name != "guarded_by":
            continue
        strings: List[str] = []
        for arg in deco.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                strings.append(arg.value)
        if strings:
            return strings[0], tuple(strings[1:])
    return None


def _is_self_attr(node: ast.AST, attr: Optional[str] = None) -> Optional[str]:
    """Return the attribute name when ``node`` is ``self.<attr>``."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        if attr is None or node.attr == attr:
            return node.attr
    return None


def _with_holds_lock(node: ast.With, lock_attr: str) -> bool:
    for item in node.items:
        expr = item.context_expr
        if _is_self_attr(expr, lock_attr):
            return True
    return False


class _ClassChecker:
    def __init__(self, ctx: FileContext, cls: ast.ClassDef, lock_attr: str, fields: Tuple[str, ...]):
        self.ctx = ctx
        self.cls = cls
        self.lock_attr = lock_attr
        self.fields = set(fields)
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        init_assigns = self._init_assigned_attrs()
        if self.lock_attr not in init_assigns:
            self.findings.append(_finding(
                self.ctx, "LK003", self.cls,
                f"@guarded_by({self.lock_attr!r}, ...) on {self.cls.name} but "
                f"__init__ never assigns self.{self.lock_attr}",
                self.cls.name,
            ))
        for stmt in self.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name == "__init__":
                    continue
                self._check_method(stmt)
        return self.findings

    def _init_assigned_attrs(self) -> Set[str]:
        assigned: Set[str] = set()
        for stmt in self.cls.body:
            if isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__":
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.Assign, ast.AnnAssign)):
                        targets = (
                            node.targets if isinstance(node, ast.Assign) else [node.target]
                        )
                        for t in targets:
                            name = _is_self_attr(t)
                            if name:
                                assigned.add(name)
        return assigned

    def _check_method(self, method: ast.FunctionDef) -> None:
        self._walk(method.body, lock_held=False, method_name=method.name)

    def _walk(self, stmts, lock_held: bool, method_name: str) -> None:
        for stmt in stmts:
            self._check_stmt(stmt, lock_held, method_name)

    def _check_stmt(self, stmt: ast.stmt, lock_held: bool, method_name: str) -> None:
        symbol = f"{self.cls.name}.{method_name}"
        if isinstance(stmt, ast.With):
            held = lock_held or _with_holds_lock(stmt, self.lock_attr)
            self._walk(stmt.body, held, method_name)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested function may run later, on another thread, with
            # the lock long released — analyze it as lock-free
            self._walk(stmt.body, False, method_name)
            return
        if not lock_held:
            for field_name, node in self._mutations_in(stmt):
                self.findings.append(_finding(
                    self.ctx, "LK001", node,
                    f"mutation of guarded attribute self.{field_name} outside "
                    f"'with self.{self.lock_attr}:' in {symbol}",
                    symbol,
                ))
        # recurse into compound statements, preserving lock state
        for child_block in self._child_blocks(stmt):
            self._walk(child_block, lock_held, method_name)

    @staticmethod
    def _child_blocks(stmt: ast.stmt):
        for attr in ("body", "orelse", "finalbody"):
            block = getattr(stmt, attr, None)
            if isinstance(block, list) and not isinstance(stmt, (ast.With, ast.FunctionDef, ast.AsyncFunctionDef)):
                yield block
        for handler in getattr(stmt, "handlers", ()) or ():
            yield handler.body
        for case in getattr(stmt, "cases", ()) or ():
            yield case.body

    def _mutations_in(self, stmt: ast.stmt):
        """(field, node) pairs for direct mutations in this statement
        only (children handled by recursion for compound statements)."""
        out = []
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            for target in targets:
                out.extend(self._target_mutations(target))
        elif isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                out.extend(self._target_mutations(target))
        elif isinstance(stmt, ast.Expr):
            for node in ast.walk(stmt.value):
                out.extend(self._call_mutations(node))
        else:
            # mutating calls buried in non-block expressions (an If/While
            # test, a Return value, a For iterable) — block bodies are
            # handled by the recursion in _check_stmt
            exprs = []
            if isinstance(stmt, (ast.If, ast.While)):
                exprs.append(stmt.test)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                exprs.append(stmt.value)
            elif isinstance(stmt, ast.For):
                exprs.append(stmt.iter)
            for expr in exprs:
                for node in ast.walk(expr):
                    out.extend(self._call_mutations(node))
        return out

    def _target_mutations(self, target: ast.AST):
        out = []
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                out.extend(self._target_mutations(elt))
            return out
        name = _is_self_attr(target)
        if name and name in self.fields:
            out.append((name, target))
            return out
        if isinstance(target, ast.Subscript):
            name = _is_self_attr(target.value)
            if name and name in self.fields:
                out.append((name, target))
        return out

    def _call_mutations(self, expr: ast.AST):
        out = []
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
            if expr.func.attr in _MUTATING_METHODS:
                name = _is_self_attr(expr.func.value)
                if name and name in self.fields:
                    out.append((name, expr))
        return out


# -- LK004: a lock with no @guarded_by declaration ----------------------------


_LOCK_FACTORIES = {"Lock", "RLock"}


def _init_lock_attrs(cls: ast.ClassDef) -> List[str]:
    """self attributes assigned a threading.Lock()/RLock() in __init__."""
    out: List[str] = []
    for stmt in cls.body:
        if not (isinstance(stmt, ast.FunctionDef) and stmt.name == "__init__"):
            continue
        for node in ast.walk(stmt):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)):
                continue
            fn = value.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None
            )
            if name not in _LOCK_FACTORIES:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                attr = _is_self_attr(t)
                if attr:
                    out.append(attr)
    return out


def _has_mutating_method(cls: ast.ClassDef) -> bool:
    """Any non-__init__ method that assigns a self attribute / subscript
    or calls a known mutating method on one."""
    for stmt in cls.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if stmt.name == "__init__":
            continue
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    if _is_self_attr(t):
                        return True
                    if isinstance(t, ast.Subscript) and _is_self_attr(t.value):
                        return True
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
                and _is_self_attr(node.func.value)
            ):
                return True
    return False


def _check_lk004(ctx: FileContext, cls: ast.ClassDef) -> List[Finding]:
    lock_attrs = _init_lock_attrs(cls)
    if not lock_attrs or not _has_mutating_method(cls):
        return []
    return [_finding(
        ctx, "LK004", cls,
        f"{cls.name} assigns {', '.join('self.' + a for a in lock_attrs)} "
        "but declares no @guarded_by: neither the LK001 lexical check nor "
        "the runtime race detectors can see what the lock guards",
        cls.name,
    )]


# -- LK002: acquire() without try/finally -------------------------------------


class _AcquireVisitor(ast.NodeVisitor):
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._scope: List[str] = []

    def visit_FunctionDef(self, node):  # noqa: N802 (ast API)
        self._scope.append(node.name)
        self._check_block(node.body)
        self.generic_visit(node)
        self._scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _check_block(self, stmts, in_protected_try: bool = False) -> None:
        for i, stmt in enumerate(stmts):
            if isinstance(stmt, ast.Expr) and self._is_acquire_call(stmt.value):
                nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                protected = in_protected_try or (
                    isinstance(nxt, ast.Try) and self._finally_releases(nxt)
                )
                if not protected:
                    self.findings.append(Finding(
                        rule="LK002",
                        category="locking",
                        file=self.ctx.relpath,
                        line=stmt.lineno,
                        col=stmt.col_offset,
                        message=(
                            "bare .acquire() without try/finally release — an "
                            "exception leaks the lock; use 'with lock:' or "
                            "follow with try/finally"
                        ),
                        symbol=".".join(self._scope),
                    ))
            for block, protected in self._sub_blocks(stmt):
                self._check_block(block, in_protected_try or protected)

    @staticmethod
    def _sub_blocks(stmt: ast.stmt):
        if isinstance(stmt, ast.Try):
            protected = _AcquireVisitor._finally_releases(stmt)
            yield stmt.body, protected
            for handler in stmt.handlers:
                yield handler.body, False
            yield stmt.orelse, False
            yield stmt.finalbody, False
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # handled by visitor recursion
        else:
            for attr in ("body", "orelse", "finalbody"):
                block = getattr(stmt, attr, None)
                if isinstance(block, list):
                    yield block, False
            for case in getattr(stmt, "cases", ()) or ():
                yield case.body, False

    @staticmethod
    def _is_acquire_call(expr: ast.AST) -> bool:
        return (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "acquire"
        )

    @staticmethod
    def _finally_releases(try_stmt: ast.Try) -> bool:
        for node in ast.walk(ast.Module(body=list(try_stmt.finalbody), type_ignores=[])):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "release"
            ):
                return True
        return False


def check(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            decl = _guarded_decl(node)
            if decl is not None:
                lock_attr, fields = decl
                findings.extend(_ClassChecker(ctx, node, lock_attr, fields).run())
            else:
                findings.extend(_check_lk004(ctx, node))
    acquire_visitor = _AcquireVisitor(ctx)
    acquire_visitor.visit(ctx.tree)
    findings.extend(acquire_visitor.findings)
    return findings
