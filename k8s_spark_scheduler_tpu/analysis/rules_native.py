"""NA — the Python↔C++ boundary audit.

The native extension (``native/fifo_solver.cpp``, ``native/snapshot.cpp``)
is reached through ctypes, which means every call crosses a contract no
existing tool checks from either side:

- **NA001** — a native-boundary call inside a ``with self.<lock>:``
  block of a ``@guarded_by`` class.  ctypes releases the GIL around
  every foreign call, so a native call under a guarded lock (a) extends
  the lock hold by the whole native runtime — a queue solve at 10k
  nodes is ~18 ms of hold time on what is usually a bookkeeping lock —
  and (b) invites real parallelism behind a lock the rest of the code
  believes serializes.  The only legal in-lock crossings are the ones
  on the GIL-safe list below: O(1) accessors that return immediately
  and touch no shared native state.  Everything else moves outside the
  lock (the delta-solve engine's ``solve()`` runs its native step
  outside ``_lock`` for exactly this reason) or carries a justified
  pragma.
- **NA002** — a raw native handle (an attribute named ``_handle``, the
  ctypes void-pointer) referenced outside the ``native/`` binding
  package.  Raw handles carry no lifetime protection: the binding
  classes (``NativeFifoSession``, ``SnapshotMaintainer``) refcount them
  and free the C++ state in ``__del__``/``close``, so a handle that
  escapes the binding can outlive its session — a use-after-free the
  sanitizer lanes can only catch if a test happens to hit it.  Sessions
  escape the engine's lock scope only as their refcounted wrapper,
  never as the raw pointer.

Detection is lexical, matching the project's binding idioms: calls to
names imported from a ``native`` module, and calls through attribute
chains containing ``native`` or ``_lib`` (``sess.native.solve(...)``,
``self._lib.snap_read(...)``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .core import FileContext, Finding
from .rules_locks import _guarded_decl, _with_holds_lock

# In-lock native calls that are proven O(1), GIL-hold-trivial accessors.
# Every entry carries its justification here — this list is the rule's
# contract, reviewed like an allowlist.
GIL_SAFE_NATIVE_CALLS = {
    # reads one cached int64 from the session struct; no allocation, no
    # solver state touched (fifo_solver.cpp fifo_sess_mem_bytes)
    "mem_bytes",
}

# attribute/receiver names that mark a call as crossing the boundary
_BOUNDARY_MARKERS = {"native", "_lib"}


def _finding(ctx: FileContext, rule: str, node: ast.AST, message: str,
             symbol: str) -> Finding:
    return Finding(
        rule=rule,
        category="native-boundary",
        file=ctx.relpath,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
        symbol=symbol,
    )


def _native_imported_names(tree: ast.Module) -> Set[str]:
    """Names bound by ``from ...native[...] import X [as Y]`` anywhere in
    the file (module- or function-level)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            module = node.module or ""
            parts = module.split(".")
            if "native" in parts:
                for alias in node.names:
                    names.add(alias.asname or alias.name)
    return names


def _attr_chain(node: ast.AST) -> List[str]:
    """['self', '_lib', 'snap_read'] for ``self._lib.snap_read``."""
    chain: List[str] = []
    while isinstance(node, ast.Attribute):
        chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        chain.append(node.id)
    chain.reverse()
    return chain


def _native_call_name(call: ast.Call, imported: Set[str]) -> Optional[str]:
    """The called symbol when this call crosses the native boundary."""
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id if fn.id in imported else None
    if isinstance(fn, ast.Attribute):
        chain = _attr_chain(fn)
        # the final element is the callee; boundary if any RECEIVER link
        # is a marker, or the callee resolves to an imported native name
        if any(link in _BOUNDARY_MARKERS for link in chain[:-1]):
            return chain[-1]
        if chain and chain[0] in imported:
            return chain[-1]
    return None


class _Na001Checker:
    """Walks a @guarded_by class, tracking the declared-lock scope."""

    def __init__(self, ctx: FileContext, cls: ast.ClassDef, lock_attr: str,
                 imported: Set[str]):
        self.ctx = ctx
        self.cls = cls
        self.lock_attr = lock_attr
        self.imported = imported
        self.findings: List[Finding] = []

    def run(self) -> List[Finding]:
        for stmt in self.cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt.body, False, stmt.name)
        return self.findings

    def _walk(self, stmts, lock_held: bool, method: str) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                held = lock_held or _with_holds_lock(stmt, self.lock_attr)
                self._walk(stmt.body, held, method)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt.body, False, stmt.name)
                continue
            if lock_held:
                # one flat scan that skips nested defs (they run later,
                # lock-free); no recursion afterwards — recursing too
                # would report each nested call once per block level
                self._report_calls(stmt, method)
                continue
            for attr in ("body", "orelse", "finalbody"):
                block = getattr(stmt, attr, None)
                if isinstance(block, list):
                    self._walk(block, lock_held, method)
            for handler in getattr(stmt, "handlers", ()) or ():
                self._walk(handler.body, lock_held, method)
            for case in getattr(stmt, "cases", ()) or ():
                self._walk(case.body, lock_held, method)

    def _report_calls(self, stmt: ast.stmt, method: str) -> None:
        stack: List[ast.AST] = [stmt]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # deferred body: not under the lock when it runs
            if isinstance(node, ast.Call):
                callee = _native_call_name(node, self.imported)
                if callee is not None and callee not in GIL_SAFE_NATIVE_CALLS:
                    self.findings.append(_finding(
                        self.ctx, "NA001", node,
                        f"native-boundary call {callee}() while "
                        f"holding self.{self.lock_attr}: ctypes "
                        "releases the GIL, so the guarded lock is "
                        "held across foreign code — move the call "
                        "outside the lock or add it to "
                        "GIL_SAFE_NATIVE_CALLS with a justification",
                        f"{self.cls.name}.{method}",
                    ))
            stack.extend(ast.iter_child_nodes(node))


def _check_na002(ctx: FileContext) -> List[Finding]:
    if ctx.relpath.startswith("native/"):
        return []
    findings: List[Finding] = []
    scope: List[str] = []

    def visit(node: ast.AST) -> None:
        pushed = False
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            scope.append(node.name)
            pushed = True
        if isinstance(node, ast.Attribute) and node.attr == "_handle":
            findings.append(_finding(
                ctx, "NA002", node,
                "raw native handle ._handle referenced outside the "
                "native/ binding package: handles carry no lifetime "
                "protection — pass the refcounted wrapper "
                "(NativeFifoSession / SnapshotMaintainer) instead",
                ".".join(scope),
            ))
        for child in ast.iter_child_nodes(node):
            visit(child)
        if pushed:
            scope.pop()

    visit(ctx.tree)
    return findings


def check(ctx: FileContext) -> List[Finding]:
    findings: List[Finding] = []
    imported = _native_imported_names(ctx.tree)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            decl = _guarded_decl(node)
            if decl is not None:
                lock_attr, _fields = decl
                findings.extend(
                    _Na001Checker(ctx, node, lock_attr, imported).run()
                )
    findings.extend(_check_na002(ctx))
    return findings
