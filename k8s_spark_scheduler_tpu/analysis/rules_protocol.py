"""PC — flow-sensitive protocol rules over :mod:`.flow` CFGs.

PR 18 made admission concurrent; PRs 14–16 made correctness hinge on
*protocol discipline* rather than any single call site.  These rules
prove the lifecycles hold on **every** path — including the exception
paths tests never take — by running typestate dataflow over the
per-function CFGs from :mod:`.flow`:

- **PC001** — a :class:`~..concurrent.commitgate.CommitGate` ticket is
  issued (``gate.ticket()``) but some path to the function's normal or
  raise exit never retires it.  A leaked ticket is a *permanent*
  head-of-line stall: every later ticket waits on it forever.
- **PC002** — a path may retire the same ticket twice (double-retire
  releases somebody else's turn).
- **PC003** — a kube-mutating call (CRD create/update/delete/patch on
  an api/client receiver) is reachable from a configured entry point
  without a dominating ``FencedWriter.check`` — computed
  *interprocedurally* over the intra-package call graph, so a fence
  check in the caller covers the callee and a fencing helper
  (``AsyncClient._pre_commit``) counts wherever it is called.
  The pervasive guarded idiom ``gate = self.fence_gate`` /
  ``if gate is not None: gate.check(op)`` is recognized and treated as
  an unconditional check (the protocol is "fenced when a fence is
  installed"; single-replica runs install none).
- **PC004** — a journal intent may be **acked on a path where its
  operation never executed**: ``record(); try: execute() finally:
  ack()`` acks the intent when ``execute`` raised, losing the replay
  *and* the effect (breaks the I-P4/J1 exactly-once contract).
  Exits in the recorded-but-unacked state are fine — that is "left
  pending", and recovery replays it.
- **PC005** — a manually opened span or lock (``x.__enter__()``,
  ``<lock>.acquire()``) has a path to an exit with no matching close
  (``__exit__``/``close``/``finish``/``release``).  ``with`` blocks are
  balanced by construction and exempt.
- **PC006** — a phase boundary (fifo-gate → binpack →
  reservation-writeback) is crossed without an intervening deadline
  check: an expired request must answer fail-fast at the boundary, not
  burn the solver's budget first.

Scope and deliberate imprecision
--------------------------------
* Typestate tracking keys on **local names** (tickets, spans, locks).
  A resource stored into ``self.*`` or returned escapes the
  intra-procedural discipline and is dropped — cross-method lifecycles
  (e.g. a server's root span) are out of scope by design.
* An acquisition that *raises* is modelled as not-acquired (RAII
  semantics); a close that raises is modelled as closed — otherwise no
  ``finally: close()`` could ever satisfy the rule.
* PC003 reports at the mutation site and names the entry point and
  call chain, so the fix target is the unfenced *path*, not the write.
* PC006 only fires inside functions that either arm a deadline check
  themselves or span two distinct phase families — a raw helper that
  wraps a single phase op is the callee side of the contract, not a
  boundary crossing.
* Entry points for PC003 default to :data:`DEFAULT_ENTRYPOINTS` and can
  be extended per file with ``# schedlint: entrypoints=Class.method``
  (used by rule fixtures).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from . import flow
from .core import FileContext, Finding

CATEGORY = "protocol"

# PC003 roots: the paths where a mutation escaping the fence protocol
# breaks I-H3.  Package-relative file → method qualnames.
DEFAULT_ENTRYPOINTS: Dict[str, Tuple[str, ...]] = {
    "scheduler/extender.py": ("SparkSchedulerExtender.predicate",),
    "policy/preempt.py": (
        "PreemptionCoordinator.commit",
        "PreemptionCoordinator.recover",
    ),
    "state/cache.py": (
        "AsyncClient._run_worker",
        "AsyncClient.replay_journal",
        "AsyncClient.nudge_recovery",
    ),
    "concurrent/engine.py": (
        "ConcurrentAdmissionEngine.predicate",
        "ConcurrentAdmissionEngine.submit_intent",
        "ConcurrentAdmissionEngine.make_intent",
    ),
}

_ENTRY_DIRECTIVE_RE = re.compile(
    r"#\s*schedlint:\s*entrypoints=([A-Za-z0-9_.]+(?:\s*,\s*[A-Za-z0-9_.]+)*)"
)

_MUTATING_ATTRS = {"create", "update", "delete", "patch", "replace"}
_CLOSE_ATTRS = {"__exit__", "close", "finish"}

_PHASE_CALL_FAMILIES = {
    "_try_device_fifo": "fifo-gate",
    "_fit_earlier_drivers": "fifo-gate",
    "create_reservations": "reservation-writeback",
}
_PHASE_SPAN_FAMILIES = {"binpack": "binpack"}
ANY_PHASE = "*"


def check(ctx: FileContext) -> List[Finding]:
    """Per-file hook kept for driver symmetry — PC rules need the whole
    file set (PC003 is interprocedural), so the work happens in
    :func:`check_package`."""
    return []


# ---------------------------------------------------------------------------
# lexical event extraction
# ---------------------------------------------------------------------------


def _attr_parts(expr: ast.expr) -> Optional[List[str]]:
    """``self.gate.retire`` → ["self", "gate", "retire"]; None when the
    chain contains anything but Names/Attributes."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _is_gateish(comp: str) -> bool:
    return "gate" in comp.lower()


def _is_fenceish(recv: Sequence[str]) -> bool:
    last = recv[-1].lower()
    if "deadline" in last:
        return False
    return any(tok in last for tok in ("gate", "fence", "writer"))


def _is_journalish(recv: Sequence[str]) -> bool:
    return "journal" in recv[-1].lower()


def _is_clientish(recv: Sequence[str]) -> bool:
    last = recv[-1]
    stripped = last.lstrip("_")
    return (
        stripped in ("api", "client", "kube")
        or last.endswith("_api")
        or last.endswith("_client")
    )


def _is_deadlineish(recv: Sequence[str]) -> bool:
    return "deadline" in recv[-1].lower()


def _const_str(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        return None  # dynamic op string arms every phase / fences its class
    return None


@dataclass
class _Event:
    kind: str  # see _events_for_call
    call: ast.Call
    var: Optional[str] = None  # tracked key (local name / dotted receiver)
    arg: Optional[str] = None  # op class / phase name


def _events_for_call(call: ast.Call) -> List[_Event]:
    func = call.func
    events: List[_Event] = []
    parts = _attr_parts(func)
    if parts is None or len(parts) < 2:
        return events
    attr, recv = parts[-1], parts[:-1]
    dotted = ".".join(recv)
    if attr == "ticket" and _is_gateish(recv[-1]):
        events.append(_Event("ticket-open", call))
    elif attr == "retire" and _is_gateish(recv[-1]):
        var = None
        if call.args and isinstance(call.args[0], ast.Name):
            var = call.args[0].id
        events.append(_Event("ticket-retire", call, var=var))
    elif attr == "check" and _is_deadlineish(recv) or (
        attr in ("_check_deadline", "check_deadline")
    ):
        phase = _const_str(call.args[0]) if call.args else None
        events.append(_Event("arm", call, arg=phase or ANY_PHASE))
    elif attr == "check" and _is_fenceish(recv):
        op = _const_str(call.args[0]) if call.args else None
        events.append(_Event("fence", call, arg=op or "*"))
    elif attr in _MUTATING_ATTRS and _is_clientish(recv):
        events.append(_Event("mutate", call, var=dotted + "." + attr))
    elif attr == "record" and _is_journalish(recv):
        events.append(_Event("record", call))
    elif attr == "ack" and _is_journalish(recv):
        events.append(_Event("ack", call))
    elif attr == "__enter__" and len(recv) == 1:
        events.append(_Event("open", call, var=recv[0]))
    elif attr in _CLOSE_ATTRS and len(recv) == 1:
        events.append(_Event("close", call, var=recv[0]))
    elif attr == "acquire" and "lock" in recv[-1].lower():
        events.append(_Event("open", call, var=dotted))
    elif attr == "release" and "lock" in recv[-1].lower():
        events.append(_Event("close", call, var=dotted))
    if attr in _PHASE_CALL_FAMILIES:
        events.append(
            _Event("phase", call, arg=_PHASE_CALL_FAMILIES[attr], var=attr)
        )
    return events


def _own_exprs(stmt: ast.AST, kind: str) -> List[ast.expr]:
    """The expressions evaluated *at this CFG node* (compound bodies are
    their own nodes)."""
    if kind == flow.WITH_EXIT:
        return []
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, ast.Match):
        return [stmt.subject]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return []
    if isinstance(stmt, ast.ExceptHandler):
        return []
    return [stmt] if isinstance(stmt, ast.expr) else list(ast.iter_child_nodes(stmt))


def _calls_in_expr(expr: ast.AST) -> List[ast.Call]:
    out: List[ast.Call] = []

    def walk(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            out.append(node)
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return out


def _guard_idiom_events(stmt: ast.If) -> List[_Event]:
    """``if gate is not None: gate.check(op)`` (or bare truthiness, no
    else) — the check is unconditional for protocol purposes."""
    if stmt.orelse:
        return []
    test = stmt.test
    guarded_ok = isinstance(test, (ast.Name, ast.Attribute)) or (
        isinstance(test, ast.Compare)
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.IsNot)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    )
    if not guarded_ok:
        return []
    events: List[_Event] = []
    for inner in stmt.body:
        if isinstance(inner, ast.Expr) and isinstance(inner.value, ast.Call):
            for ev in _events_for_call(inner.value):
                if ev.kind in ("fence", "arm"):
                    events.append(ev)
    return events


class _UnitEvents:
    """Per-CFG-node events + per-node resolvable calls for one unit."""

    def __init__(self, unit: flow.FunctionUnit, index: flow.PackageIndex):
        self.unit = unit
        self.cfg = unit.cfg()
        self.events: Dict[int, List[_Event]] = {}
        self.calls: Dict[int, List[ast.Call]] = {}
        self.ticket_opens: Dict[int, str] = {}  # node -> var bound by `v = gate.ticket()`
        self.escapes: Dict[int, Set[str]] = {}
        for node in self.cfg.nodes:
            if node.stmt is None:
                continue
            stmt = node.stmt
            evs: List[_Event] = []
            calls: List[ast.Call] = []
            if node.kind == flow.TEST and isinstance(stmt, ast.If):
                evs.extend(_guard_idiom_events(stmt))
            for expr in _own_exprs(stmt, node.kind):
                for call in _calls_in_expr(expr):
                    calls.append(call)
                    evs.extend(_events_for_call(call))
            # with items that open spans count as phase anchors
            if node.kind == flow.STMT and isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    c = item.context_expr
                    if isinstance(c, ast.Call):
                        p = _attr_parts(c.func)
                        if p and p[-1] in ("span", "child_span") and c.args:
                            name = _const_str(c.args[0])
                            if name in _PHASE_SPAN_FAMILIES:
                                evs.append(
                                    _Event(
                                        "phase",
                                        c,
                                        arg=_PHASE_SPAN_FAMILIES[name],
                                        var=f"span:{name}",
                                    )
                                )
            if node.kind != flow.TEST and isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                value = stmt.value
                if (
                    value is not None
                    and isinstance(value, ast.Call)
                    and len(targets) == 1
                    and isinstance(targets[0], ast.Name)
                ):
                    for ev in _events_for_call(value):
                        if ev.kind == "ticket-open":
                            self.ticket_opens[node.idx] = targets[0].id
            esc = _escaping_names(stmt, node.kind)
            if esc:
                self.escapes[node.idx] = esc
            if evs:
                self.events[node.idx] = evs
            if calls:
                self.calls[node.idx] = calls

    def node_events(self, idx: int, *kinds: str) -> List[_Event]:
        return [e for e in self.events.get(idx, ()) if e.kind in kinds]


def _escaping_names(stmt: ast.AST, kind: str) -> Set[str]:
    """Local names this statement aliases, returns, yields or stores —
    tracked resources named here leave the function's custody, so the
    typestate rules stop tracking them.  Names that only appear as call
    *arguments* do not escape (passing a ticket to ``speculate`` does
    not transfer the retire obligation)."""

    def direct_names(expr: ast.AST) -> Set[str]:
        found: Set[str] = set()

        def walk(node: ast.AST) -> None:
            if isinstance(node, (ast.Call, ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return
            if isinstance(node, ast.Name):
                found.add(node.id)
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(expr)
        return found

    if kind == flow.TEST:
        return set()
    out: Set[str] = set()
    if isinstance(stmt, ast.Return) and stmt.value is not None:
        out |= direct_names(stmt.value)
    elif isinstance(stmt, ast.Assign):
        # aliasing (`y = t`) or storing (`self.t = t`, `d[k] = t`);
        # names that only feed a call (`f(t)`) stay tracked
        out |= direct_names(stmt.value)
    elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, (ast.Yield, ast.YieldFrom)):
        if stmt.value.value is not None:
            out |= direct_names(stmt.value.value)
    return out


# ---------------------------------------------------------------------------
# PC001 / PC002 — ticket typestate
# ---------------------------------------------------------------------------

_ISSUED = "issued"
_RETIRED = "retired"

StateMap = Dict[str, FrozenSet[str]]


def _join_maps(a: StateMap, b: StateMap) -> StateMap:
    out = dict(a)
    for var, states in b.items():
        out[var] = out.get(var, frozenset()) | states
    return out


def _check_tickets(ue: _UnitEvents) -> List[Finding]:
    cfg, unit = ue.cfg, ue.unit
    if not any(
        e.kind in ("ticket-open", "ticket-retire")
        for evs in ue.events.values()
        for e in evs
    ):
        return []

    def apply(node: flow.Node, state: StateMap, on_raise: bool) -> StateMap:
        out = dict(state)
        for var in ue.escapes.get(node.idx, ()):
            out.pop(var, None)
        opened = ue.ticket_opens.get(node.idx)
        if opened is not None and not on_raise:
            # acquisition that raises never bound the name (RAII)
            out[opened] = frozenset({_ISSUED})
        for ev in ue.node_events(node.idx, "ticket-retire"):
            if ev.var is not None:
                # retire applies even on the raise edge: a retire that
                # itself raised cannot be meaningfully re-driven
                out[ev.var] = frozenset({_RETIRED})
        return out

    in_state = flow.forward_dataflow(
        cfg,
        init={},
        transfer=lambda n, s: apply(n, s, on_raise=False),
        transfer_exc=lambda n, s: apply(n, s, on_raise=True),
        join=_join_maps,
    )

    findings: List[Finding] = []
    open_lines: Dict[str, int] = {}
    for idx, var in ue.ticket_opens.items():
        open_lines.setdefault(var, cfg.nodes[idx].line)
    # PC002: retire may run on an already-retired ticket
    for idx, evs in sorted(ue.events.items()):
        state = in_state.get(idx)
        if state is None:
            continue
        for ev in evs:
            if ev.kind == "ticket-retire" and ev.var is not None:
                if _RETIRED in state.get(ev.var, frozenset()):
                    findings.append(
                        Finding(
                            rule="PC002",
                            category=CATEGORY,
                            file=unit.relpath,
                            line=cfg.nodes[idx].line,
                            col=ev.call.col_offset,
                            message=(
                                f"ticket '{ev.var}' may already be retired when "
                                "this retire runs (double-retire releases "
                                "someone else's commit turn)"
                            ),
                            symbol=unit.qualname,
                        )
                    )
    # PC001: a leak path to either exit
    for exit_idx, how in ((cfg.exit, "a fall-through"), (cfg.raise_exit, "an exception")):
        state = in_state.get(exit_idx)
        if not state:
            continue
        for var, states in sorted(state.items()):
            if _ISSUED in states:
                line = open_lines.get(var)
                if line is None:
                    continue  # ticket came from a parameter — caller owns it
                findings.append(
                    Finding(
                        rule="PC001",
                        category=CATEGORY,
                        file=unit.relpath,
                        line=line,
                        col=0,
                        message=(
                            f"ticket '{var}' issued here may never be retired on "
                            f"{how} path — a leaked CommitGate ticket stalls "
                            "the FIFO line forever; retire in a finally that "
                            "cannot be skipped"
                        ),
                        symbol=unit.qualname,
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# PC003 — fence dominance, interprocedural
# ---------------------------------------------------------------------------


class _FenceAnalysis:
    def __init__(self, index: flow.PackageIndex, events: Dict[Tuple[str, str], _UnitEvents]):
        self.index = index
        self.events = events
        self._fences_exit: Dict[Tuple[str, str], bool] = {}
        self._exposed: Dict[Tuple[str, str], List[Tuple[_Event, flow.FunctionUnit, Tuple[str, ...]]]] = {}

    # -- summaries ---------------------------------------------------------

    def fences_exit(self, unit: flow.FunctionUnit, stack: FrozenSet[Tuple[str, str]] = frozenset()) -> bool:
        """Does every normal completion of ``unit`` pass a fence check?"""
        key = unit.key
        if key in self._fences_exit:
            return self._fences_exit[key]
        if key in stack:
            return False
        ue = self.events.get(key)
        if ue is None:
            return False
        state = self._run_fence_flow(ue, stack | {key})
        result = bool(state.get(ue.cfg.exit, False))
        self._fences_exit[key] = result
        return result

    def _run_fence_flow(
        self, ue: _UnitEvents, stack: FrozenSet[Tuple[str, str]]
    ) -> Dict[int, bool]:
        def transfer(node: flow.Node, fenced: bool) -> bool:
            if fenced:
                return True
            if ue.node_events(node.idx, "fence"):
                return True
            for call in ue.calls.get(node.idx, ()):
                callee = self.index.resolve_call(call, ue.unit)
                if callee is not None and callee.key not in stack:
                    if self.fences_exit(callee, stack):
                        return True
            return False

        return flow.forward_dataflow(
            ue.cfg,
            init=False,
            transfer=transfer,
            join=lambda a, b: a and b,
        )

    # -- exposure ----------------------------------------------------------

    def exposed(
        self, unit: flow.FunctionUnit, stack: FrozenSet[Tuple[str, str]] = frozenset()
    ) -> List[Tuple[_Event, flow.FunctionUnit, Tuple[str, ...]]]:
        """Mutations reachable from ``unit``'s entry with no fence check
        on the way — each as (event, owning unit, call chain)."""
        key = unit.key
        if key in self._exposed:
            return self._exposed[key]
        if key in stack:
            return []
        ue = self.events.get(key)
        if ue is None:
            return []
        stack = stack | {key}
        fenced_in = self._run_fence_flow(ue, stack)
        out: List[Tuple[_Event, flow.FunctionUnit, Tuple[str, ...]]] = []
        for idx in sorted(ue.events.keys() | ue.calls.keys()):
            fenced = fenced_in.get(idx)
            if fenced is None or fenced:
                continue
            # replay this node's events/calls in lexical order: a fence
            # in the same statement covers mutations after it
            node_fenced = False
            for ev in ue.events.get(idx, ()):
                if ev.kind == "fence":
                    node_fenced = True
                elif ev.kind == "mutate" and not node_fenced:
                    out.append((ev, unit, (unit.qualname,)))
            if node_fenced:
                continue
            for call in ue.calls.get(idx, ()):
                callee = self.index.resolve_call(call, ue.unit)
                if callee is None or callee.key in stack:
                    continue
                if self.fences_exit(callee, stack):
                    continue
                for ev, owner, chain in self.exposed(callee, stack):
                    out.append((ev, owner, (unit.qualname,) + chain))
        self._exposed[key] = out
        return out


def _entrypoints_for(ctx: FileContext) -> List[str]:
    entries = list(DEFAULT_ENTRYPOINTS.get(ctx.relpath, ()))
    for m in _ENTRY_DIRECTIVE_RE.finditer(ctx.source):
        entries.extend(s.strip() for s in m.group(1).split(",") if s.strip())
    return entries


def _check_fencing(
    index: flow.PackageIndex,
    events: Dict[Tuple[str, str], _UnitEvents],
    contexts: Sequence[FileContext],
) -> List[Finding]:
    analysis = _FenceAnalysis(index, events)
    findings: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for ctx in sorted(contexts, key=lambda c: c.relpath):
        for qualname in _entrypoints_for(ctx):
            unit = index.units.get((ctx.relpath, qualname))
            if unit is None:
                continue
            for ev, owner, chain in analysis.exposed(unit):
                site = (owner.relpath, ev.call.lineno, ev.var or "")
                if site in seen:
                    continue
                seen.add(site)
                via = " -> ".join(chain)
                findings.append(
                    Finding(
                        rule="PC003",
                        category=CATEGORY,
                        file=owner.relpath,
                        line=ev.call.lineno,
                        col=ev.call.col_offset,
                        message=(
                            f"kube-mutating call {ev.var} is reachable from "
                            f"entry point {qualname} (via {via}) without a "
                            "dominating FencedWriter.check — a deposed replica "
                            "could still write (violates I-H3)"
                        ),
                        symbol=owner.qualname,
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# PC004 — journal exactly-once
# ---------------------------------------------------------------------------

_J_NONE = "none"
_J_RECORDED = "recorded"
_J_EXECUTED = "executed"
_J_ACKED = "acked"


def _check_journal(
    ue: _UnitEvents, index: flow.PackageIndex, mutates: "_MutationSummary"
) -> List[Finding]:
    cfg, unit = ue.cfg, ue.unit
    has_record = any(
        e.kind == "record" for evs in ue.events.values() for e in evs
    )
    if not has_record:
        return []

    def is_execute(node_idx: int) -> bool:
        if any(e.kind == "mutate" for e in ue.events.get(node_idx, ())):
            return True
        for call in ue.calls.get(node_idx, ()):
            callee = index.resolve_call(call, unit)
            if callee is not None and mutates.any_mutation(callee):
                return True
        return False

    def apply(node: flow.Node, state: FrozenSet[str], on_raise: bool) -> FrozenSet[str]:
        out = set(state)
        for ev in ue.events.get(node.idx, ()):
            if ev.kind == "record":
                out = {_J_RECORDED}
            elif ev.kind == "ack":
                if _J_EXECUTED in out:
                    out.discard(_J_EXECUTED)
                    out.add(_J_ACKED)
                out.discard(_J_RECORDED)  # the violation is reported, then cleared
        if is_execute(node.idx):
            if _J_RECORDED in out:
                out.add(_J_EXECUTED)
                if not on_raise:
                    # on the normal edge the execute definitely ran
                    out.discard(_J_RECORDED)
                # on the raise edge both outcomes stay possible
        return frozenset(out)

    in_state = flow.forward_dataflow(
        cfg,
        init=frozenset({_J_NONE}),
        transfer=lambda n, s: apply(n, s, on_raise=False),
        transfer_exc=lambda n, s: apply(n, s, on_raise=True),
        join=lambda a, b: a | b,
    )

    findings: List[Finding] = []
    for idx, evs in sorted(ue.events.items()):
        state = in_state.get(idx)
        if state is None:
            continue
        for ev in evs:
            if ev.kind == "ack" and _J_RECORDED in state:
                findings.append(
                    Finding(
                        rule="PC004",
                        category=CATEGORY,
                        file=unit.relpath,
                        line=cfg.nodes[idx].line,
                        col=ev.call.col_offset,
                        message=(
                            "journal intent may be acked on a path where its "
                            "operation never executed — an exception between "
                            "record and execute must leave the intent pending "
                            "for replay, not ack it away (I-P4/J1 exactly-once)"
                        ),
                        symbol=unit.qualname,
                    )
                )
    return findings


class _MutationSummary:
    """Transitive "does this unit (or anything it calls) perform a
    kube mutation?" — PC004's notion of 'the operation executed'."""

    def __init__(self, index: flow.PackageIndex, events: Dict[Tuple[str, str], _UnitEvents]):
        self.index = index
        self.events = events
        self._memo: Dict[Tuple[str, str], bool] = {}

    def any_mutation(self, unit: flow.FunctionUnit, stack: FrozenSet[Tuple[str, str]] = frozenset()) -> bool:
        key = unit.key
        if key in self._memo:
            return self._memo[key]
        if key in stack:
            return False
        ue = self.events.get(key)
        if ue is None:
            return False
        stack = stack | {key}
        result = any(
            e.kind == "mutate" for evs in ue.events.values() for e in evs
        )
        if not result:
            for calls in ue.calls.values():
                for call in calls:
                    callee = self.index.resolve_call(call, unit)
                    if callee is not None and self.any_mutation(callee, stack):
                        result = True
                        break
                if result:
                    break
        self._memo[key] = result
        return result


# ---------------------------------------------------------------------------
# PC005 — span / lock open-close
# ---------------------------------------------------------------------------


def _check_spans(ue: _UnitEvents) -> List[Finding]:
    cfg, unit = ue.cfg, ue.unit
    opens = {
        e.var
        for evs in ue.events.values()
        for e in evs
        if e.kind == "open" and e.var is not None
    }
    if not opens:
        return []

    _OPEN, _CLOSED = "open", "closed"

    def apply(node: flow.Node, state: StateMap, on_raise: bool) -> StateMap:
        out = dict(state)
        for var in ue.escapes.get(node.idx, ()):
            out.pop(var, None)
        for ev in ue.events.get(node.idx, ()):
            if ev.kind == "open" and ev.var is not None:
                if not on_raise:  # an acquire that raised never held the lock
                    out[ev.var] = frozenset({_OPEN})
            elif ev.kind == "close" and ev.var in out:
                out[ev.var] = frozenset({_CLOSED})
        return out

    in_state = flow.forward_dataflow(
        cfg,
        init={},
        transfer=lambda n, s: apply(n, s, on_raise=False),
        transfer_exc=lambda n, s: apply(n, s, on_raise=True),
        join=_join_maps,
    )

    open_lines: Dict[str, int] = {}
    for idx, evs in sorted(ue.events.items()):
        for ev in evs:
            if ev.kind == "open" and ev.var is not None:
                open_lines.setdefault(ev.var, cfg.nodes[idx].line)

    findings: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for exit_idx, how in ((cfg.exit, "a fall-through"), (cfg.raise_exit, "an exception")):
        state = in_state.get(exit_idx)
        if not state:
            continue
        for var, states in sorted(state.items()):
            if _OPEN in states and (var, how) not in reported:
                reported.add((var, how))
                findings.append(
                    Finding(
                        rule="PC005",
                        category=CATEGORY,
                        file=unit.relpath,
                        line=open_lines.get(var, cfg.nodes[0].line or 1),
                        col=0,
                        message=(
                            f"'{var}' is opened here but {how} path reaches "
                            "the end of the function without closing it — use "
                            "`with` or close in a finally"
                        ),
                        symbol=unit.qualname,
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# PC006 — phase-boundary deadline checks
# ---------------------------------------------------------------------------


def _check_phases(ue: _UnitEvents) -> List[Finding]:
    cfg, unit = ue.cfg, ue.unit
    arms = any(e.kind == "arm" for evs in ue.events.values() for e in evs)
    families = {
        e.arg for evs in ue.events.values() for e in evs if e.kind == "phase"
    }
    # a helper wrapping a single phase family is the callee side of the
    # contract; the *crossing* happens where phases meet or arms exist
    if not families or (not arms and len(families) < 2):
        return []

    def apply(node: flow.Node, state: FrozenSet[str]) -> FrozenSet[str]:
        out = state
        for ev in ue.events.get(node.idx, ()):
            if ev.kind == "arm":
                out = frozenset({ev.arg or ANY_PHASE})
            elif ev.kind == "phase":
                # running an op keeps its own phase armed (consecutive
                # same-phase ops need one check), but a later different
                # phase must re-arm
                if ANY_PHASE not in out:
                    out = out | {ev.arg}
        return out

    in_state = flow.forward_dataflow(
        cfg,
        init=frozenset(),
        transfer=apply,
        join=lambda a, b: a & b,
    )

    findings: List[Finding] = []
    for idx, evs in sorted(ue.events.items()):
        state = in_state.get(idx)
        if state is None:
            continue
        armed = set(state)
        for ev in evs:
            if ev.kind == "arm":
                armed = {ev.arg or ANY_PHASE}
            elif ev.kind == "phase":
                if ev.arg not in armed and ANY_PHASE not in armed:
                    findings.append(
                        Finding(
                            rule="PC006",
                            category=CATEGORY,
                            file=unit.relpath,
                            line=cfg.nodes[idx].line,
                            col=ev.call.col_offset,
                            message=(
                                f"phase op '{ev.var}' ({ev.arg}) runs without "
                                "an armed deadline check for this boundary — "
                                "re-check the request deadline when crossing "
                                "fifo-gate -> binpack -> reservation-writeback"
                            ),
                            symbol=unit.qualname,
                        )
                    )
                armed.add(ev.arg)
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def check_package(contexts: Sequence[FileContext]) -> List[Finding]:
    """Run the PC family over the whole analyzed file set."""
    contexts = [c for c in contexts if c.tree is not None]
    index = flow.PackageIndex(contexts)
    events: Dict[Tuple[str, str], _UnitEvents] = {}
    for key, unit in index.units.items():
        events[key] = _UnitEvents(unit, index)

    findings: List[Finding] = []
    mutation_summary = _MutationSummary(index, events)
    for key in sorted(events):
        ue = events[key]
        findings.extend(_check_tickets(ue))
        findings.extend(_check_journal(ue, index, mutation_summary))
        findings.extend(_check_spans(ue))
        findings.extend(_check_phases(ue))
    findings.extend(_check_fencing(index, events, contexts))
    return findings
