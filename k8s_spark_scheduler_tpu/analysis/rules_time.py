"""TS/DT — determinism rules: clock reads and random streams.

The discrete-event simulator (PR 2) replays hours of cluster life in
milliseconds by swapping ``timesource.now`` for a virtual clock.  That
only works if *every semantic clock read* goes through the timesource:
a direct ``time.time()`` stamps a virtual-era object with a real epoch
(breaking FIFO ordering and digest stability), and an unseeded RNG
makes two runs of the same scenario diverge.

Rules:

- **TS001** — direct ``time.time()`` call.  Semantic timestamps must go
  through ``timesource.now()``; latency measurement should use
  ``time.perf_counter()`` (allowed).
- **TS002** — direct ``time.monotonic()`` call.  Legitimate only for
  *infrastructure* deadlines that must keep binding real time while the
  sim clock is frozen — those sites live on the allowlist or carry a
  justified pragma.
- **TS003** — ``datetime.now()`` / ``datetime.utcnow()`` /
  ``date.today()``: wall-clock reads that bypass the timesource
  entirely.
- **DT001** — unseeded randomness: module-level ``random.<fn>()``
  calls (the shared global RNG) or ``random.Random()`` constructed
  without a seed.  Every random stream in the scheduler must be
  explicitly seeded so scenario replays are byte-identical.
- **DT002** — legacy NumPy global RNG (``numpy.random.<fn>()`` /
  ``np.random.seed``): global mutable RNG state is unseedable per
  stream; use ``numpy.random.default_rng(seed)``.
"""

from __future__ import annotations

import ast
from typing import List

from .core import FileContext, Finding

_DATETIME_WALL_FNS = {"now", "utcnow", "today"}
_RANDOM_GLOBAL_FNS = {
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "betavariate",
    "expovariate", "triangular", "seed", "getrandbits", "random_bytes",
}


def _finding(ctx: FileContext, rule: str, node: ast.AST, message: str, symbol: str) -> Finding:
    return Finding(
        rule=rule,
        category="determinism",
        file=ctx.relpath,
        line=getattr(node, "lineno", 1),
        col=getattr(node, "col_offset", 0),
        message=message,
        symbol=symbol,
    )


class _TimeVisitor(ast.NodeVisitor):
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.findings: List[Finding] = []
        self._scope: List[str] = []
        # names bound to the time module in this file ("import time",
        # "import time as _time")
        self.time_aliases = set()
        self.datetime_aliases = set()     # "import datetime [as d]"
        self.datetime_class_names = set() # "from datetime import datetime [as dt]"
        self.random_aliases = set()
        self.numpy_random_aliases = set() # "from numpy import random as npr"
        self.numpy_aliases = set()

    # -- imports --------------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            bound = alias.asname or alias.name.split(".")[0]
            if alias.name == "time":
                self.time_aliases.add(bound)
            elif alias.name == "datetime":
                self.datetime_aliases.add(bound)
            elif alias.name == "random":
                self.random_aliases.add(bound)
            elif alias.name in ("numpy", "numpy.random"):
                self.numpy_aliases.add(bound)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "datetime":
            for alias in node.names:
                if alias.name in ("datetime", "date"):
                    self.datetime_class_names.add(alias.asname or alias.name)
        elif node.module == "time":
            for alias in node.names:
                bound = alias.asname or alias.name
                if alias.name == "time":
                    # "from time import time" — calls look like bare time()
                    self.time_aliases.add(f"bare:{bound}")
                elif alias.name == "monotonic":
                    self.time_aliases.add(f"bare-mono:{bound}")
        elif node.module == "numpy":
            for alias in node.names:
                if alias.name == "random":
                    self.numpy_random_aliases.add(alias.asname or alias.name)
        elif node.module == "random":
            for alias in node.names:
                if alias.name in _RANDOM_GLOBAL_FNS:
                    self.random_aliases.add(f"bare:{alias.asname or alias.name}")

    # -- scopes ---------------------------------------------------------------

    def _visit_scoped(self, node, name: str) -> None:
        self._scope.append(name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node):  # noqa: N802 (ast API)
        self._visit_scoped(node, node.name)

    def visit_AsyncFunctionDef(self, node):  # noqa: N802
        self._visit_scoped(node, node.name)

    def visit_ClassDef(self, node):  # noqa: N802
        self._visit_scoped(node, node.name)

    def _symbol(self) -> str:
        return ".".join(self._scope)

    # -- calls ----------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            base, attr = fn.value.id, fn.attr
            if base in self.time_aliases:
                if attr == "time":
                    self.findings.append(_finding(
                        self.ctx, "TS001", node,
                        "direct time.time() — semantic timestamps must use "
                        "timesource.now() (sim runs swap in a virtual clock)",
                        self._symbol(),
                    ))
                elif attr == "monotonic":
                    self.findings.append(_finding(
                        self.ctx, "TS002", node,
                        "direct time.monotonic() — infra-only; allowlist the "
                        "module or pragma with a justification",
                        self._symbol(),
                    ))
            elif base in self.datetime_class_names and attr in _DATETIME_WALL_FNS:
                self.findings.append(_finding(
                    self.ctx, "TS003", node,
                    f"datetime wall-clock read {base}.{attr}() bypasses the "
                    "timesource",
                    self._symbol(),
                ))
            elif base in self.random_aliases:
                if attr in _RANDOM_GLOBAL_FNS:
                    self.findings.append(_finding(
                        self.ctx, "DT001", node,
                        f"global-RNG call random.{attr}() — use an explicitly "
                        "seeded random.Random(seed) stream",
                        self._symbol(),
                    ))
                elif attr == "Random" and not node.args and not node.keywords:
                    self.findings.append(_finding(
                        self.ctx, "DT001", node,
                        "random.Random() constructed without a seed",
                        self._symbol(),
                    ))
            elif base in self.numpy_random_aliases and attr in (
                _RANDOM_GLOBAL_FNS | {"rand", "randn", "permutation"}
            ):
                self.findings.append(_finding(
                    self.ctx, "DT002", node,
                    f"legacy NumPy global RNG numpy.random.{attr}() — use "
                    "numpy.random.default_rng(seed)",
                    self._symbol(),
                ))
        elif isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Attribute):
            # datetime.datetime.now() / np.random.rand() shapes
            inner = fn.value
            if isinstance(inner.value, ast.Name):
                if (
                    inner.value.id in self.datetime_aliases
                    and inner.attr in ("datetime", "date")
                    and fn.attr in _DATETIME_WALL_FNS
                ):
                    self.findings.append(_finding(
                        self.ctx, "TS003", node,
                        f"datetime wall-clock read "
                        f"{inner.value.id}.{inner.attr}.{fn.attr}() bypasses "
                        "the timesource",
                        self._symbol(),
                    ))
                elif (
                    inner.value.id in self.numpy_aliases
                    and inner.attr == "random"
                    and fn.attr in (_RANDOM_GLOBAL_FNS | {"rand", "randn", "permutation"})
                ):
                    self.findings.append(_finding(
                        self.ctx, "DT002", node,
                        f"legacy NumPy global RNG "
                        f"{inner.value.id}.random.{fn.attr}() — use "
                        "numpy.random.default_rng(seed)",
                        self._symbol(),
                    ))
        elif isinstance(fn, ast.Name):
            for alias in self.time_aliases:
                if alias == f"bare:{fn.id}":
                    self.findings.append(_finding(
                        self.ctx, "TS001", node,
                        "direct time() call (from-imported) — use "
                        "timesource.now()",
                        self._symbol(),
                    ))
                elif alias == f"bare-mono:{fn.id}":
                    self.findings.append(_finding(
                        self.ctx, "TS002", node,
                        "direct monotonic() call (from-imported) — infra-only",
                        self._symbol(),
                    ))
            for alias in self.random_aliases:
                if alias == f"bare:{fn.id}":
                    self.findings.append(_finding(
                        self.ctx, "DT001", node,
                        f"global-RNG call {fn.id}() (from-imported) — use a "
                        "seeded random.Random(seed) stream",
                        self._symbol(),
                    ))
        self.generic_visit(node)


def check(ctx: FileContext) -> List[Finding]:
    visitor = _TimeVisitor(ctx)
    visitor.visit(ctx.tree)
    return visitor.findings
