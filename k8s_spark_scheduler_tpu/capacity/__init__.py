"""Capacity observatory: cluster-state analytics as first-class
scheduler outputs (Borg/Firmament lineage — fragmentation, headroom,
pending-work pressure) built on the same exact integer math as the
solver itself.

- :mod:`.probe` — what-if feasibility probes: the largest admissible
  gang per resource shape (bisection over the monotone feasibility
  rule) and a per-dimension fragmentation report.  Native
  (``fifo_probe_headroom``) when the toolchain is present, an exact
  numpy twin otherwise.
- :mod:`.observatory` — the background :class:`CapacitySampler`:
  triggered by the state layer's ChangeFeed sequence (sample only on
  state change, debounced), NEVER under the extender lock, producing a
  bounded queryable timeline (``GET /state/capacity*``), Prometheus
  gauges, and time-to-admit forecasts for queued drivers.

Everything here is read-only diagnostics: no scheduling decision ever
consumes an observatory output.
"""

from __future__ import annotations

import threading

# -- extender-lock flag -------------------------------------------------------
#
# The acceptance contract is that the sampler runs ZERO solves while the
# extender (predicate) lock is held: sampling must never stretch lock
# hold time, directly or by running inside a decision.  threading.Lock
# has no owner introspection, so the extender marks lock tenure in a
# thread-local and the sampler refuses to probe (and counts the
# violation) when invoked from a lock-holding thread.
#
# Defined BEFORE the submodule imports below: observatory.py reads
# in_predicate_lock from this partially-initialized package.

_tenure = threading.local()


def enter_predicate_lock() -> None:
    _tenure.depth = getattr(_tenure, "depth", 0) + 1


def exit_predicate_lock() -> None:
    _tenure.depth = max(getattr(_tenure, "depth", 0) - 1, 0)


def in_predicate_lock() -> bool:
    return getattr(_tenure, "depth", 0) > 0


from .observatory import CapacitySample, CapacitySampler  # noqa: E402,F401
from .probe import frag_report, probe_headroom  # noqa: E402,F401
