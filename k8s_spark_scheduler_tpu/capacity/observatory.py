"""The capacity observatory sampler: fragmentation / headroom / queue
pressure as a queryable cluster-state timeline.

Sampling discipline (the whole point of the design):

- **Only on state change.**  The tensor mirror's ChangeFeed sequence is
  the trigger: an unchanged sequence proves an unchanged world, so
  ``maybe_sample`` is O(1) then.  The background thread parks on an
  Event the feed sets on publish, with a debounce so event bursts
  (a gang's worth of reservation writes) produce one sample.
- **Never under the extender lock.**  The sampler probes a snapshot —
  a consistent copy — so it needs no scheduling lock at all; the
  thread-local tenure flag (capacity/__init__) turns any accidental
  in-lock invocation into a counted refusal instead of lock-hold time.
- **Bounded everywhere.**  Probe shapes, (instance-group, zone) combos,
  and queue forecasts are capped (dropped counts are reported, never
  silent); the timeline is a ring keyed by (ChangeFeed sequence,
  snapshot content_key).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..metrics import names as mnames
from . import in_predicate_lock
from .probe import (
    DEFAULT_K_MAX,
    frag_report,
    frag_report_classes,
    probe_headroom,
    probe_headroom_classes,
)

logger = logging.getLogger(__name__)

DIM_NAMES = ("cpu", "memory", "nvidia.com/gpu")


def shape_key(driver_row, executor_row) -> str:
    """Deterministic label-safe key for a (driver, executor) resource
    shape in base units (milli-cpu / bytes / milli-gpu)."""
    d = tuple(int(x) for x in driver_row)
    e = tuple(int(x) for x in executor_row)
    return f"d{d[0]}.{d[1]}.{d[2]}-e{e[0]}.{e[1]}.{e[2]}"


@dataclass
class CapacitySample:
    """One point of the cluster-state timeline (plain data — every
    field JSON-serializable via :meth:`to_dict`)."""

    seq: int                      # ChangeFeed sequence at snapshot time
    content_key: Tuple            # (mirror instance, seq) — the exact state id
    structure_key: Tuple
    t: float                      # timesource.now() (virtual in the sim)
    trigger: str
    nodes: int = 0
    ready_nodes: int = 0
    free: Tuple[int, ...] = (0, 0, 0)             # per-dim total free
    largest_chunk: Tuple[int, ...] = (0, 0, 0)    # per-dim best single node
    usable_free_nodes: Tuple[int, ...] = (0, 0, 0)
    overdrawn_nodes: Tuple[int, ...] = (0, 0, 0)
    frag_index: Tuple[float, ...] = (0.0, 0.0, 0.0)
    # shape_key -> {"headroom": int, "usable": [3], "probes": int}
    headroom: Dict[str, Dict] = field(default_factory=dict)
    # "group|zone" -> {"nodes", "free", "largestChunk", "fragIndex",
    #                  "headroom": {shape_key: int}}
    groups: Dict[str, Dict] = field(default_factory=dict)
    # instance group -> {"used": [3], "allocatable": [3], "utilization",
    #                    "share": [3]}
    tenants: Dict[str, Dict] = field(default_factory=dict)
    # equivalence-class lane: {"count", "ratio", "indexCount",
    # "indexRatio", "free", "largestChunk", "fragIndex",
    # "headroom": {shape_key: int}, "expandMs"} — O(classes) twins of
    # the row-level analytics above, multiplicity-weighted
    classes: Dict = field(default_factory=dict)
    queue: List[Dict] = field(default_factory=list)
    queue_truncated: int = 0      # pending drivers beyond max_queue
    queued_gangs: int = 0
    pressure: int = 0             # queued gangs that do NOT fit right now
    probe_solves: int = 0
    probe_lane: str = ""
    shapes_dropped: int = 0
    groups_dropped: int = 0
    sample_ms: float = 0.0        # wall cost (diagnostic; not replayed)

    def to_dict(self) -> Dict:
        return {
            "seq": self.seq,
            "contentKey": list(self.content_key),
            "structureKey": list(self.structure_key),
            "t": self.t,
            "trigger": self.trigger,
            "nodes": self.nodes,
            "readyNodes": self.ready_nodes,
            "dims": list(DIM_NAMES),
            "free": [int(x) for x in self.free],
            "largestChunk": [int(x) for x in self.largest_chunk],
            "freeNodes": [int(x) for x in self.usable_free_nodes],
            "overdrawnNodes": [int(x) for x in self.overdrawn_nodes],
            "fragIndex": [round(float(x), 6) for x in self.frag_index],
            "headroom": self.headroom,
            "classes": self.classes,
            "groups": self.groups,
            "tenants": self.tenants,
            "queue": self.queue,
            "queueTruncated": self.queue_truncated,
            "queuedGangs": self.queued_gangs,
            "pressure": self.pressure,
            "probeSolves": self.probe_solves,
            "probeLane": self.probe_lane,
            "shapesDropped": self.shapes_dropped,
            "groupsDropped": self.groups_dropped,
            "sampleMs": round(self.sample_ms, 3),
        }


# default probe shape when the queue is empty: 1 CPU / 1 GiB / 0 GPU —
# the "could anything at all schedule" canary
_DEFAULT_SHAPE = (
    (1000, 1 << 30, 0),
    (1000, 1 << 30, 0),
)


@guarded_by(
    "_lock",
    "_ring",
    "_stats",
    "_last_seq",
    "_prev_pending",
    "_departures",
    "_last_forecast_t",
)
class CapacitySampler:
    """See module docstring.  Thread model: ``maybe_sample`` /
    ``sample_now`` may be called from the background thread, an HTTP
    read, or the sim loop; the ring and counters take the sampler lock,
    the probes themselves run lock-free on snapshot copies."""

    def __init__(
        self,
        snapshot_cache,
        pod_lister=None,
        waste_reporter=None,
        metrics=None,
        instance_group_label: str = "",
        ring_size: int = 256,
        debounce_seconds: float = 0.25,
        interval_seconds: float = 15.0,
        max_shapes: int = 16,
        max_group_zones: int = 16,
        max_queue: int = 64,
        k_max: int = DEFAULT_K_MAX,
    ):
        self._cache = snapshot_cache
        self._pod_lister = pod_lister
        self._waste = waste_reporter
        self._metrics = metrics
        self._group_label = instance_group_label
        self.debounce_seconds = float(debounce_seconds)
        self.interval_seconds = float(interval_seconds)
        self.max_shapes = int(max_shapes)
        self.max_group_zones = int(max_group_zones)
        self.max_queue = int(max_queue)
        self.k_max = int(k_max)

        self._lock = threading.Lock()
        # serializes whole samples (snapshot → probe → append → publish):
        # the HTTP freshen path and the background thread may race past
        # maybe_sample's gate together; unserialized, the slower sampler
        # could append an OLDER seq after a newer one (breaking the
        # ring's order) and its off-lock publish could prune the gauge
        # series the fresh sample just wrote.  Never taken on a
        # scheduling path — only sampler callers block on it.
        self._sample_mutex = threading.Lock()
        self._ring: Deque[CapacitySample] = deque(maxlen=ring_size)
        self._last_seq = -1
        # the tensor mirror deliberately publishes NO delta for nodeless
        # pods (queued-driver heartbeats must not churn the solver's
        # content sequence), so queue changes are detected via the pod
        # informer's driver-bucket revision — the same O(1) signal the
        # FIFO lister caches on
        self._last_queue_rev = -1
        self._stats = {
            "samples": 0,
            "skipped_unchanged": 0,
            "lock_violations": 0,
            "probe_solves": 0,
        }
        # admission-rate source for the time-to-admit forecast: pods
        # that left the pending-driver set between samples.  Each entry
        # is (interval_start, count) — the START of the inter-sample
        # interval the departures happened in, not the observation
        # time, so the rate's denominator never collapses to ~0 on the
        # first observed departure.
        self._prev_pending: set = set()
        self._departures: Deque[Tuple[float, int]] = deque(maxlen=64)
        self._last_forecast_t: Optional[float] = None

        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        feed = getattr(snapshot_cache, "feed", None)
        if feed is not None and hasattr(feed, "attach_wakeup"):
            feed.attach_wakeup(self._wake)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="capacity-sampler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    def _loop(self) -> None:
        feed = getattr(self._cache, "feed", None)
        while not self._stop.is_set():
            fired = self._wake.wait(timeout=self.interval_seconds)
            if self._stop.is_set():
                return
            if fired:
                if feed is not None and hasattr(feed, "hb_channel"):
                    # the observe side of the feed's publish→wakeup edge
                    racecheck.hb_observe(feed.hb_channel())
                self._wake.clear()
                # debounce: let the burst (one gang = many deltas) land
                # before paying one sample for all of it
                if self.debounce_seconds > 0:
                    time.sleep(self.debounce_seconds)
                self._wake.clear()
            try:
                self.maybe_sample(trigger="feed" if fired else "interval")
            except Exception:
                logger.exception("capacity sample failed (diagnostic only)")

    # -- sampling ------------------------------------------------------------

    def _queue_rev(self) -> int:
        if self._pod_lister is None:
            return -1
        try:
            from ..scheduler import labels as L

            return self._pod_lister.informer.selector_revision(
                L.SPARK_ROLE_LABEL, L.DRIVER
            )
        except Exception:
            return -1

    def maybe_sample(self, trigger: str = "feed") -> Optional[CapacitySample]:
        """Sample iff the ChangeFeed moved OR the driver queue changed
        since the last sample — O(1) when nothing changed."""
        seq = self._cache.feed.seq
        rev = self._queue_rev()
        with self._lock:
            racecheck.note_access(self, "_stats")
            if seq == self._last_seq and rev == self._last_queue_rev:
                self._stats["skipped_unchanged"] += 1
                return None
        return self.sample_now(trigger=trigger)

    def sample_now(self, trigger: str = "manual") -> Optional[CapacitySample]:
        """Probe the current snapshot unconditionally (modulo the
        extender-lock refusal) and append to the timeline."""
        if in_predicate_lock():
            # NEVER probe while holding the extender lock: refuse,
            # count, and let the next off-lock trigger pick it up
            with self._lock:
                racecheck.note_access(self, "_stats")
                self._stats["lock_violations"] += 1
            return None
        with self._sample_mutex:
            t0 = time.perf_counter()
            queue_rev = self._queue_rev()
            snap = self._cache.snapshot()
            sample = self._build_sample(snap, trigger)
            sample.sample_ms = (time.perf_counter() - t0) * 1000.0
            with self._lock:
                racecheck.note_access(self, "_ring")
                if self._ring and self._ring[-1].seq == sample.seq:
                    # an unconditional (HTTP/forced) re-sample of
                    # unchanged state replaces rather than duplicates
                    # the timeline key
                    self._ring[-1] = sample
                else:
                    self._ring.append(sample)
                self._last_seq = sample.seq
                self._last_queue_rev = queue_rev
                self._stats["samples"] += 1
                self._stats["probe_solves"] += sample.probe_solves
            self._publish(sample)
        return sample

    # -- read side -----------------------------------------------------------

    def latest(self) -> Optional[CapacitySample]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def history(self, limit: Optional[int] = None) -> List[CapacitySample]:
        with self._lock:
            items = list(self._ring)
        items.reverse()  # newest first
        if limit is not None and limit >= 0:
            items = items[:limit]
        return items

    def timeline(self) -> List[CapacitySample]:
        """Oldest-first (the artifact order)."""
        with self._lock:
            return list(self._ring)

    def find(self, seq: int) -> Optional[CapacitySample]:
        with self._lock:
            for s in self._ring:
                if s.seq == seq:
                    return s
        return None

    def diff(self, from_seq: int, to_seq: int) -> Optional[Dict]:
        """What changed between two timeline points (exact seq keys;
        ``history`` lists the available ones)."""
        a = self.find(from_seq)
        b = self.find(to_seq)
        if a is None or b is None:
            return None
        shape_keys = sorted(set(a.headroom) | set(b.headroom))
        return {
            "from": a.seq,
            "to": b.seq,
            "structureChanged": a.structure_key != b.structure_key,
            "nodes": b.nodes - a.nodes,
            "readyNodes": b.ready_nodes - a.ready_nodes,
            "free": [int(y - x) for x, y in zip(a.free, b.free)],
            "largestChunk": [
                int(y - x) for x, y in zip(a.largest_chunk, b.largest_chunk)
            ],
            "fragIndex": [
                round(float(y - x), 6)
                for x, y in zip(a.frag_index, b.frag_index)
            ],
            "headroom": {
                k: (
                    b.headroom.get(k, {}).get("headroom", 0)
                    - a.headroom.get(k, {}).get("headroom", 0)
                )
                for k in shape_keys
            },
            "pressure": b.pressure - a.pressure,
            "queuedGangs": b.queued_gangs - a.queued_gangs,
            "groupsAdded": sorted(set(b.groups) - set(a.groups)),
            "groupsRemoved": sorted(set(a.groups) - set(b.groups)),
        }

    def stats(self) -> Dict:
        with self._lock:
            out = dict(self._stats)
            out["ring"] = len(self._ring)
            out["ring_capacity"] = self._ring.maxlen
        return out

    @property
    def lock_violations(self) -> int:
        with self._lock:
            return self._stats["lock_violations"]

    # -- internals -----------------------------------------------------------

    def _pending_drivers(self) -> List:
        if self._pod_lister is None:
            return []
        from ..scheduler import labels as L

        drivers = self._pod_lister.list(
            label_selector={L.SPARK_ROLE_LABEL: L.DRIVER}
        )
        pending = [
            p
            for p in drivers
            if p.node_name == "" and p.meta.deletion_timestamp is None
        ]
        pending.sort(key=lambda p: (p.creation_timestamp, p.name))
        return pending

    def _gang_rows(self, pod):
        """(driver_row, executor_row, count) in base units, or None when
        the pod's annotations don't parse / aren't exact."""
        try:
            from ..ops.tensorize import _resources_to_base
            from ..scheduler.sparkpods import spark_app_demand_cached

            _, demand = spark_app_demand_cached(pod)
            drow, de = _resources_to_base(demand.driver_resources)
            erow, ee = _resources_to_base(demand.executor_resources)
            if not (de and ee):
                return None
            return (
                tuple(int(x) for x in drow),
                tuple(int(x) for x in erow),
                int(demand.min_executor_count),
            )
        except Exception:
            return None

    def _build_sample(self, snap, trigger: str) -> CapacitySample:
        now = timesource.now()
        sample = CapacitySample(
            seq=int(snap.content_key[1]),
            content_key=tuple(snap.content_key),
            structure_key=tuple(snap.structure_key),
            t=now,
            trigger=trigger,
        )
        n = len(snap.names)
        avail = snap.avail
        eligible = snap.ready & ~snap.unschedulable
        sample.nodes = n
        sample.ready_nodes = int(eligible.sum())

        total, largest, free_nodes, overdrawn, frag = frag_report(
            avail, eligible
        )
        sample.free = tuple(int(x) for x in total)
        sample.largest_chunk = tuple(int(x) for x in largest)
        sample.usable_free_nodes = tuple(int(x) for x in free_nodes)
        sample.overdrawn_nodes = tuple(int(x) for x in overdrawn)
        sample.frag_index = tuple(float(x) for x in frag)

        # gang shapes: the queued drivers' demands, bounded, else a canary
        pending = self._pending_drivers()
        sample.queued_gangs = len(pending)
        sample.queue_truncated = max(0, len(pending) - self.max_queue)
        # ALL pending gangs are shape-parsed (the demand parse is
        # per-pod cached — the FIFO path pays it anyway) so the
        # pressure gauge counts every known-not-fitting gang; only the
        # per-driver forecast ENTRIES are capped at max_queue
        gangs = []  # (pod, rows or None)
        shapes: Dict[str, Tuple] = {}
        dropped_shapes: set = set()
        for pod in pending:
            rows = self._gang_rows(pod)
            gangs.append((pod, rows))
            if rows is None:
                continue
            key = shape_key(rows[0], rows[1])
            if key not in shapes:
                if len(shapes) >= self.max_shapes:
                    dropped_shapes.add(key)
                    continue
                shapes[key] = (rows[0], rows[1])
        if not shapes:
            shapes[shape_key(*_DEFAULT_SHAPE)] = _DEFAULT_SHAPE
        sample.shapes_dropped = len(dropped_shapes)

        shape_list = sorted(shapes.items())
        shape_rows = np.array(
            [list(d) + list(e) for _, (d, e) in shape_list], dtype=np.int64
        )

        if n > 0 and sample.ready_nodes > 0:
            rank = np.where(eligible, np.int64(0), np.int64(2**31 - 1))
            headroom, usable, probes, lane = probe_headroom(
                avail, rank, eligible, shape_rows, self.k_max
            )
            sample.probe_lane = lane
            sample.probe_solves = int(probes.sum())
            for i, (key, _) in enumerate(shape_list):
                sample.headroom[key] = {
                    "headroom": int(headroom[i]),
                    "usable": [int(x) for x in usable[i]],
                    "probes": int(probes[i]),
                }
            self._per_group(
                snap, avail, eligible, shape_list, shape_rows, sample
            )
        else:
            sample.probe_lane = "empty"
            for key, _ in shape_list:
                sample.headroom[key] = {
                    "headroom": 0,
                    "usable": [0, 0, 0],
                    "probes": 0,
                }

        if n > 0:
            self._class_lane(snap, avail, eligible, shape_list, sample)
        self._tenants(snap, sample)
        self._forecast(gangs, pending, sample, now)
        return sample

    def _class_lane(
        self, snap, avail, eligible, shape_list, sample
    ) -> None:
        """Equivalence-class analytics (ROADMAP 2): group nodes by exact
        (availability, schedulability) and run the frag/headroom probes
        once per class with multiplicity weighting — O(classes) instead
        of O(nodes), identical results (test_class_compression.py pins
        it).  ``expandMs`` is this lane's whole wall cost: grouping +
        weighted probes + expanding class results back to the sample's
        node-level vocabulary."""
        t0 = time.perf_counter()
        try:
            from ..native import group_rows

            n_classes, cls = group_rows(
                avail, np.asarray(eligible, dtype=np.uint8)
            )
            if n_classes <= 0:
                return
            mult = np.bincount(cls, minlength=n_classes).astype(np.int64)
            # class ids are assigned in first-occurrence order, so the
            # sorted-unique first indices are the representatives
            _, reps = np.unique(cls, return_index=True)
            class_avail = avail[reps]
            class_elig = np.asarray(eligible, dtype=bool)[reps]
            total, largest, _, _, frag = frag_report_classes(
                class_avail, class_elig, mult
            )
            entry: Dict = {
                "count": int(n_classes),
                "ratio": round(len(snap.names) / n_classes, 3),
                "free": [int(x) for x in total],
                "largestChunk": [int(x) for x in largest],
                "fragIndex": [round(float(x), 6) for x in frag],
                "headroom": {},
            }
            if class_elig.any() and shape_list:
                shape_rows = np.array(
                    [list(d) + list(e) for _, (d, e) in shape_list],
                    dtype=np.int64,
                )
                headroom, _, probes = probe_headroom_classes(
                    class_avail, mult, class_elig, shape_rows, self.k_max
                )
                sample.probe_solves += int(probes.sum())
                for i, (key, _) in enumerate(shape_list):
                    entry["headroom"][key] = int(headroom[i])
            # the state-layer identity (rounded capacity × labels × AZ ×
            # schedulability, state/classindex.py) rides along: the
            # tpu.classes.{count,compression.ratio} gauges report IT —
            # the solver-facing exact grouping above is the analytics
            # lane's own key
            index = getattr(self._cache, "classes", None)
            if index is not None and hasattr(index, "stats"):
                n_cls, _n_nodes, ratio = index.stats()
                entry["indexCount"] = int(n_cls)
                entry["indexRatio"] = round(float(ratio), 3)
            entry["expandMs"] = round((time.perf_counter() - t0) * 1000.0, 3)
            sample.classes = entry
        except Exception:
            logger.exception("class analytics lane failed (diagnostic only)")

    def _per_group(
        self, snap, avail, eligible, shape_list, shape_rows, sample
    ) -> None:
        """Per-(instance-group, zone) fragmentation + headroom, bounded
        at max_group_zones combos (sorted — determinism over truncation
        luck)."""
        combos: Dict[Tuple[str, str], List[int]] = {}
        for i in range(len(snap.names)):
            group = snap.labels[i].get(self._group_label, "")
            zone = (
                snap.zone_names[snap.zone_id[i]]
                if 0 <= snap.zone_id[i] < len(snap.zone_names)
                else ""
            )
            combos.setdefault((group, zone), []).append(i)
        ordered = sorted(combos.items())
        if len(ordered) > self.max_group_zones:
            sample.groups_dropped = len(ordered) - self.max_group_zones
            ordered = ordered[: self.max_group_zones]
        for (group, zone), rows in ordered:
            idx = np.array(rows, dtype=np.int64)
            sub_avail = avail[idx]
            sub_elig = eligible[idx]
            total, largest, _, _, frag = frag_report(sub_avail, sub_elig)
            entry = {
                "nodes": len(rows),
                "readyNodes": int(sub_elig.sum()),
                "free": [int(x) for x in total],
                "largestChunk": [int(x) for x in largest],
                "fragIndex": [round(float(x), 6) for x in frag],
                "headroom": {},
            }
            if sub_elig.any():
                rank = np.where(sub_elig, np.int64(0), np.int64(2**31 - 1))
                headroom, _, probes, _ = probe_headroom(
                    sub_avail, rank, sub_elig, shape_rows, self.k_max
                )
                sample.probe_solves += int(probes.sum())
                for i, (key, _) in enumerate(shape_list):
                    entry["headroom"][key] = int(headroom[i])
            sample.groups["|".join((group, zone))] = entry

    def _tenants(self, snap, sample) -> None:
        """Per-instance-group utilization attribution: who holds the
        reserved capacity (usage rows are hard + soft reservations)."""
        groups: Dict[str, Dict] = {}
        usage = snap.usage
        alloc = snap.allocatable
        cluster_used = np.maximum(usage, 0).sum(axis=0)
        for i in range(len(snap.names)):
            group = snap.labels[i].get(self._group_label, "")
            g = groups.get(group)
            if g is None:
                g = groups[group] = {
                    "used": np.zeros(3, dtype=np.int64),
                    "allocatable": np.zeros(3, dtype=np.int64),
                }
            g["used"] += np.maximum(usage[i], 0)
            g["allocatable"] += np.maximum(alloc[i], 0)
        for group in sorted(groups):
            g = groups[group]
            used, allocatable = g["used"], g["allocatable"]
            with np.errstate(divide="ignore", invalid="ignore"):
                util = float(
                    np.max(
                        np.where(
                            allocatable > 0,
                            used / np.maximum(allocatable, 1),
                            0.0,
                        )
                    )
                )
                share = np.where(
                    cluster_used > 0, used / np.maximum(cluster_used, 1), 0.0
                )
            sample.tenants[group] = {
                "used": [int(x) for x in used],
                "allocatable": [int(x) for x in allocatable],
                "utilization": round(util, 6),
                "share": [round(float(x), 6) for x in share],
            }

    def _forecast(self, gangs, pending, sample, now: float) -> None:
        """Time-to-admit forecast per queued driver: probe verdict ×
        demand fulfillment state × the observed departure rate."""
        current_keys = {(p.namespace, p.name) for p in pending}
        with self._lock:
            racecheck.note_access(self, "_prev_pending")
            departed = len(self._prev_pending - current_keys)
            prev_t = self._last_forecast_t
            if self._prev_pending and departed and prev_t is not None:
                self._departures.append((prev_t, departed))
            self._prev_pending = current_keys
            self._last_forecast_t = now
            window = list(self._departures)
        rate = 0.0
        if window:
            # span runs from the start of the earliest interval that
            # produced a departure — a real prior sample time, so one
            # observation yields departures-per-inter-sample-interval,
            # not departures-per-epsilon
            span = now - window[0][0]
            if span > 0:
                rate = sum(n for _, n in window) / span

        # pressure is accounted over EVERY pending gang whose shape was
        # probed — the autoscaler-facing backlog signal must not cap at
        # max_queue — while forecast entries are emitted only for the
        # first max_queue positions (queueTruncated counts the rest)
        pressure = 0
        for position, (pod, rows) in enumerate(gangs):
            emit = position < self.max_queue
            entry = {
                "pod": pod.name,
                "namespace": pod.namespace,
                "queuePosition": position,
                "ageSeconds": round(max(now - pod.creation_timestamp, 0.0), 3),
            }
            if rows is None:
                if emit:
                    entry["state"] = "unparseable"
                    sample.queue.append(entry)
                continue
            drow, erow, count = rows
            key = shape_key(drow, erow)
            info = sample.headroom.get(key)
            if info is None:
                if emit:
                    entry["shape"] = key
                    entry["gangSize"] = count
                    entry["state"] = "shape-dropped"
                    sample.queue.append(entry)
                continue
            headroom = info["headroom"]
            fits = count <= headroom
            if not fits:
                pressure += 1
            if not emit:
                continue
            entry["shape"] = key
            entry["gangSize"] = count
            entry["fitsNow"] = fits
            entry["headroom"] = headroom
            if self._waste is not None and hasattr(
                self._waste, "scheduling_info"
            ):
                demand = self._waste.scheduling_info(pod.namespace, pod.name)
                if demand is None or demand.get("demandCreatedAt") is None:
                    entry["demandState"] = "no-demand"
                elif demand.get("demandFulfilledAt") is not None:
                    entry["demandState"] = "demand-fulfilled"
                else:
                    entry["demandState"] = "demand-pending"
            if fits:
                entry["state"] = (
                    "admitting-next" if position == 0 else "queued-behind"
                )
                # null, not 0.0, when no admission rate has been
                # observed yet: a queued-behind gang with an unknown
                # wait must not read like admitting-next
                if position == 0:
                    entry["forecastSeconds"] = 0.0
                elif rate > 0:
                    entry["forecastSeconds"] = round(position / rate, 3)
                else:
                    entry["forecastSeconds"] = None
            else:
                entry["state"] = "needs-scaleup"
                entry["forecastSeconds"] = None
            sample.queue.append(entry)
        sample.pressure = pressure

    # -- metrics -------------------------------------------------------------

    def _publish(self, sample: CapacitySample) -> None:
        m = self._metrics
        if m is None:
            return
        m.counter(
            mnames.CAPACITY_SAMPLE_COUNT, {"trigger": sample.trigger}
        )
        m.histogram(mnames.CAPACITY_SAMPLE_TIME, sample.sample_ms / 1000.0)
        m.histogram(mnames.CAPACITY_PROBE_SOLVES, float(sample.probe_solves))
        for j, dim in enumerate(DIM_NAMES):
            m.gauge(mnames.CAPACITY_FREE, float(sample.free[j]), {"dim": dim})
            m.gauge(
                mnames.CAPACITY_LARGEST_CHUNK,
                float(sample.largest_chunk[j]),
                {"dim": dim},
            )
            m.gauge(
                mnames.CAPACITY_FRAGMENTATION,
                float(sample.frag_index[j]),
                {"dim": dim},
            )
        headroom_tags = []
        for key, info in sample.headroom.items():
            tags = {
                "shape": key,
                mnames.TAG_INSTANCE_GROUP: "",
                mnames.TAG_ZONE: "",
            }
            headroom_tags.append(tags)
            m.gauge(mnames.CAPACITY_HEADROOM, float(info["headroom"]), tags)
        for combo, entry in sample.groups.items():
            group, _, zone = combo.partition("|")
            for key, h in entry["headroom"].items():
                tags = {
                    "shape": key,
                    mnames.TAG_INSTANCE_GROUP: group,
                    mnames.TAG_ZONE: zone,
                }
                headroom_tags.append(tags)
                m.gauge(mnames.CAPACITY_HEADROOM, float(h), tags)
        tenant_tags = []
        for group, entry in sample.tenants.items():
            tags = {mnames.TAG_INSTANCE_GROUP: group}
            tenant_tags.append(tags)
            m.gauge(mnames.CAPACITY_UTILIZATION, entry["utilization"], tags)
        # shapes and (group, zone) combos churn with the queue and the
        # fleet: drop the series this sample did NOT publish, so a
        # vanished label combination stops exporting its last stale
        # value and live cardinality stays bounded by the sampler caps
        if hasattr(m, "prune_gauges"):
            m.prune_gauges(mnames.CAPACITY_HEADROOM, headroom_tags)
            m.prune_gauges(mnames.CAPACITY_UTILIZATION, tenant_tags)
        if sample.classes:
            # fleet shape diversity: the state-layer class identity when
            # the mirror carries an index, else the analytics grouping
            m.gauge(
                mnames.CLASSES_COUNT,
                float(sample.classes.get("indexCount",
                                         sample.classes["count"])),
            )
            m.gauge(
                mnames.CLASSES_COMPRESSION_RATIO,
                float(sample.classes.get("indexRatio",
                                         sample.classes["ratio"])),
            )
            if "expandMs" in sample.classes:
                m.histogram(
                    mnames.CLASSES_EXPAND_MS,
                    float(sample.classes["expandMs"]),
                )
        m.gauge(mnames.CAPACITY_QUEUED_GANGS, float(sample.queued_gangs))
        m.gauge(mnames.CAPACITY_QUEUE_PRESSURE, float(sample.pressure))
        for entry in sample.queue:
            forecast = entry.get("forecastSeconds")
            if forecast is not None:
                m.histogram(mnames.CAPACITY_TIME_TO_ADMIT, float(forecast))
