"""What-if capacity probes: the largest admissible gang per resource
shape and a per-dimension fragmentation report, against a FIXED
availability basis.

Feasibility replicates the solver's own rule exactly (``step_app_plain``
in native/fifo_solver.cpp: clamp-sum capacity total + the driver-row
probe), which all three queue policies share — distribute-evenly only
changes placement, and the min-frag drain is work-conserving — so a
probe verdict always matches the real solver's verdict on the same
state (tests/test_capacity.py proves it across policies and seeds).
Feasibility is monotone in the executor count (per node
``min(c,k)·(k+1) ≥ min(c,k+1)·k``), so the headroom search is a
bisection: O(log k_max) feasibility evaluations over per-node
capacities computed once per shape.

Two lanes, identical results on the shared domain:

- native ``fifo_probe_headroom`` / ``fifo_frag_report`` on GCD-scaled
  int32 rows (the same scaling the solver marshal uses);
- the numpy twin below, exact on raw int64 base units — the fallback
  when the toolchain is absent or the basis cannot scale to int32.

Read-only diagnostics: no scheduling decision consumes a probe output.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

_BIG = np.int64(2**62)
INT32_SAFE = 2**31 - 1
# headroom search roof: far above any real gang, still int32-safe for
# the native lane's clamp arithmetic
DEFAULT_K_MAX = 1_000_000


def caps_unclamped(
    avail: np.ndarray, exec_ok: np.ndarray, executor: np.ndarray
) -> np.ndarray:
    """Per-node executor capacity, UNCLAMPED (values ≤ 0 = ineligible):
    exact floor division per nonzero requirement dimension, a
    zero-requirement dimension binds only when its availability is
    overdrawn — capacity.go:36-75 semantics, the mf_cap_one formula."""
    caps = np.full(avail.shape[0], _BIG, dtype=np.int64)
    for j in range(3):
        e = int(executor[j])
        if e == 0:
            caps = np.where(avail[:, j] >= 0, caps, np.int64(-1))
        else:
            caps = np.minimum(
                caps, np.floor_divide(avail[:, j], max(e, 1))
            )
    return np.where(np.asarray(exec_ok, dtype=bool), caps, np.int64(0))


def _feasible(
    avail: np.ndarray,
    exec_ok: np.ndarray,
    cand_mask: np.ndarray,
    caps: np.ndarray,
    driver: np.ndarray,
    executor: np.ndarray,
    k: int,
) -> bool:
    """step_app_plain's admission rule at queue position 0."""
    if k <= 0:
        # a zero-executor gang admits iff some candidate covers the
        # driver row (total ≥ 0 is vacuous)
        return bool((cand_mask & (avail >= driver).all(axis=1)).any())
    ck = np.clip(caps, 0, k)
    total = int(ck.sum())
    if total < k:
        return False
    idx = np.flatnonzero(cand_mask & (avail >= driver).all(axis=1))
    if not len(idx):
        return False
    cwd = np.clip(
        caps_unclamped(avail[idx] - driver, exec_ok[idx], executor), 0, k
    )
    return bool((total - ck[idx] + cwd >= k).any())


def probe_headroom_numpy(
    avail: np.ndarray,        # [N, 3] int64 availability (base units)
    driver_rank: np.ndarray,  # [N] — rank < INT32_SAFE marks a candidate
    exec_ok: np.ndarray,      # [N] bool
    shapes: np.ndarray,       # [S, 6] int64: d0..2 e0..2 (base units)
    k_max: int = DEFAULT_K_MAX,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(headroom[S], usable[S,3], probes[S]) int64 — the numpy twin of
    the native ``fifo_probe_headroom``."""
    avail = np.asarray(avail, dtype=np.int64)
    exec_ok = np.asarray(exec_ok, dtype=bool)
    shapes = np.asarray(shapes, dtype=np.int64)
    cand_mask = np.asarray(driver_rank, dtype=np.int64) < INT32_SAFE
    ns = shapes.shape[0]
    headroom = np.zeros(ns, dtype=np.int64)
    usable = np.zeros((ns, 3), dtype=np.int64)
    probes = np.zeros(ns, dtype=np.int64)
    for s in range(ns):
        d, e = shapes[s, 0:3], shapes[s, 3:6]
        caps = caps_unclamped(avail, exec_ok, e)
        total_kmax = int(np.clip(caps, 0, k_max).sum())
        usable[s] = total_kmax * e

        n_probes = 0

        def feasible(k: int) -> bool:
            nonlocal n_probes
            n_probes += 1
            return _feasible(avail, exec_ok, cand_mask, caps, d, e, k)

        hi = min(int(k_max), total_kmax)
        h = 0
        if hi >= 1:
            if feasible(hi):
                h = hi
            elif feasible(1):
                lo = 1
                while hi - lo > 1:
                    mid = lo + (hi - lo) // 2
                    if feasible(mid):
                        lo = mid
                    else:
                        hi = mid
                h = lo
        headroom[s] = h
        probes[s] = n_probes
    return headroom, usable, probes


def probe_headroom(
    avail: np.ndarray,
    driver_rank: np.ndarray,
    exec_ok: np.ndarray,
    shapes: np.ndarray,
    k_max: int = DEFAULT_K_MAX,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, str]:
    """(headroom[S], usable[S,3] in BASE units, probes[S], lane) — the
    native lane on GCD-scaled int32 rows when it applies, the exact
    numpy twin otherwise.  Both lanes return identical headrooms
    (capacities are exact integer quotients, so decisions are
    scale-invariant)."""
    avail = np.ascontiguousarray(avail, dtype=np.int64)
    shapes = np.ascontiguousarray(shapes, dtype=np.int64).reshape(-1, 6)
    n, ns = avail.shape[0], shapes.shape[0]
    if n == 0 or ns == 0:
        return (
            np.zeros(ns, dtype=np.int64),
            np.zeros((ns, 3), dtype=np.int64),
            np.zeros(ns, dtype=np.int64),
            "empty",
        )
    try:
        from ..native import scale_rows_int32
        from ..native.fifo import native_probe_available, probe_headroom_native

        if native_probe_available():
            demand_rows = shapes.reshape(-1, 3)  # [2S, 3] d/e interleaved
            ok, avail_s, demands_s, scale = scale_rows_int32(
                avail, demand_rows, n
            )
            if ok:
                rank32 = np.where(
                    np.asarray(driver_rank, dtype=np.int64) < INT32_SAFE,
                    np.arange(n, dtype=np.int64),
                    np.int64(INT32_SAFE),
                ).astype(np.int32)
                out = probe_headroom_native(
                    avail_s[:n],
                    rank32,
                    np.asarray(exec_ok, dtype=bool),
                    demands_s.reshape(ns, 6),
                    min(int(k_max), INT32_SAFE),
                )
                if out is not None:
                    headroom, usable_scaled, probes = out
                    return headroom, usable_scaled * scale[None, :], probes, "native"
    except Exception:  # toolchain/scaling problems degrade to numpy
        pass
    headroom, usable, probes = probe_headroom_numpy(
        avail, driver_rank, exec_ok, shapes, k_max
    )
    return headroom, usable, probes, "numpy"


def _feasible_classes(
    avail: np.ndarray,      # [C, 3] class representative availability
    elig: np.ndarray,       # [C] bool
    mult: np.ndarray,       # [C] int64 multiplicities
    caps: np.ndarray,       # [C] per-class executor capacity
    driver: np.ndarray,
    executor: np.ndarray,
    k: int,
) -> bool:
    """step_app_plain's admission rule over the class multiset: every
    member of a class contributes the same clamped capacity, so
    Σ_nodes min(cap, k) = Σ_classes min(cap_c, k)·mult_c, and the
    driver probe only asks whether SOME member of SOME class covers the
    driver row — verdicts are identical to the row-level rule by
    construction."""
    live = mult > 0
    if k <= 0:
        return bool((live & elig & (avail >= driver).all(axis=1)).any())
    ck = np.clip(caps, 0, k)
    total = int((ck * mult).sum())
    if total < k:
        return False
    idx = np.flatnonzero(live & elig & (avail >= driver).all(axis=1))
    if not len(idx):
        return False
    # one member of the driver class hosts the driver: its contribution
    # switches from ck to cap-with-driver, the other mult-1 keep ck
    cwd = np.clip(
        caps_unclamped(avail[idx] - driver, elig[idx], executor), 0, k
    )
    return bool((total - ck[idx] + cwd >= k).any())


def probe_headroom_classes(
    class_avail: np.ndarray,  # [C, 3] int64 class representative rows
    class_mult: np.ndarray,   # [C] int64 nodes per class
    class_elig: np.ndarray,   # [C] bool schedulability (class-uniform)
    shapes: np.ndarray,       # [S, 6] int64: d0..2 e0..2 (base units)
    k_max: int = DEFAULT_K_MAX,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(headroom[S], usable[S,3], probes[S]) — the multiplicity-weighted
    class twin of :func:`probe_headroom_numpy`: O(classes) per
    feasibility check instead of O(nodes), identical headrooms
    (tests/test_class_compression.py pins the parity)."""
    avail = np.asarray(class_avail, dtype=np.int64).reshape(-1, 3)
    mult = np.asarray(class_mult, dtype=np.int64)
    elig = np.asarray(class_elig, dtype=bool)
    shapes = np.asarray(shapes, dtype=np.int64).reshape(-1, 6)
    ns = shapes.shape[0]
    headroom = np.zeros(ns, dtype=np.int64)
    usable = np.zeros((ns, 3), dtype=np.int64)
    probes = np.zeros(ns, dtype=np.int64)
    for s in range(ns):
        d, e = shapes[s, 0:3], shapes[s, 3:6]
        caps = caps_unclamped(avail, elig, e)
        total_kmax = int((np.clip(caps, 0, k_max) * mult).sum())
        usable[s] = total_kmax * e

        n_probes = 0

        def feasible(k: int) -> bool:
            nonlocal n_probes
            n_probes += 1
            return _feasible_classes(avail, elig, mult, caps, d, e, k)

        hi = min(int(k_max), total_kmax)
        h = 0
        if hi >= 1:
            if feasible(hi):
                h = hi
            elif feasible(1):
                lo = 1
                while hi - lo > 1:
                    mid = lo + (hi - lo) // 2
                    if feasible(mid):
                        lo = mid
                    else:
                        hi = mid
                h = lo
        headroom[s] = h
        probes[s] = n_probes
    return headroom, usable, probes


def frag_report_classes(
    class_avail: np.ndarray, class_elig: np.ndarray, class_mult: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Multiplicity-weighted class twin of :func:`frag_report` (numpy
    lane): sums weight each class by its node count, maxima ignore the
    weights — outputs are identical to the row-level report on the
    expanded rows by construction."""
    avail = np.asarray(class_avail, dtype=np.int64).reshape(-1, 3)
    mult = np.asarray(class_mult, dtype=np.int64)
    mask = np.asarray(class_elig, dtype=bool) & (mult > 0)
    if avail.shape[0] == 0 or not mask.any():
        z = np.zeros(3, dtype=np.int64)
        return z, z.copy(), z.copy(), z.copy(), np.zeros(3, dtype=float)
    rows = avail[mask]
    m = mult[mask][:, None]
    pos = np.maximum(rows, 0)
    total = (pos * m).sum(axis=0)
    largest = pos.max(axis=0)
    free_nodes = ((rows > 0) * m).sum(axis=0).astype(np.int64)
    overdrawn = ((rows < 0) * m).sum(axis=0).astype(np.int64)
    return total, largest, free_nodes, overdrawn, _frag_index(total, largest)


def _frag_index(total: np.ndarray, largest: np.ndarray) -> np.ndarray:
    """Shared final step of both lanes — computed from the SAME base
    units, so native and numpy are bit-identical."""
    with np.errstate(divide="ignore", invalid="ignore"):
        frag = np.where(total > 0, 1.0 - largest / np.maximum(total, 1), 0.0)
    return frag.astype(float)


def frag_report(
    avail: np.ndarray, exec_ok: np.ndarray
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(total_free[3], largest_chunk[3], free_nodes[3], overdrawn[3],
    frag_index[3]) over the eligible rows, in base units.  frag_index =
    1 − largest/total per dimension (0 when nothing is free): 0 = all
    free capacity sits on one node (schedulable as one chunk), → 1 =
    free capacity is dust spread across many nodes.

    One native sweep (``fifo_frag_report`` on GCD-scaled int32 rows,
    totals unscaled back to base units) when the rows scale exactly,
    the numpy twin otherwise — positive sums, maxima, and sign counts
    are all scale-equivariant, so the lanes agree exactly."""
    avail = np.ascontiguousarray(avail, dtype=np.int64)
    mask = np.asarray(exec_ok, dtype=bool)
    if avail.shape[0] == 0 or not mask.any():
        z = np.zeros(3, dtype=np.int64)
        return z, z.copy(), z.copy(), z.copy(), np.zeros(3, dtype=float)
    try:
        from ..native import scale_rows_int32
        from ..native.fifo import frag_report_native

        n = avail.shape[0]
        ok, avail_s, _, scale = scale_rows_int32(
            avail, np.zeros((0, 3), dtype=np.int64), n
        )
        if ok:
            out = frag_report_native(avail_s[:n], mask)
            if out is not None:
                total = out[:, 0] * scale
                largest = out[:, 1] * scale
                return (
                    total,
                    largest,
                    out[:, 2].copy(),
                    out[:, 3].copy(),
                    _frag_index(total, largest),
                )
    except Exception:  # toolchain/scaling problems degrade to numpy
        pass
    rows = avail[mask]
    pos = np.maximum(rows, 0)
    total = pos.sum(axis=0)
    largest = pos.max(axis=0)
    free_nodes = (rows > 0).sum(axis=0).astype(np.int64)
    overdrawn = (rows < 0).sum(axis=0).astype(np.int64)
    return total, largest, free_nodes, overdrawn, _frag_index(total, largest)
