"""Reference-quirk compatibility policy (documentation home).

The reference has a handful of accidental-looking behaviors that are
nevertheless load-bearing for decision parity.  We replicate them
deliberately (each site carries a ``# QUIRK`` comment); the install key
``strict-reference-parity`` (**default on**) names the policy and lets
operators opt out of the ones that are safe to correct per-deployment.
The flag is plain configuration — threaded from ``config.Install``
through ``server/wiring.py`` into the consuming instances (no process
globals), so two servers in one process can run different modes:

1. **Double overhead-add on the executor reschedule path**
   (reference ``resource.go:638-643``): nodes carrying reservations see
   ``allocatable − reserved − 2×overhead``.  Off → overhead is counted
   once, like the driver path.  Consumer:
   ``scheduler/extender.py`` (``strict_reference_parity`` ctor arg).
2. **Minimal-fragmentation efficiency omission**
   (reference ``minimal_fragmentation.go:59-94``): executor placements
   are not folded into the reserved map, so reported packing
   efficiencies reflect only the driver.  Off → executor reservations
   are folded in and efficiencies are complete (this also changes which
   AZ ``single-az-minimal-fragmentation`` picks, since the AZ choice
   ranks by avg efficiency).  Consumers:
   ``ops/packers.make_minimal_fragmentation`` and
   ``ops/batch_adapter.TpuBatchBinpacker``, both built by
   ``ops/registry.select_binpacker(name, strict_reference_parity=...)``.

Quirks that are NOT switchable (kept identically in both modes) are the
ones that define the admission semantics shared by the host oracles and
the device kernels — correcting them would change which gangs are
admitted and break the zero-feasibility-regression gate rather than
merely report different numbers:

- FIFO post-placement usage subtraction assigns (not accumulates)
  per-node entries (``sparkpods.go:139-146``; ``scheduler/sparkpods.py``
  + ``ops/batch_solver.usage_delta``).
- ``_choose_best_result`` requires a strict efficiency improvement, so
  an all-zero-efficiency AZ set is reported infeasible
  (``single_az.go:75-97``).
- Failover's greedy node fill does not refund the failed probe
  (``failover.go:424-427``).

See ``docs/design.md`` § "Reference-parity compatibility mode" for the
full behavior table.
"""

DEFAULT_STRICT = True
