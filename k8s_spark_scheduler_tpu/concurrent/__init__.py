"""Concurrent admission: optimistic speculative solves + FIFO commits.

The contention observatory (PR 11) proved the extender's critical path
is solver tenure held under the single predicate lock (hold p95 ~32ms,
dominant segment = solve).  This package moves that tenure out from
under the lock, Borg/Omega style: independent Filter requests are
solved *speculatively* in parallel against ChangeFeed-seq-stamped
snapshot bases, then committed through a **FIFO-ordered commit gate**
that revalidates each speculative verdict against the then-current
basis (O(1) seq check → exact memcmp rescue → bounded re-solve on
conflict) before the reservation write-back.

The safety argument is by construction, not by hope:

- commits execute strictly in ticket (arrival) order, one at a time,
  through the *unchanged* serial extender — the concurrent engine never
  emits a decision the serial FIFO scheduler would not have made;
- a speculative verdict is consumed only when the commit-time basis is
  *identical* to the speculation basis (same snapshot content key, or a
  byte-equal availability/schedulability memcmp, same earlier-drivers
  queue, same skip verdicts); anything else is a conflict and the
  normal warm delta-solve runs under the lock (the bounded re-solve);
- the speculative solve uses the stateless cold tensor lane on a
  per-thread solver clone, and warm ≡ cold decision parity is already
  pinned by the delta-solve parity guard — so a consumed verdict equals
  what the serial path would have computed on the identical basis.

Multi-active operation: standby replicas from the HA fabric serve
speculative solves against their own warm bases and forward
:class:`~.commitgate.CommitIntent`\\ s to the epoch-fenced committer,
which refuses intents formed under a stale leadership epoch
(:class:`~..ha.fencing.FencedWriter` already refuses the write-back
itself by construction — I-H3).

Proof burden lives in :mod:`..analysis.mcscenarios`
(``concurrent-commit-fifo``), the crash matrix (three crash points in
the speculation→commit window), the multi-replica chaos sim scenario,
and the 5-seed byte-identity property test (``tests/test_concurrent.py``).
"""

from __future__ import annotations

from .commitgate import CommitGate, CommitIntent
from .engine import ConcurrentAdmissionEngine
from .speculation import SpeculativeVerdict, Speculator

__all__ = [
    "CommitGate",
    "CommitIntent",
    "ConcurrentAdmissionEngine",
    "SpeculativeVerdict",
    "Speculator",
]
