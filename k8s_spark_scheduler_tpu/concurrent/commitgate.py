"""FIFO-ordered commit gate: linearizable turn-taking for admission.

Tickets are issued at request arrival under the gate lock, so the gate
order *is* the arrival order.  Commits then execute strictly in ticket
order: :meth:`CommitGate.await_turn` parks a request until every earlier
ticket has retired, and :meth:`CommitGate.retire` advances the head past
the retiring ticket (and past any earlier-aborted tickets), waking
exactly the new head.  The short commit critical section this enforces
replaces solver tenure under the predicate lock — ROADMAP-1's payoff.

Aborts compose: a request whose deadline expires before its turn (or
whose speculation is cancelled) retires without committing and later
tickets skip over it — FIFO among *committed* requests is preserved,
which is the linearizability the model-check scenario
(``concurrent-commit-fifo``) proves over every explored interleaving.

Waiting is pluggable: production uses ``threading.Event``; the model
checker injects :class:`~..analysis.modelcheck.CoopEvent` so parked
turns stay visible to the cooperative scheduler (a raw blocking wait
inside a controlled thread would read as a stuck schedule).

:class:`CommitIntent` is the multi-active envelope: a standby replica's
speculative verdict plus the fencing epoch it was served under.  The
committer refuses intents from a stale epoch before they ever reach the
gate (and the :class:`~..ha.fencing.FencedWriter` on the write-back
path refuses the actual write by construction — I-H3)."""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Set

from ..analysis.guarded import guarded_by


@dataclass
class CommitIntent:
    """A speculative verdict forwarded for epoch-fenced commitment.

    ``epoch`` is the fencing epoch the speculation was served under
    (the sender's view of the current leadership term); the committer
    compares it against the live epoch and refuses mismatches —
    a deposed replica's intents can never land after failover."""

    pod_name: str
    namespace: str
    epoch: int
    args: Any = None
    verdict: Any = None
    origin: str = ""


@guarded_by(
    "_lock",
    "_next_ticket",
    "_head",
    "_retired",
    "_waiters",
    "_committed_total",
    "_aborted_total",
    "_max_queue_depth",
)
class CommitGate:
    """Ticket dispenser + FIFO turn-keeper for admission commits."""

    def __init__(self, event_factory: Callable[[], Any] = threading.Event):
        self._lock = threading.Lock()
        self._event_factory = event_factory
        # next ticket to issue (arrival order) / next ticket to commit
        self._next_ticket = 0
        self._head = 0
        # tickets that retired ahead of becoming head (aborts, or the
        # head itself mid-advance); drained by the head-advance loop
        self._retired: Set[int] = set()
        # ticket -> park event, registered under the lock so a retire
        # that advances the head can never miss a waiter (the event is
        # sticky: set-before-wait still wakes)
        self._waiters: Dict[int, Any] = {}
        self._committed_total = 0
        self._aborted_total = 0
        self._max_queue_depth = 0

    # -- tickets ----------------------------------------------------------

    def ticket(self) -> int:
        """Issue the next FIFO ticket; the issue order is the commit
        order."""
        with self._lock:
            t = self._next_ticket
            self._next_ticket += 1
            depth = self._next_ticket - self._head
            if depth > self._max_queue_depth:
                self._max_queue_depth = depth
            return t

    def head(self) -> int:
        with self._lock:
            return self._head

    def depth(self) -> int:
        """Tickets issued but not yet retired."""
        with self._lock:
            return self._next_ticket - self._head - len(self._retired)

    # -- turn-taking ------------------------------------------------------

    def await_turn(self, ticket: int) -> None:
        """Park until ``ticket`` is the commit head.  Returns
        immediately when it already is (the common uncontended case)."""
        with self._lock:
            if self._head == ticket:
                return
            ev = self._waiters.setdefault(ticket, self._event_factory())
        ev.wait()

    def retire(self, ticket: int, committed: bool) -> None:
        """Mark ``ticket`` finished (committed or aborted) and advance
        the head past every contiguously-retired ticket, waking the new
        head's waiter if one is parked."""
        wake = None
        with self._lock:
            self._retired.add(ticket)
            if committed:
                self._committed_total += 1
            else:
                self._aborted_total += 1
            while self._head in self._retired:
                self._retired.discard(self._head)
                self._waiters.pop(self._head, None)
                self._head += 1
            wake = self._waiters.get(self._head)
        if wake is not None:
            wake.set()

    # -- observability ----------------------------------------------------

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "issued": self._next_ticket,
                "head": self._head,
                "committed": self._committed_total,
                "aborted": self._aborted_total,
                "max_queue_depth": self._max_queue_depth,
            }
