"""The concurrent admission engine: speculate in parallel, commit FIFO.

:meth:`ConcurrentAdmissionEngine.predicate` is a drop-in for
``SparkSchedulerExtender.predicate`` (the HTTP layer routes through it
when ``concurrent.enabled``).  Per request:

1. a FIFO ticket is issued at arrival (the commit order);
2. the speculative solve runs on the request's own thread, outside any
   lock (:mod:`.speculation`);
3. the request waits its turn at the commit gate (:mod:`.commitgate`),
   re-checks its deadline at gate entry (expired requests abandon their
   speculative work and answer fail-fast without ever taking the
   predicate lock), then executes the *serial* extender with the
   verdict installed as the ``speculation_intake`` hook — the extender
   revalidates (seq → memcmp → conflict) inside the predicate lock and
   either consumes the verdict or re-solves on the warm delta path.

Because commits are the unchanged serial extender run strictly in
ticket order, the decision stream is byte-identical to a serial run of
the same workload — the 5-seed property test pins this.

Crash points (swept by the HA crash matrix) bracket the
speculation→commit window: ``concurrent.speculation-solved`` after the
speculative solve, ``concurrent.commit-revalidated`` after the gate
admits the commit (verdict revalidation about to execute under the
lock), ``concurrent.commit-written`` after the write-back returned but
before the response leaves — exactly-once reservation state across a
cold restart is the matrix's audit.

Multi-active: a standby replica speculates against its own warm basis
(:meth:`make_intent`) and forwards a
:class:`~.commitgate.CommitIntent`; the committer
(:meth:`submit_intent`) refuses intents stamped with a stale fencing
epoch before they reach the gate — and the
:class:`~..ha.fencing.FencedWriter` on the write-back path refuses the
actual write by construction even if one slipped through (I-H3)."""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..analysis.guarded import guarded_by
from ..ha import crashpoint
from ..ha.fencing import StaleEpochError
from ..metrics import names as mnames
from ..metrics.registry import MetricsRegistry, default_registry
from ..resilience import deadline as req_deadline
from ..scheduler.extender import FAILURE_DEADLINE
from .commitgate import CommitGate, CommitIntent
from .speculation import Speculator


@guarded_by("_stats_lock", "_commit_results")
class ConcurrentAdmissionEngine:
    """Speculation layer + FIFO commit gate over the serial extender."""

    def __init__(
        self,
        extender,
        config,
        metrics: MetricsRegistry | None = None,
        epoch_source: Optional[Callable[[], int]] = None,
    ):
        self._extender = extender
        self._config = config
        self._metrics = metrics or default_registry
        # the fencing-epoch reader (HA wiring); None on single-replica
        self._epoch_source = epoch_source
        self.gate = CommitGate()
        self.speculator = Speculator(
            extender, metrics=self._metrics,
            max_inflight=config.max_inflight_speculations,
        )
        self._stats_lock = threading.Lock()
        self._commit_results: Dict[str, int] = {}

    # -- stats ------------------------------------------------------------

    def _note_commit(self, result: str) -> None:
        self._metrics.counter(
            mnames.CONCURRENT_COMMIT_RESULT, {"result": result}
        )
        if result in ("conflict", "queue-drift", "skip-drift", "candidate-drift"):
            self._metrics.counter(mnames.CONCURRENT_COMMIT_CONFLICTS)
        with self._stats_lock:
            self._commit_results[result] = self._commit_results.get(result, 0) + 1

    def stats(self) -> Dict[str, Any]:
        with self._stats_lock:
            results = dict(self._commit_results)
        return {
            "gate": self.gate.stats(),
            "commit_results": results,
            "inflight_speculations": self.speculator.inflight(),
        }

    # -- the request path -------------------------------------------------

    def predicate(
        self,
        args,
        ticket: Optional[int] = None,
        post_commit: Optional[Callable[[Any], None]] = None,
        verdict=None,
    ):
        """Concurrent Filter: speculate, then commit in FIFO order.

        ``ticket`` lets a caller pre-assign the FIFO slot (the harness
        and the property test issue tickets in workload order before
        fanning requests across threads); ``post_commit`` runs inside
        the commit turn, after the decision — the deterministic stand-in
        for the kube bind that follows a granted Filter."""
        if ticket is None:
            ticket = self.gate.ticket()
        committed = False
        try:
            if verdict is None and self._config.speculation:
                verdict = self.speculator.speculate(ticket, args)
            crashpoint.maybe_crash(crashpoint.CONCURRENT_SPECULATION_SOLVED)

            # commit-gate entry: the deadline is checked HERE, not only
            # at the extender's phase boundaries — an expired request
            # abandons its speculative work and never takes the lock
            try:
                req_deadline.check("commit-gate")
            except req_deadline.DeadlineExceeded as err:
                if verdict is not None:
                    self._metrics.counter(
                        mnames.CONCURRENT_SPECULATION_CANCELLED,
                        {"phase": "commit-gate"},
                    )
                self._metrics.counter(
                    mnames.RESILIENCE_DEADLINE_EXPIRED_COUNT,
                    {"phase": "commit-gate"},
                )
                return self._extender._fail_with_message(
                    FAILURE_DEADLINE, args, str(err)
                )

            t0 = time.perf_counter()
            self.gate.await_turn(ticket)
            self._metrics.histogram(
                mnames.CONCURRENT_TICKET_WAIT_TIME, time.perf_counter() - t0
            )
            self._metrics.gauge(
                mnames.CONCURRENT_INFLIGHT, self.speculator.inflight()
            )
            crashpoint.maybe_crash(crashpoint.CONCURRENT_COMMIT_REVALIDATED)
            result = self._commit(args, verdict)
            committed = True
            crashpoint.maybe_crash(crashpoint.CONCURRENT_COMMIT_WRITTEN)
            if post_commit is not None:
                post_commit(result)
            return result
        finally:
            # retire must be unskippable: if finish() ever raised, a
            # skipped retire would stall the FIFO line forever
            try:
                self.speculator.finish(ticket)
            finally:
                self.gate.retire(ticket, committed)

    def _commit(self, args, verdict):
        """Execute the serial extender under this ticket's turn, with
        the speculative verdict (if any) installed as the revalidation
        intake.  Only one commit runs at a time (the gate guarantees
        it), so the hook handoff on the shared extender is single-
        writer by construction."""
        if verdict is None:
            self._note_commit("serial")
            return self._extender.predicate(args)

        def intake(driver, snap, node_names, earlier_apps, skip_allowed, current):
            served, reason = verdict.consume(
                driver, snap, node_names, earlier_apps, skip_allowed
            )
            if served is not None and verdict.artifacts is not None:
                # replay the speculative solve's artifacts into the
                # decision's provenance window so the refusal message
                # enrichment (shortfall explain) and the lane tag match
                # a serial solve byte-for-byte
                prov = self._extender._provenance
                if prov is not None and prov.enabled:
                    prov.capture(verdict.artifacts)
            self._note_commit(reason)
            return served

        self._extender.speculation_intake = intake
        try:
            return self._extender.predicate(args)
        finally:
            self._extender.speculation_intake = None

    # -- multi-active intents ---------------------------------------------

    def make_intent(self, args, origin: str = "") -> CommitIntent:
        """Standby side: speculate against the local warm basis and wrap
        the verdict as a commit intent stamped with the fencing epoch it
        was served under."""
        ticket = self.gate.ticket()
        try:
            verdict = (
                self.speculator.speculate(ticket, args)
                if self._config.speculation
                else None
            )
        finally:
            try:
                self.speculator.finish(ticket)
            finally:
                self.gate.retire(ticket, False)
        epoch = self._epoch_source() if self._epoch_source is not None else 0
        return CommitIntent(
            pod_name=args.pod.name,
            namespace=args.pod.namespace,
            epoch=epoch,
            args=args,
            verdict=verdict,
            origin=origin,
        )

    def submit_intent(self, intent: CommitIntent):
        """Committer side: refuse intents from a stale leadership epoch,
        then run the forwarded request through the normal FIFO commit
        path (the verdict revalidates exactly like a local one)."""
        if self._epoch_source is not None:
            current = self._epoch_source()
            if intent.epoch != current:
                self._metrics.counter(
                    mnames.CONCURRENT_INTENTS_FORWARDED,
                    {"result": "stale-epoch"},
                )
                raise StaleEpochError(
                    f"concurrent.commit-intent {intent.namespace}/{intent.pod_name}",
                    intent.epoch,
                    current,
                )
        self._metrics.counter(
            mnames.CONCURRENT_INTENTS_FORWARDED, {"result": "committed"}
        )
        return self.predicate(intent.args, verdict=intent.verdict)
