"""Speculative driver solves against seq-stamped snapshot bases.

A speculation is the read-only front half of the extender's tensor fast
path, executed *outside* the predicate lock on the request's own thread:
take a :class:`~..state.tensor_snapshot.TensorSnapshot` (seq-stamped,
copy-on-read, safe without the lock), assemble the earlier-drivers
queue and skip verdicts exactly as the serial path would, and run the
stateless cold tensor solve on a per-thread solver clone.  The product
is a :class:`SpeculativeVerdict`: the would-be decision plus everything
needed to prove, at commit time, that the basis did not move.

Revalidation (inside the predicate lock, via the extender's
``speculation_intake`` hook) is three steps, cheapest first:

1. **seq check** — ``content_key`` equality is O(1) and proves the
   mirror absorbed no mutation since the speculation;
2. **memcmp rescue** — same ``structure_key`` (node table unchanged)
   plus byte-equal avail/schedulable/res-entry arrays proves the
   content is identical even though the feed sequence moved (benign
   churn: pod events that cancel out row-wise);
3. anything else is a **conflict**: the verdict is discarded and the
   serial path's warm delta-solve runs under the lock (the bounded
   re-solve).

Either way the queue identity must also match: the earlier-apps list is
compared by object identity (``spark_app_demand_cached`` returns a
stable object per pod version, the same trick the solver's tensorize
cache uses) and the skip verdicts byte-for-byte — a queue re-order,
a new earlier driver, or a skip flip is a conflict, never a stale hit.

Footprint overlap: a speculation that would race an earlier in-flight
driver whose speculative verdict is success-shaped (its commit WILL
move the basis) is skipped up front — the optimistic bet is only taken
when it can pay.  Wasted speculation is never a correctness problem
(commit revalidates); overlap detection is purely a throughput lever.

Deadline-aware cancellation: the request deadline is checked before and
after the speculative solve; expiry abandons the in-flight speculative
work and counts ``tpu.concurrent.speculation.cancelled`` — overload
sheds speculative work instead of queueing it."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis.guarded import guarded_by
from ..metrics import names as mnames
from ..metrics.registry import MetricsRegistry, default_registry
from ..resilience import deadline as req_deadline
from ..scheduler import labels as L
from ..scheduler.sparkpods import (
    AnnotationError,
    spark_app_demand_cached,
    spark_resources,
)


class SpeculativeVerdict:
    """One speculative decision + its revalidation evidence."""

    __slots__ = (
        "pod_key",
        "node_names",
        "snap",
        "earlier_ids",
        "skip_allowed",
        "outcome",
        "zones",
        "artifacts",
        "will_commit",
    )

    def __init__(
        self,
        pod_key,
        node_names,
        snap,
        earlier_ids,
        skip_allowed,
        outcome,
        zones,
        artifacts=None,
    ):
        self.pod_key = pod_key
        self.node_names = node_names
        self.snap = snap
        self.earlier_ids = earlier_ids
        self.skip_allowed = skip_allowed
        self.outcome = outcome
        self.zones = zones
        # the solve artifacts the serial solver would have pushed into
        # provenance (shortfall explain, blocker set): replayed at
        # consume time so a consumed verdict's refusal message carries
        # the same enrichment a serial solve produces
        self.artifacts = artifacts
        # success-shaped: this commit will mutate the shared basis
        # (reservation write-back) — used by footprint-overlap skips
        self.will_commit = bool(
            outcome.earlier_ok
            and outcome.result is not None
            and outcome.result.has_capacity
        )

    def consume(
        self, driver, snap, node_names, earlier_apps, skip_allowed
    ) -> Tuple[Optional[Tuple[Any, Dict[str, str]]], str]:
        """Commit-time revalidation against the then-current basis.
        Returns ``((outcome, zones), reason)`` on a hit or
        ``(None, reason)`` on a conflict."""
        if (driver.namespace, driver.name) != self.pod_key:
            return None, "pod-mismatch"
        if tuple(node_names) != self.node_names:
            return None, "candidate-drift"
        if tuple(map(id, earlier_apps)) != self.earlier_ids:
            return None, "queue-drift"
        if tuple(skip_allowed) != self.skip_allowed:
            return None, "skip-drift"
        if snap.content_key == self.snap.content_key:
            return (self.outcome, self.zones), "seq-hit"
        if (
            snap.exact
            and self.snap.exact
            and snap.structure_key == self.snap.structure_key
            and np.array_equal(snap.avail, self.snap.avail)
            and np.array_equal(snap.schedulable, self.snap.schedulable)
            and np.array_equal(snap.res_entries, self.snap.res_entries)
        ):
            return (self.outcome, self.zones), "memcmp-hit"
        return None, "conflict"


class _Flight:
    __slots__ = ("ticket", "instance_group", "will_commit")

    def __init__(self, ticket: int, instance_group: str):
        self.ticket = ticket
        self.instance_group = instance_group
        # None = still solving (unknown); True = success-shaped verdict
        # pending commit; False = refusal-shaped (basis-neutral)
        self.will_commit: Optional[bool] = None


@guarded_by("_lock", "_inflight")
class Speculator:
    """Runs speculative solves and tracks in-flight footprints."""

    def __init__(
        self,
        extender,
        metrics: MetricsRegistry | None = None,
        max_inflight: int = 8,
    ):
        self._extender = extender
        self._metrics = metrics or default_registry
        self._max_inflight = max_inflight
        self._lock = threading.Lock()
        self._inflight: Dict[int, _Flight] = {}
        # per-thread solver clone: the shared queue solver keeps per-call
        # state (last_queue_lane, the earlier-tensor cache), so parallel
        # speculative solves each get their own instance — same class,
        # same policy knobs, therefore the same decisions
        self._local = threading.local()

    # -- bookkeeping ------------------------------------------------------

    def _decline(self, reason: str) -> None:
        self._metrics.counter(
            mnames.CONCURRENT_SPECULATION_COUNT, {"outcome": reason}
        )
        return None

    def finish(self, ticket: int) -> None:
        with self._lock:
            self._inflight.pop(ticket, None)

    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def _solver_clone(self):
        solver = getattr(self._extender.binpacker, "queue_solver", None)
        if solver is None or not hasattr(solver, "solve_tensor"):
            return None
        clone = getattr(self._local, "clone", None)
        if clone is not None and type(clone) is type(solver):
            return clone
        try:
            clone = type(solver)(
                assignment_policy=solver.assignment_policy,
                backend=solver.backend,
                strict_reference_parity=solver.strict_reference_parity,
            )
        except TypeError:
            return None
        self._local.clone = clone
        return clone

    # -- the speculation --------------------------------------------------

    def speculate(self, ticket: int, args) -> Optional[SpeculativeVerdict]:
        """Speculative fast-path solve for a driver Filter request;
        ``None`` means "no verdict — commit serially" (executor
        requests, replays, unsupported shapes, overlap skips,
        cancellations).  Never raises: any surprise declines."""
        ext = self._extender
        pod = args.pod
        if pod.labels.get(L.SPARK_ROLE_LABEL, "") != L.DRIVER:
            return self._decline("not-driver")
        if not getattr(ext, "_fast_path_ok", False) or ext._tensor_snapshot is None:
            return self._decline("no-fast-path")
        if ext._policy is not None:
            # the policy engine's queue hooks keep their own state; keep
            # speculation off that path — commits stay serial and exact
            return self._decline("policy-engine")
        solver = self._solver_clone()
        if solver is None:
            return self._decline("no-tensor-solver")

        instance_group, ok = L.find_instance_group_from_pod_spec(
            pod, ext._instance_group_label
        )
        if not ok:
            instance_group = ""

        # footprint overlap: an earlier in-flight driver with a
        # success-shaped verdict will move the basis when it commits —
        # our speculation would conflict anyway, so skip the solve
        with self._lock:
            if len(self._inflight) >= self._max_inflight:
                return self._decline("inflight-cap")
            for flight in self._inflight.values():
                if (
                    flight.ticket < ticket
                    and flight.instance_group == instance_group
                    and flight.will_commit
                ):
                    return self._decline("overlap")
            flight = _Flight(ticket, instance_group)
            self._inflight[ticket] = flight

        try:
            try:
                req_deadline.check("speculation-start")
            except req_deadline.DeadlineExceeded:
                self._metrics.counter(
                    mnames.CONCURRENT_SPECULATION_CANCELLED,
                    {"phase": "speculation-start"},
                )
                return None

            app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
            if ext._rrm.get_resource_reservation(app_id, pod.namespace) is not None:
                # idempotent replay: the serial path answers O(1) from
                # the reservation — nothing to speculate
                return self._decline("replay")

            from ..ops.fast_path import build_cluster_tensor
            from ..ops.sparkapp import AppDemand

            try:
                app_resources = spark_resources(pod)
            except AnnotationError:
                return self._decline("annotations")

            snap = ext._tensor_snapshot.snapshot()
            if not snap.exact:
                return self._decline("inexact")
            earlier_apps: List[Any] = []
            skip_allowed: List[bool] = []
            if ext._is_fifo:
                skip_cutoff = ext._fifo_skip_cutoff(instance_group)
                for queued in ext._earlier_drivers(pod):
                    try:
                        _, demand = spark_app_demand_cached(queued)
                    except AnnotationError:
                        continue
                    earlier_apps.append(demand)
                    skip_allowed.append(ext._skip_verdict(queued, pod, skip_cutoff))
            current = AppDemand(
                app_resources.driver_resources,
                app_resources.executor_resources,
                app_resources.min_executor_count,
            )
            built = build_cluster_tensor(
                snap,
                pod,
                args.node_names,
                driver_label_priority=ext._node_sorter.driver_label_priority,
                executor_label_priority=ext._node_sorter.executor_label_priority,
            )
            if built is None:
                return self._decline("affinity-shape")
            cluster, zones = built

            # collect the clone's solve artifacts locally (the shared
            # solver pushes them straight into provenance; a speculation
            # must not touch shared provenance state off-turn) — they
            # replay into the tracker at consume time
            captured: List[Any] = []
            if (
                ext._provenance is not None
                and ext._provenance.enabled
                and hasattr(solver, "capture_sink")
            ):
                solver.capture_sink = captured.append
            with ext._tracer.span(
                "speculation.solve", {"pod": pod.name, "ticket": str(ticket)}
            ):
                outcome = solver.solve_tensor(
                    cluster, earlier_apps, skip_allowed, current
                )
            if not outcome.supported:
                return self._decline("unsupported")

            try:
                req_deadline.check("speculation-solved")
            except req_deadline.DeadlineExceeded:
                # the native step already ran; the request is past its
                # deadline — drop the verdict so commit answers
                # fail-fast without consuming it
                self._metrics.counter(
                    mnames.CONCURRENT_SPECULATION_CANCELLED,
                    {"phase": "speculation-solved"},
                )
                return None

            verdict = SpeculativeVerdict(
                (pod.namespace, pod.name),
                tuple(args.node_names),
                snap,
                tuple(map(id, earlier_apps)),
                tuple(skip_allowed),
                outcome,
                zones,
                artifacts=captured[-1] if captured else None,
            )
            with self._lock:
                if ticket in self._inflight:
                    self._inflight[ticket].will_commit = verdict.will_commit
            self._metrics.counter(
                mnames.CONCURRENT_SPECULATION_COUNT, {"outcome": "solved"}
            )
            return verdict
        except Exception:
            return self._decline("error")
