"""Install-time configuration (reference ``config/config.go:24-84``).

The reference binds ``var/conf/install.yml`` into the Install struct; we
accept the same shape from a dict — the server CLI parses JSON natively
and YAML when pyyaml is installed (the optional ``[yaml]`` extra).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from . import compat
from .ops.nodesort import LabelPriorityOrder
from .scheduler.labels import DEFAULT_INSTANCE_GROUP_LABEL


@dataclass
class FifoConfig:
    """config.go:58-64: enforce FIFO only after a driver is older than
    this (seconds), per instance group."""

    default_enforce_after_pod_age: float = 0.0
    enforce_after_pod_age_by_instance_group: Dict[str, float] = field(default_factory=dict)


@dataclass
class AsyncClientConfig:
    """config.go:72-77."""

    max_retry_count: int = 5


@dataclass
class ResilienceConfig:
    """Overload protection / degraded mode (resilience/).

    ``request_deadline_seconds`` mirrors kube-scheduler's extender
    ``httpTimeout`` (examples/extender.yml: 30s); the server answers
    fail-fast ``deadline_margin_seconds`` before the caller hangs up.
    """

    request_deadline_seconds: float = 30.0
    deadline_margin_seconds: float = 1.0
    # concurrent /predicates requests admitted (holding + queued on the
    # extender lock) before excess requests are shed with a retriable
    # failure
    admission_max_waiters: int = 16
    # consecutive API-server write failures before the write-back
    # breaker opens and diverts reservation writes to the intent journal
    breaker_failure_threshold: int = 5
    breaker_cooloff_seconds: float = 30.0
    # durable JSONL intent journal; None keeps intents in memory only
    # (still replayed on in-process recovery, lost on process death)
    journal_path: Optional[str] = None
    # consecutive kernel-lane failures (or over-budget successes) before
    # the lane is demoted to the host/native path
    lane_failure_threshold: int = 3
    lane_cooloff_seconds: float = 60.0
    lane_latency_budget_seconds: Optional[float] = 5.0
    # journal compaction: rewrite the file to pending-only once dead
    # records (acked / superseded puts + ack markers) exceed this
    # fraction of the file, but never below the record floor — small
    # journals aren't worth the rewrite churn
    journal_compact_fraction: float = 0.5
    journal_compact_min_records: int = 64

    @staticmethod
    def from_dict(d: dict) -> "ResilienceConfig":
        return ResilienceConfig(
            request_deadline_seconds=d.get("request-deadline-seconds", 30.0),
            deadline_margin_seconds=d.get("deadline-margin-seconds", 1.0),
            admission_max_waiters=d.get("admission-max-waiters", 16),
            breaker_failure_threshold=d.get("breaker-failure-threshold", 5),
            breaker_cooloff_seconds=d.get("breaker-cooloff-seconds", 30.0),
            journal_path=d.get("journal-path"),
            lane_failure_threshold=d.get("lane-failure-threshold", 3),
            lane_cooloff_seconds=d.get("lane-cooloff-seconds", 60.0),
            lane_latency_budget_seconds=d.get("lane-latency-budget-seconds", 5.0),
            journal_compact_fraction=d.get("journal-compact-fraction", 0.5),
            journal_compact_min_records=d.get("journal-compact-min-records", 64),
        )


@dataclass
class ProvenanceConfig:
    """Decision provenance (provenance/): unschedulability explainer,
    shortfall telemetry, anomaly flight recorder.

    Diagnostic only — decisions are identical enabled or disabled.
    ``bundle_dir`` (or the ``SCHED_PROVENANCE_DIR`` env var) is where
    trigger-fired flight-recorder bundles persist; None keeps the
    bundle ring in memory only.  ``parity_check_interval`` > 0 re-runs
    every Nth warm delta-solve against the stateless cold solver and
    fires the flight recorder on divergence (a full cold solve per
    check — leave 0 in latency-sensitive production)."""

    enabled: bool = True
    ring_size: int = 128
    recorder_size: int = 8
    bundle_dir: Optional[str] = None
    max_bundle_nodes: int = 4096
    parity_check_interval: int = 0
    # per-trigger persist debounce (seconds): an overload-driven trigger
    # storm writes one bundle file per trigger type per interval, not
    # one per failed request
    trigger_min_interval_seconds: float = 30.0

    @staticmethod
    def from_dict(d: dict) -> "ProvenanceConfig":
        return ProvenanceConfig(
            enabled=d.get("enabled", True),
            ring_size=d.get("ring-size", 128),
            recorder_size=d.get("recorder-size", 8),
            bundle_dir=d.get("bundle-dir"),
            max_bundle_nodes=d.get("max-bundle-nodes", 4096),
            parity_check_interval=d.get("parity-check-interval", 0),
            trigger_min_interval_seconds=d.get(
                "trigger-min-interval-seconds", 30.0
            ),
        )


@dataclass
class CapacityConfig:
    """Capacity observatory (capacity/): fragmentation/headroom
    analytics, queue-pressure forecasts, and the ``/state/capacity``
    timeline.  Diagnostic only — no scheduling decision consumes an
    observatory output.

    Sampling is change-triggered (the state layer's ChangeFeed wakes
    the sampler thread, debounced) with ``interval_seconds`` as the
    idle-heartbeat fallback.  Cardinality caps bound both the probe
    cost and the label sets the headroom gauge can emit."""

    enabled: bool = True
    ring_size: int = 256
    debounce_seconds: float = 0.25
    interval_seconds: float = 15.0
    max_shapes: int = 16
    max_group_zones: int = 16
    max_queue: int = 64

    @staticmethod
    def from_dict(d: dict) -> "CapacityConfig":
        return CapacityConfig(
            enabled=d.get("enabled", True),
            ring_size=d.get("ring-size", 256),
            debounce_seconds=d.get("debounce-seconds", 0.25),
            interval_seconds=d.get("interval-seconds", 15.0),
            max_shapes=d.get("max-shapes", 16),
            max_group_zones=d.get("max-group-zones", 16),
            max_queue=d.get("max-queue", 64),
        )


@dataclass
class LifecycleConfig:
    """Gang lifecycle ledger + SLO engine (lifecycle/): per-application
    state machine, burn-rate objectives, and the ``/slo`` +
    ``/lifecycle`` scorecard endpoints.  Diagnostic only — no
    scheduling decision consumes a ledger or SLO output.

    Draining is change-triggered (EventLog emits and the state layer's
    ChangeFeed wake the ledger thread, debounced) with
    ``interval_seconds`` as the idle-heartbeat fallback.
    ``window_scale`` multiplies every SLO alert window (1 h/5 m and
    6 h/30 m) so short virtual sim timelines can compress the policy
    without changing the algebra; ``objectives`` overrides per-objective
    ``target``/``threshold`` (keys: time_to_admit, filter_latency,
    eviction_waste, fairness_gap)."""

    enabled: bool = True
    ring_size: int = 2048
    debounce_seconds: float = 0.05
    interval_seconds: float = 5.0
    window_scale: float = 1.0
    sample_cap: int = 4096
    objectives: Dict[str, Dict[str, float]] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict) -> "LifecycleConfig":
        return LifecycleConfig(
            enabled=d.get("enabled", True),
            ring_size=d.get("ring-size", 2048),
            debounce_seconds=d.get("debounce-seconds", 0.05),
            interval_seconds=d.get("interval-seconds", 5.0),
            window_scale=d.get("window-scale", 1.0),
            sample_cap=d.get("sample-cap", 4096),
            objectives=d.get("objectives", {}),
        )


@dataclass
class ContentionConfig:
    """Contention observatory (contention/): lock wait/hold telemetry
    and per-request critical-path decomposition behind
    ``/debug/contention`` + ``/debug/criticalpath``.  Diagnostic only.

    ``enabled`` turns the process-wide timekeeper on (TimedLock
    wrappers exist regardless; disabled they cost one attribute read
    per acquire).  ``ring_size`` bounds the per-request decomposition
    ring; ``sample_every`` is the uncontended-acquire sampling stride
    for ``@guarded_by`` locks (contended acquires always record)."""

    enabled: bool = True
    ring_size: int = 256
    sample_every: int = 64

    @staticmethod
    def from_dict(d: dict) -> "ContentionConfig":
        return ContentionConfig(
            enabled=d.get("enabled", True),
            ring_size=d.get("ring-size", 256),
            sample_every=d.get("sample-every", 64),
        )


@dataclass
class PolicyConfig:
    """Scheduling-policy engine (policy/): priority classes, pluggable
    queue ordering, conservative backfill, gang-aware preemption, and
    DRF fair share.

    ``enabled=False`` (the default) constructs no engine at all —
    extender decisions are byte-identical to pre-policy behavior
    (pinned by tests/test_policy.py).  ``ordering`` is one of ``fifo``,
    ``priority-then-fifo``, ``drf``; ``bands`` maps band name → rank
    (higher = more important) read from the driver pod's ``band_label``
    label.  Preemption evicts WHOLE applications only, each victim set
    validated by a what-if solve and journaled before any delete."""

    enabled: bool = False
    ordering: str = "fifo"
    band_label: str = "spark-priority-band"
    bands: Dict[str, int] = field(
        default_factory=lambda: {"low": 0, "normal": 1, "high": 2}
    )
    default_band: str = "normal"
    tenant_label: str = "spark-tenant"
    preemption_enabled: bool = False
    # a preemptor must outrank a victim by at least this many bands
    preemption_min_band_gap: int = 1
    max_victims: int = 4
    backfill: bool = False
    # backfill may never skip a queue head older than this (I-P3)
    starvation_age_seconds: float = 600.0
    tenant_weights: Dict[str, float] = field(default_factory=dict)
    recent_evictions: int = 64

    @staticmethod
    def from_dict(d: dict) -> "PolicyConfig":
        return PolicyConfig(
            enabled=d.get("enabled", False),
            ordering=d.get("ordering", "fifo"),
            band_label=d.get("band-label", "spark-priority-band"),
            bands=dict(d.get("bands", {"low": 0, "normal": 1, "high": 2})),
            default_band=d.get("default-band", "normal"),
            tenant_label=d.get("tenant-label", "spark-tenant"),
            preemption_enabled=d.get("preemption-enabled", False),
            preemption_min_band_gap=d.get("preemption-min-band-gap", 1),
            max_victims=d.get("max-victims", 4),
            backfill=d.get("backfill", False),
            starvation_age_seconds=d.get("starvation-age-seconds", 600.0),
            tenant_weights=dict(d.get("tenant-weights", {})),
            recent_evictions=d.get("recent-evictions", 64),
        )


@dataclass
class HAConfig:
    """HA failover fabric (ha/): lease-fenced multi-replica operation.

    Disabled (the default) wires nothing — no elector, no fence gates,
    single-replica behavior byte-identical to pre-HA builds.  Enabled,
    the replica elects over a coordination lease, stamps every fenced
    write with its epoch, and runs full state reconciliation on
    takeover.
    """

    enabled: bool = False
    lease_namespace: str = "default"
    lease_name: str = "tpu-gang-scheduler"
    # how stale a lease may go before a candidate may steal it; mirrors
    # client-go's LeaseDuration default (resource.go:57-59)
    lease_duration_seconds: float = 15.0
    # background renewal cadence (prod); the sim and tests step the
    # elector manually under the virtual clock
    renew_interval_seconds: float = 5.0
    # replica identity on the lease; "" = <hostname>-<pid> at wiring
    identity: str = ""
    # start the background renewal thread from start_background();
    # the sim/tests disable this and drive fabric.step() themselves
    background: bool = True

    @staticmethod
    def from_dict(d: dict) -> "HAConfig":
        return HAConfig(
            enabled=d.get("enabled", False),
            lease_namespace=d.get("lease-namespace", "default"),
            lease_name=d.get("lease-name", "tpu-gang-scheduler"),
            lease_duration_seconds=d.get("lease-duration-seconds", 15.0),
            renew_interval_seconds=d.get("renew-interval-seconds", 5.0),
            identity=d.get("identity", ""),
            background=d.get("background", True),
        )


@dataclass
class ConcurrentConfig:
    """Concurrent admission engine (concurrent/): optimistic speculative
    solves committed through a FIFO commit gate.

    Disabled (the default) wires nothing — Filter requests run the
    serial extender exactly as before, byte-identical decisions.
    Enabled, independent requests speculate in parallel against
    seq-stamped snapshot bases and commit in strict arrival order; the
    commit gate revalidates every verdict, so decisions are *still*
    byte-identical to the serial extender (the 5-seed property test
    pins this) — the switch trades nothing but CPU for latency.
    """

    enabled: bool = False
    # run the speculative solve at all; off = requests still serialize
    # through the FIFO commit gate but never solve outside the lock
    # (a degraded mode for conflict-storm fallback — see
    # docs/operations.md "running multi-active admission")
    speculation: bool = True
    # concurrent speculations beyond this bound skip straight to the
    # serial commit path (memory bound: each holds a snapshot basis)
    max_inflight_speculations: int = 8
    # accept forwarded commit intents from standby replicas (multi-
    # active operation); requires the HA fabric for epoch fencing
    multi_active: bool = False

    @staticmethod
    def from_dict(d: dict) -> "ConcurrentConfig":
        return ConcurrentConfig(
            enabled=d.get("enabled", False),
            speculation=d.get("speculation", True),
            max_inflight_speculations=d.get("max-inflight-speculations", 8),
            multi_active=d.get("multi-active", False),
        )


@dataclass
class ClassesConfig:
    """Equivalence-class node aggregation (ROADMAP 2): class-compressed
    native solves, the O(1) class-digest warm tier in the delta-solve
    engine, and per-class observatory analytics.

    Decisions are byte-identical enabled or disabled — the compressed
    solver expands to concrete nodes at bind time and the property
    suite (tests/test_class_compression.py) pins parity — so
    ``enabled`` is an operator kill switch, not a semantics switch.
    ``min_nodes`` keeps the compressed session solver off small fleets
    where partition upkeep isn't worth it (the 10k perf-gate lanes run
    the row-level path unchanged)."""

    enabled: bool = True
    min_nodes: int = 20000

    @staticmethod
    def from_dict(d: dict) -> "ClassesConfig":
        return ClassesConfig(
            enabled=d.get("enabled", True),
            min_nodes=d.get("min-nodes", 20000),
        )


@dataclass
class ConversionWebhookConfig:
    """Where the apiserver reaches the CRD conversion webhook (the
    reference wires this from the witchcraft server's service identity,
    conversionwebhook/resource_reservation.go:44-98).  ca_bundle_file
    holds the PEM CA the apiserver must trust — conversion is HTTPS-only
    on a real cluster."""

    service_namespace: str = "spark"
    service_name: str = "spark-scheduler"
    service_port: int = 443
    path: str = "/convert"
    ca_bundle_file: Optional[str] = None


@dataclass
class Install:
    """config.go:24-47."""

    fifo: bool = False
    fifo_config: FifoConfig = field(default_factory=FifoConfig)
    qps: float = 0.0
    burst: int = 0
    binpack_algo: str = "distribute-evenly"
    should_schedule_dynamically_allocated_executors_in_same_az: bool = False
    instance_group_label: str = DEFAULT_INSTANCE_GROUP_LABEL
    async_client: AsyncClientConfig = field(default_factory=AsyncClientConfig)
    unschedulable_pod_timeout_seconds: float = 600.0
    driver_prioritized_node_label: Optional[LabelPriorityOrder] = None
    executor_prioritized_node_label: Optional[LabelPriorityOrder] = None
    resource_reservation_crd_annotations: Dict[str, str] = field(default_factory=dict)
    conversion_webhook: Optional[ConversionWebhookConfig] = None
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    # replicate the reference's accidental-but-load-bearing behaviors
    # (see compat.py for the list); off = corrected semantics
    strict_reference_parity: bool = compat.DEFAULT_STRICT
    # incremental delta-solve engine (ops/deltasolve.py): persistent
    # native solver sessions + prefix-feasibility reuse on the driver
    # fast path.  Decisions are identical either way (the kill switch
    # exists for operators, not semantics).
    delta_solve: bool = True
    # decision provenance: explainer + shortfall telemetry + flight
    # recorder (provenance/) — diagnostic only, decisions unchanged
    provenance: ProvenanceConfig = field(default_factory=ProvenanceConfig)
    # capacity observatory: fragmentation/headroom analytics and the
    # /state/capacity timeline (capacity/) — diagnostic only
    capacity: CapacityConfig = field(default_factory=CapacityConfig)
    # contention observatory: lock wait/hold telemetry + critical-path
    # decomposition (contention/) — diagnostic only
    contention: ContentionConfig = field(default_factory=ContentionConfig)
    # scheduling policy: priority bands, ordering, backfill, preemption,
    # DRF (policy/) — disabled = byte-identical FIFO decisions
    policy: PolicyConfig = field(default_factory=PolicyConfig)
    # HA failover fabric: leader election + fencing + takeover
    # reconciliation (ha/) — disabled = single-replica, nothing wired
    ha: HAConfig = field(default_factory=HAConfig)
    # gang lifecycle ledger + SLO burn-rate engine (lifecycle/) —
    # diagnostic only, decisions unchanged
    lifecycle: LifecycleConfig = field(default_factory=LifecycleConfig)
    # concurrent admission engine: parallel speculative solves + FIFO
    # commit gate (concurrent/) — disabled = serial extender, and
    # enabled is still decision-identical by construction
    concurrent: ConcurrentConfig = field(default_factory=ConcurrentConfig)
    # equivalence-class aggregation: class-compressed solves at scale +
    # class-digest warm tier (state/classindex.py, ops/deltasolve.py) —
    # byte-identical decisions either way
    classes: ClassesConfig = field(default_factory=ClassesConfig)

    @staticmethod
    def from_dict(d: dict) -> "Install":
        fifo_cfg = d.get("fifo-config", {})
        driver_label = d.get("driver-prioritized-node-label")
        executor_label = d.get("executor-prioritized-node-label")
        return Install(
            fifo=d.get("fifo", False),
            fifo_config=FifoConfig(
                default_enforce_after_pod_age=fifo_cfg.get(
                    "default-enforce-after-pod-age-seconds", 0.0
                ),
                enforce_after_pod_age_by_instance_group=fifo_cfg.get(
                    "enforce-after-pod-age-by-instance-group", {}
                ),
            ),
            qps=d.get("qps", 0.0),
            burst=d.get("burst", 0),
            binpack_algo=d.get("binpack", "distribute-evenly"),
            should_schedule_dynamically_allocated_executors_in_same_az=d.get(
                "should-schedule-dynamically-allocated-executors-in-same-az", False
            ),
            # back-compat default (cmd/server.go:67-71)
            instance_group_label=d.get("instance-group-label", DEFAULT_INSTANCE_GROUP_LABEL),
            async_client=AsyncClientConfig(
                max_retry_count=d.get("async-client", {}).get("max-retry-count", 5)
            ),
            unschedulable_pod_timeout_seconds=d.get(
                "unschedulable-pod-timeout-seconds", 600.0
            ),
            driver_prioritized_node_label=(
                LabelPriorityOrder(
                    driver_label["name"], driver_label["descending-priority-values"]
                )
                if driver_label
                else None
            ),
            executor_prioritized_node_label=(
                LabelPriorityOrder(
                    executor_label["name"], executor_label["descending-priority-values"]
                )
                if executor_label
                else None
            ),
            resource_reservation_crd_annotations=d.get(
                "resource-reservation-crd-annotations", {}
            ),
            # only present keys are passed so the dataclass defaults stay
            # the single source of truth
            conversion_webhook=(
                ConversionWebhookConfig(
                    **{
                        field_name: wh[key]
                        for key, field_name in (
                            ("service-namespace", "service_namespace"),
                            ("service-name", "service_name"),
                            ("service-port", "service_port"),
                            ("path", "path"),
                            ("ca-bundle-file", "ca_bundle_file"),
                        )
                        if key in wh
                    }
                )
                if (wh := d.get("conversion-webhook")) is not None
                else None
            ),
            strict_reference_parity=d.get(
                "strict-reference-parity", compat.DEFAULT_STRICT
            ),
            delta_solve=d.get("delta-solve", True),
            resilience=ResilienceConfig.from_dict(d.get("resilience", {})),
            provenance=ProvenanceConfig.from_dict(d.get("provenance", {})),
            capacity=CapacityConfig.from_dict(d.get("capacity", {})),
            contention=ContentionConfig.from_dict(d.get("contention", {})),
            policy=PolicyConfig.from_dict(d.get("policy", {})),
            ha=HAConfig.from_dict(d.get("ha", {})),
            lifecycle=LifecycleConfig.from_dict(d.get("lifecycle", {})),
            concurrent=ConcurrentConfig.from_dict(d.get("concurrent", {})),
            classes=ClassesConfig.from_dict(d.get("classes", {})),
        )
