"""Contention observatory: lock wait/hold telemetry, critical-path
latency decomposition, and the data behind ``GET /debug/contention`` /
``GET /debug/criticalpath`` (ISSUE 11; the before/after yardstick for
breaking the single extender lock, ROADMAP item 1).

- :mod:`.locktime` — ``TimedLock`` + the process-wide
  :class:`~.locktime.LockTimekeeper` switchboard: per-lock wait/hold
  reservoirs, span-phase holder attribution, top-blocker tables.
- :mod:`.criticalpath` — span-tree walker that decomposes each request
  into gate-queue / lock-wait / serde / solve / write-back / other.
"""

from .criticalpath import CriticalPathAnalyzer, decompose  # noqa: F401
from .locktime import LockTimekeeper, TimedLock  # noqa: F401
