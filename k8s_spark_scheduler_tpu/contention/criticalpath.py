"""Critical-path extraction — "where does the millisecond go".

Walks every completed request trace (a :class:`tracing.spans.Tracer`
observer fires on root-span exit) and decomposes end-to-end handler
latency into named gating segments:

- ``gate-queue``  — admission-gate entry wait (``gateWaitMs`` root tag)
- ``lock-wait``   — extender predicate-lock wait (``lockWaitMs`` root
  tag, stamped by the lock's ``TimedLock`` wrapper while the request's
  root span is active)
- ``serde``       — request read/decode + response encode spans
- ``solve``       — the predicate span tree: snapshot build, FIFO gate,
  binpack/kernel time
- ``write-back``  — reservation/state write-back spans
- ``other``       — the unattributed remainder (kept explicit so the
  decomposition always sums to the request, and so a growing "other"
  is itself a finding)

Attribution is *exclusive* (self-time): each span's duration minus its
children is charged to the nearest classified ancestor, so nothing is
counted twice and the segments plus ``other`` reconstruct the root
duration exactly.  The two synthetic gap segments (gate-queue,
lock-wait) happen between spans — they are carved out of the root's
self-time using the tags measured at the wait sites.

Per-request records land in a bounded ring served by
``GET /debug/criticalpath``; per-segment histograms and the coverage
ratio (attributed / total) go to the metrics registry.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

from ..analysis.guarded import guarded_by

# span name -> segment; spans with unlisted names inherit the nearest
# classified ancestor's segment (descendants of "predicate" therefore
# default to "solve" — kernel and helper spans included)
SPAN_SEGMENTS: Dict[str, str] = {
    "http.read": "serde",
    "serde.decode": "serde",
    "serde.encode": "serde",
    "predicate": "solve",
    "reconcile": "solve",
    "fifo_gate": "solve",
    "binpack": "solve",
    "fast_path.build_tensor": "solve",
    "executor.fast_reschedule": "solve",
    # the concurrent engine's speculative solve runs pre-lock on the
    # request's own thread; classifying it apart from "solve" keeps the
    # lock-tenure segment honest when speculation is on (a consumed
    # verdict means the under-lock solve never ran)
    "speculation.solve": "speculate",
    "reservation.writeback": "write-back",
    "state.writeback.enqueue": "write-back",
}

SEGMENT_NAMES = (
    "gate-queue", "lock-wait", "serde", "solve", "speculate", "write-back",
    "other",
)


def decompose(root) -> Optional[Dict[str, Any]]:
    """One request's segment decomposition, or None for traces that are
    not scheduling requests (or carry no measurable duration — e.g.
    virtual-time sim traces where the clock never advanced)."""
    if root.name == "http.request":
        if root.tags.get("path") != "/predicates":
            return None
    elif root.name != "predicate":
        return None
    total_ms = (root.duration or 0.0) * 1000.0
    if total_ms <= 0.0:
        return None
    segments = {name: 0.0 for name in SEGMENT_NAMES}

    def walk(span, inherited: str) -> None:
        segment = SPAN_SEGMENTS.get(span.name, inherited)
        duration_ms = (span.duration or 0.0) * 1000.0
        children_ms = 0.0
        for child in span.children:
            children_ms += (child.duration or 0.0) * 1000.0
            walk(child, segment)
        segments[segment] += max(duration_ms - children_ms, 0.0)

    walk(root, "other")
    # the synthetic gap segments: measured at the wait sites, carved
    # out of the root self-time where those waits actually happened
    gate_ms = float(root.tags.get("gateWaitMs") or 0.0)
    lock_ms = float(root.tags.get("lockWaitMs") or 0.0)
    segments["gate-queue"] = gate_ms
    segments["lock-wait"] = lock_ms
    segments["other"] = max(segments["other"] - gate_ms - lock_ms, 0.0)
    attributed = total_ms - segments["other"]
    dominant = max(segments, key=lambda name: segments[name])
    return {
        "traceId": root.trace_id,
        "startTime": root.start_time,
        "totalMs": round(total_ms, 4),
        "segments": {name: round(ms, 4) for name, ms in segments.items()},
        "coverage": round(min(max(attributed / total_ms, 0.0), 1.0), 4),
        "dominant": dominant,
        "outcome": root.tags.get("outcome", ""),
    }


def _pct(ordered: List[float], q: float) -> float:
    if not ordered:
        return 0.0
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


@guarded_by("_lock", "_ring", "_dominant_counts", "_requests")
class CriticalPathAnalyzer:
    """Tracer observer + bounded per-request ring + metric emission.

    ``on_trace`` runs on the request thread at root-span exit (outside
    the tracer's ring lock) — the walk is O(#spans) over a tree that is
    already in cache, and metric recording happens outside this
    object's own lock."""

    def __init__(self, metrics=None, capacity: int = 256):
        self._metrics = metrics
        self._ring: deque = deque(maxlen=capacity)
        self._dominant_counts: Dict[str, int] = {}
        self._requests = 0
        self._lock = threading.Lock()

    def on_trace(self, root) -> None:
        record = decompose(root)
        if record is None:
            return
        with self._lock:
            self._requests += 1
            self._ring.append(record)
            self._dominant_counts[record["dominant"]] = (
                self._dominant_counts.get(record["dominant"], 0) + 1
            )
        metrics = self._metrics
        if metrics is not None:
            from ..metrics import names as M

            for name, ms in record["segments"].items():
                metrics.histogram(
                    M.CRITICALPATH_SEGMENT_TIME,
                    ms / 1000.0,
                    {M.TAG_SEGMENT: name},
                )
            metrics.histogram(M.CRITICALPATH_COVERAGE, record["coverage"])
            metrics.counter(
                M.CRITICALPATH_DOMINANT_COUNT,
                {M.TAG_SEGMENT: record["dominant"]},
            )

    # -- read side -------------------------------------------------------------

    def recent(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
        out.reverse()
        if limit is not None:
            out = out[: max(limit, 0)]
        return out

    def summary(self) -> Dict[str, Any]:
        """Percentile decomposition over the ring: per-segment p50/p95/
        p99/mean plus total and coverage — the /debug/criticalpath
        payload head."""
        with self._lock:
            records = list(self._ring)
            requests = self._requests
            dominant = dict(self._dominant_counts)
        totals = sorted(r["totalMs"] for r in records)
        coverages = sorted(r["coverage"] for r in records)
        segments: Dict[str, Dict[str, float]] = {}
        for name in SEGMENT_NAMES:
            values = sorted(r["segments"][name] for r in records)
            segments[name] = {
                "p50Ms": round(_pct(values, 0.50), 4),
                "p95Ms": round(_pct(values, 0.95), 4),
                "p99Ms": round(_pct(values, 0.99), 4),
                "meanMs": round(sum(values) / len(values), 4) if values else 0.0,
            }
        return {
            "requests": requests,
            "window": len(records),
            "totalMs": {
                "p50": round(_pct(totals, 0.50), 4),
                "p95": round(_pct(totals, 0.95), 4),
                "p99": round(_pct(totals, 0.99), 4),
            },
            "coverage": {
                "p50": round(_pct(coverages, 0.50), 4),
                "min": round(coverages[0], 4) if coverages else 0.0,
            },
            "segments": segments,
            "dominant": dominant,
        }
