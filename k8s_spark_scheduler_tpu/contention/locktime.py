"""Always-on lock wait/hold telemetry — the timing half of the
contention observatory.

``TimedLock`` wraps a raw ``threading.Lock``/``RLock`` and measures,
per lock:

- **wait time** — how long ``acquire`` blocked (sampled reservoir,
  contended acquires always recorded);
- **hold time** — how long the lock was held, attributed to the
  *phase* that held it (the active span name, read from the tracing
  ``ContextVar``);
- **top blockers** — who I waited on, for how long: the holder's
  phase is snapshotted just before blocking, so every contended wait
  is charged to the phase that caused it.

Wrapping layers compose with PR 9's race detector: ``@guarded_by``
wraps the raw lock in a ``TimedLock`` first, and — only when
``SCHEDLINT_RACECHECK`` is active — racecheck then wraps the
``TimedLock`` in its ``TrackedLock`` proxy, so the timing layer sits
innermost and times the real lock, not the detector.

Why this is cheap and safe:

- every statistics mutation happens **while the measured lock is
  held** (wait is recorded just after acquiring, hold just before
  releasing), so the lock serializes its own bookkeeping — no extra
  lock on the hot path, ever;
- a waiter reads the current holder's attribution tuple *without*
  the lock — a benign racy read of an immutable tuple that can at
  worst misattribute one wait to an adjacent holder;
- uncontended acquires are *sampled* (1 in ``sample_every``); the
  contended path — where the signal lives — always records;
- the disabled path costs one module-attribute read plus the
  delegation call, mirroring ``racecheck.note_access``.

Module switchboard (mirrors ``analysis.racecheck``): ``enable()``
installs the process-wide :class:`LockTimekeeper`; ``active()`` /
``get()`` read it; ``disable()`` removes it.  TimedLocks exist either
way — they just stop recording when no keeper is installed.
"""

from __future__ import annotations

import random
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

_keeper: Optional["LockTimekeeper"] = None

# every TimedLock in the process, for snapshot()/publish() enumeration
# (weak: a dropped lock must not leak its stats forever)
_registry_lock = threading.Lock()
_locks: "weakref.WeakSet[TimedLock]" = weakref.WeakSet()

_LOCK_TYPE = type(threading.Lock())

RESERVOIR_CAP = 256
BLOCKER_TABLE_CAP = 32
PHASE_TABLE_CAP = 64
PENDING_CAP = 512
DEFAULT_SAMPLE_EVERY = 64
# stride used for locks wrapped via @guarded_by; wiring sets it from
# ContentionConfig before the server's guarded singletons construct
_default_sample_every = DEFAULT_SAMPLE_EVERY


def set_default_sample_every(stride: int) -> None:
    global _default_sample_every
    _default_sample_every = max(1, int(stride))


def active() -> bool:
    return _keeper is not None


def get() -> Optional["LockTimekeeper"]:
    return _keeper


def enable(keeper: Optional["LockTimekeeper"] = None) -> "LockTimekeeper":
    """Install (idempotently) the process-wide timekeeper and return
    it.  Safe to call from every server wiring in a test process —
    the first call wins unless an explicit keeper is passed."""
    global _keeper
    if keeper is not None:
        _keeper = keeper
    elif _keeper is None:
        _keeper = LockTimekeeper()
    return _keeper


def disable() -> None:
    global _keeper
    _keeper = None


# phase attribution: the active span's name.  Lazy import breaks the
# contention → tracing → guarded → contention cycle; cached so the hot
# path pays one global read, not a sys.modules lookup.
_current_span = None


def _phase() -> str:
    global _current_span
    cs = _current_span
    if cs is None:
        from ..tracing.spans import current_span as cs

        _current_span = cs
    span = cs()
    name = getattr(span, "name", None)
    return name if name is not None else ""


class _Reservoir:
    """Algorithm-R sampled reservoir + exact count/total/max.  Own RNG
    seeded from the lock name: deterministic per lock, no global
    random state touched on the hot path."""

    __slots__ = ("cap", "values", "count", "total", "max", "_rng")

    def __init__(self, cap: int, seed: int):
        self.cap = cap
        self.values: List[float] = []
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._rng = random.Random(seed)

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v
        if v > self.max:
            self.max = v
        if len(self.values) < self.cap:
            self.values.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < self.cap:
                self.values[j] = v

    def snapshot_ms(self) -> Dict[str, Any]:
        vals = sorted(self.values)

        def pct(q: float) -> float:
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1, int(q * len(vals)))] * 1000.0

        return {
            "count": self.count,
            "mean": round(self.total / self.count * 1000.0, 4) if self.count else 0.0,
            "p50": round(pct(0.50), 4),
            "p95": round(pct(0.95), 4),
            "p99": round(pct(0.99), 4),
            "max": round(self.max * 1000.0, 4),
        }


class TimedLock:
    """Lock proxy with wait/hold timing.  Exposes the full protocol
    racecheck's ``TrackedLock`` needs from an inner lock —
    ``acquire(blocking, timeout)``, ``release()``, ``locked()``,
    context manager — so the two proxies stack cleanly."""

    __slots__ = (
        "name",
        "sample_every",
        "tag_waits",
        "_inner",
        "_reentrant",
        "_tl",
        "_wait",
        "_hold",
        "_acquisitions",
        "_contended",
        "_holder",
        "_hold_t0",
        "_hold_phase",
        "_by_phase",
        "_blockers",
        "_pending_wait",
        "_pending_hold",
        "__weakref__",
    )

    def __init__(
        self,
        inner,
        name: str,
        sample_every: int = DEFAULT_SAMPLE_EVERY,
        tag_waits: bool = False,
    ):
        self._inner = inner
        self.name = name
        self.sample_every = max(1, sample_every)
        # annotate the active span with accumulated lockWaitMs — only
        # for request-path locks (the extender predicate lock), so the
        # critical-path extractor can carve the wait out of the request
        self.tag_waits = tag_waits
        self._reentrant = not isinstance(inner, _LOCK_TYPE)
        self._tl = threading.local() if self._reentrant else None
        seed = hash(name) & 0xFFFF ^ 0x5EED
        self._wait = _Reservoir(RESERVOIR_CAP, seed)
        self._hold = _Reservoir(RESERVOIR_CAP, seed ^ 0xA5A5)
        self._acquisitions = 0
        self._contended = 0
        # (phase, thread name) of the current holder — written only by
        # the holder, read racily by waiters for blame attribution
        self._holder: Optional[Tuple[str, str]] = None
        self._hold_t0: Optional[float] = None
        self._hold_phase = ""
        self._by_phase: Dict[str, List[float]] = {}  # phase -> [holds, total_s, max_s]
        self._blockers: Dict[str, List[float]] = {}  # phase -> [waits, total_s]
        # bounded recent-sample buffers, drained by publish() into the
        # metrics registry as real histogram points
        self._pending_wait: List[float] = []
        self._pending_hold: List[Tuple[str, float]] = []
        with _registry_lock:
            _locks.add(self)

    # -- lock protocol ---------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if _keeper is None:
            got = self._inner.acquire(blocking, timeout)
            if got and self._reentrant:
                # depth stays tracked even while disabled: locked() needs
                # it (a same-thread RLock probe succeeds reentrantly, so
                # probing can never detect our own hold), and a keeper
                # enabled mid-hold must still see consistent depths
                tl = self._tl
                tl.depth = getattr(tl, "depth", 0) + 1
            return got
        return self._timed_acquire(blocking, timeout)

    def release(self) -> None:
        if _keeper is None:
            if self._reentrant:
                tl = self._tl
                depth = getattr(tl, "depth", 0)
                if depth:
                    tl.depth = depth - 1
            self._holder = None
            self._hold_t0 = None
            self._inner.release()
            return
        self._timed_release()

    def locked(self) -> bool:
        fn = getattr(self._inner, "locked", None)
        if fn is not None:
            return fn()
        # RLock grows .locked() only in Python 3.14; approximate: held
        # by this thread (probing would succeed reentrantly and lie),
        # else a net-zero non-blocking probe
        if self._reentrant and getattr(self._tl, "depth", 0) > 0:
            return True
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<TimedLock {self.name!r} wrapping {self._inner!r}>"

    # -- timed paths -----------------------------------------------------------

    def _timed_acquire(self, blocking: bool, timeout: float) -> bool:
        inner = self._inner
        got = inner.acquire(False)
        wait_s = 0.0
        blocker: Optional[Tuple[str, str]] = None
        if not got:
            if not blocking:
                # failed probe: no lock held, so no stats (they would
                # race); probes are rare and carry no latency signal
                return False
            # blame whoever holds it right now (benign racy read)
            blocker = self._holder
            t0 = time.perf_counter()
            got = inner.acquire(True, timeout)
            wait_s = time.perf_counter() - t0
            if not got:
                return False
        if self._reentrant:
            tl = self._tl
            depth = getattr(tl, "depth", 0)
            tl.depth = depth + 1
            if depth:
                return True  # only the outermost acquire/release is timed
        # -- we hold the lock: everything below is serialized by it --
        self._acquisitions += 1
        contended = blocker is not None or wait_s > 0.0
        sampled = contended or (self._acquisitions % self.sample_every == 0)
        if contended:
            self._contended += 1
            self._wait.add(wait_s)
            phase = blocker[0] if blocker and blocker[0] else "unknown"
            slot = self._blockers.get(phase)
            if slot is not None:
                slot[0] += 1
                slot[1] += wait_s
            elif len(self._blockers) < BLOCKER_TABLE_CAP:
                self._blockers[phase] = [1, wait_s]
            if len(self._pending_wait) < PENDING_CAP:
                self._pending_wait.append(wait_s)
        elif sampled:
            self._wait.add(0.0)
        # holder attribution is written on EVERY timed acquire (cheap:
        # one ContextVar read) so a waiter can always blame someone;
        # the perf_counter + reservoir work stays sampled
        my_phase = _phase()
        self._holder = (my_phase, threading.current_thread().name)
        if sampled:
            self._hold_phase = my_phase
            self._hold_t0 = time.perf_counter()
        else:
            self._hold_t0 = None
        if self.tag_waits:
            self._tag_active_span(wait_s)
        return True

    def _timed_release(self) -> None:
        if self._reentrant:
            tl = self._tl
            depth = getattr(tl, "depth", 0)
            if depth > 1:
                tl.depth = depth - 1
                self._inner.release()
                return
            if depth:
                tl.depth = 0
        t0 = self._hold_t0
        if t0 is not None:
            hold_s = time.perf_counter() - t0
            phase = self._hold_phase
            self._hold.add(hold_s)
            slot = self._by_phase.get(phase)
            if slot is not None:
                slot[0] += 1
                slot[1] += hold_s
                if hold_s > slot[2]:
                    slot[2] = hold_s
            elif len(self._by_phase) < PHASE_TABLE_CAP:
                self._by_phase[phase] = [1, hold_s, hold_s]
            if len(self._pending_hold) < PENDING_CAP:
                self._pending_hold.append((phase, hold_s))
        self._holder = None
        self._hold_t0 = None
        self._inner.release()

    def _tag_active_span(self, wait_s: float) -> None:
        global _current_span
        cs = _current_span
        if cs is None:
            from ..tracing.spans import current_span as cs

            _current_span = cs
        span = cs()
        tags = getattr(span, "tags", None)
        if span is not None and tags is not None:
            tags["lockWaitMs"] = round(
                tags.get("lockWaitMs", 0.0) + wait_s * 1000.0, 4
            )

    # -- introspection ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Racy-but-consistent-enough view for /debug/contention."""
        blockers = sorted(
            (
                {
                    "holderPhase": phase,
                    "waits": int(slot[0]),
                    "totalWaitMs": round(slot[1] * 1000.0, 4),
                }
                for phase, slot in list(self._blockers.items())
            ),
            key=lambda b: -b["totalWaitMs"],
        )
        by_phase = {
            phase: {
                "holds": int(slot[0]),
                "totalMs": round(slot[1] * 1000.0, 4),
                "maxMs": round(slot[2] * 1000.0, 4),
            }
            for phase, slot in sorted(self._by_phase.items())
        }
        return {
            "name": self.name,
            "acquisitions": self._acquisitions,
            "contended": self._contended,
            "sampleEvery": self.sample_every,
            "waitMs": self._wait.snapshot_ms(),
            "holdMs": self._hold.snapshot_ms(),
            "byPhase": by_phase,
            "topBlockers": blockers,
        }


class LockTimekeeper:
    """Process-wide handle over every TimedLock: snapshot aggregation
    for ``/debug/contention`` and metric publication for ``/metrics``.
    Holds no per-lock state — each lock carries its own, serialized by
    itself (see module docstring)."""

    def snapshot(self, name_filter: Optional[str] = None) -> List[Dict[str, Any]]:
        """Per-lock-name aggregate stats, busiest first.  Many
        instances of one guarded class share a name; their snapshots
        merge so the table stays O(#lock sites), not O(#instances)."""
        with _registry_lock:
            locks = list(_locks)
        merged: Dict[str, Dict[str, Any]] = {}
        for lk in locks:
            if name_filter is not None and lk.name != name_filter:
                continue
            snap = lk.snapshot()
            agg = merged.get(lk.name)
            if agg is None:
                snap["instances"] = 1
                merged[lk.name] = snap
            else:
                agg["instances"] += 1
                agg["acquisitions"] += snap["acquisitions"]
                agg["contended"] += snap["contended"]
                _merge_dist(agg["waitMs"], snap["waitMs"])
                _merge_dist(agg["holdMs"], snap["holdMs"])
                _merge_phase(agg["byPhase"], snap["byPhase"])
                agg["topBlockers"] = _merge_blockers(
                    agg["topBlockers"], snap["topBlockers"]
                )
        return sorted(
            merged.values(), key=lambda s: (-s["contended"], -s["acquisitions"])
        )

    def publish(self, metrics) -> None:
        """Drain each lock's pending samples into the metrics registry
        as real histogram points, plus cumulative-count gauges.  Called
        from the reporter tick and on /debug/contention reads — never
        from the lock hot path (the registry's own lock is timed too;
        recording from inside acquire/release would recurse)."""
        from ..metrics import names as M

        with _registry_lock:
            locks = list(_locks)
        for lk in locks:
            if not lk._acquisitions:
                continue
            tags = {M.TAG_LOCK: lk.name}
            pending_wait, lk._pending_wait = lk._pending_wait, []
            pending_hold, lk._pending_hold = lk._pending_hold, []
            for wait_s in pending_wait:
                metrics.histogram(M.LOCK_WAIT_TIME, wait_s, tags)
            for phase, hold_s in pending_hold:
                metrics.histogram(
                    M.LOCK_HOLD_TIME,
                    hold_s,
                    {M.TAG_LOCK: lk.name, M.TAG_PHASE: phase or "-"},
                )
            metrics.gauge(M.LOCK_ACQUIRE_COUNT, float(lk._acquisitions), tags)
            metrics.gauge(M.LOCK_CONTENDED_COUNT, float(lk._contended), tags)
            for phase, slot in list(lk._blockers.items()):
                metrics.gauge(
                    M.LOCK_BLOCKED_SECONDS,
                    round(slot[1], 6),
                    {M.TAG_LOCK: lk.name, M.TAG_HOLDER: phase},
                )


def _merge_dist(agg: Dict[str, Any], other: Dict[str, Any]) -> None:
    total = agg["count"] + other["count"]
    if total:
        agg["mean"] = round(
            (agg["mean"] * agg["count"] + other["mean"] * other["count"]) / total, 4
        )
    # percentiles across instances: keep the worst observed (the
    # conservative read for a contention table)
    for key in ("p50", "p95", "p99", "max"):
        agg[key] = max(agg[key], other[key])
    agg["count"] = total


def _merge_phase(agg: Dict[str, Any], other: Dict[str, Any]) -> None:
    for phase, stats in other.items():
        slot = agg.get(phase)
        if slot is None:
            if len(agg) < PHASE_TABLE_CAP:
                agg[phase] = dict(stats)
        else:
            slot["holds"] += stats["holds"]
            slot["totalMs"] = round(slot["totalMs"] + stats["totalMs"], 4)
            slot["maxMs"] = max(slot["maxMs"], stats["maxMs"])


def _merge_blockers(agg: List[Dict], other: List[Dict]) -> List[Dict]:
    by_phase: Dict[str, Dict] = {b["holderPhase"]: dict(b) for b in agg}
    for b in other:
        slot = by_phase.get(b["holderPhase"])
        if slot is None:
            if len(by_phase) < BLOCKER_TABLE_CAP:
                by_phase[b["holderPhase"]] = dict(b)
        else:
            slot["waits"] += b["waits"]
            slot["totalWaitMs"] = round(slot["totalWaitMs"] + b["totalWaitMs"], 4)
    return sorted(by_phase.values(), key=lambda b: -b["totalWaitMs"])


def wrap_instance(obj: Any, cls: type, lock_attr: str) -> None:
    """Swap a freshly constructed ``@guarded_by`` instance's raw lock
    for a TimedLock named after the declaration site.  Idempotent;
    runs unconditionally from the guarded ``__init__`` wrapper —
    that is what "always-on" means (recording still gates on the
    keeper switchboard)."""
    inner = getattr(obj, lock_attr, None)
    if inner is None or isinstance(inner, TimedLock):
        return
    # never time the race detector's proxy: timing wraps the raw lock
    from ..analysis import racecheck

    if isinstance(inner, racecheck.TrackedLock):
        return
    if not hasattr(inner, "acquire") or not hasattr(inner, "release"):
        return
    object.__setattr__(
        obj,
        lock_attr,
        TimedLock(
            inner,
            f"{cls.__name__}.{lock_attr}",
            sample_every=_default_sample_every,
        ),
    )


def snapshot(name_filter: Optional[str] = None) -> List[Dict[str, Any]]:
    keeper = _keeper
    return keeper.snapshot(name_filter) if keeper is not None else []


def publish(metrics) -> None:
    keeper = _keeper
    if keeper is not None:
        keeper.publish(metrics)
