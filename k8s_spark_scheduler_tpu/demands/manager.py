"""Demand manager (reference ``internal/demands/demand.go``).

Creates Demand CRs when an app or executor doesn't fit (signaling the
cluster autoscaler) and deletes them on success, with event emission and
source attribution.  Demand name = ``demand-<podName>``
(internal/common/utils/demands.go:60-62).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..events import events as ev
from ..ops.registry import Binpacker
from ..scheduler.labels import SPARK_APP_ID_LABEL, find_instance_group_from_pod_spec
from ..state.typed_caches import SafeDemandCache
from ..types.objects import (
    Demand,
    DemandSpec,
    DemandUnit,
    ObjectMeta,
    OwnerReference,
    Pod,
)
from ..types.resources import Resources

logger = logging.getLogger(__name__)


def demand_name(pod: Pod) -> str:
    return "demand-" + pod.name


def pod_name_from_demand(demand: Demand) -> str:
    return demand.name.removeprefix("demand-")


class DemandManager:
    """demands.Manager (demand.go:37-42)."""

    def __init__(
        self,
        demands: SafeDemandCache,
        binpacker: Binpacker,
        instance_group_label: str,
        event_log: Optional[ev.EventLog] = None,
    ):
        self._demands = demands
        self._binpacker = binpacker
        self._instance_group_label = instance_group_label
        self._event_log = event_log

    # -- create --------------------------------------------------------------

    def create_demand_for_application_in_any_zone(
        self, driver_pod: Pod, application_resources
    ) -> None:
        if not self._demands.crd_exists():
            return
        self._create_demand(
            driver_pod, self._application_units(driver_pod, application_resources), None
        )

    def create_demand_for_executor_in_any_zone(
        self, executor_pod: Pod, executor_resources: Resources
    ) -> None:
        self.create_demand_for_executor_in_specific_zone(executor_pod, executor_resources, None)

    def create_demand_for_executor_in_specific_zone(
        self, executor_pod: Pod, executor_resources: Resources, zone: Optional[str]
    ) -> None:
        if not self._demands.crd_exists():
            return
        units = [
            DemandUnit(
                count=1,
                resources=executor_resources,
                pod_names_by_namespace={executor_pod.namespace: [executor_pod.name]},
            )
        ]
        self._create_demand(executor_pod, units, zone)

    def _create_demand(self, pod: Pod, units: List[DemandUnit], zone: Optional[str]) -> None:
        instance_group, ok = find_instance_group_from_pod_spec(pod, self._instance_group_label)
        if not ok:
            logger.error(
                "no instance group label %s on pod %s; skipping demand",
                self._instance_group_label,
                pod.name,
            )
            return
        demand = self._new_demand(pod, instance_group, units, zone)
        if demand is None:
            return
        try:
            self._demands.create(demand)
        except Exception:
            # demand already exists for this pod → no action (demand.go:120-126)
            if self._demands.get(demand.namespace, demand.name) is not None:
                return
            logger.exception("failed to create demand %s", demand.name)
            return
        ev.emit_demand_created(demand, self._event_log)

    def _new_demand(
        self, pod: Pod, instance_group: str, units: List[DemandUnit], zone: Optional[str]
    ) -> Optional[Demand]:
        """demand.go:149-173."""
        app_id = pod.labels.get(SPARK_APP_ID_LABEL)
        if app_id is None:
            logger.error("pod %s has no %s label", pod.name, SPARK_APP_ID_LABEL)
            return None
        return Demand(
            meta=ObjectMeta(
                name=demand_name(pod),
                namespace=pod.namespace,
                labels={SPARK_APP_ID_LABEL: app_id},
                owner_references=[
                    OwnerReference(kind="Pod", name=pod.name, uid=pod.meta.uid)
                ],
            ),
            spec=DemandSpec(
                instance_group=instance_group,
                units=units,
                enforce_single_zone_scheduling=self._binpacker.is_single_az,
                zone=zone,
            ),
        )

    @staticmethod
    def _application_units(driver_pod: Pod, application_resources) -> List[DemandUnit]:
        """demand.go:175-201: 1 driver unit (deduped by pod name) +
        min-executor-count executor units."""
        units = [
            DemandUnit(
                count=1,
                resources=application_resources.driver_resources,
                pod_names_by_namespace={driver_pod.namespace: [driver_pod.name]},
            )
        ]
        if application_resources.min_executor_count > 0:
            units.append(
                DemandUnit(
                    count=application_resources.min_executor_count,
                    resources=application_resources.executor_resources,
                )
            )
        return units

    # -- delete --------------------------------------------------------------

    def delete_demand_if_exists(self, pod: Pod, source: str) -> None:
        """demand.go:136-147."""
        if not self._demands.crd_exists():
            return
        name = demand_name(pod)
        demand = self._demands.get(pod.namespace, name)
        if demand is not None:
            self._demands.delete(pod.namespace, name)
            ev.emit_demand_deleted(demand, source, self._event_log)
