"""Structured event log (reference ``internal/events/events.go:28-82``).

Three event types: application_scheduled, demand_created,
demand_deleted.  Events are appended to a bounded in-memory ring (for
tests/inspection) and emitted to the standard logger (the reference's
evt2log analog).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List
from ..analysis import racecheck
from ..analysis.guarded import guarded_by

logger = logging.getLogger("k8s_spark_scheduler_tpu.events")

APPLICATION_SCHEDULED = "foundry.spark.scheduler.application_scheduled"
DEMAND_CREATED = "foundry.spark.scheduler.demand_created"
DEMAND_DELETED = "foundry.spark.scheduler.demand_deleted"


@dataclass
class Event:
    name: str
    values: Dict[str, Any]
    timestamp: float = field(default_factory=time.time)
    # trace of the scheduling request that emitted this event ("" when
    # emitted outside any traced request): joins the event ring to
    # GET /traces and the request log without grepping timestamps
    trace_id: str = ""


@guarded_by("_lock", "_events")
class EventLog:
    def __init__(self, capacity: int = 4096):
        self._events: deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def emit(self, name: str, **values: Any) -> None:
        from ..tracing import current_trace_id

        event = Event(name, values, trace_id=current_trace_id() or "")
        with self._lock:
            racecheck.note_access(self, "_events")
            self._events.append(event)
        if event.trace_id:
            logger.info("%s traceId=%s %s", name, event.trace_id, values)
        else:
            logger.info("%s %s", name, values)

    def all(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    def by_name(self, name: str) -> List[Event]:
        return [e for e in self.all() if e.name == name]

    def by_trace_id(self, trace_id: str) -> List[Event]:
        return [e for e in self.all() if trace_id and e.trace_id == trace_id]


# module-level default sink (swappable for tests)
default_event_log = EventLog()


def emit_application_scheduled(
    instance_group: str,
    spark_app_id: str,
    pod_name: str,
    pod_namespace: str,
    driver_resources,
    executor_resources,
    min_executor_count: int,
    max_executor_count: int,
    event_log: EventLog | None = None,
) -> None:
    """events.go:34-58."""
    from ..tracing import current_trace_id

    (event_log or default_event_log).emit(
        APPLICATION_SCHEDULED,
        traceId=current_trace_id() or "",
        instanceGroup=instance_group,
        sparkAppID=spark_app_id,
        podName=pod_name,
        podNamespace=pod_namespace,
        driverCPU=driver_resources.cpu.serialize(),
        driverMemory=driver_resources.memory.serialize(),
        driverNvidiaGPUs=driver_resources.nvidia_gpu.serialize(),
        executorCPU=executor_resources.cpu.serialize(),
        executorMemory=executor_resources.memory.serialize(),
        executorNvidiaGPUs=executor_resources.nvidia_gpu.serialize(),
        minExecutorCount=min_executor_count,
        maxExecutorCount=max_executor_count,
    )


def emit_demand_created(demand, event_log: EventLog | None = None) -> None:
    (event_log or default_event_log).emit(
        DEMAND_CREATED,
        demandName=demand.name,
        demandNamespace=demand.namespace,
        instanceGroup=demand.spec.instance_group,
    )


def emit_demand_deleted(demand, source: str, event_log: EventLog | None = None) -> None:
    (event_log or default_event_log).emit(
        DEMAND_DELETED,
        demandName=demand.name,
        demandNamespace=demand.namespace,
        instanceGroup=demand.spec.instance_group,
        source=source,
    )
