"""Structured event log (reference ``internal/events/events.go:28-82``).

Three event types: application_scheduled, demand_created,
demand_deleted.  Events are appended to a bounded in-memory ring (for
tests/inspection) and emitted to the standard logger (the reference's
evt2log analog).

The ring carries a monotonic sequence so cursor-based consumers (the
lifecycle ledger) can drain incrementally off-thread, and per-key
secondary indexes (name, trace id) evicted in lockstep with the ring
so ``by_name``/``by_trace_id`` are O(matches) instead of a full scan.
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by

logger = logging.getLogger("k8s_spark_scheduler_tpu.events")

APPLICATION_SCHEDULED = "foundry.spark.scheduler.application_scheduled"
DEMAND_CREATED = "foundry.spark.scheduler.demand_created"
DEMAND_DELETED = "foundry.spark.scheduler.demand_deleted"


@dataclass
class Event:
    name: str
    values: Dict[str, Any]
    # semantic instant through the pluggable source: virtual in sim
    timestamp: float = field(default_factory=timesource.now)
    # trace of the scheduling request that emitted this event ("" when
    # emitted outside any traced request): joins the event ring to
    # GET /traces and the request log without grepping timestamps
    trace_id: str = ""


@guarded_by("_lock", "_events", "_seq", "_by_name", "_by_trace")
class EventLog:
    def __init__(self, capacity: int = 4096):
        self._capacity = capacity
        self._events: deque[Event] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # total appends ever — the ring holds events with sequence in
        # (_seq - len(_events), _seq]; consumers cursor on this
        self._seq = 0
        # secondary indexes, evicted in lockstep with the ring: each
        # bucket is a deque in insertion order, so the ring's oldest
        # event is also the leftmost entry of its buckets
        self._by_name: Dict[str, deque] = {}
        self._by_trace: Dict[str, deque] = {}
        # optional wakeup Events set on every emit (outside the lock),
        # so the lifecycle ledger drains on activity instead of polling
        self._wakeups: Tuple[Any, ...] = ()
        # happens-before channel for the emit→wakeup edge (the waiter
        # calls hb_observe on this channel after its Event.wait)
        self._hb_key = ("eventlog", racecheck.channel_token())

    def emit(self, name: str, **values: Any) -> None:
        from ..tracing import current_trace_id

        event = Event(name, values, trace_id=current_trace_id() or "")
        with self._lock:
            racecheck.note_access(self, "_events")
            racecheck.note_access(self, "_seq")
            if len(self._events) == self._capacity:
                self._unindex_oldest()
            self._events.append(event)
            self._seq += 1
            self._by_name.setdefault(event.name, deque()).append(event)
            if event.trace_id:
                self._by_trace.setdefault(event.trace_id, deque()).append(
                    event
                )
            wakeups = self._wakeups
        if wakeups:
            # Event.set is synchronization the lock tracker can't see:
            # record the emit→wakeup happens-before edge explicitly
            racecheck.hb_publish(self.hb_channel())
            for wakeup in wakeups:
                wakeup.set()
        if event.trace_id:
            logger.info("%s traceId=%s %s", name, event.trace_id, values)
        else:
            logger.info("%s %s", name, values)

    def _unindex_oldest(self) -> None:
        """Drop the about-to-be-evicted ring head from its index
        buckets (insertion order makes it each bucket's leftmost)."""
        racecheck.note_access(self, "_by_name")
        racecheck.note_access(self, "_by_trace")
        old = self._events[0]
        bucket = self._by_name.get(old.name)
        if bucket:
            bucket.popleft()
            if not bucket:
                del self._by_name[old.name]  # schedlint: disable=LK001 -- _unindex_oldest is only called with _lock held (see callers)
        if old.trace_id:
            bucket = self._by_trace.get(old.trace_id)
            if bucket:
                bucket.popleft()
                if not bucket:
                    del self._by_trace[old.trace_id]  # schedlint: disable=LK001 -- _unindex_oldest is only called with _lock held (see callers)

    def attach_wakeup(self, event) -> None:
        """Add a wakeup Event set on every emit.  Multi-listener:
        appends rather than replaces (wiring-time call)."""
        with self._lock:
            self._wakeups = self._wakeups + (event,)

    def hb_channel(self) -> tuple:
        return self._hb_key

    @property
    def seq(self) -> int:
        with self._lock:
            return self._seq

    def all(self) -> List[Event]:
        with self._lock:
            return list(self._events)

    def by_name(self, name: str) -> List[Event]:
        with self._lock:
            bucket = self._by_name.get(name)
            return list(bucket) if bucket else []

    def by_trace_id(self, trace_id: str) -> List[Event]:
        if not trace_id:
            return []
        with self._lock:
            bucket = self._by_trace.get(trace_id)
            return list(bucket) if bucket else []

    def events_since(self, seq: int) -> Tuple[List[Event], int]:
        """Events appended after ``seq`` (oldest first, truncated to
        the ring's reach) and the current sequence to cursor on."""
        with self._lock:
            total = self._seq
            fresh = total - seq
            if fresh <= 0:
                return [], total
            n = min(fresh, len(self._events))
            if n == 0:
                return [], total
            events = list(self._events)[-n:]
        return events, total


# module-level default sink (swappable for tests)
default_event_log = EventLog()


def emit_application_scheduled(
    instance_group: str,
    spark_app_id: str,
    pod_name: str,
    pod_namespace: str,
    driver_resources,
    executor_resources,
    min_executor_count: int,
    max_executor_count: int,
    event_log: EventLog | None = None,
) -> None:
    """events.go:34-58."""
    from ..tracing import current_trace_id

    (event_log or default_event_log).emit(
        APPLICATION_SCHEDULED,
        traceId=current_trace_id() or "",
        instanceGroup=instance_group,
        sparkAppID=spark_app_id,
        podName=pod_name,
        podNamespace=pod_namespace,
        driverCPU=driver_resources.cpu.serialize(),
        driverMemory=driver_resources.memory.serialize(),
        driverNvidiaGPUs=driver_resources.nvidia_gpu.serialize(),
        executorCPU=executor_resources.cpu.serialize(),
        executorMemory=executor_resources.memory.serialize(),
        executorNvidiaGPUs=executor_resources.nvidia_gpu.serialize(),
        minExecutorCount=min_executor_count,
        maxExecutorCount=max_executor_count,
    )


def emit_demand_created(demand, event_log: EventLog | None = None) -> None:
    (event_log or default_event_log).emit(
        DEMAND_CREATED,
        demandName=demand.name,
        demandNamespace=demand.namespace,
        instanceGroup=demand.spec.instance_group,
    )


def emit_demand_deleted(demand, source: str, event_log: EventLog | None = None) -> None:
    (event_log or default_event_log).emit(
        DEMAND_DELETED,
        demandName=demand.name,
        demandNamespace=demand.namespace,
        instanceGroup=demand.spec.instance_group,
        source=source,
    )
