"""HA failover fabric: lease-fenced multi-replica operation.

The reference runs 2 replicas behind Kubernetes leader election
(SURVEY §2.10); Borg (EuroSys'15) is the architectural template — an
elected master whose replicas recover by replaying a log, *fenced* so a
deposed leader's in-flight writes can never corrupt the cell.  This
package supplies the three pieces our reproduction was missing:

- :mod:`.lease` — lease-based leader election over a coordination
  Lease object (the embedded API server in tests/sim, coordination.k8s.io
  via the rest layer in prod), issuing a **monotone fencing epoch** per
  leadership grant;
- :mod:`.fencing` — the :class:`~.fencing.FencedWriter` gate every
  state-mutating write path (reservation write-back, demand CRD writes,
  preemption deletes, journal acks) consults; once a newer epoch is
  observed every write is refused with
  :class:`~.fencing.StaleEpochError`;
- :mod:`.crashpoint` — named crash-injection points threaded through
  the write-back pipeline, both journals, preemption commit, and lease
  renewal, swept as a matrix by :mod:`.crashmatrix`;
- :mod:`.reconcile` — full state reconciliation at takeover: replay
  both journals, diff CRDs against pod reality, finish half-evicted
  gangs, reset the delta-solve session and ChangeFeed.

:class:`HAFabric` below is the facade wiring owns: it glues elector →
fence → reconciler and serves ``/status/ha``.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from .. import timesource
from ..metrics import names as mnames
from .crashpoint import maybe_crash
from .fencing import FenceState, FencedWriter, StaleEpochError  # noqa: F401
from .lease import LeaderElector, Lease  # noqa: F401

logger = logging.getLogger(__name__)


class HAFabric:
    """Facade over elector + fence + reconciler for one replica.

    ``step()`` drives one election/renewal round; prod wiring runs it on
    a background thread (``start()``), tests and the simulator call it
    explicitly so elections stay deterministic under the virtual clock.
    """

    def __init__(
        self,
        elector: LeaderElector,
        fence: FenceState,
        reconciler=None,
        metrics=None,
        renew_interval_seconds: float = 5.0,
        writer=None,
    ):
        self.elector = elector
        self.fence = fence
        self.reconciler = reconciler
        # the shared FencedWriter gate installed on the write paths;
        # kept here so probes (readiness, chaos cells) can exercise the
        # exact gate production writes go through
        self.writer = writer
        self._metrics = metrics
        self._renew_interval = renew_interval_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_report: dict = {}
        elector.on_elected = self._on_elected
        elector.on_deposed = self._on_deposed

    # -- election callbacks --------------------------------------------------

    def _on_elected(self, epoch: int) -> None:
        logger.info("ha: elected leader at epoch %d", epoch)
        if self._metrics is not None:
            self._metrics.counter(mnames.HA_TRANSITIONS, {"to": "leader"})
        if self.reconciler is not None:
            try:
                self._last_report = self.reconciler.run(epoch)
            except Exception:
                logger.exception("ha: takeover reconciliation failed")

    def _on_deposed(self, epoch: int) -> None:
        logger.warning(
            "ha: deposed (observed epoch %d > held %d); all fenced writes "
            "will refuse with stale-epoch until re-elected",
            epoch,
            self.fence.epoch(),
        )
        if self._metrics is not None:
            self._metrics.counter(mnames.HA_TRANSITIONS, {"to": "follower"})

    # -- driving -------------------------------------------------------------

    def step(self) -> bool:
        """One election/renewal round; returns is_leader.  Refuses to
        run inside the extender's predicate lock (same in-lock refusal
        pattern as the capacity sampler): leader election does I/O and
        must never stretch a scheduling decision's lock hold."""
        # imported here, not at module top: capacity pulls the native/
        # ops stack, and resilience/journal.py imports this package
        from ..capacity import in_predicate_lock

        if in_predicate_lock():
            return self.elector.is_leader()
        maybe_crash("lease.pre-renew")
        leader = self.elector.step()
        if self._metrics is not None:
            self._metrics.gauge(mnames.HA_LEADER_STATE, 1.0 if leader else 0.0)
            self._metrics.gauge(mnames.HA_EPOCH, float(self.fence.epoch()))
        return leader

    def is_leader(self) -> bool:
        return self.elector.is_leader()

    def start(self) -> None:
        """Background renewal loop (prod wiring only; sim/tests step
        manually)."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="ha-elector", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.step()
            except Exception:
                logger.exception("ha: election step failed")
            # real-time wait: the renewal cadence is wall-clock by
            # nature (the lease TTL is wall-clock)
            self._stop.wait(self._renew_interval)  # schedlint: disable=TS002 -- lease renewal cadence is wall-clock by contract

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
            self._thread = None

    # -- observability -------------------------------------------------------

    def status(self) -> dict:
        """The ``/status/ha`` payload."""
        lease = self.elector.peek()
        return {
            "identity": self.elector.identity,
            "leader": self.elector.is_leader(),
            "epoch": self.fence.epoch(),
            "highestObservedEpoch": self.fence.highest_observed(),
            "fence": self.fence.state(),
            "lease": {
                "holder": lease.holder if lease is not None else "",
                "epoch": lease.epoch if lease is not None else 0,
                "renewedAt": lease.renewed_at if lease is not None else 0.0,
                "durationSeconds": (
                    lease.duration_seconds if lease is not None else 0.0
                ),
                "history": list(lease.history) if lease is not None else [],
            },
            "reconciliation": dict(self._last_report),
            "asOf": timesource.now(),
        }
