"""Crash-point matrix: kill the scheduler at every registered crash
point, cold-restart a successor on the same API server + journal files,
and prove recovery restores every invariant.

One matrix cell = one full lifecycle:

1. fresh embedded API server + durable journal dir; incarnation A boots
   with the HA fabric enabled and wins the lease (epoch 1);
2. the cell's scenario drives real traffic through the path the crash
   point lives on (write-back create, journal divert, journal ack,
   whole-app preemption, lease renewal) with the point armed — the
   point fires :class:`~.crashpoint.SimulatedCrash` (a BaseException,
   so no ``except Exception`` handler can save the incarnation: the
   thread it fires on is dead, exactly like ``kill -9`` landing
   mid-instruction);
3. incarnation A is hard-killed — background threads reaped, **no**
   graceful lease step-down, no journal flush beyond what already hit
   the file line-by-line;
4. incarnation B boots on the same API server and journal path: boot
   replay runs unfenced, the lease TTL lapses, B acquires epoch+1 and
   runs full takeover reconciliation (:mod:`.reconcile`);
5. the audit: scheduler invariants I1–I5 green, both journals drained,
   the victim of a mid-preemption crash fully evicted (never
   half-evicted), zero stale-epoch commits.

Exactly-once is the point: whatever instant the process died, each
reservation intent and each eviction lands exactly once across the
restart — replayed if the ack was lost, never doubled if the write
already landed.

CI runs the matrix against the failover scenario's cluster shape::

    python -m k8s_spark_scheduler_tpu.ha.crashmatrix \\
        --scenario examples/sim/failover.json --json report.json

``--handoff`` runs the complementary *planned* chaos cell instead: two
live replicas on one API server, the leader steps down (rolling
restart), the standby takes over at epoch+1 and the deposed replica's
fenced write paths must refuse 100% of writes.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from ..config import (
    ConcurrentConfig,
    HAConfig,
    Install,
    PolicyConfig,
    ResilienceConfig,
)
from ..kube.apiserver import APIServer
from ..kube.crd import DEMAND_CRD_NAME, demand_crd_spec
from ..kube.errors import APIError
from ..policy.victims import VictimCandidate, VictimPlan
from ..scheduler import invariants
from ..server.wiring import init_server_with_clients
from ..testing.harness import Harness
from ..types.extenderapi import ExtenderArgs
from ..types.objects import Node, ObjectMeta, Pod, PodPhase, ResourceReservation
from ..types.resources import ZONE_LABEL, Resources
from . import crashpoint
from .crashpoint import SimulatedCrash
from .fencing import StaleEpochError

# lease TTL for matrix incarnations: short so the successor's takeover
# wait is bounded (the TTL is wall-clock by contract)
_LEASE_TTL_S = 0.3

_PREEMPT_POINTS = {
    crashpoint.PREEMPT_POST_JOURNAL,
    crashpoint.PREEMPT_MID_EXECUTE,
    crashpoint.PREEMPT_PRE_ACK,
}
# points that need a divert first (write failures push the intent into
# the journal, which is where the append points live)
_DIVERT_POINTS = {
    crashpoint.JOURNAL_PRE_APPEND,
    crashpoint.JOURNAL_POST_APPEND,
}
# speculation→commit window points (concurrent/engine.py): fire
# synchronously on the Filter caller's thread inside engine.predicate
_CONCURRENT_POINTS = {
    crashpoint.CONCURRENT_SPECULATION_SOLVED,
    crashpoint.CONCURRENT_COMMIT_REVALIDATED,
    crashpoint.CONCURRENT_COMMIT_WRITTEN,
}


def _wait(cond, timeout: float = 10.0, tick: float = 0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)  # schedlint: disable=TS002 -- matrix cells run on real threads/TTLs, not the virtual clock
    return False


class CrashMatrix:
    """Runs the cells; one instance per matrix sweep."""

    def __init__(
        self,
        nodes: int = 3,
        node_cpu: str = "16",
        node_memory: str = "32Gi",
        lease_name: str = "tpu-gang-scheduler",
    ):
        self.nodes = nodes
        self.node_cpu = node_cpu
        self.node_memory = node_memory
        self.lease_name = lease_name

    # -- incarnation lifecycle -------------------------------------------

    def _install(self, identity: str, journal_path: str) -> Install:
        return Install(
            fifo=True,
            binpack_algo="tightly-pack",
            resilience=ResilienceConfig(journal_path=journal_path),
            policy=PolicyConfig(enabled=True, preemption_enabled=True),
            ha=HAConfig(
                enabled=True,
                background=False,
                lease_name=self.lease_name,
                lease_duration_seconds=_LEASE_TTL_S,
                identity=identity,
            ),
            # every cell's Filter traffic runs through the concurrent
            # admission engine, so the speculation→commit window's crash
            # points sit on the live request path
            concurrent=ConcurrentConfig(enabled=True),
        )

    def _boot(self, api: APIServer, identity: str, journal_path: str):
        server = init_server_with_clients(
            api,
            self._install(identity, journal_path),
            start_background=True,
            demand_poll_interval=0.02,
            unschedulable_polling_interval=1e9,
        )
        server.lazy_demand_informer.wait_ready(5)
        return server

    @staticmethod
    def _hard_kill(server) -> None:
        """kill -9 analog: reap the background threads so the dead
        incarnation cannot keep mutating the shared API server from
        beyond the grave, but NO graceful lease step-down and no
        journal housekeeping — the successor finds exactly what a real
        crash leaves behind."""
        server.ha = None  # skip stop()'s graceful step_down/handoff
        server.stop()

    # -- scenario primitives ---------------------------------------------

    def _seed_nodes(self, api: APIServer) -> None:
        for i in range(self.nodes):
            api.create(
                Node(
                    meta=ObjectMeta(
                        name=f"node-{i + 1:03d}",
                        labels={
                            ZONE_LABEL: "zone1",
                            "resource_channel": "batch-medium-priority",
                        },
                    ),
                    allocatable=Resources.of(self.node_cpu, self.node_memory, "0"),
                    ready=True,
                )
            )

    @staticmethod
    def _schedule_app(server, api: APIServer, app_id: str, executors: int = 2) -> List[str]:
        """Submit + schedule one gang through the real extender; binds
        successes exactly as the kube-scheduler would.  Returns bound
        pod names."""
        pods = Harness.static_allocation_spark_pods(app_id, executors)
        for pod in pods:
            api.create(pod)
        node_names = sorted(n.name for n in api.list(Node.KIND))
        bound = []
        engine = getattr(server, "concurrent", None)
        predicate = (
            engine.predicate if engine is not None else server.extender.predicate
        )
        for pod in pods:
            fresh = api.get(Pod.KIND, pod.namespace, pod.name)
            result = predicate(
                ExtenderArgs(pod=fresh, node_names=list(node_names))
            )
            if result.node_names:
                landed = api.get(Pod.KIND, pod.namespace, pod.name)
                landed.node_name = result.node_names[0]
                landed.phase = PodPhase.RUNNING
                api.update(landed)
                bound.append(landed.name)
        return bound

    @staticmethod
    def _drain(server, timeout: float = 10.0) -> bool:
        """Drive the write-back + journal to empty (post-recovery)."""
        cache = server.resource_reservation_cache

        def settled():
            if any(cache.inflight_queue_lengths()):
                return False
            if cache.journal_depth() != 0:
                cache.nudge_recovery(force=True)
                return False
            return True

        return _wait(settled, timeout=timeout)

    # -- one matrix cell -------------------------------------------------

    def run_point(self, point: str) -> Dict:
        journal_dir = tempfile.mkdtemp(prefix="crashmatrix-")
        journal_path = f"{journal_dir}/intents.jsonl"
        api = APIServer()
        api.create_crd(DEMAND_CRD_NAME, demand_crd_spec())
        report: Dict = {"point": point, "crashed": False, "ok": False}
        server_a = server_b = None
        try:
            server_a = self._boot(api, "replica-a", journal_path)
            self._seed_nodes(api)
            server_a.ha.step()  # epoch 1
            report["crashed"] = self._drive(server_a, api, point, report)
            # the lease must lapse before B can steal it; A never
            # steps down (it is dead)
            kill_at = time.monotonic()
            self._hard_kill(server_a)
            server_a = None
            remaining = _LEASE_TTL_S + 0.2 - (time.monotonic() - kill_at)
            if remaining > 0:
                time.sleep(remaining)  # schedlint: disable=TS002 -- waiting out the dead leader's real lease TTL

            server_b = self._boot(api, "replica-b", journal_path)
            elected = _wait(server_b.ha.step, timeout=5.0, tick=0.05)
            report["recovered"] = elected
            report["recoveredEpoch"] = server_b.ha.fence.epoch()
            self._drain(server_b)
            self._audit(server_b, api, point, report)
        finally:
            crashpoint.disarm()
            api.set_write_fault(None)
            for server in (server_a, server_b):
                if server is not None:
                    try:
                        server.stop()
                    except Exception:
                        pass
            shutil.rmtree(journal_dir, ignore_errors=True)
        return report

    def _drive(self, server, api: APIServer, point: str, report: Dict) -> bool:
        """Arm the point and push traffic through its path; returns
        whether the crash actually fired."""
        if point in _PREEMPT_POINTS:
            # a running victim whose whole-gang eviction will crash
            # mid-commit; the successor must finish it exactly once
            victim_pods = self._schedule_app(server, api, "victim-app")
            report["victimPods"] = victim_pods
            self._drain(server)
            crashpoint.arm(point)
            plan = VictimPlan(
                preemptor_app="matrix-preemptor",
                preemptor_band="high",
                victims=[
                    VictimCandidate(
                        namespace="default",
                        app_id="victim-app",
                        band="low",
                        band_rank=0,
                        tenant="",
                        created=0.0,
                        freed=np.zeros((self.nodes, 3), dtype=np.int64),
                        pods=victim_pods,
                    )
                ],
                whatif_ms=0.0,
                lane="matrix",
            )
            try:
                server.policy.coordinator.commit(plan)
            except SimulatedCrash:
                return True
            return False

        if point == crashpoint.LEASE_PRE_RENEW:
            self._schedule_app(server, api, "app-001")
            self._drain(server)
            crashpoint.arm(point)
            try:
                server.ha.step()
            except SimulatedCrash:
                return True
            return False

        if point in _DIVERT_POINTS or point == crashpoint.JOURNAL_POST_ACK:
            # the append points live on the divert path: fail the RR
            # writes so the worker journals the intent (and dies there).
            # post-ack needs one more beat — ack() only reaches it when
            # a journaled intent actually lands, so the crash is armed
            # for the REPLAY's ack, not the divert
            def inject(op, kind, ns, name):
                if kind == ResourceReservation.KIND:
                    return APIError(f"injected write failure ({op} {ns}/{name})")
                return None

            if point in _DIVERT_POINTS:
                crashpoint.arm(point)
            api.set_write_fault(inject)
            self._schedule_app(server, api, "app-001")
            cache = server.resource_reservation_cache
            fired = _wait(
                lambda: crashpoint.armed() is None
                if point in _DIVERT_POINTS
                else cache.journal_depth() > 0
            )
            api.set_write_fault(None)
            if point == crashpoint.JOURNAL_POST_ACK:
                if not fired:
                    return False
                crashpoint.arm(point)
                cache.nudge_recovery(force=True)
                fired = _wait(lambda: crashpoint.armed() is None)
            return fired

        if point in _CONCURRENT_POINTS:
            # the speculation→commit window: the point fires on the
            # Filter caller's thread inside engine.predicate — before
            # the commit for speculation-solved / commit-revalidated,
            # after the reservation write-back for commit-written
            crashpoint.arm(point)
            try:
                self._schedule_app(server, api, "app-001")
            except SimulatedCrash:
                return True
            return False

        # write-back commit and journal-ack points fire on the worker
        # thread during the very first reservation write
        crashpoint.arm(point)
        self._schedule_app(server, api, "app-001")
        return _wait(lambda: crashpoint.armed() is None)

    def _audit(self, server, api: APIServer, point: str, report: Dict) -> None:
        violations = [str(v) for v in invariants.check(server, raise_on_violation=False)]
        cache = server.resource_reservation_cache
        report["journalDepth"] = cache.journal_depth()
        coord = server.policy.coordinator if server.policy is not None else None
        report["evictJournalDepth"] = coord.journal_depth() if coord is not None else 0
        report["staleCommits"] = server.ha.fence.stale_commits()
        if point in _PREEMPT_POINTS:
            # exactly-once eviction: no half-evicted gang survives the
            # crash — reservation gone AND every victim pod gone
            if cache.get("default", "victim-app") is not None:
                violations.append("victim-app still holds a reservation")
            from ..kube.errors import NotFoundError

            for name in report.get("victimPods", ()):
                try:
                    api.get(Pod.KIND, "default", name)
                except NotFoundError:
                    continue
                violations.append(f"victim pod {name} still exists")
        if point in _CONCURRENT_POINTS:
            # exactly-once across the restart: a crash BEFORE the commit
            # leaves zero reservation state (the gang was never
            # admitted; the retry re-admits); a crash AFTER the
            # reservation write leaves either the complete reservation
            # or none (the bind never happened, so an unflushed write-
            # back losing the race is still all-or-nothing) — never a
            # half-committed gang
            rr = cache.get("default", "app-001")
            report["reservationPresent"] = rr is not None
            if point != crashpoint.CONCURRENT_COMMIT_WRITTEN:
                if rr is not None:
                    violations.append(
                        "crash before commit left a reservation for app-001"
                    )
            elif rr is not None and not rr.spec.reservations:
                violations.append("app-001 reservation survived half-committed")
        if report["journalDepth"] != 0:
            violations.append(f"{report['journalDepth']} write intents still pending")
        if report["evictJournalDepth"] != 0:
            violations.append(f"{report['evictJournalDepth']} evict intents still pending")
        if report["staleCommits"] != 0:
            violations.append(f"{report['staleCommits']} stale-epoch commits")
        if not report.get("recovered"):
            violations.append("successor failed to acquire leadership")
        report["violations"] = violations
        report["ok"] = report["crashed"] and not violations

    # -- two-replica graceful handoff ------------------------------------

    def run_handoff(self) -> Dict:
        """Chaos cell for the *planned* path: two live replicas share
        one API server; the leader steps down (rolling restart), the
        standby must take over at epoch+1 and the deposed replica's
        write paths must refuse 100% of writes with zero stale-epoch
        commits.  The unplanned (kill -9) path is :meth:`run_point`."""
        journal_dir = tempfile.mkdtemp(prefix="crashmatrix-handoff-")
        api = APIServer()
        api.create_crd(DEMAND_CRD_NAME, demand_crd_spec())
        report: Dict = {"cell": "two-replica-handoff", "ok": False}
        violations: List[str] = []
        server_a = server_b = None
        try:
            server_a = self._boot(api, "replica-a", f"{journal_dir}/a.jsonl")
            self._seed_nodes(api)
            server_a.ha.step()  # replica-a wins epoch 1
            if not server_a.ha.is_leader():
                violations.append("replica-a failed to win the initial election")
            self._schedule_app(server_a, api, "app-pre-handoff")
            self._drain(server_a)

            server_b = self._boot(api, "replica-b", f"{journal_dir}/b.jsonl")
            server_b.ha.step()  # standby: observes epoch 1, stays follower
            if server_b.ha.is_leader():
                violations.append("standby replica-b claimed leadership under a live lease")

            # planned handoff: a releases, b acquires epoch 2, a's next
            # step observes the newer epoch and fences itself
            server_a.ha.elector.step_down()
            if not server_b.ha.step():
                violations.append("replica-b failed to take over after step-down")
            server_a.ha.step()
            report["handoffEpoch"] = server_b.ha.fence.epoch()
            if report["handoffEpoch"] != 2:
                violations.append(f"expected takeover at epoch 2, got {report['handoffEpoch']}")
            if server_a.ha.is_leader():
                violations.append("deposed replica-a still reports leadership")

            # the deposed replica must refuse every fenced write path
            refusals_before = sum(server_a.ha.fence.state()["refusals"].values())
            for op in ("writeback.create", "writeback.update", "writeback.delete",
                       "demand.create", "preempt.commit"):
                try:
                    server_a.ha.writer.check(op)
                    violations.append(f"deposed replica-a write {op!r} was NOT fenced")
                except StaleEpochError:
                    pass
            refused = sum(server_a.ha.fence.state()["refusals"].values()) - refusals_before
            report["deposedRefusals"] = refused

            # the new leader schedules real work on the shared cluster
            bound = self._schedule_app(server_b, api, "app-post-handoff")
            if not bound:
                violations.append("new leader replica-b failed to schedule")
            if not self._drain(server_b):
                violations.append("replica-b write-back did not drain")
            violations.extend(
                str(v) for v in invariants.check(server_b, raise_on_violation=False)
            )
            report["staleCommits"] = {}
            for name, server in (("replica-a", server_a), ("replica-b", server_b)):
                stale = server.ha.fence.stale_commits()
                report["staleCommits"][name] = stale
                if stale:
                    violations.append(f"{name}: {stale} stale-epoch commits")
        finally:
            for server in (server_a, server_b):
                if server is not None:
                    try:
                        server.stop()
                    except Exception:
                        pass
            shutil.rmtree(journal_dir, ignore_errors=True)
        report["violations"] = violations
        report["ok"] = not violations
        return report

    # -- the sweep -------------------------------------------------------

    def run_matrix(self, points: Optional[List[str]] = None) -> Dict:
        points = list(points or crashpoint.registered_points())
        cells = [self.run_point(p) for p in points]
        return {
            "points": {c["point"]: c for c in cells},
            "ok": all(c["ok"] for c in cells),
        }


def run_matrix(
    scenario_path: Optional[str] = None, points: Optional[List[str]] = None
) -> Dict:
    """Sweep the matrix; when ``scenario_path`` is given the cluster
    shape and lease name come from the scenario's ``cluster``/``ha``
    blocks so CI exercises the same topology the chaos sim runs."""
    nodes, cpu, memory = 3, "16", "32Gi"
    lease_name = "tpu-gang-scheduler"
    if scenario_path:
        with open(scenario_path) as f:
            sc = json.load(f)
        cluster = sc.get("cluster", {})
        nodes = min(int(cluster.get("nodes", nodes)), 6)
        cpu = str(cluster.get("cpu", cpu))
        memory = str(cluster.get("memory", memory))
        lease_name = sc.get("ha", {}).get("lease-name", lease_name)
    matrix = CrashMatrix(
        nodes=nodes, node_cpu=cpu, node_memory=memory, lease_name=lease_name
    )
    report = matrix.run_matrix(points)
    report["scenario"] = scenario_path or "builtin"
    return report


def run_handoff(scenario_path: Optional[str] = None) -> Dict:
    """Run the two-replica graceful-handoff cell (cluster shape from
    the scenario, like :func:`run_matrix`)."""
    nodes, cpu, memory = 3, "16", "32Gi"
    lease_name = "tpu-gang-scheduler"
    if scenario_path:
        with open(scenario_path) as f:
            sc = json.load(f)
        cluster = sc.get("cluster", {})
        nodes = min(int(cluster.get("nodes", nodes)), 6)
        cpu = str(cluster.get("cpu", cpu))
        memory = str(cluster.get("memory", memory))
        lease_name = sc.get("ha", {}).get("lease-name", lease_name)
    matrix = CrashMatrix(
        nodes=nodes, node_cpu=cpu, node_memory=memory, lease_name=lease_name
    )
    return matrix.run_handoff()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Sweep the HA crash-point matrix and audit recovery."
    )
    parser.add_argument("--scenario", default=None, help="sim scenario JSON (cluster shape + lease name)")
    parser.add_argument("--json", dest="json_out", default=None, help="write the full report here")
    parser.add_argument("--points", default=None, help="comma-separated subset of crash points")
    parser.add_argument(
        "--handoff",
        action="store_true",
        help="run the two-replica graceful-handoff chaos cell instead of the crash matrix",
    )
    args = parser.parse_args(argv)
    if args.handoff:
        report = run_handoff(scenario_path=args.scenario)
        report["scenario"] = args.scenario or "builtin"
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(report, f, indent=2, sort_keys=True)
        status = "ok" if report["ok"] else "FAILED"
        detail = "" if report["ok"] else f"  {'; '.join(report['violations'])}"
        print(f"handoff: {status} epoch={report.get('handoffEpoch', '?')} "
              f"refusals={report.get('deposedRefusals', '?')}{detail}")
        return 0 if report["ok"] else 1
    points = args.points.split(",") if args.points else None
    report = run_matrix(scenario_path=args.scenario, points=points)
    for name, cell in sorted(report["points"].items()):
        status = "ok" if cell["ok"] else "FAIL"
        detail = "" if cell["ok"] else f"  {'; '.join(cell.get('violations', []))}"
        print(f"{name:24s} crash={'yes' if cell['crashed'] else 'NO':3s} "
              f"epoch={cell.get('recoveredEpoch', '?')} {status}{detail}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
    print(f"matrix: {'ok' if report['ok'] else 'FAILED'} "
          f"({len(report['points'])} points)")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
