"""Named crash-point injection for failover testing.

``maybe_crash("journal.post-append")`` sits at every point where a
process death would leave interesting partial state: around the async
write-back's API calls, both journals' append/ack, preemption commit,
and lease renewal.  The crash-matrix harness (:mod:`.crashmatrix`)
sweeps every registered point: scenario → crash at point k →
cold-restart recovery → invariant audit.

The disabled cost is ONE module-attribute read (``_ARMED is None``) —
pinned by tests/test_perf_guard.py the same way locktime's disabled
path is.  Arming is one-shot: the first traversal of the armed point
raises and disarms, so recovery after the simulated death cannot
re-crash at the same instruction.

:class:`SimulatedCrash` derives from **BaseException**, not Exception:
a real ``kill -9`` does not flow through ``except Exception`` recovery
handlers (the async worker loop catches Exception to keep draining),
and neither may the simulated one.
"""

from __future__ import annotations

import threading
from typing import List, Optional

# the armed point name, or None.  Read unsynchronized on every
# traversal (module-attr read; GIL-atomic), written under _ARM_LOCK.
_ARMED: Optional[str] = None
_ARM_LOCK = threading.Lock()
# every point name ever declared via register(); the crash matrix
# sweeps this
_POINTS: set = set()


class SimulatedCrash(BaseException):
    """The process 'died' at a crash point.  BaseException so recovery
    code's ``except Exception`` cannot accidentally survive it."""

    def __init__(self, point: str):
        super().__init__(f"simulated crash at {point}")
        self.point = point


def register(name: str) -> str:
    """Declare a crash point (module import time).  Returns the name so
    call sites can do ``PT = register("x.y")`` and pass the constant."""
    _POINTS.add(name)
    return name


def registered_points() -> List[str]:
    return sorted(_POINTS)


def arm(name: str) -> None:
    """Arm one point; the next traversal raises SimulatedCrash once."""
    global _ARMED
    if name not in _POINTS:
        raise ValueError(f"unknown crash point {name!r}; known: {registered_points()}")
    with _ARM_LOCK:
        _ARMED = name


def disarm() -> None:
    global _ARMED
    with _ARM_LOCK:
        _ARMED = None


def armed() -> Optional[str]:
    return _ARMED


def maybe_crash(name: str) -> None:
    """The hot-path check: one module-attr read when disabled."""
    if _ARMED is None:
        return
    _maybe_crash_slow(name)


def _maybe_crash_slow(name: str) -> None:
    global _ARMED
    with _ARM_LOCK:
        if _ARMED != name:
            return
        _ARMED = None  # one-shot: recovery must not re-die here
    raise SimulatedCrash(name)


# -- the registry ------------------------------------------------------------
# Declared here (not at the call sites) so ``registered_points()`` is
# complete after importing this module alone — the crash matrix and CI
# job must not depend on import order to see the full sweep set.

# async write-back pipeline (state/cache.py): around each API call
WRITEBACK_PRE_COMMIT = register("writeback.pre-commit")
WRITEBACK_POST_COMMIT = register("writeback.post-commit")
# intent journal (resilience/journal.py): divert + ack, both journals
JOURNAL_PRE_APPEND = register("journal.pre-append")
JOURNAL_POST_APPEND = register("journal.post-append")
JOURNAL_PRE_ACK = register("journal.pre-ack")
JOURNAL_POST_ACK = register("journal.post-ack")
# preemption commit (policy/preempt.py)
PREEMPT_POST_JOURNAL = register("preempt.post-journal")
PREEMPT_MID_EXECUTE = register("preempt.mid-execute")
PREEMPT_PRE_ACK = register("preempt.pre-ack")
# lease renewal (ha/__init__.py step loop)
LEASE_PRE_RENEW = register("lease.pre-renew")
# concurrent admission engine (concurrent/engine.py): the
# speculation→commit window — after the speculative solve, after the
# commit gate admits the revalidated verdict, and after the reservation
# write-back returned but before the response leaves
CONCURRENT_SPECULATION_SOLVED = register("concurrent.speculation-solved")
CONCURRENT_COMMIT_REVALIDATED = register("concurrent.commit-revalidated")
CONCURRENT_COMMIT_WRITTEN = register("concurrent.commit-written")
