"""Fencing tokens: the write-side half of leader election.

Leases alone cannot stop a paused leader that wakes up mid-write after
its lease expired (the classic GC-pause split-brain).  The fix is the
fencing-token pattern: every leadership grant carries a **monotone
epoch**; every state-mutating write path checks the epoch at the write
boundary and refuses with :class:`StaleEpochError` once a newer epoch
exists.  The refusal is *deterministic*, not probabilistic: the
:class:`FencedWriter` gate re-reads the lease (read-through) before
each fenced write, so a deposed leader's very first post-pause write is
refused — there is no window where a stale write can land.

The read-through costs one lease ``get`` per write-back operation; the
write paths this guards are the async worker threads and the
preemption executor, never the Filter hot path (Filter only mutates
local caches — the perf guard pins that).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Optional

from ..analysis import racecheck
from ..analysis.guarded import guarded_by

logger = logging.getLogger(__name__)


class StaleEpochError(Exception):
    """A fenced write was refused: this writer's epoch is stale."""

    def __init__(self, op: str, held_epoch: int, observed_epoch: int):
        super().__init__(
            f"fenced write refused: {op!r} at epoch {held_epoch} but epoch "
            f"{observed_epoch} has been observed (deposed leader)"
        )
        self.op = op
        self.held_epoch = held_epoch
        self.observed_epoch = observed_epoch


@guarded_by("_lock", "_epoch", "_highest", "_refusals", "_commits", "_stale_commits")
class FenceState:
    """This replica's view of the fencing epoch: the epoch it holds (0 =
    never elected) and the highest epoch it has observed anywhere."""

    def __init__(self, metrics=None):
        self._lock = threading.Lock()
        self._metrics = metrics
        self._epoch = 0
        self._highest = 0
        self._refusals: Dict[str, int] = {}
        self._commits = 0
        # I-H3 witness: commits that went through while a newer epoch
        # was already observed.  By construction always 0; the auditor
        # asserts it.
        self._stale_commits = 0

    def grant(self, epoch: int) -> None:
        with self._lock:
            racecheck.note_access(self, "_epoch")
            self._epoch = epoch
            self._highest = max(self._highest, epoch)

    def observe(self, epoch: int) -> bool:
        """Note an epoch seen on the lease; returns True if this writer
        is now deposed (a newer epoch exists)."""
        with self._lock:
            racecheck.note_access(self, "_highest")
            if epoch > self._highest:
                self._highest = epoch
            return self._highest > self._epoch

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def highest_observed(self) -> int:
        with self._lock:
            return self._highest

    def deposed(self) -> bool:
        with self._lock:
            return self._highest > self._epoch

    # -- accounting (FencedWriter calls these) -------------------------------

    def note_refusal(self, op: str) -> None:
        with self._lock:
            racecheck.note_access(self, "_refusals")
            self._refusals[op] = self._refusals.get(op, 0) + 1
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(mnames.HA_FENCE_REFUSALS, {"op": op})

    def note_commit(self) -> None:
        with self._lock:
            racecheck.note_access(self, "_commits")
            self._commits += 1
            if self._highest > self._epoch:
                self._stale_commits += 1
        if self._stale_commits and self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(mnames.HA_FENCE_STALE_COMMITS)

    def stale_commits(self) -> int:
        with self._lock:
            return self._stale_commits

    def refusals(self) -> int:
        with self._lock:
            return sum(self._refusals.values())

    def state(self) -> dict:
        with self._lock:
            return {
                "epoch": self._epoch,
                "highestObserved": self._highest,
                "commits": self._commits,
                "staleCommits": self._stale_commits,
                "refusals": dict(self._refusals),
            }


class FencedWriter:
    """The gate installed at every state-mutating write boundary.

    ``check(op)`` must be called immediately before the API-server
    mutation (or journal ack); it raises :class:`StaleEpochError` when
    this replica is not the current leader.  ``commit()`` is called
    after the mutation lands, closing the I-H3 accounting loop.

    ``lease_reader`` is the read-through hook (the elector's ``peek``):
    when set, every check re-reads the lease so deposition is observed
    on the write path itself, not only at the next renewal tick.
    """

    def __init__(
        self,
        fence: FenceState,
        lease_reader: Optional[Callable[[], object]] = None,
        metrics=None,
    ):
        self.fence = fence
        self._lease_reader = lease_reader

    def check(self, op: str) -> int:
        """Refuse-or-pass; returns the epoch to stamp on the write."""
        fence = self.fence
        if fence.deposed():
            fence.note_refusal(op)
            raise StaleEpochError(op, fence.epoch(), fence.highest_observed())
        reader = self._lease_reader
        if reader is not None:
            lease = reader()
            if lease is not None and fence.observe(lease.epoch):
                fence.note_refusal(op)
                logger.warning(
                    "ha: fenced write %s refused — lease moved to epoch %d "
                    "(held %d)",
                    op,
                    lease.epoch,
                    fence.epoch(),
                )
                raise StaleEpochError(op, fence.epoch(), lease.epoch)
        epoch = fence.epoch()
        if epoch == 0:
            # never elected: a replica that has not held the lease may
            # not mutate shared state at all
            fence.note_refusal(op)
            raise StaleEpochError(op, 0, fence.highest_observed())
        return epoch

    def commit(self) -> None:
        self.fence.note_commit()
