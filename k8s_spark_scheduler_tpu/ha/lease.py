"""Lease-based leader election with monotone fencing epochs.

One coordination Lease object is the election ground truth: the holder
renews ``renewed_at`` within ``duration_seconds``; a candidate acquires
by CAS-updating an expired (or absent) lease with ``epoch + 1``.  The
API server's optimistic concurrency (resourceVersion → 409 Conflict)
makes the CAS atomic — exactly client-go's ``leaderelection`` resource
lock, reproduced over our embedded/REST API-server interface.

Every successful acquisition appends ``(epoch, holder, at)`` to the
lease's bounded ``history``, which is the I-H1 audit witness: at most
one fenced writer per epoch, epochs strictly increasing.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .. import timesource
from ..analysis.guarded import guarded_by
from ..kube.errors import AlreadyExistsError, ConflictError, NotFoundError
from ..types.objects import APIObject, ObjectMeta

logger = logging.getLogger(__name__)

# bounded so a long-lived cluster's lease object stays small
HISTORY_LIMIT = 64


@dataclass
class Lease(APIObject):
    """Coordination lease (coordination.k8s.io/v1 Lease analog)."""

    KIND = "Lease"

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    epoch: int = 0
    acquired_at: float = 0.0
    renewed_at: float = 0.0
    duration_seconds: float = 15.0
    # [[epoch, holder, acquired_at], ...] — the I-H1 audit trail
    history: List[list] = field(default_factory=list)

    def expired(self, now: float) -> bool:
        return now - self.renewed_at > self.duration_seconds

    def deepcopy(self) -> "Lease":
        return Lease(
            meta=self.meta.copy(),
            holder=self.holder,
            epoch=self.epoch,
            acquired_at=self.acquired_at,
            renewed_at=self.renewed_at,
            duration_seconds=self.duration_seconds,
            history=[list(h) for h in self.history],
        )


def lease_to_wire(lease: Lease) -> dict:
    """coordination.k8s.io/v1 wire form; the epoch rides on
    leaseTransitions (monotone, like client-go's) and the history on an
    annotation so real-cluster deployments keep the audit trail."""
    import json

    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {
            "name": lease.name,
            "namespace": lease.namespace,
            "resourceVersion": str(lease.meta.resource_version),
            "annotations": {"tpu.ha/history": json.dumps(lease.history)},
        },
        "spec": {
            "holderIdentity": lease.holder,
            "leaseDurationSeconds": int(lease.duration_seconds),
            "acquireTime": lease.acquired_at,
            "renewTime": lease.renewed_at,
            "leaseTransitions": lease.epoch,
        },
    }


def lease_from_wire(wire: dict) -> Lease:
    import json

    meta = wire.get("metadata") or {}
    spec = wire.get("spec") or {}
    try:
        history = json.loads((meta.get("annotations") or {}).get("tpu.ha/history", "[]"))
    except (ValueError, TypeError):
        history = []
    rv = meta.get("resourceVersion") or "0"
    try:
        rv_int = int(rv)
    except ValueError:
        rv_int = 0
    return Lease(
        meta=ObjectMeta(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            resource_version=rv_int,
        ),
        holder=spec.get("holderIdentity", "") or "",
        epoch=int(spec.get("leaseTransitions", 0) or 0),
        acquired_at=float(spec.get("acquireTime", 0.0) or 0.0),
        renewed_at=float(spec.get("renewTime", 0.0) or 0.0),
        duration_seconds=float(spec.get("leaseDurationSeconds", 15.0) or 15.0),
        history=history if isinstance(history, list) else [],
    )


@guarded_by("_lock", "_last_renewal", "_held")
class LeaderElector:
    """Drives one replica's acquire/renew/step-down over the lease.

    ``step()`` is one round: create-or-read the lease, renew if ours,
    acquire if free/expired, observe the epoch otherwise.  All writes
    go through the API server's CAS, so two electors stepping
    concurrently resolve to exactly one holder per epoch.
    """

    def __init__(
        self,
        api,
        identity: str,
        fence,
        namespace: str = "default",
        name: str = "tpu-gang-scheduler",
        duration_seconds: float = 15.0,
        on_elected: Optional[Callable[[int], None]] = None,
        on_deposed: Optional[Callable[[int], None]] = None,
    ):
        self._api = api
        self.identity = identity
        self.fence = fence
        self._namespace = namespace
        self._name = name
        self._duration = duration_seconds
        self.on_elected = on_elected
        self.on_deposed = on_deposed
        self._lock = threading.Lock()
        self._last_renewal = float("-inf")
        self._held = False

    # -- lease access --------------------------------------------------------

    def peek(self) -> Optional[Lease]:
        """Read the lease without mutating (the fence's read-through)."""
        try:
            lease = self._api.get(Lease.KIND, self._namespace, self._name)
        except NotFoundError:
            return None
        except Exception:
            logger.exception("ha: lease read failed")
            return None
        return lease if isinstance(lease, Lease) else None

    def is_leader(self) -> bool:
        """Held, not deposed, and the lease TTL has not lapsed since our
        last successful renewal — a partitioned leader stops claiming
        leadership (and readiness) once its own lease could have been
        taken, even before it observes the taker."""
        with self._lock:
            held, last = self._held, self._last_renewal
        return (
            held
            and not self.fence.deposed()
            and timesource.now() - last <= self._duration
        )

    # -- the election round --------------------------------------------------

    def step(self) -> bool:
        now = timesource.now()
        lease = self.peek()
        if lease is None:
            return self._try_create(now)
        if lease.holder == self.identity and lease.epoch == self.fence.epoch():
            return self._try_renew(lease, now)
        # someone else's lease (or our own from a previous incarnation):
        # observe its epoch, acquire if expired
        deposed = self.fence.observe(lease.epoch)
        if deposed and self._was_leader():
            self._mark_follower()
            if self.on_deposed is not None:
                self.on_deposed(lease.epoch)
        if lease.expired(now):
            return self._try_acquire(lease, now)
        return False

    def step_down(self) -> None:
        """Voluntary handoff: expire our lease immediately so a standby
        acquires on its next step without waiting out the TTL."""
        lease = self.peek()
        if lease is None or lease.holder != self.identity:
            return
        lease = lease.deepcopy()
        lease.renewed_at = timesource.now() - lease.duration_seconds - 1.0
        try:
            self._api.update(lease)
        except (ConflictError, NotFoundError):
            pass
        self._mark_follower()

    # -- internals -----------------------------------------------------------

    def _was_leader(self) -> bool:
        with self._lock:
            return self._held

    def _mark_follower(self) -> None:
        with self._lock:
            self._held = False
            self._last_renewal = float("-inf")

    def _mark_leader(self, now: float) -> None:
        with self._lock:
            self._held = True
            self._last_renewal = now

    def _try_create(self, now: float) -> bool:
        lease = Lease(
            meta=ObjectMeta(name=self._name, namespace=self._namespace),
            holder=self.identity,
            epoch=1,
            acquired_at=now,
            renewed_at=now,
            duration_seconds=self._duration,
            history=[[1, self.identity, now]],
        )
        try:
            self._api.create(lease)
        except AlreadyExistsError:
            return False  # lost the race; next step observes the winner
        except Exception:
            logger.exception("ha: lease create failed")
            return False
        return self._won(1, now)

    def _try_renew(self, lease: Lease, now: float) -> bool:
        lease = lease.deepcopy()
        lease.renewed_at = now
        try:
            self._api.update(lease)
        except ConflictError:
            return False  # a rival CAS won; next step observes it
        except Exception:
            logger.exception("ha: lease renew failed")
            return self.is_leader()
        self._mark_leader(now)
        return True

    def _try_acquire(self, lease: Lease, now: float) -> bool:
        lease = lease.deepcopy()
        new_epoch = lease.epoch + 1
        lease.holder = self.identity
        lease.epoch = new_epoch
        lease.acquired_at = now
        lease.renewed_at = now
        lease.history.append([new_epoch, self.identity, now])
        del lease.history[:-HISTORY_LIMIT]
        try:
            self._api.update(lease)
        except ConflictError:
            return False
        except Exception:
            logger.exception("ha: lease acquire failed")
            return False
        return self._won(new_epoch, now)

    def _won(self, epoch: int, now: float) -> bool:
        self.fence.grant(epoch)
        self._mark_leader(now)
        if self.on_elected is not None:
            self.on_elected(epoch)
        return True
