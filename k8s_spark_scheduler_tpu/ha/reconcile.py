"""Full state reconciliation at leadership takeover.

A replica that wins the lease inherits whatever the deposed leader left
behind: unlanded write-back intents in the RR journal, half-committed
eviction plans in the evict journal, ResourceReservations that no
longer match pod reality (the predecessor crashed between binding and
write-back), and a delta-solve session whose warm basis describes the
OLD replica's view of the cluster.  :class:`Reconciler.run` repairs all
four, in dependency order, before the new leader serves its first
decision:

1. **journal replay** — RR intents recorded by the predecessor replay
   through the idempotent write path (create → AlreadyExists folds,
   delete → NotFound is success), evict intents finish their
   half-evicted gangs (pods deleted, reservation still present);
2. **CRD-vs-pod diff** — the extender's failover sync
   (scheduler/failover.py) rebuilds reservations for scheduled pods
   missing from every RR and garbage-collects demands of now-scheduled
   pods, run under the predicate lock so no Filter call observes the
   half-repaired state;
3. **solver reset** — the delta-solve session is invalidated (its warm
   basis is the predecessor's world) and a takeover delta is published
   on the ChangeFeed so every seq-caching consumer (capacity sampler,
   snapshot mirrors) re-verifies.

The report dict is served verbatim at ``/status/ha`` and summarized by
``tpu.ha.reconcile.*`` metrics.
"""

from __future__ import annotations

import logging
import time

from ..metrics import names as mnames

logger = logging.getLogger(__name__)


class Reconciler:
    """Bound to one Server; ``run(epoch)`` executes a full takeover
    reconciliation and returns the report."""

    def __init__(self, server, metrics=None):
        self._server = server
        self._metrics = metrics

    def run(self, epoch: int) -> dict:
        server = self._server
        t0 = time.perf_counter()
        report: dict = {"epoch": epoch}

        # 1a. RR write-back intents the predecessor journaled but never
        # landed.  recover_from_journal handles the cold-boot case
        # (store seeded by the lister); nudge_recovery(force) covers a
        # warm standby whose own breaker was open at takeover.
        replayed = 0
        try:
            replayed += server.resource_reservation_cache.recover_from_journal()
            replayed += server.resource_reservation_cache.nudge_recovery(force=True)
        except Exception:
            logger.exception("ha: reservation journal replay failed")
        report["journalReplays"] = replayed

        # 1b. evict intents: finish half-evicted gangs exactly once
        evictions = 0
        policy = getattr(server, "policy", None)
        if policy is not None:
            try:
                evictions = policy.recover()
            except Exception:
                logger.exception("ha: evict journal replay failed")
        report["evictionReplays"] = evictions

        # 2. diff reservations/demands against pod reality, under the
        # predicate lock so no concurrent Filter sees half-repaired
        # state (same discipline as the extender's idle reconcile)
        try:
            from ..scheduler.failover import (
                sync_resource_reservations_and_demands,
            )

            with server.extender._predicate_lock:
                sync_resource_reservations_and_demands(server.extender)
            report["crdDiffRan"] = True
        except Exception:
            logger.exception("ha: CRD-vs-pod reconciliation failed")
            report["crdDiffRan"] = False

        # 3. the warm solver basis and every seq-caching mirror
        # describe the predecessor's world: invalidate + publish a
        # takeover delta so they all re-verify
        delta_engine = getattr(server.extender, "delta_engine", None)
        if delta_engine is not None:
            try:
                delta_engine.invalidate()
            except Exception:
                logger.exception("ha: delta-solve invalidate failed")
        snapshot = getattr(server, "tensor_snapshot", None)
        if snapshot is not None:
            try:
                snapshot.feed.publish("ha-takeover")
            except Exception:
                logger.exception("ha: takeover feed publish failed")

        elapsed = time.perf_counter() - t0
        report["elapsedSeconds"] = round(elapsed, 6)
        repairs = replayed + evictions
        report["repairs"] = repairs
        if self._metrics is not None:
            self._metrics.histogram(mnames.HA_RECONCILE_TIME, elapsed)
            if repairs:
                self._metrics.counter(
                    mnames.HA_RECONCILE_REPAIRS, inc=float(repairs)
                )
        logger.info(
            "ha: takeover reconciliation at epoch %d: %d journal replays, "
            "%d eviction replays, %.1fms",
            epoch,
            replayed,
            evictions,
            elapsed * 1e3,
        )
        return report
