"""Embedded state-store server: the framework's L1 substrate.

Plays the role the Kubernetes API server plays for the reference
(SURVEY §2.10: informers in, rate-limited writes out).  It is a
thread-safe, resource-versioned object store with watch fan-out:

- every mutation bumps a global monotonically-increasing
  ``resourceVersion`` (like etcd's revision);
- updates require the caller's object to carry the current
  resourceVersion, else :class:`ConflictError` (optimistic concurrency,
  the contract the async write-back client's 409 path exercises);
- watchers receive (event_type, object) callbacks post-commit;
- namespaces can be marked terminating to reproduce the reference's
  create-refused path (async.go:88-91).

In production deployments the same interface can be backed by a real
k8s API server or etcd; tests and the single-process runtime use this
in-memory implementation (the reference's tests do the same with fake
clientsets, extendertest/extender_test_utils.go:70-72).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from ..types.objects import APIObject
from ..analysis.guarded import guarded_by
from .errors import (
    AlreadyExistsError,
    ConflictError,
    NamespaceTerminatingError,
    NotFoundError,
)

WatchHandler = Callable[[str, APIObject], None]

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@guarded_by("_lock", "_objects", "_uid_counts", "_owner_index", "_watchers", "_terminating_namespaces", "_crds")
class APIServer:
    """In-memory resource-versioned object store with watch fan-out."""

    def __init__(self):
        self._lock = threading.RLock()
        self._rv = 0
        # kind → {(namespace, name) → object}
        self._objects: Dict[str, Dict[Tuple[str, str], APIObject]] = defaultdict(dict)
        # uid → live-object count, maintained by create/delete: the
        # dangling-owner check used to rebuild a set over EVERY stored
        # object per create (O(cluster) on the async write-back threads —
        # ~4ms of stolen GIL per reservation at 10k nodes)
        self._uid_counts: Dict[str, int] = {}
        # owner uid → {(kind, ns, name)} of dependents: owner-reference
        # GC used to scan every stored object per delete (O(cluster) —
        # the app-finished flow deletes pods constantly)
        self._owner_index: Dict[str, set] = {}
        self._watchers: Dict[str, List[WatchHandler]] = defaultdict(list)
        self._terminating_namespaces: set[str] = set()
        # registered CRD kinds → established flag
        self._crds: Dict[str, dict] = {}
        # chaos hook (sim apiserver_outage / apiserver_latency faults):
        # fn(op, kind, namespace, name) -> Optional[Exception]; a returned
        # exception is raised BEFORE the mutation commits, exactly as a
        # real API server refusing/timing out a write
        self._write_fault = None

    def set_write_fault(self, fn) -> None:
        self._write_fault = fn

    def _check_write_fault(self, op: str, kind: str, namespace: str, name: str) -> None:
        fn = self._write_fault
        if fn is not None:
            err = fn(op, kind, namespace, name)
            if err is not None:
                raise err

    @property
    def resource_version(self) -> int:
        """The current global revision (etcd's header revision analog);
        list responses must carry this even when empty, so a watch
        resumed from a list never silently skips a truncated history."""
        with self._lock:
            return self._rv

    # -- namespace lifecycle ------------------------------------------------

    def mark_namespace_terminating(self, namespace: str) -> None:
        with self._lock:
            self._terminating_namespaces.add(namespace)

    # -- CRD registry (stands in for apiextensions) --------------------------

    def create_crd(self, name: str, spec: dict) -> None:
        with self._lock:
            if name in self._crds:
                raise AlreadyExistsError(f"crd {name} already exists")
            self._crds[name] = dict(spec, established=spec.get("established", True))

    def update_crd(self, name: str, spec: dict) -> None:
        with self._lock:
            if name not in self._crds:
                raise NotFoundError(f"crd {name} not found")
            established = self._crds[name].get("established", True)
            self._crds[name] = dict(spec, established=spec.get("established", established))

    def get_crd(self, name: str) -> Optional[dict]:
        with self._lock:
            crd = self._crds.get(name)
            return dict(crd) if crd is not None else None

    def delete_crd(self, name: str) -> None:
        with self._lock:
            self._crds.pop(name, None)

    def set_crd_established(self, name: str, established: bool) -> None:
        with self._lock:
            if name in self._crds:
                self._crds[name]["established"] = established

    def crd_established(self, name: str) -> bool:
        with self._lock:
            crd = self._crds.get(name)
            return bool(crd and crd.get("established"))

    # -- object CRUD ---------------------------------------------------------

    def create(self, obj: APIObject) -> APIObject:
        self._check_write_fault("create", obj.KIND, obj.namespace, obj.name)
        with self._lock:
            kind = obj.KIND
            key = (obj.namespace, obj.name)
            if obj.namespace in self._terminating_namespaces:
                raise NamespaceTerminatingError(obj.namespace)
            if key in self._objects[kind]:
                raise AlreadyExistsError(f"{kind} {key} already exists")
            stored = obj.deepcopy()
            stored.meta.ensure_identity()
            self._rv += 1
            stored.meta.resource_version = self._rv
            self._objects[kind][key] = stored
            if stored.meta.uid:
                self._uid_counts[stored.meta.uid] = (
                    self._uid_counts.get(stored.meta.uid, 0) + 1
                )
            self._index_owners(stored, kind, key, add=True)
            out = stored.deepcopy()
            dangling = self._has_dangling_owner(stored)
        self._notify(kind, ADDED, stored)
        if dangling:
            # an object created with owner references to an already-dead
            # owner: real k8s GC collects it shortly after; collecting it
            # immediately keeps state deterministic when an async
            # write-back create races the owner's deletion
            try:
                self._delete_impl(kind, key[0], key[1])
            except NotFoundError:
                pass
        return out

    def _has_dangling_owner(self, obj: APIObject) -> bool:
        if not obj.meta.owner_references:
            return False
        return any(
            ref.uid and ref.uid not in self._uid_counts
            for ref in obj.meta.owner_references
        )

    def _index_owners(self, obj: APIObject, kind: str, key, add: bool) -> None:
        entry = (kind, key[0], key[1])
        for ref in obj.meta.owner_references:
            if not ref.uid:
                continue
            if add:
                self._owner_index.setdefault(ref.uid, set()).add(entry)  # schedlint: disable=LK001 -- private helper, every caller holds _lock (see callers)
            else:
                deps = self._owner_index.get(ref.uid)
                if deps is not None:
                    deps.discard(entry)
                    if not deps:
                        del self._owner_index[ref.uid]  # schedlint: disable=LK001 -- private helper, every caller holds _lock (see callers)

    def update(self, obj: APIObject) -> APIObject:
        self._check_write_fault("update", obj.KIND, obj.namespace, obj.name)
        with self._lock:
            kind = obj.KIND
            key = (obj.namespace, obj.name)
            current = self._objects[kind].get(key)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            if obj.meta.resource_version != current.meta.resource_version:
                raise ConflictError(
                    f"{kind} {key}: resourceVersion mismatch "
                    f"(have {obj.meta.resource_version}, want {current.meta.resource_version})"
                )
            stored = obj.deepcopy()
            stored.meta.uid = current.meta.uid
            stored.meta.creation_timestamp = current.meta.creation_timestamp
            self._rv += 1
            stored.meta.resource_version = self._rv
            self._objects[kind][key] = stored
            # owner references may change across an update
            self._index_owners(current, kind, key, add=False)
            self._index_owners(stored, kind, key, add=True)
            out = stored.deepcopy()
        self._notify(kind, MODIFIED, stored)
        return out

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._check_write_fault("delete", kind, namespace, name)
        self._delete_impl(kind, namespace, name)

    def _delete_impl(self, kind: str, namespace: str, name: str) -> None:
        # server-side deletes (owner GC, dangling-owner collection) come
        # here directly: they model the API server's own machinery, which
        # a client-write fault (apiserver_outage) never interrupts
        with self._lock:
            key = (namespace, name)
            current = self._objects[kind].pop(key, None)
            if current is None:
                raise NotFoundError(f"{kind} {key} not found")
            if current.meta.uid:
                n = self._uid_counts.get(current.meta.uid, 0) - 1
                if n > 0:
                    self._uid_counts[current.meta.uid] = n
                else:
                    self._uid_counts.pop(current.meta.uid, None)
            self._index_owners(current, kind, key, add=False)
            # deletes advance the revision too (as in etcd) so the DELETED
            # event is strictly newer than any prior MODIFIED for this key
            self._rv += 1
            current.meta.resource_version = self._rv
        self._notify(kind, DELETED, current)
        self._garbage_collect_owned(current)

    def get(self, kind: str, namespace: str, name: str) -> APIObject:
        with self._lock:
            current = self._objects[kind].get((namespace, name))
            if current is None:
                raise NotFoundError(f"{kind} ({namespace}, {name}) not found")
            return current.deepcopy()

    def list(self, kind: str, namespace: Optional[str] = None) -> List[APIObject]:
        with self._lock:
            return [
                o.deepcopy()
                for (ns, _), o in self._objects[kind].items()
                if namespace is None or ns == namespace
            ]

    # -- watch ---------------------------------------------------------------

    def watch(self, kind: str, handler: WatchHandler, replay: bool = True) -> None:
        """Register a watch handler; replays existing objects as ADDED
        (list+watch semantics) unless replay=False."""
        with self._lock:
            self._watchers[kind].append(handler)
            snapshot = list(self._objects[kind].values()) if replay else []
        for obj in snapshot:
            handler(ADDED, obj.deepcopy())

    def _notify(self, kind: str, event: str, obj: APIObject) -> None:
        with self._lock:
            handlers = list(self._watchers[kind])
        for handler in handlers:
            handler(event, obj.deepcopy())

    def _garbage_collect_owned(self, owner: APIObject) -> None:
        """Owner-reference GC: deleting an owner cascades to dependents
        (the reference relies on k8s GC via ownerReferences,
        resourcereservations.go:515, demand.go:162-164).  Served from
        the owner index — the full-store scan per delete was O(cluster)
        and the app-finished flow deletes pods constantly."""
        owner_uid = owner.meta.uid
        if not owner_uid:
            return
        with self._lock:
            to_delete = list(self._owner_index.get(owner_uid, ()))
        for kind, ns, name in to_delete:
            try:
                self._delete_impl(kind, ns, name)
            except NotFoundError:
                pass
