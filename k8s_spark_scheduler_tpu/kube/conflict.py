"""Unified 409-Conflict discipline: get → refresh resourceVersion → retry.

Every optimistic-concurrency write in the scheduler resolves a 409 the
same way the reference does (async.go:111-120): re-read the object,
rebase the mutation on the server's resourceVersion, and retry.  Before
this module each write site hand-rolled that loop (the async
write-back's inline recursion, the unschedulable marker's swallow-all);
they now share :func:`run_with_conflict_retry`, which adds two things
the ad-hoc sites lacked:

- **capped full jitter** between attempts — the same curve as
  ``watch_backoff_delay`` (kube/restbackend.py), because N replicas'
  workers re-colliding on the same object need desynchronizing exactly
  like a watcher herd does;
- a ``tpu.kube.conflict.retry.count`` metric, so dashboards see
  conflict churn (a rising rate under multi-replica operation means
  two writers think they own a key — the fencing gate's job to stop).

Only :class:`~.errors.ConflictError` is handled here; every other
error propagates to the caller's taxonomy (NotFound, namespace
terminating, breaker accounting) unchanged.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, TypeVar

from .errors import ConflictError
from .restbackend import WATCH_BACKOFF_CAP_S  # noqa: F401  (same curve family)

logger = logging.getLogger(__name__)

T = TypeVar("T")

# conflicts resolve in milliseconds (one competing write), so the
# window starts small and caps low — but the *shape* (full jitter over
# a doubling window) is watch_backoff_delay's, for the same
# herd-desynchronization reason
CONFLICT_BACKOFF_INITIAL_S = 0.02
CONFLICT_BACKOFF_CAP_S = 1.0
DEFAULT_MAX_ATTEMPTS = 5


def conflict_backoff_delay(backoff: float, rng=random) -> float:
    """One full-jitter delay draw: uniform over [0, min(backoff, cap)]."""
    return rng.uniform(0.0, min(backoff, CONFLICT_BACKOFF_CAP_S))


def next_conflict_backoff(backoff: float) -> float:
    return min(backoff * 2, CONFLICT_BACKOFF_CAP_S)


def run_with_conflict_retry(
    attempt: Callable[[], T],
    refresh: Callable[[], bool],
    *,
    kind: str = "",
    metrics=None,
    max_attempts: int = DEFAULT_MAX_ATTEMPTS,
    rng=random,
    sleep: Callable[[float], None] = time.sleep,
) -> Optional[T]:
    """Run ``attempt()``; on 409, ``refresh()`` then retry with jitter.

    ``attempt`` performs the write and may abort by returning None (the
    object vanished locally).  ``refresh`` re-reads the server copy and
    rebases — returning False aborts the loop (the key is gone or no
    longer ours to write).  Exhausted attempts re-raise the last
    ConflictError so callers see the failure through their normal error
    taxonomy.
    """
    backoff = CONFLICT_BACKOFF_INITIAL_S
    for i in range(max_attempts):
        try:
            return attempt()
        except ConflictError:
            if metrics is not None:
                from ..metrics import names as mnames

                metrics.counter(mnames.KUBE_CONFLICT_RETRIES, {"kind": kind})
            if i == max_attempts - 1:
                raise
            if not refresh():
                return None
            if i > 0:
                # first retry is immediate (the rebase alone resolves
                # the single-competitor case); later ones jitter so
                # replica herds spread out
                sleep(conflict_backoff_delay(backoff, rng))  # schedlint: disable=TS002 -- conflict backoff is wall-clock by contract, like the watch reconnect's
                backoff = next_conflict_backoff(backoff)
    return None
