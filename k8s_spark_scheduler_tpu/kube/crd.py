"""CRD lifecycle (reference ``internal/crd/utils.go`` +
``lib/pkg/apis/.../crd_resource_reservation.go`` / ``crd_demand.go``).

CRD *definitions* here are metadata records in the embedded API server's
registry: group/versions/storage version/annotations/conversion
strategy.  ``ensure_resource_reservations_crd`` reproduces the
create-or-upgrade + wait-until-established flow (utils.go:32-151).
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Optional

from .apiserver import APIServer
from .errors import AlreadyExistsError

logger = logging.getLogger(__name__)

RESOURCE_RESERVATION_CRD_NAME = "resourcereservations.sparkscheduler.palantir.com"
DEMAND_CRD_NAME = "demands.scaler.palantir.com"

RR_GROUP = "sparkscheduler.palantir.com"
RR_PLURAL = "resourcereservations"
RR_SHORT_NAME = "rr"
# v1beta2 is storage/hub; v1beta1 is served for back-compat
# (crd_resource_reservation.go, conversion strategy webhook)
RR_VERSIONS = ({"name": "v1beta2", "served": True, "storage": True},
               {"name": "v1beta1", "served": True, "storage": False})

DEMAND_GROUP = "scaler.palantir.com"
DEMAND_VERSIONS = ({"name": "v1alpha2", "served": True, "storage": True},
                   {"name": "v1alpha1", "served": True, "storage": False})


def resource_reservation_crd_spec(
    annotations: Optional[Dict[str, str]] = None,
    conversion_webhook=None,
) -> dict:
    """conversion_webhook (config.ConversionWebhookConfig) fills the
    webhook clientConfig the apiserver dials for v1beta1↔v1beta2
    conversion — HTTPS-only, so the caBundle is mandatory there
    (ref conversionwebhook/resource_reservation.go:44-98)."""
    conversion: dict = {"strategy": "Webhook"}
    if conversion_webhook is not None:
        client_config: dict = {
            "service": {
                "namespace": conversion_webhook.service_namespace,
                "name": conversion_webhook.service_name,
                "port": conversion_webhook.service_port,
                "path": conversion_webhook.path,
            }
        }
        if conversion_webhook.ca_bundle_file:
            import base64

            with open(conversion_webhook.ca_bundle_file, "rb") as f:
                client_config["caBundle"] = base64.b64encode(f.read()).decode()
        conversion["webhook"] = {
            "clientConfig": client_config,
            "conversionReviewVersions": ["v1"],
        }
    return {
        "group": RR_GROUP,
        "plural": RR_PLURAL,
        "short_names": [RR_SHORT_NAME],
        "versions": [dict(v) for v in RR_VERSIONS],
        "annotations": dict(annotations or {}),
        "conversion": conversion,
        "established": True,
    }


def demand_crd_spec() -> dict:
    return {
        "group": DEMAND_GROUP,
        "plural": "demands",
        "versions": [dict(v) for v in DEMAND_VERSIONS],
        "annotations": {},
        "established": True,
    }


def _specs_equivalent(existing: dict, desired: dict, check_conversion: bool) -> bool:
    """utils.go's verifyCRD: compare versions + annotations subset, and
    — only when this process actually manages the webhook identity —
    the conversion stanza (a caBundle/service change must roll out).
    Without a configured webhook we must NOT force our bare
    {strategy: Webhook} over an existing CRD's valid clientConfig: a
    real apiserver rejects Webhook strategy without a webhook block."""
    if existing.get("versions") != desired.get("versions"):
        return False
    if check_conversion and existing.get("conversion") != desired.get("conversion"):
        return False
    existing_annotations = existing.get("annotations", {})
    return all(existing_annotations.get(k) == v for k, v in desired.get("annotations", {}).items())


def ensure_resource_reservations_crd(
    api: APIServer,
    annotations: Optional[Dict[str, str]] = None,
    timeout_seconds: float = 60.0,
    conversion_webhook=None,
) -> None:
    """utils.go:98-151: create or upgrade, then wait for Established."""
    desired = resource_reservation_crd_spec(annotations, conversion_webhook)
    existing = api.get_crd(RESOURCE_RESERVATION_CRD_NAME)
    if existing is None:
        try:
            api.create_crd(RESOURCE_RESERVATION_CRD_NAME, desired)
        except AlreadyExistsError:
            existing = api.get_crd(RESOURCE_RESERVATION_CRD_NAME)
    if existing is not None and not _specs_equivalent(
        existing, desired, check_conversion=conversion_webhook is not None
    ):
        logger.info("upgrading resource reservation CRD")
        api.update_crd(RESOURCE_RESERVATION_CRD_NAME, desired)

    deadline = time.monotonic() + timeout_seconds  # schedlint: disable=TS002 -- boot-time wait for CRD Established bounds real wall time, must not freeze with a virtual clock
    while time.monotonic() < deadline:  # schedlint: disable=TS002 -- same bounded boot wait as the deadline above
        if api.crd_established(RESOURCE_RESERVATION_CRD_NAME):
            return
        time.sleep(0.05)
    api.delete_crd(RESOURCE_RESERVATION_CRD_NAME)
    raise TimeoutError("resource reservation CRD did not become established")
