"""k8s-style API error model (status reasons the scheduler reacts to).

The reference distinguishes Conflict (409 → refresh resourceVersion and
retry inline, async.go:111-120), NotFound, AlreadyExists, and the
namespace-terminating Forbidden/NotFound shapes (async.go:160-163).
"""

from __future__ import annotations


class APIError(Exception):
    reason = "Unknown"

    def __init__(self, message: str = ""):
        super().__init__(message or self.reason)
        self.message = message or self.reason


class ConflictError(APIError):
    reason = "Conflict"


class NotFoundError(APIError):
    reason = "NotFound"


class AlreadyExistsError(APIError):
    reason = "AlreadyExists"


class ForbiddenError(APIError):
    reason = "Forbidden"


class NamespaceTerminatingError(ForbiddenError):
    """Create refused because the namespace is being deleted."""

    def __init__(self, namespace: str):
        super().__init__(
            f"unable to create new content in namespace {namespace} because it is being terminated"
        )
        self.namespace = namespace


def is_namespace_terminating(err: Exception) -> bool:
    """async.go:160-163."""
    if isinstance(err, NamespaceTerminatingError):
        return True
    if isinstance(err, ForbiddenError) and "because it is being terminated" in str(err):
        return True
    if isinstance(err, NotFoundError) and "namespaces" in str(err) and "not found" in str(err):
        return True
    return False


def is_conflict(err: Exception) -> bool:
    return isinstance(err, ConflictError)


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)
