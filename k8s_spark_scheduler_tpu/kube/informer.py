"""Shared informers + listers over the embedded API server.

The reference's read path is client-go shared informers (watch + 30s
resync, cmd/server.go:91-92); handlers get add/update/delete events and
listers serve label-selected reads from the informer's local store.  This
module reproduces that shape: an :class:`Informer` keeps a local mirror
fed by watch events and dispatches to registered handlers; a
:class:`Lister` reads the mirror.

Event delivery is synchronous with the mutation (the embedded server
commits before notifying), which is strictly *fresher* than client-go's
eventually-consistent delivery — any reconcile logic correct under the
reference's staleness is correct here.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..types.objects import APIObject
from .apiserver import ADDED, APIServer, DELETED, MODIFIED
from ..analysis import racecheck
from ..analysis.guarded import guarded_by

Handler = Callable[[APIObject], None]
UpdateHandler = Callable[[APIObject, APIObject], None]


@guarded_by("_lock", "_store", "_indexes", "_last_rv", "_selector_revs")
class Informer:
    """A shared informer for one kind."""

    # bound on remembered last-seen resourceVersions for departed objects
    # (guards against a late stale MODIFIED resurrecting a deleted object)
    _TOMBSTONE_LIMIT = 16384
    # bound on per-(label, value) selector revision stamps (unbounded-
    # value labels like spark-app-id would otherwise leak one entry per
    # application for the life of the process)
    _SELECTOR_REVS_LIMIT = 16384

    def __init__(self, api: APIServer, kind: str, index_labels: Tuple[str, ...] = ()):
        self._api = api
        self.kind = kind
        self._lock = threading.RLock()
        self._store: Dict[Tuple[str, str], APIObject] = {}
        # secondary indexes: label key → label value → set of store keys;
        # turns the reference's O(all pods) label-selector scans
        # (client-go listers re-filter on every call) into O(result)
        self._index_labels = tuple(index_labels)
        self._indexes: Dict[str, Dict[str, set]] = {k: {} for k in self._index_labels}
        # key → highest resourceVersion ever delivered; events are globally
        # ordered by rv at the server, so delivery races are filtered here
        self._last_rv: Dict[Tuple[str, str], int] = {}
        self._add_handlers: List[Handler] = []
        self._update_handlers: List[UpdateHandler] = []
        self._delete_handlers: List[Handler] = []
        self._synced = False
        # bumped on every applied event — consumers key derived-view
        # caches on it, directly or via selector_revision (client-go's
        # informer cache has no analog; our hot paths re-derive views
        # per request without it)
        self.revision = 0
        # finer-grained: per indexed (label key, value) revisions, so a
        # view over one label bucket (e.g. spark-role=driver) is not
        # invalidated by churn in other buckets (executor pod events).
        # Values are global-revision stamps (monotone even across the
        # bounded prune below); unindexed keys fall back to the global
        # revision so a consumer cache can never silently freeze.
        self._selector_revs: Dict[Tuple[str, str], int] = {}
        # floor returned for missing buckets: bumped to the global
        # revision whenever _selector_revs is pruned, so a cleared
        # bucket can never read a value a consumer might have cached
        # (0 would repeat across clears and freeze a stale view)
        self._selector_floor = 0

    def start(self) -> None:
        self._api.watch(self.kind, self._on_event)
        self._synced = True

    def has_synced(self) -> bool:
        return self._synced

    def _on_event(self, event: str, obj: APIObject) -> None:
        key = (obj.namespace, obj.name)
        with self._lock:
            racecheck.note_access(self, "_store")
            # drop out-of-order deliveries: the server's rv is a global
            # monotonic commit order, so a lower rv is a stale event
            rv = obj.meta.resource_version
            if rv <= self._last_rv.get(key, -1):
                return
            self._last_rv[key] = rv
            self.revision += 1
            if len(self._last_rv) > self._TOMBSTONE_LIMIT:
                # prune entries for objects we no longer mirror
                self._last_rv = {
                    k: v for k, v in self._last_rv.items() if k in self._store
                }
            old = self._store.get(key)
            if event == DELETED:
                self._store.pop(key, None)
            else:
                self._store[key] = obj
            for label_key, index in self._indexes.items():
                if old is not None:
                    old_value = old.labels.get(label_key)
                    if old_value is not None:
                        bucket = index.get(old_value)
                        if bucket is not None:
                            bucket.discard(key)
                            if not bucket:
                                del index[old_value]
                if event != DELETED:
                    value = obj.labels.get(label_key)
                    if value is not None:
                        index.setdefault(value, set()).add(key)
                touched = set()
                if old is not None and old.labels.get(label_key) is not None:
                    touched.add(old.labels[label_key])
                if event != DELETED and obj.labels.get(label_key) is not None:
                    touched.add(obj.labels[label_key])
                for v in touched:
                    # stamp with the global revision: monotone and
                    # collision-free even after a prune (a pruned bucket
                    # reads 0, then restarts above any stamp a consumer
                    # could have cached)
                    self._selector_revs[(label_key, v)] = self.revision
                if len(self._selector_revs) > self._SELECTOR_REVS_LIMIT:
                    # unbounded-value labels (spark-app-id) would leak an
                    # entry per app forever; a full clear is safe because
                    # the floor rises to the current revision — strictly
                    # above every stamp a consumer could have cached
                    self._selector_revs.clear()
                    self._selector_floor = self.revision
            add_handlers = list(self._add_handlers)
            update_handlers = list(self._update_handlers)
            delete_handlers = list(self._delete_handlers)
        if event == ADDED:
            for h in add_handlers:
                h(obj)
        elif event == MODIFIED:
            for h in update_handlers:
                h(old, obj)
            if old is None:  # replayed as modify before sync: treat as add
                for h in add_handlers:
                    h(obj)
        elif event == DELETED:
            for h in delete_handlers:
                h(obj)

    def add_event_handler(
        self,
        on_add: Optional[Handler] = None,
        on_update: Optional[UpdateHandler] = None,
        on_delete: Optional[Handler] = None,
        filter_func: Optional[Callable[[APIObject], bool]] = None,
    ) -> None:
        """client-go FilteringResourceEventHandler equivalent."""

        def wrap_add(obj):
            if on_add and (filter_func is None or filter_func(obj)):
                on_add(obj)

        def wrap_update(old, new):
            if on_update and (filter_func is None or filter_func(new)):
                on_update(old, new)

        def wrap_delete(obj):
            if on_delete and (filter_func is None or filter_func(obj)):
                on_delete(obj)

        with self._lock:
            if on_add:
                self._add_handlers.append(wrap_add)
            if on_update:
                self._update_handlers.append(wrap_update)
            if on_delete:
                self._delete_handlers.append(wrap_delete)
            snapshot = list(self._store.values()) if on_add else []
        # client-go semantics: a late-registered handler receives synthetic
        # ADD events for everything already in the store, so components
        # wired after the informer started (overhead computer, stores) see
        # pre-existing objects
        for obj in snapshot:
            wrap_add(obj)

    # -- lister interface ----------------------------------------------------

    def selector_revision(self, label_key: str, value: str) -> int:
        """Revision of one indexed label bucket: changes only when an
        event touched an object carrying (label_key, value).  For a key
        the informer does NOT index, falls back to the global revision —
        coarser invalidation, but a derived-view cache can never freeze
        on a permanently-stale bucket."""
        with self._lock:
            if label_key not in self._indexes:
                return self.revision
            return self._selector_revs.get((label_key, value), self._selector_floor)

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[APIObject]:
        with self._lock:
            # serve from a secondary index when one covers the selector
            candidates = None
            if label_selector:
                for k, v in label_selector.items():
                    if k in self._indexes:
                        keys = self._indexes[k].get(v, set())
                        candidates = [self._store[key] for key in keys if key in self._store]
                        break
            pool = candidates if candidates is not None else self._store.values()
            out = []
            for obj in pool:
                if namespace is not None and obj.namespace != namespace:
                    continue
                if label_selector and any(
                    obj.labels.get(k) != v for k, v in label_selector.items()
                ):
                    continue
                out.append(obj)
            return out

    def get(self, namespace: str, name: str) -> Optional[APIObject]:
        with self._lock:
            return self._store.get((namespace, name))

    def list_with_predicate(self, predicate: Callable[[APIObject], bool]) -> List[APIObject]:
        """utils.ListWithPredicate (internal/common/utils/pods.go:110-128)."""
        with self._lock:
            return [o for o in self._store.values() if predicate(o)]


@guarded_by("_lock", "_informers")
class InformerFactory:
    """Shared-informer factory: one informer per kind."""

    def __init__(self, api: APIServer):
        self._api = api
        self._informers: Dict[str, Informer] = {}
        self._lock = threading.Lock()

    def informer(self, kind: str, index_labels: Tuple[str, ...] = ()) -> Informer:
        with self._lock:
            inf = self._informers.get(kind)
            if inf is None:
                inf = Informer(self._api, kind, index_labels=index_labels)
                self._informers[kind] = inf
            elif index_labels and set(index_labels) - set(inf._index_labels):
                raise ValueError(
                    f"informer for {kind} already created without indexes "
                    f"{set(index_labels) - set(inf._index_labels)}; create the "
                    "indexed informer first"
                )
            return inf

    def start(self) -> None:
        with self._lock:
            informers = list(self._informers.values())
        for inf in informers:
            if not inf.has_synced():
                inf.start()

    def wait_for_cache_sync(self) -> bool:
        return all(inf.has_synced() for inf in self._informers.values())
