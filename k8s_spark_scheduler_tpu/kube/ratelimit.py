"""Client-side rate limiting (reference cmd/clients.go:53-54: the kube
clientsets are built with configured QPS + Burst).

A token bucket: capacity=burst, refill=qps tokens/sec; acquire() blocks
until a token is available — or, with a timeout, only until the caller's
budget runs out, so a rate-limited write can respect the request
deadline propagated by the resilience layer instead of blocking a
worker (or the request path) indefinitely.  qps<=0 disables limiting
(the reference leaves the client defaults; we treat unset as unlimited).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from .errors import APIError
from ..analysis.guarded import guarded_by


class RateLimitTimeoutError(APIError):
    """Gave up waiting for a rate-limit token (deadline/timeout).  A
    retriable client-side condition — nothing reached the server."""

    reason = "RateLimitTimeout"


@guarded_by("_lock", "_tokens", "_last")
class TokenBucket:
    def __init__(self, qps: float, burst: int):
        self.qps = qps
        self.burst = max(burst, 1)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self, timeout: Optional[float] = None) -> bool:
        """Take one token.  Blocks until available; with ``timeout``
        (seconds) gives up and returns False once waiting any longer
        would exceed it.  ``timeout <= 0`` means no budget left: only an
        immediately-available token succeeds."""
        if self.qps <= 0:
            return True
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return True
                wait = (1.0 - self._tokens) / self.qps
            if deadline is not None and time.monotonic() + wait > deadline:
                return False
            time.sleep(wait)


def acquire_within_deadline(bucket: TokenBucket) -> None:
    """Take one token, waiting at most the propagated request deadline
    (resilience/deadline.py) when one is bound.  Raises
    :class:`RateLimitTimeoutError` — retriable, nothing was sent — when
    the wait cannot fit, instead of blocking past the caller's timeout."""
    from ..resilience import deadline as req_deadline

    remaining = req_deadline.remaining()
    if not bucket.acquire(timeout=remaining):
        raise RateLimitTimeoutError(
            f"rate-limit token wait exceeds the request deadline "
            f"({remaining:.3f}s remaining)"
        )


class RateLimitedClient:
    """Wraps a TypedClient-shaped client with a shared token bucket;
    token waits are deadline-bounded (see acquire_within_deadline)."""

    def __init__(self, delegate, bucket: TokenBucket):
        self._delegate = delegate
        self._bucket = bucket

    def _acquire(self) -> None:
        acquire_within_deadline(self._bucket)

    def create(self, obj):
        self._acquire()
        return self._delegate.create(obj)

    def update(self, obj):
        self._acquire()
        return self._delegate.update(obj)

    def delete(self, namespace: str, name: str):
        self._acquire()
        return self._delegate.delete(namespace, name)

    def get(self, namespace: str, name: str):
        self._acquire()
        return self._delegate.get(namespace, name)
