"""Client-side rate limiting (reference cmd/clients.go:53-54: the kube
clientsets are built with configured QPS + Burst).

A token bucket: capacity=burst, refill=qps tokens/sec; acquire() blocks
until a token is available.  qps<=0 disables limiting (the reference
leaves the client defaults; we treat unset as unlimited).
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    def __init__(self, qps: float, burst: int):
        self.qps = qps
        self.burst = max(burst, 1)
        self._tokens = float(self.burst)
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def acquire(self) -> None:
        if self.qps <= 0:
            return
        while True:
            with self._lock:
                now = time.monotonic()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.qps
                )
                self._last = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return
                wait = (1.0 - self._tokens) / self.qps
            time.sleep(wait)


class RateLimitedClient:
    """Wraps a TypedClient-shaped client with a shared token bucket."""

    def __init__(self, delegate, bucket: TokenBucket):
        self._delegate = delegate
        self._bucket = bucket

    def create(self, obj):
        self._bucket.acquire()
        return self._delegate.create(obj)

    def update(self, obj):
        self._bucket.acquire()
        return self._delegate.update(obj)

    def delete(self, namespace: str, name: str):
        self._bucket.acquire()
        return self._delegate.delete(namespace, name)

    def get(self, namespace: str, name: str):
        self._bucket.acquire()
        return self._delegate.get(namespace, name)
