"""Real-cluster backend: the embedded APIServer interface served by a
Kubernetes API server over REST.

The rest of the framework (informers, write-back caches, CRD ensure,
the unschedulable marker) is written against the embedded
``kube/apiserver.py`` interface; this class implements that same
interface with client-go-equivalent behavior (SURVEY §2.10 L1):

- **reads**: per-kind list+watch loops on background threads feeding
  the registered handlers — bookmarks keep the resourceVersion fresh,
  HTTP 410 triggers a relist, stream drops reconnect with backoff
  (the reflector loop of ``cmd/server.go:91-127``);
- **writes**: plain REST with the k8s Status error taxonomy mapped to
  ``kube/errors.py`` so the async write-back's 409/terminating-namespace
  handling (``state/cache.py``, ref ``async.go:88-96,111-123``) works
  unchanged;
- **CRDs**: apiextensions/v1 objects translated to/from the embedded
  registry's spec-dict form, with Established read from status
  conditions (``internal/crd/utils.go:32-151``).

Watch event objects convert through ``types/serde.py``; unknown kinds
raise early rather than silently serving nothing.
"""

from __future__ import annotations

import logging
import random
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..types import serde
from ..types.objects import APIObject, Demand, Node, Pod, ResourceReservation
from .apiserver import ADDED, DELETED, MODIFIED
from .errors import NotFoundError
from .restclient import ClusterConfig, GoneError, RestClient
from ..analysis.guarded import guarded_by

logger = logging.getLogger(__name__)

WatchHandler = Callable[[str, APIObject], None]

CRD_BASE = "/apis/apiextensions.k8s.io/v1/customresourcedefinitions"

# watch-reconnect backoff: full jitter over an exponentially-growing
# window, capped.  Full jitter (AWS architecture blog shape) desynchronizes
# a fleet of watchers hammering a recovering API server; both error paths
# (stream drop AND relist failure) MUST draw from the same distribution —
# a jitterless path re-synchronizes the herd on exactly the retries that
# matter most.
WATCH_BACKOFF_INITIAL_S = 0.2
WATCH_BACKOFF_CAP_S = 30.0


def watch_backoff_delay(backoff: float, rng=random) -> float:
    """One full-jitter delay draw: uniform over [0, min(backoff, cap)]."""
    return rng.uniform(0.0, min(backoff, WATCH_BACKOFF_CAP_S))


def next_watch_backoff(backoff: float) -> float:
    """The window for the NEXT retry: doubled, capped."""
    return min(backoff * 2, WATCH_BACKOFF_CAP_S)


@dataclass
class _Resource:
    kind: str
    base: str  # e.g. /api/v1 or /apis/<group>/<version>
    plural: str
    namespaced: bool
    to_wire: Callable[[APIObject], dict]
    from_wire: Callable[[dict], APIObject]

    def path(self, namespace: Optional[str] = None, name: Optional[str] = None) -> str:
        p = self.base
        if self.namespaced and namespace is not None:
            p += f"/namespaces/{namespace}"
        p += f"/{self.plural}"
        if name is not None:
            p += f"/{name}"
        return p


def _pod_to_wire(pod: Pod) -> dict:
    d = serde.pod_to_dict(pod)
    d["apiVersion"] = "v1"
    d["kind"] = "Pod"
    return d


_RESOURCES: Dict[str, _Resource] = {
    Pod.KIND: _Resource(
        Pod.KIND, "/api/v1", "pods", True, _pod_to_wire, serde.pod_from_dict
    ),
    Node.KIND: _Resource(
        Node.KIND, "/api/v1", "nodes", False, serde.node_to_dict, serde.node_from_dict
    ),
    ResourceReservation.KIND: _Resource(
        ResourceReservation.KIND,
        "/apis/sparkscheduler.palantir.com/v1beta2",
        "resourcereservations",
        True,
        serde.rr_to_dict_v1beta2,
        serde.rr_from_dict_v1beta2,
    ),
    Demand.KIND: _Resource(
        Demand.KIND,
        "/apis/scaler.palantir.com/v1alpha2",
        "demands",
        True,
        serde.demand_to_dict_v1alpha2,
        serde.demand_from_dict_v1alpha2,
    ),
}


def _register_lease_resource() -> None:
    # deferred: ha/lease.py imports kube/errors, keep this module's
    # import graph acyclic by registering the Lease mapping lazily on
    # first module load of either side
    from ..ha.lease import Lease, lease_from_wire, lease_to_wire

    _RESOURCES.setdefault(
        Lease.KIND,
        _Resource(
            Lease.KIND,
            "/apis/coordination.k8s.io/v1",
            "leases",
            True,
            lease_to_wire,
            lease_from_wire,
        ),
    )


_register_lease_resource()


def _k8s_wire(obj_dict: dict) -> dict:
    """Adapt the embedded wire form to real k8s wire shape — the ONE
    place float timestamps become RFC3339 (metadata timestamps and pod
    condition transition times; metav1.Time rejects JSON numbers), and
    server-assigned identity fields are stripped when empty."""
    meta = obj_dict.get("metadata") or {}
    for key in ("creationTimestamp", "deletionTimestamp"):
        v = meta.get(key)
        if isinstance(v, (int, float)):
            if v:
                meta[key] = serde.ts_to_rfc3339(float(v))
            else:
                meta.pop(key, None)
    for cond in (obj_dict.get("status") or {}).get("conditions") or []:
        t = cond.get("lastTransitionTime")
        if isinstance(t, (int, float)):
            if t:
                cond["lastTransitionTime"] = serde.ts_to_rfc3339(float(t))
            else:
                cond.pop("lastTransitionTime", None)
    if not meta.get("resourceVersion") or meta.get("resourceVersion") == "0":
        meta.pop("resourceVersion", None)
    if not meta.get("uid"):
        meta.pop("uid", None)
    return obj_dict


# resource_version is deliberately NOT declared: it is confined to
# the reflector thread (primed before the thread starts)
@guarded_by("lock", "handlers", "mirror")
class _KindWatch:
    """One reflector: list → replay → stream, shared by all handlers of
    a kind."""

    def __init__(self, backend: "RestAPIServer", resource: _Resource):
        self.backend = backend
        self.resource = resource
        self.handlers: List[WatchHandler] = []
        self.lock = threading.Lock()
        self.stop_event = threading.Event()
        self.thread: Optional[threading.Thread] = None
        # local mirror so late handlers can replay without a relist
        self.mirror: Dict[tuple, APIObject] = {}
        self.resource_version = "0"

    def add_handler(self, handler: WatchHandler, replay: bool) -> None:
        with self.lock:
            started = self.thread is not None
            if started:
                snapshot = list(self.mirror.values()) if replay else []
                self.handlers.append(handler)
        if started:
            for obj in snapshot:
                handler(ADDED, obj.deepcopy())
            return
        # first handler: synchronous list (so callers observe list+watch
        # semantics like the embedded server), then start the stream
        items = self._list_and_prime()
        with self.lock:
            self.handlers.append(handler)
        if replay:
            for obj in items:
                handler(ADDED, obj.deepcopy())
        self.thread = threading.Thread(
            target=self._run, name=f"watch-{self.resource.kind}", daemon=True
        )
        self.thread.start()

    def _list_and_prime(self) -> List[APIObject]:
        data = self.backend.client.request("GET", self.resource.path())
        self.resource_version = (data.get("metadata") or {}).get(
            "resourceVersion", "0"
        )
        items = [self.resource.from_wire(item) for item in data.get("items") or []]
        with self.lock:
            self.mirror = {(o.namespace, o.name): o for o in items}
        return items

    def _dispatch(self, event: str, obj: APIObject) -> None:
        with self.lock:
            key = (obj.namespace, obj.name)
            if event == DELETED:
                self.mirror.pop(key, None)
            else:
                self.mirror[key] = obj
            handlers = list(self.handlers)
        for handler in handlers:
            try:
                handler(event, obj.deepcopy())
            except Exception:
                logger.exception("watch handler failed for %s", self.resource.kind)

    def _run(self) -> None:
        backoff = WATCH_BACKOFF_INITIAL_S
        while not self.stop_event.is_set():
            try:
                for etype, wire in self.backend.client.watch(
                    self.resource.path(),
                    self.resource_version,
                    stop=self.stop_event,
                ):
                    backoff = WATCH_BACKOFF_INITIAL_S
                    if etype == "BOOKMARK":
                        rv = (wire.get("metadata") or {}).get("resourceVersion")
                        if rv:
                            self.resource_version = rv
                        continue
                    obj = self.resource.from_wire(wire)
                    self.resource_version = str(obj.meta.resource_version)
                    self._dispatch(etype, obj)
                # clean stream end (server-side timeout): resume from the
                # last seen rv
            except GoneError:
                # 410: our rv fell out of the server's watch window —
                # relist and synthesize events against the mirror
                # (client-go's reflector + DeltaFIFO Replace equivalent)
                try:
                    self._relist_and_diff()
                except Exception:
                    logger.exception("relist after 410 failed; backing off")
                    self.stop_event.wait(watch_backoff_delay(backoff))
                    backoff = next_watch_backoff(backoff)
            except Exception:
                if self.stop_event.is_set():
                    return
                logger.exception(
                    "watch stream for %s dropped; reconnecting", self.resource.kind
                )
                self.stop_event.wait(watch_backoff_delay(backoff))
                backoff = next_watch_backoff(backoff)

    def _relist_and_diff(self) -> None:
        with self.lock:
            before = dict(self.mirror)
        data = self.backend.client.request("GET", self.resource.path())
        self.resource_version = (data.get("metadata") or {}).get("resourceVersion", "0")
        items = [self.resource.from_wire(item) for item in data.get("items") or []]
        after = {(o.namespace, o.name): o for o in items}
        for key, obj in after.items():
            old = before.get(key)
            if old is None:
                self._dispatch(ADDED, obj)
            elif old.meta.resource_version != obj.meta.resource_version:
                self._dispatch(MODIFIED, obj)
        for key, obj in before.items():
            if key not in after:
                self._dispatch(DELETED, obj)

    def stop(self) -> None:
        self.stop_event.set()


@guarded_by("_lock", "_watches")
class RestAPIServer:
    """APIServer-interface adapter over a real Kubernetes API server."""

    def __init__(self, config: ClusterConfig):
        self.client = RestClient(config)
        self._watches: Dict[str, _KindWatch] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _resource(kind: str) -> _Resource:
        res = _RESOURCES.get(kind)
        if res is None:
            raise ValueError(f"kind {kind!r} has no REST mapping")
        return res

    # -- object CRUD (apiserver.py signatures) -------------------------------

    def create(self, obj: APIObject) -> APIObject:
        res = self._resource(obj.KIND)
        wire = _k8s_wire(res.to_wire(obj))
        out = self.client.request(
            "POST", res.path(obj.namespace if res.namespaced else None), body=wire
        )
        return res.from_wire(out)

    def update(self, obj: APIObject) -> APIObject:
        res = self._resource(obj.KIND)
        wire = _k8s_wire(res.to_wire(obj))
        # updates MUST carry the caller's resourceVersion for optimistic
        # concurrency (the 409 path state/cache.py resolves inline)
        wire.setdefault("metadata", {})["resourceVersion"] = str(
            obj.meta.resource_version
        )
        path = res.path(obj.namespace if res.namespaced else None, obj.name)
        # the scheduler's only Pod mutation is the unschedulable marker's
        # condition write (unschedulablepods.go:168-180) — pod status is
        # a subresource on a real apiserver, a spec-path PUT would
        # silently drop it
        if obj.KIND == Pod.KIND:
            path += "/status"
        out = self.client.request("PUT", path, body=wire)
        return res.from_wire(out)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        res = self._resource(kind)
        self.client.request(
            "DELETE", res.path(namespace if res.namespaced else None, name)
        )

    def get(self, kind: str, namespace: str, name: str) -> APIObject:
        res = self._resource(kind)
        out = self.client.request(
            "GET", res.path(namespace if res.namespaced else None, name)
        )
        return res.from_wire(out)

    def list(self, kind: str, namespace: Optional[str] = None) -> List[APIObject]:
        res = self._resource(kind)
        out = self.client.request(
            "GET", res.path(namespace if res.namespaced else None)
        )
        return [res.from_wire(item) for item in out.get("items") or []]

    # -- watch ---------------------------------------------------------------

    def watch(self, kind: str, handler: WatchHandler, replay: bool = True) -> None:
        res = self._resource(kind)
        with self._lock:
            kw = self._watches.get(kind)
            if kw is None:
                kw = _KindWatch(self, res)
                self._watches[kind] = kw
        kw.add_handler(handler, replay)

    def stop(self) -> None:
        with self._lock:
            watches = list(self._watches.values())
        for kw in watches:
            kw.stop()

    # alias used by server shutdown paths
    close = stop

    # -- CRD registry (apiextensions/v1) -------------------------------------

    @staticmethod
    def _crd_to_wire(name: str, spec: dict) -> dict:
        group = spec.get("group", "")
        plural = spec.get("plural", name.split(".", 1)[0])
        wire: dict = {
            "apiVersion": "apiextensions.k8s.io/v1",
            "kind": "CustomResourceDefinition",
            "metadata": {"name": name, "annotations": dict(spec.get("annotations") or {})},
            "spec": {
                "group": group,
                "scope": "Namespaced",
                "names": {
                    "plural": plural,
                    "singular": plural.rstrip("s"),
                    "kind": spec.get("kind")
                    or plural.rstrip("s").title().replace("-", ""),
                    "shortNames": list(spec.get("short_names") or []),
                },
                "versions": [
                    {
                        "name": v["name"],
                        "served": bool(v.get("served", True)),
                        "storage": bool(v.get("storage", False)),
                        "schema": {
                            "openAPIV3Schema": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            }
                        },
                    }
                    for v in spec.get("versions") or []
                ],
            },
        }
        conversion = spec.get("conversion")
        if conversion:
            wire["spec"]["conversion"] = conversion
        return wire

    @staticmethod
    def _crd_from_wire(wire: dict) -> dict:
        spec = wire.get("spec") or {}
        names = spec.get("names") or {}
        conditions = (wire.get("status") or {}).get("conditions") or []
        established = any(
            c.get("type") == "Established" and c.get("status") == "True"
            for c in conditions
        )
        return {
            "group": spec.get("group", ""),
            "plural": names.get("plural", ""),
            "short_names": list(names.get("shortNames") or []),
            "versions": [
                {
                    "name": v.get("name"),
                    "served": bool(v.get("served")),
                    "storage": bool(v.get("storage")),
                }
                for v in spec.get("versions") or []
            ],
            "annotations": dict(
                (wire.get("metadata") or {}).get("annotations") or {}
            ),
            "conversion": spec.get("conversion"),
            "established": established,
        }

    def create_crd(self, name: str, spec: dict) -> None:
        self.client.request("POST", CRD_BASE, body=self._crd_to_wire(name, spec))

    def update_crd(self, name: str, spec: dict) -> None:
        # two replicas ensuring the CRD at boot race on this PUT; resolve
        # 409s through the shared conflict-retry discipline
        from .conflict import run_with_conflict_retry

        state = {"rv": ""}

        def refresh() -> bool:
            current = self.client.request("GET", f"{CRD_BASE}/{name}")
            state["rv"] = (current.get("metadata") or {}).get("resourceVersion", "")
            return True

        def attempt():
            wire = self._crd_to_wire(name, spec)
            wire["metadata"]["resourceVersion"] = state["rv"]
            return self.client.request("PUT", f"{CRD_BASE}/{name}", body=wire)

        refresh()
        run_with_conflict_retry(attempt, refresh, kind="CustomResourceDefinition")

    def get_crd(self, name: str) -> Optional[dict]:
        try:
            return self._crd_from_wire(self.client.request("GET", f"{CRD_BASE}/{name}"))
        except NotFoundError:
            return None

    def delete_crd(self, name: str) -> None:
        try:
            self.client.request("DELETE", f"{CRD_BASE}/{name}")
        except NotFoundError:
            pass

    def crd_established(self, name: str) -> bool:
        crd = self.get_crd(name)
        return bool(crd and crd.get("established"))
