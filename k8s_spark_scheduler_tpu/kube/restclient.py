"""Kubernetes REST client: kubeconfig/in-cluster auth, rate-limited
writes, k8s Status→error mapping, and streaming watches.

This is the real-cluster L1 substrate the reference builds with
client-go (``cmd/clients.go:30-76``: kubeconfig path or in-cluster
config, QPS/Burst rate limits applied to every clientset).  Stdlib-only:
``http.client`` over an ``ssl.SSLContext``; no external dependencies.

Error mapping follows the k8s ``metav1.Status`` contract the scheduler's
write-back layer reacts to (``state/cache.py``): HTTP 409 with reason
``AlreadyExists`` vs ``Conflict``, 404 ``NotFound``, 403 with the
namespace-terminating cause (``async.go:88-96,160-163``).
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import ssl
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Tuple
from urllib.parse import urlencode, urlsplit
from ..analysis.guarded import guarded_by

from .errors import (
    AlreadyExistsError,
    APIError,
    ConflictError,
    ForbiddenError,
    NamespaceTerminatingError,
    NotFoundError,
)
from .ratelimit import TokenBucket

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclass
class ClusterConfig:
    """Connection + auth material for one API server."""

    host: str  # e.g. https://10.0.0.1:6443
    ca_file: Optional[str] = None
    ca_data: Optional[bytes] = None  # PEM
    client_cert_file: Optional[str] = None
    client_key_file: Optional[str] = None
    bearer_token: Optional[str] = None
    # re-read periodically: bound service-account tokens rotate (~1h);
    # a static copy would 401 forever after expiry (client-go reloads
    # the projected token file the same way)
    bearer_token_file: Optional[str] = None
    insecure_skip_verify: bool = False
    # client-side write rate limits (clients.go:53-54)
    qps: float = 0.0
    burst: int = 0

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.host.startswith("https"):
            return None
        ctx = ssl.create_default_context()
        if self.insecure_skip_verify:
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
        elif self.ca_file:
            ctx.load_verify_locations(cafile=self.ca_file)
        elif self.ca_data:
            ctx.load_verify_locations(cadata=self.ca_data.decode())
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file)
        return ctx


def in_cluster_config(qps: float = 0.0, burst: int = 0) -> ClusterConfig:
    """Pod-mounted service account (the reference's rest.InClusterConfig
    leg, clients.go:37-44)."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise RuntimeError(
            "not running in-cluster: KUBERNETES_SERVICE_HOST is unset"
        )
    token_path = os.path.join(SERVICE_ACCOUNT_DIR, "token")
    ca_path = os.path.join(SERVICE_ACCOUNT_DIR, "ca.crt")
    with open(token_path) as f:
        f.read()  # fail fast when the mount is missing/unreadable
    return ClusterConfig(
        host=f"https://{host}:{port}",
        ca_file=ca_path if os.path.exists(ca_path) else None,
        bearer_token_file=token_path,
        qps=qps,
        burst=burst,
    )


def load_kubeconfig(
    path: Optional[str] = None,
    context: Optional[str] = None,
    qps: float = 0.0,
    burst: int = 0,
) -> ClusterConfig:
    """Parse a kubeconfig file (the reference's
    clientcmd.BuildConfigFromFlags leg, clients.go:38-43).  YAML needs
    the optional pyyaml extra; JSON kubeconfigs work without it."""
    path = path or os.environ.get("KUBECONFIG") or os.path.expanduser("~/.kube/config")
    with open(path) as f:
        raw = f.read()
    try:
        cfg = json.loads(raw)
    except json.JSONDecodeError:
        try:
            import yaml
        except ImportError as err:
            raise RuntimeError(
                f"kubeconfig {path} is YAML but pyyaml is not installed "
                "(pip install 'tpu-gang-scheduler[yaml]')"
            ) from err
        cfg = yaml.safe_load(raw)

    ctx_name = context or cfg.get("current-context")
    ctx = next(
        (c["context"] for c in cfg.get("contexts", []) if c.get("name") == ctx_name),
        None,
    )
    if ctx is None:
        raise RuntimeError(f"kubeconfig context {ctx_name!r} not found in {path}")
    cluster = next(
        (
            c["cluster"]
            for c in cfg.get("clusters", [])
            if c.get("name") == ctx.get("cluster")
        ),
        None,
    )
    user = next(
        (u["user"] for u in cfg.get("users", []) if u.get("name") == ctx.get("user")),
        {},
    )
    if cluster is None:
        raise RuntimeError(f"kubeconfig cluster {ctx.get('cluster')!r} not found")

    def _inline_or_file(data_key: str, file_key: str, source: dict) -> Optional[str]:
        """base64 inline data wins over file paths, matching client-go."""
        data = source.get(data_key)
        if data:
            f = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
            f.write(base64.b64decode(data))
            f.close()
            return f.name
        return source.get(file_key)

    return ClusterConfig(
        host=cluster.get("server", ""),
        ca_file=_inline_or_file("certificate-authority-data", "certificate-authority", cluster),
        insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
        client_cert_file=_inline_or_file("client-certificate-data", "client-certificate", user),
        client_key_file=_inline_or_file("client-key-data", "client-key", user),
        bearer_token=user.get("token"),
        bearer_token_file=user.get("tokenFile"),
        qps=qps,
        burst=burst,
    )


def _error_from_status(code: int, body: bytes) -> APIError:
    """metav1.Status → the error taxonomy state/cache.py handles."""
    try:
        status = json.loads(body.decode() or "{}")
    except json.JSONDecodeError:
        status = {}
    reason = status.get("reason", "")
    message = status.get("message", "") or f"HTTP {code}"
    if code == 404 or reason == "NotFound":
        return NotFoundError(message)
    if code == 409:
        if reason == "AlreadyExists":
            return AlreadyExistsError(message)
        return ConflictError(message)
    if code == 403:
        if "because it is being terminated" in message or reason == "NamespaceTerminating":
            ns = (status.get("details") or {}).get("name", "")
            return NamespaceTerminatingError(ns or message)
        return ForbiddenError(message)
    err = APIError(message)
    err.code = code
    return err


class GoneError(APIError):
    """HTTP 410: the watch resourceVersion is too old — relist."""

    reason = "Gone"


@guarded_by("_token_lock", "_token")
class RestClient:
    """Thin requester with per-host connection reuse and a write-side
    token bucket (QPS/Burst, ratelimit.py — reads are unthrottled, like
    client-go's default which throttles the whole clientset; we scope it
    to mutations where the scheduler's burst actually lands)."""

    def __init__(self, config: ClusterConfig):
        self.config = config
        split = urlsplit(config.host)
        self._netloc = split.netloc
        self._https = split.scheme == "https"
        self._ssl = config.ssl_context()
        self._bucket = (
            TokenBucket(config.qps, config.burst) if config.qps > 0 else None
        )
        self._local = threading.local()
        self._token_lock = threading.Lock()
        self._token: Optional[str] = config.bearer_token
        self._token_read_at = 0.0

    # -- connection handling -------------------------------------------------

    # a pooled connection idle past this is assumed dropped server-side
    # and is replaced BEFORE sending — mutations are never blind-retried
    # (a replayed POST that actually landed turns into AlreadyExists,
    # which the write-back cache would mis-handle as a permanent failure)
    _IDLE_RECONNECT_S = 30.0

    def _conn(self, fresh_for_write: bool = False) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        last_used = getattr(self._local, "conn_used_at", 0.0)
        if conn is not None and fresh_for_write and (
            time.monotonic() - last_used > self._IDLE_RECONNECT_S
        ):
            conn.close()
            conn = None
        if conn is None:
            conn = self._new_conn()
            self._local.conn = conn
        self._local.conn_used_at = time.monotonic()
        return conn

    def _new_conn(self) -> http.client.HTTPConnection:
        if self._https:
            return http.client.HTTPSConnection(
                self._netloc, context=self._ssl, timeout=30
            )
        return http.client.HTTPConnection(self._netloc, timeout=30)

    _TOKEN_REFRESH_S = 60.0

    def _bearer(self) -> Optional[str]:
        if not self.config.bearer_token_file:
            return self._token
        with self._token_lock:
            now = time.monotonic()
            if now - self._token_read_at >= self._TOKEN_REFRESH_S:
                try:
                    with open(self.config.bearer_token_file) as f:
                        self._token = f.read().strip()
                    self._token_read_at = now
                except OSError:
                    pass  # keep the last good token; retry next window
            return self._token

    def _headers(self) -> Dict[str, str]:
        headers = {"Accept": "application/json", "Content-Type": "application/json"}
        token = self._bearer()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers

    # -- request -------------------------------------------------------------

    def request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        params: Optional[Dict[str, str]] = None,
    ) -> dict:
        if params:
            path = f"{path}?{urlencode(params)}"
        mutating = method in ("POST", "PUT", "PATCH", "DELETE")
        if self._bucket is not None and mutating:
            # bound the token wait by the propagated request deadline:
            # a write that cannot be sent in time fails retriably
            # instead of blocking past the caller
            from .ratelimit import acquire_within_deadline

            acquire_within_deadline(self._bucket)
        payload = json.dumps(body).encode() if body is not None else None
        # GETs are idempotent: one silent retry on a stale keep-alive
        # conn.  Mutations get a pre-emptively fresh connection instead
        # of a retry — replaying a POST/PUT that may have landed would
        # corrupt write-back state (see _IDLE_RECONNECT_S).
        attempts = (0, 1) if not mutating else (0,)
        for attempt in attempts:
            conn = self._conn(fresh_for_write=mutating)
            try:
                conn.request(method, path, body=payload, headers=self._headers())
                resp = conn.getresponse()
                data = resp.read()
                break
            except (http.client.HTTPException, ConnectionError, OSError):
                self._local.conn = None
                if attempt == attempts[-1]:
                    raise
        if resp.status == 410:
            raise GoneError(data.decode(errors="replace")[:200])
        if resp.status >= 400:
            raise _error_from_status(resp.status, data)
        return json.loads(data.decode() or "{}")

    # -- watch ---------------------------------------------------------------

    def watch(
        self,
        path: str,
        resource_version: str,
        timeout_seconds: int = 300,
        stop: Optional[threading.Event] = None,
    ) -> Iterator[Tuple[str, dict]]:
        """Yield (event type, object dict) from a streaming watch.  Runs
        on a DEDICATED connection (never the pooled one — the stream
        holds it for minutes).  Raises GoneError on 410 so the caller
        relists (the reference relies on client-go's reflector doing the
        same, cmd/server.go:91-127)."""
        params = {
            "watch": "1",
            "resourceVersion": resource_version,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(timeout_seconds),
        }
        conn = self._new_conn()
        try:
            conn.timeout = timeout_seconds + 30
            conn.request(
                "GET", f"{path}?{urlencode(params)}", headers=self._headers()
            )
            resp = conn.getresponse()
            if resp.status == 410:
                raise GoneError("watch expired")
            if resp.status >= 400:
                raise _error_from_status(resp.status, resp.read())
            buf = b""
            while stop is None or not stop.is_set():
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    event = json.loads(line)
                    etype = event.get("type", "")
                    obj = event.get("object") or {}
                    if etype == "ERROR":
                        # metav1.Status in the stream: 410 shows up here
                        if obj.get("code") == 410 or obj.get("reason") == "Expired":
                            raise GoneError(obj.get("message", "watch expired"))
                        raise _error_from_status(int(obj.get("code") or 500), line)
                    yield etype, obj
        finally:
            conn.close()
