"""Trace lab: production-scale workload synthesis + policy-matrix
evaluation (ROADMAP item 4).

The lab is the judging apparatus for every other roadmap item: scale
refactors and policy changes land with trace-level before/after
evidence, not microbenchmarks.

- :mod:`.synth` — seeded synthesizer for production-shaped workloads
  (heavy-tailed gang sizes, diurnal arrival intensity, multi-tenant
  band/weight mixes) at 10^5–10^6 app arrivals, dumped as the same
  JSONL trace format ``sim/workload.py`` replays;
- :mod:`.spec` — declarative matrix experiment spec (ordering ×
  preemption × backfill × DRF weights × autoscaler lag × chaos)
  validated up front and expanded into named cells;
- :mod:`.engine` — the gang-level discrete-event replay engine: one
  isolated VirtualClock per cell, deterministic admission/backfill/
  preemption/fair-share dynamics over integer resource math, emitting
  the PR 16 scorecard schema per cell;
- :mod:`.runner` — parallel worker *processes* executing cells with a
  self-describing artifact directory per cell (scorecard.json +
  run_manifest.json), digest-verified cross-process;
- :mod:`.report` — folds per-cell scorecards into one matrix report
  (packing / wait / waste / fairness rankings, canonical digests,
  leaf-level cell diffs via ``lifecycle/scorecard.py``).

CLI: ``python -m k8s_spark_scheduler_tpu.lab {synth,run,report,diff}``.
"""

from .engine import CellResult, GangLabSim, run_cell
from .report import build_matrix_report, diff_cells
from .runner import run_matrix
from .spec import MatrixCell, MatrixSpec, SpecError
from .synth import SynthSpec, synthesize

__all__ = [
    "CellResult",
    "GangLabSim",
    "run_cell",
    "build_matrix_report",
    "diff_cells",
    "run_matrix",
    "MatrixCell",
    "MatrixSpec",
    "SpecError",
    "SynthSpec",
    "synthesize",
]
