"""Policy-lab CLI.

    python -m k8s_spark_scheduler_tpu.lab synth  --spec synth.json --out trace.jsonl
    python -m k8s_spark_scheduler_tpu.lab run    --spec matrix.json --out out/ --workers 4
    python -m k8s_spark_scheduler_tpu.lab report --matrix out/matrix.json
    python -m k8s_spark_scheduler_tpu.lab diff   --matrix out/matrix.json --cells A B

``synth`` generates a seed-reproducible production-shaped trace;
``run`` expands and executes the matrix (optionally across worker
processes, optionally cross-process digest-verified); ``report`` folds
cell scorecards into rankings; ``diff`` prints leaf-level scorecard
differences between two cells.  See docs/operations.md ("Running the
policy lab") for the full runbook.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ..sim.manifest import write_run_manifest
from ..sim.workload import dump_trace
from .report import build_matrix_report, diff_cells, render_report_text
from .runner import run_matrix
from .spec import MatrixSpec
from .synth import SynthSpec, synthesize


def _load_json(path: str):
    with open(path) as f:
        return json.load(f)


def _cmd_synth(args: argparse.Namespace) -> int:
    raw = _load_json(args.spec) if args.spec else {}
    if args.seed is not None:
        raw["seed"] = args.seed
    if args.arrivals is not None:
        raw["arrivals"] = args.arrivals
    spec = SynthSpec.from_dict(raw)
    apps = synthesize(spec)
    dump_trace(apps, args.out)
    print(f"wrote {len(apps)} apps -> {args.out} (seed={spec.seed})")
    return 0


def _cmd_run(args: argparse.Namespace) -> int:
    raw = _load_json(args.spec)
    if args.trace:
        raw["trace"] = args.trace
    spec = MatrixSpec.from_dict(raw)
    matrix = run_matrix(
        spec, workers=args.workers, out_dir=args.out, verify=args.verify
    )
    report = build_matrix_report(matrix)
    if args.out:
        with open(os.path.join(args.out, "report.json"), "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
        # refresh the manifest so report.json is hashed alongside
        # matrix.json (the manifest covers every sibling artifact)
        write_run_manifest(
            args.out,
            kind="lab-matrix",
            digests={
                "spec": matrix["specDigest"],
                "trace": matrix["traceDigest"],
                "report": report["digest"],
            },
            extra={"name": matrix["name"], "cells": [c["cell"] for c in matrix["cells"]]},
        )
    print(render_report_text(report))
    return 0


def _cmd_report(args: argparse.Namespace) -> int:
    matrix = _load_json(args.matrix)
    report = build_matrix_report(matrix)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        print(render_report_text(report))
    return 0


def _cmd_diff(args: argparse.Namespace) -> int:
    matrix = _load_json(args.matrix)
    cell_a, cell_b = args.cells
    diffs = diff_cells(matrix, cell_a, cell_b)
    if not diffs:
        print(f"{cell_a} and {cell_b} have identical scorecard bodies")
        return 0
    print(f"{len(diffs)} scorecard leaves differ ({cell_a} vs {cell_b}):")
    for path, a, b in diffs:
        print(f"  {path}: {a!r} -> {b!r}")
    return 1


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m k8s_spark_scheduler_tpu.lab",
        description="trace synthesis + policy-matrix evaluation lab",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("synth", help="generate a production-shaped trace")
    p.add_argument("--spec", help="synth spec JSON (defaults apply if omitted)")
    p.add_argument("--out", required=True, help="output trace JSONL path")
    p.add_argument("--seed", type=int, help="override spec seed")
    p.add_argument("--arrivals", type=int, help="override spec arrival count")
    p.set_defaults(func=_cmd_synth)

    p = sub.add_parser("run", help="execute a policy matrix")
    p.add_argument("--spec", required=True, help="matrix spec JSON")
    p.add_argument("--trace", help="override the spec's trace path")
    p.add_argument("--out", help="artifact directory (cells/, matrix.json, report.json)")
    p.add_argument("--workers", type=int, default=0, help="worker processes (0 = in-process)")
    p.add_argument(
        "--verify",
        type=int,
        default=0,
        help="re-run first N cells in-process and compare digests",
    )
    p.set_defaults(func=_cmd_run)

    p = sub.add_parser("report", help="fold a matrix document into rankings")
    p.add_argument("--matrix", required=True, help="matrix.json from a run")
    p.add_argument("--out", help="write report JSON here")
    p.add_argument("--json", action="store_true", help="print JSON instead of a table")
    p.set_defaults(func=_cmd_report)

    p = sub.add_parser("diff", help="leaf-diff two cells' scorecards")
    p.add_argument("--matrix", required=True, help="matrix.json from a run")
    p.add_argument("--cells", nargs=2, required=True, metavar=("A", "B"))
    p.set_defaults(func=_cmd_diff)

    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
