"""Gang-level discrete-event replay engine: one matrix cell = one run.

The full sim (``sim/runner.py``) drives the REAL extender stack and
costs milliseconds per decision — perfect for chaos fidelity, hopeless
for 10^5–10^6-arrival traces × a 24-cell policy matrix.  The lab engine
is the Firmament-style complement (OSDI '16): it models the cluster at
*gang* granularity — integer per-node free vectors, gang-atomic
admission, policy ordering, EASY backfill, Borg preemption, DRF fair
share, autoscaler fulfillment lag, leader-crash outage windows — on an
isolated :class:`~..sim.workload`-trace replay with its own
:class:`~..sim.clock.VirtualClock` per cell, so whole cluster lifetimes
replay in seconds and cells are embarrassingly parallel across worker
processes.

Determinism contract (the per-cell digest is the acceptance gate):

- single-threaded event loop; ties broken by (time, sequence);
- all resource math in exact integers (millicores / bytes);
- every float that enters the event digest or scorecard derives from
  trace values that the synthesizer already rounded — no libm in the
  replay path, so digests are byte-identical across processes and
  platforms;
- the scorecard is rendered by ``lifecycle/scorecard.py`` — the SAME
  schema (and digest algebra) a live server serves on ``GET /slo`` and
  the full sim writes as ``scorecard.json``.

Policy semantics (deliberately small, stated here so matrix deltas are
interpretable):

- ``fifo``: strict arrival order; head-of-line blocks the queue.
- ``priority-then-fifo``: highest band first, FIFO within a band.
- ``drf``: pick the queued tenant with the lowest weighted dominant
  share (NSDI '11), FIFO within the tenant.
- backfill (EASY, JSSPP '95): when the head cannot fit, reserve its
  start at the earliest instant running-gang completions free enough
  capacity; later gangs may jump ONLY if they fit now and either finish
  by that instant or fit inside the spare capacity it leaves.
- preemption (Borg): a blocked head may evict whole gangs of bands at
  least ``min_band_gap`` below it (lowest band, least work lost first,
  at most ``max_victims``) — victims requeue and their lost runtime is
  the eviction-waste metric.
- leader-crash chaos: an admission outage window — arrivals queue,
  completions land, nothing admits until the window clears.
"""

from __future__ import annotations

import hashlib
import json
import time
from bisect import insort
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..lifecycle.scorecard import build_scorecard, scorecard_digest
from ..lifecycle.slo import SloEngine
from ..sim.clock import VirtualClock
from ..sim.workload import AppSpec

# gang states
_QUEUED, _RUNNING, _DONE, _UNSCHEDULABLE = 0, 1, 2, 3

_DEFAULT_BANDS = {"low": 0, "normal": 1, "high": 2}


_CPU_CACHE: Dict[str, int] = {}
_MEM_CACHE: Dict[str, int] = {}
_MEM_SUFFIX = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4}


def _parse_cpu(text: str) -> int:
    """Kubernetes cpu quantity -> millicores (exact integers only).
    Memoized — traces draw from a tiny menu of size strings."""
    cached = _CPU_CACHE.get(text)
    if cached is not None:
        return cached
    s = str(text)
    value = int(s[:-1]) if s.endswith("m") else int(float(s) * 1000)
    _CPU_CACHE[text] = value
    return value


def _parse_mem(text: str) -> int:
    """Kubernetes memory quantity -> bytes.  Memoized."""
    cached = _MEM_CACHE.get(text)
    if cached is not None:
        return cached
    s = str(text)
    value = None
    for suffix, mult in _MEM_SUFFIX.items():
        if s.endswith(suffix):
            value = int(float(s[: -len(suffix)]) * mult)
            break
    if value is None:
        value = int(float(s))
    _MEM_CACHE[text] = value
    return value


class _Gang:
    __slots__ = (
        "app_id",
        "arrival",
        "submit_t",
        "lifetime",
        "band",
        "band_rank",
        "tenant",
        "n_exec",
        "dcpu",
        "dmem",
        "ecpu",
        "emem",
        "cpu",
        "mem",
        "state",
        "start_t",
        "placements",
        "evictions",
        "seq",
    )

    def __init__(self, spec: AppSpec, bands: Dict[str, int], seq: int):
        self.app_id = spec.app_id
        self.arrival = spec.arrival
        self.submit_t = spec.arrival
        self.lifetime = spec.lifetime
        self.band = spec.band
        self.band_rank = bands.get(spec.band, bands.get("normal", 1))
        self.tenant = spec.tenant
        # gang-atomic demand: driver + (min executors for dynamic
        # allocation, full count for static) — DA extras are soft
        self.n_exec = spec.min_executor_count if spec.dynamic else spec.executor_count
        self.n_exec = max(1, int(self.n_exec))
        self.dcpu = _parse_cpu(spec.driver_cpu)
        self.dmem = _parse_mem(spec.driver_mem)
        self.ecpu = _parse_cpu(spec.executor_cpu)
        self.emem = _parse_mem(spec.executor_mem)
        self.cpu = self.dcpu + self.n_exec * self.ecpu
        self.mem = self.dmem + self.n_exec * self.emem
        self.state = _QUEUED
        self.start_t = 0.0
        self.placements: List[Tuple[int, int, int]] = []
        self.evictions = 0
        self.seq = seq


def compute_cell_digest(
    scorecard_digest_value: str, events_digest: str, kpis: Dict
) -> str:
    """The canonical per-cell digest.  Exposed so the matrix gate can
    RECOMPUTE it from a cell document instead of trusting the stored
    value — a forged baseline digest cannot mask a drift."""
    body = {
        "scorecard": scorecard_digest_value,
        "events": events_digest,
        "kpis": kpis,
    }
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


class CellResult:
    """Everything one cell produces: the PR 16 scorecard, flat KPIs,
    counters, and the deterministic digests the matrix gate compares."""

    def __init__(
        self,
        cell_id: str,
        axes: Dict,
        scorecard: Dict,
        kpis: Dict,
        counters: Dict,
        events_digest: str,
        events: int,
        wall_s: float,
    ):
        self.cell_id = cell_id
        self.axes = axes
        self.scorecard = scorecard
        self.kpis = kpis
        self.counters = counters
        self.events_digest = events_digest
        self.events = events
        self.wall_s = wall_s
        self.digest = compute_cell_digest(
            scorecard["digest"], events_digest, kpis
        )

    def to_dict(self) -> Dict:
        return {
            "cell": self.cell_id,
            "axes": self.axes,
            "digest": self.digest,
            "eventsDigest": self.events_digest,
            "events": self.events,
            "wallSeconds": round(self.wall_s, 3),
            "kpis": self.kpis,
            "counters": self.counters,
            "scorecard": self.scorecard,
        }


class _LedgerView:
    """Adapter handing ``build_scorecard`` a ledger-shaped summary —
    same leaves as ``LifecycleLedger.summary()`` so the scorecard
    schema (and its digest algebra) never forks on source."""

    def __init__(self, summary: Dict):
        self._summary = summary

    def summary(self) -> Dict:
        return self._summary


class GangLabSim:
    """One cell: replay ``apps`` under one policy configuration.

    ``cfg`` keys (all optional; the spec layer fills them):
    ``ordering``, ``preemption``, ``backfill``, ``drf_weights``,
    ``autoscaler_lag``, ``chaos``, ``nodes``, ``node_cpu``,
    ``node_memory``, ``horizon``, ``bands``, ``min_band_gap``,
    ``max_victims``, ``backfill_depth``, ``window_scale``,
    ``max_extra_nodes``.
    """

    def __init__(self, apps: List[AppSpec], cfg: Dict):
        self.cfg = dict(cfg)
        self.ordering = cfg.get("ordering", "fifo")
        self.preemption = bool(cfg.get("preemption", False))
        self.backfill = bool(cfg.get("backfill", False))
        self.backfill_depth = int(cfg.get("backfill_depth", 32))
        self.min_band_gap = int(cfg.get("min_band_gap", 1))
        self.max_victims = int(cfg.get("max_victims", 4))
        self.bands = dict(cfg.get("bands", _DEFAULT_BANDS))
        self.drf_weights = dict(cfg.get("drf_weights") or {})
        lag = cfg.get("autoscaler_lag")
        self.autoscaler_lag = None if lag is None else float(lag)
        self.chaos = cfg.get("chaos") or None
        self.horizon = float(cfg.get("horizon", 0.0)) or (
            (apps[-1].arrival if apps else 0.0) + 3600.0
        )
        self.node_cpu = _parse_cpu(cfg.get("node_cpu", "16"))
        self.node_mem = _parse_mem(cfg.get("node_memory", "64Gi"))
        n_nodes = int(cfg.get("nodes", 16))
        self.ncpu = [self.node_cpu] * n_nodes
        self.nmem = [self.node_mem] * n_nodes
        self.cap_cpu = self.node_cpu * n_nodes
        self.cap_mem = self.node_mem * n_nodes
        self.free_cpu = self.cap_cpu
        self.free_mem = self.cap_mem
        self.max_extra_nodes = int(cfg.get("max_extra_nodes", n_nodes))

        self.clock = VirtualClock(start=0.0)
        self.apps = apps
        self._gangs: List[_Gang] = []
        self._seq = 0

        # queues: fifo -> one deque; priority -> per-band; drf -> per-tenant
        self._fifo: deque = deque()
        self._by_band: Dict[int, deque] = {}
        self._by_tenant: Dict[str, deque] = {}
        # running accounting
        self._running: Dict[str, _Gang] = {}
        self._band_running: Dict[int, List[int]] = {}  # rank -> [cpu, mem]
        self._tenant_running: Dict[str, List[int]] = {}
        # sorted future completions: (end_t, seq, cpu, mem)
        self._completions: List[Tuple[float, int, int, int]] = []
        # EASY shadow reservation for the blocked head
        self._shadow_head: Optional[str] = None
        self._shadow_until = 0.0
        self._shadow_spare = (0, 0)
        # autoscaler orders: (fulfill_t, n_nodes); extra nodes added so far
        self._orders_outstanding = 0
        self._nodes_added = 0
        self._chaos_active = False
        self._chaos_started = 0.0

        # metrics
        self._waits: List[float] = []
        self._waste: List[float] = []
        self._fair_gaps: List[float] = []
        # incremental event digest: hashing newline-terminated lines as
        # they happen instead of storing 10^5+ strings for a final join
        self._events_hash = hashlib.sha256()
        self._events_count = 0
        self._last_t = 0.0
        self._util_cpu = 0.0
        self._util_mem = 0.0
        self._cap_cpu_integral = 0.0
        self._cap_mem_integral = 0.0
        self.counters = {
            "arrived": 0,
            "admissions": 0,
            "completed": 0,
            "evictions": 0,
            "preemptions": 0,
            "backfill_admits": 0,
            "backfill_skips": 0,
            "unschedulable": 0,
            "scaleup_orders": 0,
            "nodes_added": 0,
            "chaos_windows": 0,
            "gangs_spanning_chaos": 0,
            "passes": 0,
        }
        self.slo = SloEngine(
            window_scale=float(cfg.get("window_scale", 1.0)),
            overrides=cfg.get("slo_overrides"),
        )

    # -- event loop -----------------------------------------------------------

    def run(self) -> CellResult:
        wall0 = time.perf_counter()
        clock = self.clock
        if self.chaos is not None:
            at = float(self.chaos.get("at", self.horizon / 2))
            duration = float(self.chaos.get("duration", 300.0))
            every = self.chaos.get("every")
            while at < self.horizon:
                clock.schedule(at, "chaos-on", self._chaos_on)
                clock.schedule(at + duration, "chaos-off", self._chaos_off)
                if not every:
                    break
                at += float(every)
        apps = self.apps
        i, n = 0, len(apps)
        while True:
            t_ev = clock.peek_time()
            t_arr = apps[i].arrival if i < n else None
            if t_arr is None and t_ev is None:
                break
            # arrivals win ties: a gang submitted at instant T is
            # visible to every other event at T (deterministic order)
            if t_arr is not None and (t_ev is None or t_arr <= t_ev):
                if t_arr > self.horizon:
                    break
                self._advance(t_arr)
                self._on_arrival(apps[i])
                i += 1
                continue
            if t_ev > self.horizon:
                break
            clock.run_next()
        self._advance(self.horizon)
        wall_s = time.perf_counter() - wall0
        return self._result(wall_s)

    def _advance(self, t: float) -> None:
        """Move utilization integrals forward to ``t`` (clock time is
        advanced by the VirtualClock itself when events pop)."""
        dt = t - self._last_t
        if dt > 0:
            used_cpu = self.cap_cpu - self.free_cpu
            used_mem = self.cap_mem - self.free_mem
            self._util_cpu += used_cpu * dt
            self._util_mem += used_mem * dt
            self._cap_cpu_integral += self.cap_cpu * dt
            self._cap_mem_integral += self.cap_mem * dt
            self._last_t = t
        self.clock.advance_to(t)

    def _event(self, line: str) -> None:
        self._events_hash.update(line.encode())
        self._events_hash.update(b"\n")
        self._events_count += 1

    # -- event handlers -------------------------------------------------------

    def _on_arrival(self, spec: AppSpec) -> None:
        gang = _Gang(spec, self.bands, self._seq)
        self._seq += 1
        self._gangs.append(gang)
        self.counters["arrived"] += 1
        self._enqueue(gang)
        self._event(f"{spec.arrival:.3f} arr {spec.app_id}")
        self._pass(spec.arrival)

    def _on_complete(self, gang: _Gang, end_t: float) -> None:
        if gang.state != _RUNNING:
            return  # evicted before its completion event fired
        self._advance(end_t)
        self._release(gang, end_t)
        gang.state = _DONE
        self.counters["completed"] += 1
        self._event(f"{end_t:.3f} done {gang.app_id}")
        self._pass(end_t)

    def _chaos_on(self) -> None:
        now = self.clock.now()
        self._advance(now)
        self._chaos_active = True
        self._chaos_started = now
        self.counters["chaos_windows"] += 1
        self._event(f"{now:.3f} chaos-on")

    def _chaos_off(self) -> None:
        now = self.clock.now()
        self._advance(now)
        self._chaos_active = False
        spanning = sum(
            1 for g in self._running.values() if g.start_t < self._chaos_started
        )
        self.counters["gangs_spanning_chaos"] += spanning
        self._event(f"{now:.3f} chaos-off {spanning}")
        self._pass(now)

    def _on_scaleup(self, count: int) -> None:
        now = self.clock.now()
        self._advance(now)
        self._orders_outstanding -= count
        for _ in range(count):
            self.ncpu.append(self.node_cpu)
            self.nmem.append(self.node_mem)
        add_cpu = count * self.node_cpu
        add_mem = count * self.node_mem
        self.cap_cpu += add_cpu
        self.cap_mem += add_mem
        self.free_cpu += add_cpu
        self.free_mem += add_mem
        self._nodes_added += count
        self.counters["nodes_added"] += count
        self._event(f"{now:.3f} scale-up {count}")
        self._pass(now)

    # -- queues ---------------------------------------------------------------

    def _enqueue(self, gang: _Gang) -> None:
        if self.ordering == "priority-then-fifo":
            self._by_band.setdefault(gang.band_rank, deque()).append(gang)
        elif self.ordering == "drf":
            self._by_tenant.setdefault(gang.tenant, deque()).append(gang)
        else:
            self._fifo.append(gang)

    def _peek_head(self) -> Optional[_Gang]:
        if self.ordering == "priority-then-fifo":
            for rank in sorted(self._by_band, reverse=True):
                q = self._by_band[rank]
                while q and q[0].state != _QUEUED:
                    q.popleft()
                if q:
                    return q[0]
            return None
        if self.ordering == "drf":
            best, best_key = None, None
            for tenant in sorted(self._by_tenant):
                q = self._by_tenant[tenant]
                while q and q[0].state != _QUEUED:
                    q.popleft()
                if not q:
                    continue
                key = (self._dominant_share(tenant), tenant)
                if best_key is None or key < best_key:
                    best, best_key = q[0], key
            return best
        q = self._fifo
        while q and q[0].state != _QUEUED:
            q.popleft()
        return q[0] if q else None

    def _backfill_candidates(self, head: _Gang):
        """Up to ``backfill_depth`` queued gangs after the head, in
        policy order (generator; skips tombstoned entries)."""
        depth = self.backfill_depth
        yielded = 0
        if self.ordering == "priority-then-fifo":
            for rank in sorted(self._by_band, reverse=True):
                for g in self._by_band[rank]:
                    if g.state != _QUEUED or g is head:
                        continue
                    yield g
                    yielded += 1
                    if yielded >= depth:
                        return
        elif self.ordering == "drf":
            tenants = sorted(
                self._by_tenant, key=lambda t: (self._dominant_share(t), t)
            )
            for tenant in tenants:
                for g in self._by_tenant[tenant]:
                    if g.state != _QUEUED or g is head:
                        continue
                    yield g
                    yielded += 1
                    if yielded >= depth:
                        return
        else:
            for g in self._fifo:
                if g.state != _QUEUED or g is head:
                    continue
                yield g
                yielded += 1
                if yielded >= depth:
                    return

    def _dominant_share(self, tenant: str) -> float:
        usage = self._tenant_running.get(tenant)
        if usage is None or self.cap_cpu == 0:
            return 0.0
        share = max(usage[0] / self.cap_cpu, usage[1] / self.cap_mem)
        weight = self.drf_weights.get(tenant, 1.0)
        return share / weight if weight > 0 else share

    # -- admission ------------------------------------------------------------

    def _pass(self, now: float) -> None:
        """One scheduling pass: admit in policy order until the head
        blocks, then try preemption, autoscaling, and EASY backfill."""
        if self._chaos_active:
            return
        self.counters["passes"] += 1
        while True:
            head = self._peek_head()
            if head is None:
                return
            if self._try_admit(head, now):
                head.state = _RUNNING  # tombstone in whichever deque holds it
                continue
            # head is blocked
            if head.cpu > self.cap_cpu or head.mem > self.cap_mem:
                if self.autoscaler_lag is None or not self._order_nodes(head, now):
                    # can never fit (and autoscaling is off or capped)
                    head.state = _UNSCHEDULABLE
                    self.counters["unschedulable"] += 1
                    self._event(f"{now:.3f} unsched {head.app_id}")
                    continue
                break
            if self.preemption and self._try_preempt(head, now):
                if self._try_admit(head, now):
                    head.state = _RUNNING
                    continue
            if self.autoscaler_lag is not None:
                self._order_nodes(head, now)
            if self.backfill:
                self._run_backfill(head, now)
            return

    def _try_admit(self, gang: _Gang, now: float) -> bool:
        if gang.cpu > self.free_cpu or gang.mem > self.free_mem:
            return False
        placements = self._binpack(gang)
        if placements is None:
            return False
        self._commit(gang, placements, now)
        return True

    def _binpack(self, gang: _Gang) -> Optional[List[Tuple[int, int, int]]]:
        """First-fit the driver, then greedily fill executors node by
        node.  Returns committed-per-node (idx, cpu, mem) amounts, or
        None when fragmentation defeats the gang despite aggregate fit."""
        ncpu, nmem = self.ncpu, self.nmem
        dcpu, dmem = gang.dcpu, gang.dmem
        ecpu, emem = gang.ecpu, gang.emem
        remaining = gang.n_exec
        placements: List[Tuple[int, int, int]] = []
        driver_idx = -1
        for i in range(len(ncpu)):
            fc, fm = ncpu[i], nmem[i]
            take_cpu = 0
            take_mem = 0
            if driver_idx < 0 and fc >= dcpu and fm >= dmem:
                driver_idx = i
                take_cpu, take_mem = dcpu, dmem
                fc -= dcpu
                fm -= dmem
            if remaining > 0:
                k = min(remaining, fc // ecpu, fm // emem)
                if k > 0:
                    take_cpu += k * ecpu
                    take_mem += k * emem
                    remaining -= k
            if take_cpu or take_mem:
                placements.append((i, take_cpu, take_mem))
            if driver_idx >= 0 and remaining == 0:
                return placements
        return None

    def _commit(self, gang: _Gang, placements: List[Tuple[int, int, int]], now: float) -> None:
        ncpu, nmem = self.ncpu, self.nmem
        for i, c, m in placements:
            ncpu[i] -= c
            nmem[i] -= m
        self.free_cpu -= gang.cpu
        self.free_mem -= gang.mem
        gang.placements = placements
        gang.state = _RUNNING
        gang.start_t = now
        end_t = round(now + gang.lifetime, 3)
        self._running[gang.app_id] = gang
        band = self._band_running.setdefault(gang.band_rank, [0, 0])
        band[0] += gang.cpu
        band[1] += gang.mem
        tenant = self._tenant_running.setdefault(gang.tenant, [0, 0])
        tenant[0] += gang.cpu
        tenant[1] += gang.mem
        insort(self._completions, (end_t, gang.seq, gang.cpu, gang.mem))
        self.clock.schedule(end_t, "done", lambda g=gang, t=end_t: self._on_complete(g, t))
        wait = round(now - gang.submit_t, 3)
        self._waits.append(wait)
        self.slo.observe("time_to_admit", wait, t=now)
        self.counters["admissions"] += 1
        self._event(f"{now:.3f} admit {gang.app_id} w={wait:.3f}")
        if self._shadow_head == gang.app_id:
            self._shadow_head = None
        if len(self._tenant_running) >= 2:
            shares = [
                self._dominant_share(t) for t in sorted(self._tenant_running)
            ]
            self._fair_gaps.append(max(shares) - min(shares))
            self.slo.observe("fairness_gap", self._fair_gaps[-1], t=now)

    def _release(self, gang: _Gang, now: float) -> None:
        ncpu, nmem = self.ncpu, self.nmem
        for i, c, m in gang.placements:
            ncpu[i] += c
            nmem[i] += m
        self.free_cpu += gang.cpu
        self.free_mem += gang.mem
        gang.placements = []
        self._running.pop(gang.app_id, None)
        band = self._band_running.get(gang.band_rank)
        if band is not None:
            band[0] -= gang.cpu
            band[1] -= gang.mem
        tenant = self._tenant_running.get(gang.tenant)
        if tenant is not None:
            tenant[0] -= gang.cpu
            tenant[1] -= gang.mem
        # remove the scheduled completion entry (evictions cancel it)
        end_t = round(gang.start_t + gang.lifetime, 3)
        entry = (end_t, gang.seq, gang.cpu, gang.mem)
        from bisect import bisect_left

        idx = bisect_left(self._completions, entry)
        if idx < len(self._completions) and self._completions[idx] == entry:
            self._completions.pop(idx)

    # -- preemption (Borg) ----------------------------------------------------

    def _try_preempt(self, head: _Gang, now: float) -> bool:
        """Evict whole low-band gangs to make room for the head; only
        commits when a sufficient victim set exists within max_victims."""
        limit_rank = head.band_rank - self.min_band_gap
        if limit_rank < 0:
            return False
        evictable_cpu = evictable_mem = 0
        for rank, totals in self._band_running.items():
            if rank <= limit_rank:
                evictable_cpu += totals[0]
                evictable_mem += totals[1]
        if (
            self.free_cpu + evictable_cpu < head.cpu
            or self.free_mem + evictable_mem < head.mem
        ):
            return False
        candidates = [
            g for g in self._running.values() if g.band_rank <= limit_rank
        ]
        # lowest band first, least work lost first (Borg's waste-min)
        candidates.sort(key=lambda g: (g.band_rank, -g.start_t, g.app_id))
        victims: List[_Gang] = []
        acc_cpu = acc_mem = 0
        for g in candidates:
            if len(victims) >= self.max_victims:
                break
            victims.append(g)
            acc_cpu += g.cpu
            acc_mem += g.mem
            if (
                self.free_cpu + acc_cpu >= head.cpu
                and self.free_mem + acc_mem >= head.mem
            ):
                break
        if (
            self.free_cpu + acc_cpu < head.cpu
            or self.free_mem + acc_mem < head.mem
        ):
            return False
        for g in victims:
            self._evict(g, now)
        self.counters["preemptions"] += 1
        return True

    def _evict(self, gang: _Gang, now: float) -> None:
        self._release(gang, now)
        waste = round(now - gang.start_t, 3)
        self._waste.append(waste)
        self.slo.observe("eviction_waste", waste, t=now)
        gang.state = _QUEUED
        gang.submit_t = now
        gang.evictions += 1
        self.counters["evictions"] += 1
        self._enqueue(gang)
        self._event(f"{now:.3f} evict {gang.app_id} waste={waste:.3f}")

    # -- autoscaler -----------------------------------------------------------

    def _order_nodes(self, head: _Gang, now: float) -> bool:
        budget = self.max_extra_nodes - self._nodes_added - self._orders_outstanding
        if budget <= 0:
            return False
        deficit_cpu = head.cpu - self.free_cpu
        deficit_mem = head.mem - self.free_mem
        pending = self._orders_outstanding * self.node_cpu
        pending_mem = self._orders_outstanding * self.node_mem
        deficit_cpu -= pending
        deficit_mem -= pending_mem
        if deficit_cpu <= 0 and deficit_mem <= 0:
            return True  # already on order
        need = max(
            -(-deficit_cpu // self.node_cpu) if deficit_cpu > 0 else 0,
            -(-deficit_mem // self.node_mem) if deficit_mem > 0 else 0,
        )
        count = int(min(need, budget))
        if count <= 0:
            return False
        self._orders_outstanding += count
        self.counters["scaleup_orders"] += 1
        self.clock.schedule(
            now + self.autoscaler_lag,
            "scale-up",
            lambda c=count: self._on_scaleup(c),
        )
        return True

    # -- EASY backfill --------------------------------------------------------

    def _head_reservation(self, head: _Gang, now: float) -> Tuple[float, int, int]:
        """Shadow-reserve the blocked head: walk future completions
        (and pending scale-ups) until enough frees, returning the
        promised start instant and the spare capacity beyond the head's
        demand at that instant."""
        acc_cpu, acc_mem = self.free_cpu, self.free_mem
        events: List[Tuple[float, int, int]] = [
            (t, c, m) for t, _, c, m in self._completions
        ]
        t_start = float("inf")
        for t, c, m in events:
            acc_cpu += c
            acc_mem += m
            if acc_cpu >= head.cpu and acc_mem >= head.mem:
                t_start = t
                break
        del now
        return t_start, max(0, acc_cpu - head.cpu), max(0, acc_mem - head.mem)

    def _run_backfill(self, head: _Gang, now: float) -> None:
        if self._shadow_head != head.app_id:
            t_start, spare_cpu, spare_mem = self._head_reservation(head, now)
            self._shadow_head = head.app_id
            self._shadow_until = t_start
            self._shadow_spare = (spare_cpu, spare_mem)
        t_start = self._shadow_until
        spare_cpu, spare_mem = self._shadow_spare
        for g in list(self._backfill_candidates(head)):
            fits_by_time = now + g.lifetime <= t_start
            fits_in_spare = g.cpu <= spare_cpu and g.mem <= spare_mem
            if not (fits_by_time or fits_in_spare):
                self.counters["backfill_skips"] += 1
                continue
            if not self._try_admit(g, now):
                continue
            g.state = _RUNNING
            self.counters["backfill_admits"] += 1
            if not fits_by_time:
                spare_cpu -= g.cpu
                spare_mem -= g.mem
        self._shadow_spare = (spare_cpu, spare_mem)

    # -- results --------------------------------------------------------------

    def _lifecycle_summary(self) -> Dict:
        phases: Dict[str, int] = {}
        queued = running = completed = expired = 0
        for g in self._gangs:
            if g.state == _QUEUED:
                queued += 1
            elif g.state == _RUNNING:
                running += 1
            elif g.state == _DONE:
                completed += 1
            else:
                expired += 1
        if queued:
            phases["queued"] = queued
        if running:
            phases["running"] = running
        if completed:
            phases["completed"] = completed
        if expired:
            phases["expired"] = expired
        waits = sorted(self._waits)
        c = self.counters
        transitions = c["arrived"] + c["admissions"] + c["completed"] + c["evictions"]
        out: Dict = {
            "gangs": len(self._gangs),
            "phases": phases,
            "transitions": transitions,
            "queueWait": {
                "count": len(waits),
                "p50": _pct(waits, 0.50),
                "p95": _pct(waits, 0.95),
                "p99": _pct(waits, 0.99),
            },
            "evictionsByCause": (
                {"preempted": c["evictions"]} if c["evictions"] else {}
            ),
            "epochContinuity": {
                "gangsSpanningEpochs": c["gangs_spanning_chaos"],
                "epochRegressions": 0,
            },
            # operational counters (excluded from the scorecard digest,
            # same as the live ledger's drain-loop cadence)
            "drains": c["passes"],
            "lockViolations": 0,
        }
        return out

    def _kpis(self) -> Dict:
        waits = sorted(self._waits)
        waste_total = round(sum(self._waste), 3)
        gaps = sorted(self._fair_gaps)
        c = self.counters
        util_cpu = (
            self._util_cpu / self._cap_cpu_integral if self._cap_cpu_integral else 0.0
        )
        util_mem = (
            self._util_mem / self._cap_mem_integral if self._cap_mem_integral else 0.0
        )
        return {
            "packing_efficiency": {
                "cpu": round(util_cpu, 6),
                "memory": round(util_mem, 6),
                "max": round(max(util_cpu, util_mem), 6),
            },
            "wait_seconds": {
                "count": len(waits),
                "mean": round(sum(waits) / len(waits), 3) if waits else 0.0,
                "p50": _pct(waits, 0.50) or 0.0,
                "p95": _pct(waits, 0.95) or 0.0,
                "p99": _pct(waits, 0.99) or 0.0,
            },
            "eviction_waste_seconds": {
                "total": waste_total,
                "events": c["evictions"],
                "mean": round(waste_total / c["evictions"], 3) if c["evictions"] else 0.0,
            },
            "fairness_gap": {
                "samples": len(gaps),
                "p95": round(_pct(gaps, 0.95) or 0.0, 6),
                "max": round(gaps[-1], 6) if gaps else 0.0,
            },
            "throughput": {
                "arrived": c["arrived"],
                "admitted": c["admissions"],
                "completed": c["completed"],
                "pending_at_end": sum(1 for g in self._gangs if g.state == _QUEUED),
                "unschedulable": c["unschedulable"],
            },
        }

    def _result(self, wall_s: float) -> CellResult:
        self.slo.evaluate(now=self.horizon)
        summary = self._lifecycle_summary()
        cell_id = self.cfg.get("cell_id", "cell")
        scorecard = build_scorecard(
            _LedgerView(summary),
            self.slo,
            meta={
                "source": "lab",
                "cell": cell_id,
                "seed": self.cfg.get("seed", 0),
                "trace": self.cfg.get("trace_digest", ""),
                "arrivals": len(self.apps),
            },
            now=self.horizon,
        )
        events_digest = self._events_hash.hexdigest()
        axes = {
            "ordering": self.ordering,
            "preemption": self.preemption,
            "backfill": self.backfill,
            "drf_weights": self.drf_weights,
            "autoscaler_lag": self.autoscaler_lag,
            "chaos": bool(self.chaos),
        }
        return CellResult(
            cell_id=cell_id,
            axes=axes,
            scorecard=scorecard,
            kpis=self._kpis(),
            counters=dict(self.counters),
            events_digest=events_digest,
            events=self._events_count,
            wall_s=wall_s,
        )


def run_cell(apps: List[AppSpec], cfg: Dict) -> CellResult:
    """Convenience wrapper: one isolated cell run."""
    return GangLabSim(apps, cfg).run()


def _pct(sorted_values: List[float], q: float) -> Optional[float]:
    if not sorted_values:
        return None
    idx = min(
        len(sorted_values) - 1, max(0, int(q * len(sorted_values) + 0.5) - 1)
    )
    return round(sorted_values[idx], 6)


# sanity check at import: the scorecard digest algebra must be the
# shared one — a fork here would silently decouple the matrix gate from
# the live /slo contract
assert scorecard_digest is not None
