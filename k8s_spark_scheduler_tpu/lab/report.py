"""Matrix comparison layer: fold per-cell scorecards into one report.

The report is what a reviewer reads to judge a policy change: every
cell's KPI row (packing efficiency, p50/p95/p99 wait, eviction waste,
DRF fairness gap, SLO burn verdicts), rankings per dimension, and a
canonical digest over the deterministic body so the report itself can
be baselined.  Cell-vs-cell comparisons reuse
``lifecycle/scorecard.py::scorecard_diff`` — the SAME leaf-walk the
policy-regression gate prints, so a lab diff and a CI gate failure
read identically.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Optional, Tuple

from ..lifecycle.scorecard import scorecard_diff

REPORT_SCHEMA = "tpu-gang-scheduler-matrix-report"
REPORT_VERSION = 1

# ranking dimensions: (name, kpi extractor, better-direction)
_DIMENSIONS = (
    ("packing", lambda k: k["packing_efficiency"]["max"], "desc"),
    ("wait_p50", lambda k: k["wait_seconds"]["p50"], "asc"),
    ("wait_p99", lambda k: k["wait_seconds"]["p99"], "asc"),
    ("eviction_waste", lambda k: k["eviction_waste_seconds"]["total"], "asc"),
    ("fairness_gap", lambda k: k["fairness_gap"]["p95"], "asc"),
)


def _slo_verdict(scorecard: Dict) -> Dict[str, str]:
    return {
        name: obj.get("state", "ok")
        for name, obj in sorted(scorecard.get("objectives", {}).items())
    }


def _worst_state(verdicts: Dict[str, str]) -> str:
    rank = {"ok": 0, "ticket": 1, "page": 2}
    worst = "ok"
    for state in verdicts.values():
        if rank.get(state, 0) > rank.get(worst, 0):
            worst = state
    return worst


def build_matrix_report(matrix: Dict) -> Dict:
    """Fold a matrix results document (``runner.run_matrix`` output)
    into the comparison report."""
    cells = matrix.get("cells", [])
    rows = []
    for doc in cells:
        kpis = doc["kpis"]
        verdicts = _slo_verdict(doc["scorecard"])
        rows.append(
            {
                "cell": doc["cell"],
                "axes": doc["axes"],
                "digest": doc["digest"],
                "scorecardDigest": doc["scorecard"]["digest"],
                "packing": kpis["packing_efficiency"]["max"],
                "wait_p50": kpis["wait_seconds"]["p50"],
                "wait_p95": kpis["wait_seconds"]["p95"],
                "wait_p99": kpis["wait_seconds"]["p99"],
                "eviction_waste": kpis["eviction_waste_seconds"]["total"],
                "evictions": kpis["eviction_waste_seconds"]["events"],
                "fairness_gap": kpis["fairness_gap"]["p95"],
                "completed": kpis["throughput"]["completed"],
                "pending_at_end": kpis["throughput"]["pending_at_end"],
                "slo": verdicts,
                "sloWorst": _worst_state(verdicts),
            }
        )

    rankings: Dict[str, List[str]] = {}
    for name, extract, direction in _DIMENSIONS:
        order = sorted(
            cells,
            key=lambda d: (
                -extract(d["kpis"]) if direction == "desc" else extract(d["kpis"]),
                d["cell"],
            ),
        )
        rankings[name] = [d["cell"] for d in order]

    report: Dict = {
        "schema": REPORT_SCHEMA,
        "version": REPORT_VERSION,
        "name": matrix.get("name", "matrix"),
        "specDigest": matrix.get("specDigest", ""),
        "traceDigest": matrix.get("traceDigest", ""),
        "arrivals": matrix.get("arrivals", 0),
        "cellCount": len(rows),
        "cells": rows,
        "rankings": rankings,
        "leaders": {name: order[0] if order else None for name, order in rankings.items()},
    }
    report["digest"] = _report_digest(report)
    return report


def _report_digest(report: Dict) -> str:
    body = {k: v for k, v in report.items() if k != "digest"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def diff_cells(
    matrix: Dict, cell_a: str, cell_b: str
) -> List[Tuple[str, object, object]]:
    """Leaf-level scorecard differences between two cells of a matrix
    document (``scorecard_diff`` semantics: (path, a, b) tuples)."""
    a = _find_cell(matrix, cell_a)
    b = _find_cell(matrix, cell_b)
    return scorecard_diff(a["scorecard"], b["scorecard"])


def _find_cell(matrix: Dict, cell_id: str) -> Dict:
    for doc in matrix.get("cells", []):
        if doc.get("cell") == cell_id:
            return doc
    known = [d.get("cell") for d in matrix.get("cells", [])]
    raise KeyError(f"cell {cell_id!r} not in matrix (cells: {known})")


def render_report_text(report: Dict, limit: Optional[int] = None) -> str:
    """Human-readable table for the CLI (kept deliberately plain)."""
    lines = [
        f"matrix report: {report['name']}  cells={report['cellCount']}  "
        f"arrivals={report['arrivals']}",
        f"spec={report['specDigest'][:12]} trace={report['traceDigest'][:12]}",
        "",
        f"{'cell':<40} {'pack':>7} {'p50':>8} {'p99':>9} {'waste':>10} "
        f"{'fair':>7} {'slo':>7}",
    ]
    rows = report["cells"][:limit] if limit else report["cells"]
    for row in rows:
        lines.append(
            f"{row['cell']:<40} {row['packing']:>7.3f} {row['wait_p50']:>8.1f} "
            f"{row['wait_p99']:>9.1f} {row['eviction_waste']:>10.1f} "
            f"{row['fairness_gap']:>7.3f} {row['sloWorst']:>7}"
        )
    lines.append("")
    for name, leader in sorted(report["leaders"].items()):
        lines.append(f"best {name}: {leader}")
    return "\n".join(lines)
