"""Parallel matrix execution across worker processes.

Each cell is a fully isolated replay — its own :class:`GangLabSim`,
its own VirtualClock, no process-global timesource — so cells are safe
to fan out across a ``multiprocessing`` pool.  The pool uses the
*spawn* start method (fork under a thread-carrying parent is how you
mint deadlocks) and a per-worker initializer that parses the trace
ONCE per worker instead of once per cell.

Determinism is the contract: a cell's digest is a pure function of
(trace bytes, cell config), so the same spec + trace must produce
byte-identical digests whether a cell runs in a worker process or
in-process.  ``run_matrix(verify=k)`` re-runs the first ``k`` cells
in-process and refuses to hand back a matrix whose parallel and serial
digests disagree.
"""

from __future__ import annotations

import hashlib
import json
import multiprocessing
import os
from typing import Dict, List, Optional

from ..sim.manifest import write_run_manifest
from ..sim.workload import AppSpec, load_trace
from .engine import run_cell
from .spec import MatrixSpec

MATRIX_SCHEMA = "tpu-gang-scheduler-matrix"
MATRIX_VERSION = 1

_WORKER: Dict = {}


def _trace_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _load_apps(trace_path: str, arrival_limit: int) -> List[AppSpec]:
    apps = load_trace(trace_path)
    if arrival_limit:
        apps = apps[:arrival_limit]
    return apps


def _worker_init(trace_path: str, arrival_limit: int) -> None:
    _WORKER["apps"] = _load_apps(trace_path, arrival_limit)


def _worker_run(task: Dict) -> Dict:
    cfg = task["cfg"]
    result = run_cell(_WORKER["apps"], cfg)
    doc = result.to_dict()
    cell_dir = task.get("cell_dir")
    if cell_dir:
        _write_cell_artifacts(cell_dir, doc, cfg)
    return doc


def _write_cell_artifacts(cell_dir: str, doc: Dict, cfg: Dict) -> None:
    os.makedirs(cell_dir, exist_ok=True)
    with open(os.path.join(cell_dir, "scorecard.json"), "w") as f:
        json.dump(doc["scorecard"], f, indent=2, sort_keys=True)
        f.write("\n")
    with open(os.path.join(cell_dir, "cell.json"), "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    write_run_manifest(
        cell_dir,
        kind="lab-cell",
        seed=cfg.get("seed"),
        digests={
            "cell": doc["digest"],
            "events": doc["eventsDigest"],
            "scorecard": doc["scorecard"]["digest"],
            "spec": cfg.get("spec_digest", ""),
            "trace": cfg.get("trace_digest", ""),
        },
        extra={"cell": doc["cell"]},
    )


def run_matrix(
    spec: MatrixSpec,
    *,
    workers: int = 0,
    out_dir: Optional[str] = None,
    verify: int = 0,
    apps: Optional[List[AppSpec]] = None,
    metrics=None,
) -> Dict:
    """Expand ``spec`` and execute every cell, returning the matrix
    results document.

    ``workers=0`` runs cells serially in-process (no pool); ``workers>0``
    fans cells out over that many spawned worker processes.  ``verify``
    re-runs the first N cells in-process after a parallel run and raises
    ``RuntimeError`` on any digest divergence — the cross-process
    determinism gate.  ``apps`` short-circuits trace loading (tests).
    """
    cells = spec.expand()
    spec_digest = spec.digest()
    trace_digest = ""
    if spec.trace and os.path.exists(spec.trace):
        trace_digest = _trace_digest(spec.trace)
    if apps is None:
        if not spec.trace:
            raise ValueError("matrix spec has no trace and no apps were supplied")
        apps = _load_apps(spec.trace, spec.arrival_limit)

    tasks = []
    for cell in cells:
        cfg = dict(cell.cfg)
        cfg["spec_digest"] = spec_digest
        cfg["trace_digest"] = trace_digest
        cell_dir = (
            os.path.join(out_dir, "cells", cell.cell_id) if out_dir else None
        )
        tasks.append({"cfg": cfg, "cell_dir": cell_dir})

    if workers > 0:
        ctx = multiprocessing.get_context("spawn")
        if not spec.trace:
            raise ValueError("parallel workers need a trace path to load")
        with ctx.Pool(
            processes=workers,
            initializer=_worker_init,
            initargs=(spec.trace, spec.arrival_limit),
        ) as pool:
            docs = pool.map(_worker_run, tasks, chunksize=1)
    else:
        docs = [
            _run_local(task, apps)
            for task in tasks
        ]

    verification = None
    if verify > 0:
        checked = []
        ok = True
        for task, doc in list(zip(tasks, docs))[:verify]:
            local = run_cell(apps, task["cfg"]).to_dict()
            match = local["digest"] == doc["digest"]
            ok = ok and match
            checked.append(
                {"cell": doc["cell"], "match": match, "digest": doc["digest"]}
            )
        verification = {"cells": checked, "ok": ok}
        if not ok:
            raise RuntimeError(
                "cross-process digest divergence: "
                + json.dumps([c for c in checked if not c["match"]])
            )

    if metrics is not None:
        _publish(metrics, docs)

    matrix: Dict = {
        "schema": MATRIX_SCHEMA,
        "version": MATRIX_VERSION,
        "name": spec.name,
        "specDigest": spec_digest,
        "traceDigest": trace_digest,
        "arrivals": len(apps),
        "workers": workers,
        "cells": docs,
    }
    if verification is not None:
        matrix["verification"] = verification
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "matrix.json"), "w") as f:
            json.dump(matrix, f, indent=2, sort_keys=True)
            f.write("\n")
        write_run_manifest(
            out_dir,
            kind="lab-matrix",
            digests={"spec": spec_digest, "trace": trace_digest},
            extra={"name": spec.name, "cells": [c.cell_id for c in cells]},
        )
    return matrix


def _publish(metrics, docs: List[Dict]) -> None:
    from ..metrics import names as mnames

    metrics.counter(mnames.LAB_MATRIX_CELLS, inc=float(len(docs)))
    for doc in docs:
        tags = {mnames.TAG_CELL: doc["cell"]}
        metrics.histogram(mnames.LAB_CELL_WALL_TIME, doc["wallSeconds"], tags)
        metrics.gauge(mnames.LAB_CELL_EVENTS, float(doc["events"]), tags)
        metrics.gauge(
            mnames.LAB_CELL_EVICTIONS,
            float(doc["counters"]["evictions"]),
            tags,
        )


def _run_local(task: Dict, apps: List[AppSpec]) -> Dict:
    doc = run_cell(apps, task["cfg"]).to_dict()
    if task.get("cell_dir"):
        _write_cell_artifacts(task["cell_dir"], doc, task["cfg"])
    return doc
