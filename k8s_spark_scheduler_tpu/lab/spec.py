"""Declarative matrix experiment specs.

A matrix spec is a reviewable JSON document: one shared trace + cluster
shape, and an ``axes`` block whose cross product is the cell set.  The
spec layer owns validation (up front, with actionable dotted-path
messages — same contract satellite 1 adds to ``sim/scenario.py``) and
deterministic expansion: cell ids are derived from axis values, the
expansion order is the sorted cross product, and the spec digest covers
the canonicalized document so a matrix baseline can say exactly which
experiment it gates.
"""

from __future__ import annotations

import hashlib
import itertools
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_KNOWN_KEYS = {
    "name",
    "trace",
    "cluster",
    "axes",
    "horizon",
    "bands",
    "min_band_gap",
    "max_victims",
    "backfill_depth",
    "window_scale",
    "slo_overrides",
    "arrival_limit",
}
_KNOWN_CLUSTER = {"nodes", "node_cpu", "node_memory", "max_extra_nodes"}
_KNOWN_AXES = {
    "ordering",
    "preemption",
    "backfill",
    "drf_weights",
    "autoscaler_lag",
    "chaos",
}
_ORDERINGS = {"fifo", "priority-then-fifo", "drf"}


class SpecError(ValueError):
    """Actionable matrix-spec validation failure."""


def _axis_token(name: str, value) -> str:
    """Stable short token naming one axis value inside a cell id."""
    if name == "ordering":
        return {"fifo": "fifo", "priority-then-fifo": "prio", "drf": "drf"}[value]
    if name == "preemption":
        return "pre" if value else "nopre"
    if name == "backfill":
        return "bf" if value else "nobf"
    if name == "drf_weights":
        if not value:
            return "w-flat"
        blob = json.dumps(value, sort_keys=True, separators=(",", ":"))
        return "w-" + hashlib.sha256(blob.encode()).hexdigest()[:6]
    if name == "autoscaler_lag":
        return "as-off" if value is None else f"as{int(value)}"
    if name == "chaos":
        return "chaos" if value else "calm"
    return str(value)


@dataclass
class MatrixCell:
    """One expanded cell: id + the full engine configuration."""

    cell_id: str
    axes: Dict
    cfg: Dict


@dataclass
class MatrixSpec:
    name: str = "matrix"
    trace: str = ""
    cluster: Dict = field(
        default_factory=lambda: {"nodes": 16, "node_cpu": "16", "node_memory": "64Gi"}
    )
    axes: Dict = field(default_factory=dict)
    horizon: float = 0.0
    bands: Optional[Dict[str, int]] = None
    min_band_gap: int = 1
    max_victims: int = 4
    backfill_depth: int = 32
    window_scale: float = 1.0
    slo_overrides: Optional[Dict] = None
    arrival_limit: int = 0  # 0 = replay the whole trace

    @staticmethod
    def from_dict(d: Dict) -> "MatrixSpec":
        if not isinstance(d, dict):
            raise SpecError(f"matrix spec: expected an object, got {type(d).__name__}")
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise SpecError(
                f"matrix spec: unknown keys {sorted(unknown)} (known: {sorted(_KNOWN_KEYS)})"
            )
        cluster = d.get("cluster", {})
        if not isinstance(cluster, dict):
            raise SpecError(
                f"matrix.cluster: expected an object, got {type(cluster).__name__}"
            )
        unknown = set(cluster) - _KNOWN_CLUSTER
        if unknown:
            raise SpecError(
                f"matrix.cluster: unknown keys {sorted(unknown)} "
                f"(known: {sorted(_KNOWN_CLUSTER)})"
            )
        axes = d.get("axes", {})
        if not isinstance(axes, dict):
            raise SpecError(f"matrix.axes: expected an object, got {type(axes).__name__}")
        unknown = set(axes) - _KNOWN_AXES
        if unknown:
            raise SpecError(
                f"matrix.axes: unknown axes {sorted(unknown)} (known: {sorted(_KNOWN_AXES)})"
            )
        spec = MatrixSpec(
            name=str(d.get("name", "matrix")),
            trace=str(d.get("trace", "")),
            cluster={**MatrixSpec().cluster, **cluster},
            axes={k: list(v) for k, v in axes.items()},
            horizon=float(d.get("horizon", 0.0)),
            bands=d.get("bands"),
            min_band_gap=int(d.get("min_band_gap", 1)),
            max_victims=int(d.get("max_victims", 4)),
            backfill_depth=int(d.get("backfill_depth", 32)),
            window_scale=float(d.get("window_scale", 1.0)),
            slo_overrides=d.get("slo_overrides"),
            arrival_limit=int(d.get("arrival_limit", 0)),
        )
        spec.validate()
        return spec

    def validate(self) -> None:
        for name, values in self.axes.items():
            if not isinstance(values, list) or not values:
                raise SpecError(
                    f"matrix.axes.{name}: expected a non-empty list of values"
                )
            if name == "ordering":
                for v in values:
                    if v not in _ORDERINGS:
                        raise SpecError(
                            f"matrix.axes.ordering: unknown ordering {v!r} "
                            f"(known: {sorted(_ORDERINGS)})"
                        )
            elif name in ("preemption", "backfill"):
                for v in values:
                    if not isinstance(v, bool):
                        raise SpecError(
                            f"matrix.axes.{name}: expected booleans, got {v!r}"
                        )
            elif name == "drf_weights":
                for v in values:
                    if v is not None and not isinstance(v, dict):
                        raise SpecError(
                            f"matrix.axes.drf_weights: expected null or "
                            f"tenant->weight objects, got {v!r}"
                        )
            elif name == "autoscaler_lag":
                for v in values:
                    if v is not None and (
                        isinstance(v, bool) or not isinstance(v, (int, float)) or v < 0
                    ):
                        raise SpecError(
                            f"matrix.axes.autoscaler_lag: expected null or "
                            f"seconds >= 0, got {v!r}"
                        )
            elif name == "chaos":
                for v in values:
                    if v is not None and not isinstance(v, dict):
                        raise SpecError(
                            f"matrix.axes.chaos: expected null or "
                            f"{{at, duration[, every]}} objects, got {v!r}"
                        )
        nodes = self.cluster.get("nodes", 16)
        if isinstance(nodes, bool) or not isinstance(nodes, int) or nodes < 1:
            raise SpecError(f"matrix.cluster.nodes: expected a positive int, got {nodes!r}")

    def digest(self) -> str:
        """Canonical digest of the spec document (cells + config)."""
        blob = json.dumps(self.to_dict(), sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    def to_dict(self) -> Dict:
        return {
            "name": self.name,
            "trace": self.trace,
            "cluster": self.cluster,
            "axes": self.axes,
            "horizon": self.horizon,
            "bands": self.bands,
            "min_band_gap": self.min_band_gap,
            "max_victims": self.max_victims,
            "backfill_depth": self.backfill_depth,
            "window_scale": self.window_scale,
            "slo_overrides": self.slo_overrides,
            "arrival_limit": self.arrival_limit,
        }

    def expand(self) -> List[MatrixCell]:
        """Cross product of the axes, in deterministic order.  Axes not
        named in the spec take the single default value."""
        defaults = {
            "ordering": ["fifo"],
            "preemption": [False],
            "backfill": [False],
            "drf_weights": [None],
            "autoscaler_lag": [None],
            "chaos": [None],
        }
        axis_names = list(defaults)
        values = [self.axes.get(n, defaults[n]) for n in axis_names]
        cells: List[MatrixCell] = []
        for combo in itertools.product(*values):
            axes = dict(zip(axis_names, combo))
            tokens = [
                _axis_token(n, axes[n])
                for n in axis_names
                if n in self.axes  # only spec-varied axes name the cell
            ]
            cell_id = "-".join(tokens) if tokens else "cell"
            cfg = {
                "cell_id": cell_id,
                "ordering": axes["ordering"],
                "preemption": axes["preemption"],
                "backfill": axes["backfill"],
                "drf_weights": axes["drf_weights"],
                "autoscaler_lag": axes["autoscaler_lag"],
                "chaos": axes["chaos"],
                "nodes": self.cluster.get("nodes", 16),
                "node_cpu": self.cluster.get("node_cpu", "16"),
                "node_memory": self.cluster.get("node_memory", "64Gi"),
                "max_extra_nodes": self.cluster.get(
                    "max_extra_nodes", self.cluster.get("nodes", 16)
                ),
                "horizon": self.horizon,
                "min_band_gap": self.min_band_gap,
                "max_victims": self.max_victims,
                "backfill_depth": self.backfill_depth,
                "window_scale": self.window_scale,
                "slo_overrides": self.slo_overrides,
            }
            if self.bands:
                cfg["bands"] = self.bands
            cells.append(MatrixCell(cell_id=cell_id, axes=axes, cfg=cfg))
        ids = [c.cell_id for c in cells]
        if len(set(ids)) != len(ids):
            raise SpecError("matrix spec: duplicate cell ids after expansion")
        return cells
