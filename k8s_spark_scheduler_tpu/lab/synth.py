"""Production-shaped workload synthesis (Borg-trace lineage).

A synth spec is a plain dict (reviewable JSON) describing the three
properties production traces have that hand-written scenarios never do
(EuroSys '15):

- **heavy-tailed gang sizes** — executor counts drawn lognormal or
  Pareto, so most gangs are small and a fat tail is enormous;
- **diurnal arrival intensity** — an inhomogeneous arrival process
  whose rate swings sinusoidally over a daily period;
- **multi-tenant mixes** — every app belongs to a tenant with its own
  arrival share, DRF weight hint, and priority-band profile.

``synthesize`` draws exactly ``arrivals`` apps from one
``random.Random(seed)`` — a Poisson process conditioned on its count
has i.i.d. arrival instants with density proportional to the intensity,
so rejection-sampling against the diurnal curve gives an exact-count,
seed-reproducible trace.  Every float is rounded before it lands in an
``AppSpec`` so traces (and every digest computed downstream) are
byte-identical across platforms and libm builds.

The output is a list of :class:`~..sim.workload.AppSpec`, dumped via
``sim/workload.py::dump_trace`` — the SAME JSONL format the full sim's
``{"workload": {"trace": path}}`` replay path consumes.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass, field
from typing import Dict, List

from ..sim.workload import AppSpec, _SIZE_MENU

_KNOWN_KEYS = {
    "name",
    "seed",
    "arrivals",
    "horizon",
    "gang_size",
    "lifetime",
    "diurnal",
    "tenants",
    "dynamic_fraction",
    "instance_group",
    "namespace",
}
_GANG_DISTS = {"lognormal", "pareto", "uniform"}
_LIFETIME_DISTS = {"lognormal", "uniform"}


class SynthError(ValueError):
    """Actionable synth-spec validation failure."""


def _require_number(spec_path: str, value, lo=None, hi=None) -> float:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise SynthError(f"{spec_path}: expected a number, got {value!r}")
    if lo is not None and value < lo:
        raise SynthError(f"{spec_path}: must be >= {lo}, got {value!r}")
    if hi is not None and value > hi:
        raise SynthError(f"{spec_path}: must be <= {hi}, got {value!r}")
    return float(value)


@dataclass
class TenantProfile:
    name: str
    share: float = 1.0  # arrival-mix weight (relative)
    weight: float = 1.0  # DRF weight hint carried into matrix specs
    bands: Dict[str, float] = field(default_factory=lambda: {"normal": 1.0})


@dataclass
class SynthSpec:
    """Validated synthesizer parameters (see module docstring)."""

    name: str = "synth"
    seed: int = 0
    arrivals: int = 100_000
    horizon: float = 604_800.0  # one week of cluster life
    # gang-size distribution: lognormal {mu, sigma}, pareto {alpha,
    # minimum}, uniform {minimum, maximum}; all clamped to [1, maximum]
    gang_size: Dict = field(
        default_factory=lambda: {"dist": "lognormal", "mu": 1.1, "sigma": 0.9, "maximum": 64}
    )
    # lifetime seconds: lognormal {median, sigma}, uniform — clamped to
    # [minimum, maximum]
    lifetime: Dict = field(
        default_factory=lambda: {
            "dist": "lognormal",
            "median": 600.0,
            "sigma": 1.0,
            "minimum": 30.0,
            "maximum": 21_600.0,
        }
    )
    # intensity(t) = 1 + (peak_ratio - 1) * (1 - cos(2*pi*t/period))/2
    diurnal: Dict = field(
        default_factory=lambda: {"peak_ratio": 3.0, "period": 86_400.0}
    )
    tenants: List[TenantProfile] = field(default_factory=list)
    dynamic_fraction: float = 0.2
    instance_group: str = "batch-medium-priority"
    namespace: str = "default"

    @staticmethod
    def from_dict(d: Dict) -> "SynthSpec":
        if not isinstance(d, dict):
            raise SynthError(f"synth spec: expected an object, got {type(d).__name__}")
        unknown = set(d) - _KNOWN_KEYS
        if unknown:
            raise SynthError(
                f"synth spec: unknown keys {sorted(unknown)} (known: {sorted(_KNOWN_KEYS)})"
            )
        spec = SynthSpec(
            name=str(d.get("name", "synth")),
            seed=int(_require_number("synth.seed", d.get("seed", 0))),
            arrivals=int(_require_number("synth.arrivals", d.get("arrivals", 100_000), lo=1)),
            horizon=_require_number("synth.horizon", d.get("horizon", 604_800.0), lo=1.0),
            gang_size=dict(d.get("gang_size", SynthSpec().gang_size)),
            lifetime=dict(d.get("lifetime", SynthSpec().lifetime)),
            diurnal=dict(d.get("diurnal", SynthSpec().diurnal)),
            tenants=_parse_tenants(d.get("tenants", {})),
            dynamic_fraction=_require_number(
                "synth.dynamic_fraction", d.get("dynamic_fraction", 0.2), lo=0.0, hi=1.0
            ),
            instance_group=str(d.get("instance_group", "batch-medium-priority")),
            namespace=str(d.get("namespace", "default")),
        )
        spec.validate()
        return spec

    def validate(self) -> None:
        dist = self.gang_size.get("dist", "lognormal")
        if dist not in _GANG_DISTS:
            raise SynthError(
                f"synth.gang_size.dist: unknown distribution {dist!r} (known: {sorted(_GANG_DISTS)})"
            )
        _require_number("synth.gang_size.maximum", self.gang_size.get("maximum", 64), lo=1)
        if dist == "pareto":
            _require_number("synth.gang_size.alpha", self.gang_size.get("alpha", 1.5), lo=0.1)
        ldist = self.lifetime.get("dist", "lognormal")
        if ldist not in _LIFETIME_DISTS:
            raise SynthError(
                f"synth.lifetime.dist: unknown distribution {ldist!r} "
                f"(known: {sorted(_LIFETIME_DISTS)})"
            )
        lo = _require_number("synth.lifetime.minimum", self.lifetime.get("minimum", 30.0), lo=0.0)
        hi = _require_number("synth.lifetime.maximum", self.lifetime.get("maximum", 21_600.0))
        if hi < lo:
            raise SynthError(f"synth.lifetime: maximum {hi} < minimum {lo}")
        _require_number("synth.diurnal.peak_ratio", self.diurnal.get("peak_ratio", 3.0), lo=1.0)
        _require_number("synth.diurnal.period", self.diurnal.get("period", 86_400.0), lo=1.0)
        for t in self.tenants:
            if not t.bands:
                raise SynthError(f"synth.tenants.{t.name}: empty band profile")
            for band, w in t.bands.items():
                _require_number(f"synth.tenants.{t.name}.bands.{band}", w, lo=0.0)

    def drf_weights(self) -> Dict[str, float]:
        """The per-tenant DRF weight hints, for matrix-spec plumbing."""
        return {t.name: t.weight for t in self.tenants}


def _parse_tenants(block) -> List[TenantProfile]:
    if not isinstance(block, dict):
        raise SynthError(
            f"synth.tenants: expected an object of name -> profile, got {type(block).__name__}"
        )
    out: List[TenantProfile] = []
    for name in sorted(block):
        profile = block[name]
        if not isinstance(profile, dict):
            raise SynthError(f"synth.tenants.{name}: expected an object, got {profile!r}")
        unknown = set(profile) - {"share", "weight", "bands"}
        if unknown:
            raise SynthError(
                f"synth.tenants.{name}: unknown keys {sorted(unknown)} "
                "(known: ['bands', 'share', 'weight'])"
            )
        out.append(
            TenantProfile(
                name=name,
                share=_require_number(f"synth.tenants.{name}.share", profile.get("share", 1.0), lo=0.0),
                weight=_require_number(
                    f"synth.tenants.{name}.weight", profile.get("weight", 1.0), lo=0.0
                ),
                bands=dict(profile.get("bands", {"normal": 1.0})),
            )
        )
    return out


# -- draws ---------------------------------------------------------------------


def _draw_gang_size(rng: random.Random, cfg: Dict) -> int:
    dist = cfg.get("dist", "lognormal")
    cap = int(cfg.get("maximum", 64))
    if dist == "lognormal":
        raw = rng.lognormvariate(float(cfg.get("mu", 1.1)), float(cfg.get("sigma", 0.9)))
        size = 1 + int(raw)
    elif dist == "pareto":
        raw = float(cfg.get("minimum", 1)) * rng.paretovariate(float(cfg.get("alpha", 1.5)))
        size = max(1, int(raw))
    else:  # uniform
        size = rng.randint(int(cfg.get("minimum", 1)), cap)
    return min(size, cap)


def _draw_lifetime(rng: random.Random, cfg: Dict) -> float:
    dist = cfg.get("dist", "lognormal")
    lo = float(cfg.get("minimum", 30.0))
    hi = float(cfg.get("maximum", 21_600.0))
    if dist == "lognormal":
        raw = rng.lognormvariate(math.log(float(cfg.get("median", 600.0))), float(cfg.get("sigma", 1.0)))
    else:
        raw = rng.uniform(lo, hi)
    return round(min(max(raw, lo), hi), 3)


def _draw_arrivals(rng: random.Random, spec: SynthSpec) -> List[float]:
    """Exactly ``spec.arrivals`` instants with density proportional to
    the diurnal intensity (rejection sampling; acceptance >= 1/peak)."""
    peak = float(spec.diurnal.get("peak_ratio", 3.0))
    period = float(spec.diurnal.get("period", 86_400.0))
    horizon = spec.horizon
    out: List[float] = []
    if peak <= 1.0:
        out = [rng.uniform(0.0, horizon) for _ in range(spec.arrivals)]
    else:
        lam_max = peak
        two_pi = 2.0 * math.pi
        while len(out) < spec.arrivals:
            t = rng.uniform(0.0, horizon)
            lam_t = 1.0 + (peak - 1.0) * 0.5 * (1.0 - math.cos(two_pi * t / period))
            if rng.random() * lam_max <= lam_t:
                out.append(t)
    out.sort()
    return [round(t, 3) for t in out]


def synthesize(spec: SynthSpec, metrics=None) -> List[AppSpec]:
    """Generate the trace (see module docstring).  One rng, fixed draw
    order per app — the trace is a pure function of the spec."""
    rng = random.Random(spec.seed)
    arrivals = _draw_arrivals(rng, spec)
    tenants = spec.tenants or [TenantProfile(name="", share=1.0)]
    tenant_weights = [t.share for t in tenants]
    band_choices = {
        t.name: (sorted(t.bands), [t.bands[b] for b in sorted(t.bands)]) for t in tenants
    }
    apps: List[AppSpec] = []
    for i, t in enumerate(arrivals):
        tenant = rng.choices(tenants, weights=tenant_weights)[0]
        band_names, band_ws = band_choices[tenant.name]
        band = rng.choices(band_names, weights=band_ws)[0]
        count = _draw_gang_size(rng, spec.gang_size)
        dynamic = rng.random() < spec.dynamic_fraction
        min_count = rng.randint(max(1, count // 2), count) if dynamic else count
        sizes = rng.choice(_SIZE_MENU)
        apps.append(
            AppSpec(
                app_id=f"app-{i:06d}",
                arrival=t,
                executor_count=count,
                min_executor_count=min_count,
                dynamic=dynamic,
                lifetime=_draw_lifetime(rng, spec.lifetime),
                driver_cpu=sizes[0],
                driver_mem=sizes[1],
                executor_cpu=sizes[2],
                executor_mem=sizes[3],
                instance_group=spec.instance_group,
                namespace=spec.namespace,
                band=band,
                tenant=tenant.name,
            )
        )
    if metrics is not None:
        from ..metrics import names as mnames

        metrics.counter(mnames.LAB_TRACE_APPS, inc=float(len(apps)))
    return apps
