"""Gang lifecycle ledger + SLO burn-rate engine (ISSUE 16).

The ledger tracks every application through
submitted → … → completed/evicted/expired off the change feed and the
event log — never under the predicate lock; the SLO engine judges the
stream against declarative objectives with multi-window multi-burn-rate
alerting; the scorecard renders both into the one schema shared by
``GET /slo``, the sim runner, and the policy-regression CI gate.
"""

from .ledger import PHASES, TERMINAL, GangRecord, LifecycleLedger
from .scorecard import (
    SCHEMA_NAME,
    SCHEMA_VERSION,
    build_scorecard,
    scorecard_diff,
    scorecard_digest,
)
from .slo import DEFAULT_ALERT_POLICY, DEFAULT_OBJECTIVES, Objective, SloEngine

__all__ = [
    "PHASES",
    "TERMINAL",
    "GangRecord",
    "LifecycleLedger",
    "SCHEMA_NAME",
    "SCHEMA_VERSION",
    "build_scorecard",
    "scorecard_diff",
    "scorecard_digest",
    "DEFAULT_ALERT_POLICY",
    "DEFAULT_OBJECTIVES",
    "Objective",
    "SloEngine",
]
