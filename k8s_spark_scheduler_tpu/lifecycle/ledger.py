"""Gang lifecycle ledger: per-application state machine + drain loop.

Every Spark application is tracked through
``submitted → queued → solving → reserved → bound → running →
completed | evicted | expired`` with first-arrival timestamps per
phase, queue-wait and solve-tenure durations, eviction causes, and the
HA epochs it was observed under (epoch continuity across failover).

Feeding never happens under the predicate lock (the capacity-
observatory pattern, PR 7):

- informer handlers (pod add/update/delete, reservation add) run on
  API/informer threads and record phase transitions directly;
- everything that originates inside the predicate
  (``application_scheduled`` events, completed predicate traces,
  policy evictions) is drained by cursor off-thread: the background
  thread parks on wakeup Events attached to the EventLog and the
  tensor-mirror ChangeFeed, debounces, and pulls
  ``events_since``/``completed_since``/coordinator deltas.

``drain`` refuses to run while the calling thread holds the predicate
lock (``in_predicate_lock``), counting ``lock_violations`` — the
perf-guard structural check asserts the counter stays zero.  The sim
stops the thread and drives ``maybe_drain`` per event after quiesce,
so scenario scorecards are deterministic.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..capacity import in_predicate_lock

logger = logging.getLogger("k8s_spark_scheduler_tpu.lifecycle")

PHASES: Tuple[str, ...] = (
    "submitted",
    "queued",
    "solving",
    "reserved",
    "bound",
    "running",
    "completed",
    "evicted",
    "expired",
    # admission-gate shed: terminal for the Filter ATTEMPT (the request
    # answered fail-fast without a solve), but revivable — kube-scheduler
    # retries Pending pods, and the retry re-enters the lifecycle
    "shed",
)
TERMINAL = frozenset(("completed", "evicted", "expired", "shed"))
_PHASE_RANK = {p: i for i, p in enumerate(PHASES)}


@dataclass
class GangRecord:
    app_id: str
    namespace: str = ""
    driver_pod: str = ""
    instance_group: str = ""
    phase: str = "submitted"
    # first time each phase was reached (timesource — virtual in sim)
    phase_times: Dict[str, float] = field(default_factory=dict)
    min_executors: int = 0
    max_executors: int = 0
    executors_bound: int = 0
    queue_wait_s: Optional[float] = None
    solve_count: int = 0
    solve_tenure_s: float = 0.0
    eviction_cause: str = ""
    # most recent scheduling-request traces touching this gang
    trace_ids: List[str] = field(default_factory=list)
    # distinct HA epochs this gang was observed under, in order
    epochs: List[int] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "app": self.app_id,
            "namespace": self.namespace,
            "driverPod": self.driver_pod,
            "instanceGroup": self.instance_group,
            "phase": self.phase,
            "phaseTimes": {
                p: round(t, 6) for p, t in self.phase_times.items()
            },
            "minExecutors": self.min_executors,
            "maxExecutors": self.max_executors,
            "executorsBound": self.executors_bound,
            "queueWaitSeconds": (
                None
                if self.queue_wait_s is None
                else round(self.queue_wait_s, 6)
            ),
            "solveCount": self.solve_count,
            "solveTenureSeconds": round(self.solve_tenure_s, 6),
            "evictionCause": self.eviction_cause,
            "traceIds": list(self.trace_ids),
            "epochs": list(self.epochs),
        }


@guarded_by(
    "_lock",
    "_records",
    "_order",
    "_by_driver",
    "_stats",
    "_queue_waits",
    "_transitions",
)
class LifecycleLedger:
    """See module docstring.  Thread model: informer handlers and the
    drain path both take the ledger lock per transition; whole drains
    are serialized by ``_drain_mutex`` (never taken on a scheduling
    path)."""

    def __init__(
        self,
        event_log=None,
        tracer=None,
        feed=None,
        policy=None,
        slo=None,
        metrics=None,
        epoch_source: Optional[Callable[[], int]] = None,
        ring_size: int = 2048,
        debounce_seconds: float = 0.05,
        interval_seconds: float = 5.0,
    ):
        self._event_log = event_log
        self._tracer = tracer
        self._feed = feed
        self._policy = policy
        self._slo = slo
        self._metrics = metrics
        # attribute, re-pointed by wiring once the HA fence exists
        self.epoch_source = epoch_source
        self.ring_size = int(ring_size)
        self.debounce_seconds = float(debounce_seconds)
        self.interval_seconds = float(interval_seconds)

        self._lock = threading.Lock()
        # serializes whole drains (cursor reads → marks → evaluate):
        # the HTTP freshen path and the background thread may pass
        # maybe_drain's gate together
        self._drain_mutex = threading.Lock()
        self._records: Dict[str, GangRecord] = {}
        self._order: deque = deque()  # app ids, insertion order
        self._by_driver: Dict[str, str] = {}  # driver pod name → app id
        self._queue_waits: deque = deque(maxlen=ring_size)
        self._transitions = 0
        self._stats = {
            "drains": 0,
            "skipped_unchanged": 0,
            "lock_violations": 0,
            "epoch_regressions": 0,
        }

        # drain cursors
        self._event_seq = 0
        self._trace_cursor = 0
        self._evictions_seen = 0
        self._last_gate: Tuple = ()

        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for source in (event_log, feed):
            if source is not None and hasattr(source, "attach_wakeup"):
                source.attach_wakeup(self._wake)

    # -- wiring ---------------------------------------------------------------

    def wire_informers(self, pod_informer=None, rr_informer=None) -> None:
        """Register informer handlers (wiring time).  Handlers run on
        API/informer threads — never under the predicate lock."""
        from ..scheduler import labels as L

        if pod_informer is not None:
            pod_informer.add_event_handler(
                on_add=self._on_pod_add,
                on_update=self._on_pod_update,
                on_delete=self._on_pod_delete,
                filter_func=L.is_spark_scheduler_pod,
            )
        if rr_informer is not None:
            rr_informer.add_event_handler(on_add=self._on_reservation)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="lifecycle-ledger"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=5.0)

    def _loop(self) -> None:
        while not self._stop.is_set():
            fired = self._wake.wait(timeout=self.interval_seconds)
            if self._stop.is_set():
                return
            if fired:
                for source in (self._event_log, self._feed):
                    if source is not None and hasattr(source, "hb_channel"):
                        # observe side of the emit/publish→wakeup edge
                        racecheck.hb_observe(source.hb_channel())
                self._wake.clear()
                # debounce: one drain for a burst of emits
                if self.debounce_seconds > 0:
                    time.sleep(self.debounce_seconds)
                self._wake.clear()
            try:
                self.maybe_drain(trigger="feed" if fired else "interval")
            except Exception:
                logger.exception("lifecycle drain failed (diagnostic only)")

    # -- informer handlers (API threads; off the predicate lock) -------------

    def _on_pod_add(self, pod) -> None:
        from ..scheduler import labels as L

        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
        if not app_id:
            return
        role = pod.labels.get(L.SPARK_ROLE_LABEL, "")
        now = timesource.now()
        if role == L.DRIVER:
            with self._lock:
                record = self._record_locked(app_id, now)
                record.namespace = pod.namespace
                record.driver_pod = pod.name
                racecheck.note_access(self, "_by_driver")
                self._by_driver[pod.name] = app_id
                self._advance_locked(record, "queued", now)
            if pod.node_name:
                self._mark_bound(app_id, now)
        elif role == L.EXECUTOR and pod.node_name:
            self._mark_executor_bound(app_id, now)

    def _on_pod_update(self, old, new) -> None:
        from ..scheduler import labels as L

        if not L.on_pod_scheduled(old, new):
            return
        app_id = new.labels.get(L.SPARK_APP_ID_LABEL, "")
        if not app_id:
            return
        now = timesource.now()
        if new.labels.get(L.SPARK_ROLE_LABEL) == L.DRIVER:
            self._mark_bound(app_id, now)
        else:
            self._mark_executor_bound(app_id, now)

    def _on_pod_delete(self, pod) -> None:
        from ..scheduler import labels as L

        if pod.labels.get(L.SPARK_ROLE_LABEL) != L.DRIVER:
            return
        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
        if not app_id:
            return
        now = timesource.now()
        with self._lock:
            record = self._records.get(app_id)
            if record is None or record.phase in TERMINAL:
                return
            # a driver that dies after binding completed its run; one
            # that vanishes still queued expired.  Policy evictions are
            # re-marked with their cause at the next drain (the
            # coordinator's recent ring is authoritative).
            terminal = (
                "completed"
                if record.phase in ("bound", "running")
                else "expired"
            )
            self._advance_locked(record, terminal, now)

    def _on_reservation(self, rr) -> None:
        # ResourceReservation name == app id (reservations_manager)
        app_id = getattr(rr, "name", "")
        if not app_id:
            return
        now = timesource.now()
        with self._lock:
            record = self._records.get(app_id)
            if record is None:
                record = self._record_locked(app_id, now)
                record.namespace = getattr(rr, "namespace", "")
            self._advance_locked(record, "reserved", now)

    # -- transition plumbing --------------------------------------------------

    def _record_locked(self, app_id: str, now: float) -> GangRecord:
        record = self._records.get(app_id)
        if record is not None:
            return record
        racecheck.note_access(self, "_records")
        racecheck.note_access(self, "_order")
        record = GangRecord(app_id=app_id)
        record.phase_times["submitted"] = now
        self._records[app_id] = record  # schedlint: disable=LK001 -- _record_locked is only called with _lock held (see callers)
        self._order.append(app_id)  # schedlint: disable=LK001 -- _record_locked is only called with _lock held (see callers)
        while len(self._order) > self.ring_size:
            self._evict_one_locked()
        return record

    def _evict_one_locked(self) -> None:
        """Drop the oldest terminal record (or the oldest outright when
        every record is live) to bound memory."""
        for app_id in list(self._order):
            record = self._records.get(app_id)
            if record is None or record.phase in TERMINAL:
                self._order.remove(app_id)  # schedlint: disable=LK001 -- _evict_one_locked is only called with _lock held (see callers)
                if record is not None:
                    self._records.pop(app_id, None)  # schedlint: disable=LK001 -- _evict_one_locked is only called with _lock held (see callers)
                    self._by_driver.pop(record.driver_pod, None)  # schedlint: disable=LK001 -- _evict_one_locked is only called with _lock held (see callers)
                return
        app_id = self._order.popleft()
        record = self._records.pop(app_id, None)
        if record is not None:
            self._by_driver.pop(record.driver_pod, None)  # schedlint: disable=LK001 -- _evict_one_locked is only called with _lock held (see callers)

    def _advance_locked(
        self, record: GangRecord, phase: str, now: float, cause: str = ""
    ) -> bool:
        """Move ``record`` to ``phase`` if that is forward progress (or
        a terminal re-mark with a cause).  Stamps first-arrival time
        and the current HA epoch; returns True when a transition
        happened."""
        racecheck.note_access(self, "_transitions")
        current = record.phase
        if phase == current:
            return False
        re_terminal = phase in TERMINAL and bool(cause)
        # "shed" is the one escapable terminal: the gang was never
        # admitted, so a retried Filter revives it into the live phases
        revival = current == "shed" and phase not in TERMINAL
        if _PHASE_RANK[phase] < _PHASE_RANK[current] and not (
            re_terminal or revival
        ):
            # drains lag the informer path, so an earlier phase (e.g.
            # "solving" off the event log) can arrive after "bound" was
            # observed live — record its first-arrival time without
            # moving the state machine backwards
            if phase not in TERMINAL and current not in TERMINAL:
                record.phase_times.setdefault(phase, now)  # schedlint: disable=LK001 -- _advance_locked is only called with _lock held (see callers)
            return False
        if current in TERMINAL and not (re_terminal or revival):
            return False
        record.phase = phase
        record.phase_times.setdefault(phase, now)
        if cause:
            record.eviction_cause = cause
        self._stamp_epoch_locked(record)
        self._transitions += 1  # schedlint: disable=LK001 -- _advance_locked is only called with _lock held (see callers)
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(
                mnames.LIFECYCLE_TRANSITIONS,
                tags={mnames.TAG_PHASE: phase},
            )
        return True

    def _stamp_epoch_locked(self, record: GangRecord) -> None:
        if self.epoch_source is None:
            return
        try:
            epoch = int(self.epoch_source())
        except Exception:
            return
        if record.epochs and record.epochs[-1] == epoch:
            return
        if record.epochs and epoch < record.epochs[-1]:
            racecheck.note_access(self, "_stats")
            self._stats["epoch_regressions"] += 1  # schedlint: disable=LK001 -- _stamp_epoch_locked is only called with _lock held (see callers)
        record.epochs.append(epoch)

    def _mark_bound(self, app_id: str, now: float) -> None:
        with self._lock:
            record = self._records.get(app_id)
            if record is None:
                record = self._record_locked(app_id, now)
            if self._advance_locked(record, "bound", now):
                submitted = record.phase_times.get("submitted", now)
                record.queue_wait_s = max(0.0, now - submitted)
                racecheck.note_access(self, "_queue_waits")
                self._queue_waits.append(record.queue_wait_s)
                queue_wait = record.queue_wait_s
            else:
                queue_wait = None
            # a gang with no minimum (or already-satisfied minimum) is
            # running as soon as its driver binds
            if (
                record.phase == "bound"
                and record.executors_bound >= record.min_executors
            ):
                self._advance_locked(record, "running", now)
        if queue_wait is not None:
            if self._slo is not None:
                self._slo.observe("time_to_admit", queue_wait, t=now)
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.histogram(
                    mnames.LIFECYCLE_QUEUE_WAIT, queue_wait
                )

    def _mark_executor_bound(self, app_id: str, now: float) -> None:
        with self._lock:
            record = self._records.get(app_id)
            if record is None:
                return
            racecheck.note_access(self, "_records")
            record.executors_bound += 1
            if (
                record.phase == "bound"
                and record.executors_bound >= max(record.min_executors, 1)
            ):
                self._advance_locked(record, "running", now)

    def mark_shed(self, pod) -> None:
        """An AdmissionGate shed answered this gang's Filter without a
        solve — record the verdict so shed gangs are visible in the
        ledger instead of silently vanishing.  Terminal for the attempt
        only: kube-scheduler retries Pending pods, and the retry's next
        transition revives the record out of ``shed``."""
        from ..scheduler import labels as L

        app_id = pod.labels.get(L.SPARK_APP_ID_LABEL, "")
        if not app_id:
            return
        now = timesource.now()
        with self._lock:
            record = self._record_locked(app_id, now)
            if not record.namespace:
                record.namespace = pod.namespace
            if (
                pod.labels.get(L.SPARK_ROLE_LABEL) == L.DRIVER
                and not record.driver_pod
            ):
                record.driver_pod = pod.name
                racecheck.note_access(self, "_by_driver")
                self._by_driver[pod.name] = app_id
            self._advance_locked(record, "shed", now)

    # -- drain (cursor consumers; never under the predicate lock) -------------

    def _gate(self) -> Tuple:
        ev = self._event_log.seq if self._event_log is not None else 0
        tr = (
            self._tracer.completed_total
            if self._tracer is not None
            and hasattr(self._tracer, "completed_total")
            else 0
        )
        ev_total = 0
        coordinator = getattr(self._policy, "coordinator", None)
        if coordinator is not None:
            ev_total = coordinator.state()["evictionsTotal"]
        with self._lock:
            transitions = self._transitions
        return (ev, tr, ev_total, transitions)

    def maybe_drain(self, trigger: str = "feed") -> Optional[Dict[str, Any]]:
        """Drain iff any cursor source moved since the last drain —
        O(1) when nothing changed."""
        gate = self._gate()
        if gate == self._last_gate:
            with self._lock:
                racecheck.note_access(self, "_stats")
                self._stats["skipped_unchanged"] += 1
            return None
        return self.drain(trigger=trigger)

    def drain(self, trigger: str = "manual") -> Optional[Dict[str, Any]]:
        """Pull every cursor source forward and re-evaluate the SLOs.
        Refuses (and counts) when called while the predicate lock is
        held — the ledger must add zero work there."""
        if in_predicate_lock():
            with self._lock:
                racecheck.note_access(self, "_stats")
                self._stats["lock_violations"] += 1
            return None
        with self._drain_mutex:
            gate = self._gate()
            self._drain_events()
            self._drain_traces()
            self._drain_evictions()
            self._probe_fairness()
            now = timesource.now()
            if self._slo is not None:
                self._slo.evaluate(now=now)
            self._last_gate = gate
            with self._lock:
                racecheck.note_access(self, "_stats")
                self._stats["drains"] += 1
            if self._metrics is not None:
                self._publish_gauges()
        return self.summary()

    def _drain_events(self) -> None:
        if self._event_log is None:
            return
        from ..events import events as ev

        fresh, self._event_seq = self._event_log.events_since(
            self._event_seq
        )
        for event in fresh:
            if event.name != ev.APPLICATION_SCHEDULED:
                continue
            values = event.values
            app_id = values.get("sparkAppID", "")
            if not app_id:
                continue
            with self._lock:
                record = self._record_locked(app_id, event.timestamp)
                record.namespace = values.get(
                    "podNamespace", record.namespace
                )
                record.driver_pod = values.get("podName", record.driver_pod)
                record.instance_group = values.get(
                    "instanceGroup", record.instance_group
                )
                record.min_executors = int(values.get("minExecutorCount", 0))
                record.max_executors = int(values.get("maxExecutorCount", 0))
                racecheck.note_access(self, "_by_driver")
                if record.driver_pod:
                    self._by_driver[record.driver_pod] = app_id
                self._advance_locked(record, "solving", event.timestamp)
                if event.trace_id and event.trace_id not in record.trace_ids:
                    record.trace_ids.append(event.trace_id)
                    del record.trace_ids[:-8]

    def _drain_traces(self) -> None:
        if self._tracer is None or not hasattr(
            self._tracer, "completed_since"
        ):
            return
        fresh, self._trace_cursor = self._tracer.completed_since(
            self._trace_cursor
        )
        for trace in fresh:
            duration_s = trace.get("durationMs", 0.0) / 1000.0
            if self._slo is not None:
                self._slo.observe(
                    "filter_latency",
                    duration_s,
                    t=trace.get("startTime", 0.0) + duration_s,
                )
            pod = trace.get("root", {}).get("tags", {}).get("pod", "")
            if not pod:
                continue
            with self._lock:
                app_id = self._by_driver.get(pod)
                record = (
                    self._records.get(app_id) if app_id is not None else None
                )
                if record is None:
                    continue
                racecheck.note_access(self, "_records")
                record.solve_count += 1
                record.solve_tenure_s += duration_s
                trace_id = trace.get("traceId", "")
                if trace_id and trace_id not in record.trace_ids:
                    record.trace_ids.append(trace_id)
                    del record.trace_ids[:-8]
                solve_tenure = duration_s
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.histogram(
                    mnames.LIFECYCLE_SOLVE_TENURE, solve_tenure
                )

    def _drain_evictions(self) -> None:
        coordinator = getattr(self._policy, "coordinator", None)
        if coordinator is None:
            return
        st = coordinator.state()
        fresh = st["evictionsTotal"] - self._evictions_seen
        if fresh <= 0:
            return
        self._evictions_seen = st["evictionsTotal"]
        recent = st["recent"][-fresh:] if fresh <= len(st["recent"]) else st["recent"]
        for entry in recent:
            app_id = entry.get("app", "")
            if not app_id:
                continue
            cause = entry.get("reason", "") or "preempted"
            at = entry.get("at", timesource.now())
            with self._lock:
                record = self._records.get(app_id)
                if record is None:
                    record = self._record_locked(app_id, at)
                    record.namespace = entry.get("namespace", "")
                self._advance_locked(record, "evicted", at, cause=cause)
            if self._metrics is not None:
                from ..metrics import names as mnames

                self._metrics.counter(
                    mnames.LIFECYCLE_EVICTIONS,
                    tags={mnames.TAG_CAUSE: _cause_bucket(cause)},
                )

    def _probe_fairness(self) -> None:
        if self._slo is None:
            return
        drf = getattr(self._policy, "drf", None)
        if drf is None:
            return
        try:
            tenants = drf.state()
        except Exception:
            return
        if len(tenants) < 2:
            return
        shares = [info["dominantShare"] for info in tenants.values()]
        gap = max(shares) - min(shares)
        self._slo.observe("fairness_gap", gap)

    # -- read side ------------------------------------------------------------

    def record(self, app_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            record = self._records.get(app_id)
            return record.to_dict() if record is not None else None

    def records_brief(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {
                    "app": r.app_id,
                    "phase": r.phase,
                    "queueWaitSeconds": (
                        None
                        if r.queue_wait_s is None
                        else round(r.queue_wait_s, 6)
                    ),
                    "evictionCause": r.eviction_cause,
                }
                for r in (self._records[a] for a in self._order)
            ]

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            phase_counts = {p: 0 for p in PHASES}
            evictions_by_cause: Dict[str, int] = {}
            spanning = 0
            for record in self._records.values():
                phase_counts[record.phase] += 1
                if record.phase == "evicted":
                    bucket = _cause_bucket(record.eviction_cause)
                    evictions_by_cause[bucket] = (
                        evictions_by_cause.get(bucket, 0) + 1
                    )
                if len(record.epochs) > 1:
                    spanning += 1
            waits = sorted(self._queue_waits)
            stats = dict(self._stats)
            transitions = self._transitions
            total = len(self._records)
        return {
            "gangs": total,
            "phases": {p: c for p, c in phase_counts.items() if c},
            "transitions": transitions,
            "queueWait": {
                "count": len(waits),
                "p50": _pct(waits, 0.50),
                "p95": _pct(waits, 0.95),
                "p99": _pct(waits, 0.99),
            },
            "evictionsByCause": evictions_by_cause,
            "epochContinuity": {
                "gangsSpanningEpochs": spanning,
                "epochRegressions": stats["epoch_regressions"],
            },
            "drains": stats["drains"],
            "lockViolations": stats["lock_violations"],
        }

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    @property
    def lock_violations(self) -> int:
        with self._lock:
            return self._stats["lock_violations"]

    def _publish_gauges(self) -> None:
        from ..metrics import names as mnames

        with self._lock:
            phase_counts: Dict[str, int] = {}
            for record in self._records.values():
                phase_counts[record.phase] = (
                    phase_counts.get(record.phase, 0) + 1
                )
        for phase in PHASES:
            self._metrics.gauge(
                mnames.LIFECYCLE_GANGS,
                float(phase_counts.get(phase, 0)),
                {mnames.TAG_PHASE: phase},
            )


def _pct(sorted_values: List[float], q: float) -> Optional[float]:
    if not sorted_values:
        return None
    idx = min(
        len(sorted_values) - 1, max(0, int(q * len(sorted_values) + 0.5) - 1)
    )
    return round(sorted_values[idx], 6)


def _cause_bucket(cause: str) -> str:
    """Collapse free-text eviction reasons to a bounded tag set."""
    text = (cause or "").lower()
    if "replay" in text:
        return "replayed"
    if "preempt" in text or "band" in text:
        return "preempted"
    if "share" in text or "drf" in text or "fair" in text:
        return "fair-share"
    return "other" if text else "unknown"
