"""Scorecard: the one schema judged by operators, the sim, and CI.

``build_scorecard`` renders the same JSON document from a live server
(``GET /slo``) and from a sim scenario run (``summary["slo"]`` /
``scorecard.json``), so dashboards and the policy-regression gate
never fork on source.  ``scorecard_digest`` hashes the deterministic
subset — schema, objective outcomes, lifecycle counts — with floats
rounded and the free-form ``meta`` block excluded, so a sim scenario
re-run yields a byte-identical digest and a policy change that shifts
any outcome shows up as a digest mismatch in CI.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional

from .. import timesource

SCHEMA_NAME = "tpu-gang-scheduler-scorecard"
SCHEMA_VERSION = 1

# operational health counters in the lifecycle summary: how often the
# drain loop ran, not what the scheduler decided.  They depend on thread
# timing (a background drain racing shutdown shifts them by one), so the
# policy digest excludes them — they stay visible in the document
_OPERATIONAL_LIFECYCLE_KEYS = ("drains", "lockViolations")


def _digest_lifecycle(lifecycle: Any) -> Any:
    if not isinstance(lifecycle, dict):
        return lifecycle
    return {
        k: v
        for k, v in lifecycle.items()
        if k not in _OPERATIONAL_LIFECYCLE_KEYS
    }


def build_scorecard(
    ledger,
    slo,
    meta: Optional[Dict[str, Any]] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """One scorecard document.  ``meta`` (source, scenario, seed,
    asOf…) is display-only and excluded from the digest."""
    now = timesource.now() if now is None else now
    card: Dict[str, Any] = {
        "schema": {"name": SCHEMA_NAME, "version": SCHEMA_VERSION},
        "meta": dict(meta or {}),
        "objectives": slo.status(now=now) if slo is not None else {},
        "lifecycle": ledger.summary() if ledger is not None else {},
    }
    card["digest"] = scorecard_digest(card)
    return card


def scorecard_digest(card: Dict[str, Any]) -> str:
    """sha256 over the canonical deterministic subset of a scorecard
    (everything except ``meta`` and the digest itself)."""
    body = {
        "schema": card.get("schema", {}),
        "objectives": card.get("objectives", {}),
        "lifecycle": _digest_lifecycle(card.get("lifecycle", {})),
    }
    canonical = json.dumps(
        _canonical(body), sort_keys=True, separators=(",", ":")
    )
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest()


def scorecard_diff(a: Dict[str, Any], b: Dict[str, Any]) -> list:
    """Leaf-level differences between two scorecards' digested bodies:
    ``(path, a_value, b_value)`` tuples, for actionable gate output."""
    out: list = []
    _walk_diff(
        {
            "schema": a.get("schema"),
            "objectives": a.get("objectives"),
            "lifecycle": _digest_lifecycle(a.get("lifecycle")),
        },
        {
            "schema": b.get("schema"),
            "objectives": b.get("objectives"),
            "lifecycle": _digest_lifecycle(b.get("lifecycle")),
        },
        "",
        out,
    )
    return out


def _walk_diff(a: Any, b: Any, path: str, out: list) -> None:
    # a whole nested block added/removed on one side: descend so every
    # sub-leaf is reported against "<absent>" (actionable paths), rather
    # than one opaque dict-valued tuple
    if a == "<absent>" and isinstance(b, dict) and b:
        a = {}
    if b == "<absent>" and isinstance(a, dict) and a:
        b = {}
    if isinstance(a, dict) and isinstance(b, dict):
        for key in sorted(set(a) | set(b)):
            _walk_diff(
                a.get(key, "<absent>"),
                b.get(key, "<absent>"),
                f"{path}.{key}" if path else str(key),
                out,
            )
        return
    if _canonical(a) != _canonical(b):
        out.append((path, a, b))


def _canonical(value: Any) -> Any:
    """Round floats (exposition noise must not churn digests) and
    normalize containers for stable JSON."""
    if isinstance(value, float):
        return round(value, 9)
    if isinstance(value, dict):
        return {str(k): _canonical(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    return value
