"""SLO objectives with multi-window multi-burn-rate evaluation.

Four declarative objectives judge the scheduler end to end (the Borg
operator-facing truths: wait time, latency, eviction waste, fairness):

- ``time_to_admit``   — gang queue wait (submitted → bound) within
  threshold for ``target`` of admissions.
- ``filter_latency``  — scheduling-request root-span duration within
  threshold for ``target`` of requests.
- ``eviction_waste``  — scheduling-waste samples (WasteMetricsReporter
  is the single source of truth) within threshold for ``target`` of
  samples.
- ``fairness_gap``    — per-drain DRF probe: dominant-share spread
  across tenants within threshold for ``target`` of probes.

Every objective is a good/bad event stream; burn rate over a window is
``bad_fraction(window) / (1 - target)`` — Google-SRE multi-window
multi-burn-rate alerting pages when burn ≥ 14.4 over BOTH the 1 h and
5 m windows, tickets when burn ≥ 6 over both 6 h and 30 m.  Windows
scale by ``window_scale`` so short virtual sim timelines can compress
the policy without changing the algebra.

Timestamps flow through ``timesource.now()``: virtual in the sim, so a
scenario's burn rates (and the scorecard digest over them) are
deterministic.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import timesource
from ..analysis import racecheck
from ..analysis.guarded import guarded_by

# (state, long_window_s, short_window_s, burn_threshold) — evaluated in
# order, first match wins (page dominates warn)
DEFAULT_ALERT_POLICY: Tuple[Tuple[str, float, float, float], ...] = (
    ("page", 3600.0, 300.0, 14.4),
    ("warn", 21600.0, 1800.0, 6.0),
)

# objective name → (target, threshold, unit, description)
DEFAULT_OBJECTIVES: Tuple[Tuple[str, float, float, str, str], ...] = (
    (
        "time_to_admit",
        0.99,
        300.0,
        "seconds",
        "gang queue wait submitted->bound within threshold",
    ),
    (
        "filter_latency",
        0.99,
        0.1,
        "seconds",
        "scheduling-request root span duration within threshold",
    ),
    (
        "eviction_waste",
        0.95,
        60.0,
        "seconds",
        "scheduling-waste sample duration within threshold",
    ),
    (
        "fairness_gap",
        0.95,
        0.25,
        "dominant-share fraction",
        "DRF dominant-share spread across tenants within threshold",
    ),
)

_STATE_RANK = {"ok": 0, "warn": 1, "page": 2}


class Objective:
    """One good/bad event stream plus its target.  Not thread-safe on
    its own — the engine's lock serializes all access."""

    __slots__ = (
        "name",
        "target",
        "threshold",
        "unit",
        "description",
        "samples",
        "good_total",
        "bad_total",
    )

    def __init__(
        self,
        name: str,
        target: float,
        threshold: float,
        unit: str = "",
        description: str = "",
        sample_cap: int = 4096,
    ):
        self.name = name
        self.target = float(target)
        self.threshold = float(threshold)
        self.unit = unit
        self.description = description
        # (timestamp, good) — bounded; windows far exceeding the cap
        # degrade to the retained tail, never to unbounded memory
        self.samples: deque = deque(maxlen=sample_cap)
        self.good_total = 0
        self.bad_total = 0

    def observe(self, t: float, good: bool) -> None:
        self.samples.append((t, bool(good)))
        if good:
            self.good_total += 1
        else:
            self.bad_total += 1

    def bad_fraction(self, now: float, window: float) -> Optional[float]:
        """Fraction of bad samples in [now - window, now], or None when
        the window holds no samples (no data is not an alert)."""
        lo = now - window
        good = bad = 0
        for t, ok in reversed(self.samples):
            if t < lo:
                break
            if t > now:
                continue
            if ok:
                good += 1
            else:
                bad += 1
        total = good + bad
        if total == 0:
            return None
        return bad / total

    def burn_rate(self, now: float, window: float) -> Optional[float]:
        frac = self.bad_fraction(now, window)
        if frac is None:
            return None
        budget = 1.0 - self.target
        if budget <= 0.0:
            return float("inf") if frac > 0 else 0.0
        return frac / budget


@guarded_by("_lock", "_objectives", "_alert_tag", "_evaluations")
class SloEngine:
    """Objective registry + burn-rate evaluator + alert-tag source.

    ``observe``/``waste_sample`` may be called from informer threads,
    the waste reporter, or the ledger drain; ``evaluate`` runs at drain
    time and precomputes ``alert_tag`` so the extender's decision-trace
    tagging is one attribute read — never a burn-rate computation under
    the predicate lock.
    """

    def __init__(
        self,
        metrics=None,
        window_scale: float = 1.0,
        sample_cap: int = 4096,
        overrides: Optional[Dict[str, Dict[str, float]]] = None,
    ):
        self._lock = threading.Lock()
        self._metrics = metrics
        self.window_scale = float(window_scale) if window_scale > 0 else 1.0
        self._objectives: Dict[str, Objective] = {}
        for name, target, threshold, unit, desc in DEFAULT_OBJECTIVES:
            ov = (overrides or {}).get(name, {})
            self._objectives[name] = Objective(
                name,
                float(ov.get("target", target)),
                float(ov.get("threshold", threshold)),
                unit,
                desc,
                sample_cap=sample_cap,
            )
        self._evaluations = 0
        # precomputed at evaluate(): "" when every objective is ok,
        # else "obj:state,..." — the extender reads this one attribute
        self._alert_tag = ""

    # -- ingest ---------------------------------------------------------------

    def observe(
        self,
        objective: str,
        value: float,
        good: Optional[bool] = None,
        t: Optional[float] = None,
    ) -> None:
        """Record one sample.  ``good`` defaults to value ≤ threshold."""
        with self._lock:
            obj = self._objectives.get(objective)
            if obj is None:
                return
            racecheck.note_access(self, "_objectives")
            if good is None:
                good = value <= obj.threshold
            obj.observe(timesource.now() if t is None else t, good)
        if self._metrics is not None:
            from ..metrics import names as mnames

            self._metrics.counter(
                mnames.SLO_EVENTS,
                tags={
                    mnames.TAG_OBJECTIVE: objective,
                    mnames.TAG_OUTCOME: "good" if good else "bad",
                },
            )

    def waste_sample(
        self, waste_type: str, duration: float, t: Optional[float] = None
    ) -> None:
        """Sink for WasteMetricsReporter (the single source of truth
        for eviction-waste): one waste phase measurement becomes one
        eviction_waste sample."""
        del waste_type  # classification lives in the waste metrics
        self.observe("eviction_waste", float(duration), t=t)

    # -- evaluation -----------------------------------------------------------

    def _status_locked(self, now: float) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for name, obj in self._objectives.items():
            windows: Dict[str, Any] = {}
            state = "ok"
            for st, long_w, short_w, burn in DEFAULT_ALERT_POLICY:
                long_s = long_w * self.window_scale
                short_s = short_w * self.window_scale
                b_long = obj.burn_rate(now, long_s)
                b_short = obj.burn_rate(now, short_s)
                windows[st] = {
                    "longWindowSeconds": long_s,
                    "shortWindowSeconds": short_s,
                    "burnThreshold": burn,
                    "longBurnRate": _round(b_long),
                    "shortBurnRate": _round(b_short),
                }
                if (
                    state == "ok"
                    and b_long is not None
                    and b_short is not None
                    and b_long >= burn
                    and b_short >= burn
                ):
                    state = st
            # budget remaining over the long ticket window: 1 - burn
            budget_window = DEFAULT_ALERT_POLICY[-1][1] * self.window_scale
            burn = obj.burn_rate(now, budget_window)
            budget_remaining = None if burn is None else max(0.0, 1.0 - burn)
            out[name] = {
                "target": obj.target,
                "threshold": obj.threshold,
                "unit": obj.unit,
                "description": obj.description,
                "good": obj.good_total,
                "bad": obj.bad_total,
                "total": obj.good_total + obj.bad_total,
                "state": state,
                "budgetRemaining": _round(budget_remaining),
                "windows": windows,
            }
        return out

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Recompute every objective's burn state, refresh gauges and
        the precomputed alert tag, and return the status dict."""
        now = timesource.now() if now is None else now
        with self._lock:
            racecheck.note_access(self, "_evaluations")
            racecheck.note_access(self, "_alert_tag")
            status = self._status_locked(now)
            self._evaluations += 1
            alerting = [
                f"{name}:{s['state']}"
                for name, s in status.items()
                if s["state"] != "ok"
            ]
            alerting.sort(
                key=lambda item: -_STATE_RANK.get(item.split(":")[1], 0)
            )
            self._alert_tag = ",".join(alerting)
        if self._metrics is not None:
            self._publish(status)
        return status

    def status(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Per-objective burn-rate status (no gauge side effects)."""
        now = timesource.now() if now is None else now
        with self._lock:
            return self._status_locked(now)

    @property
    def alert_tag(self) -> str:
        """Precomputed at evaluate(): O(1) read for decision tracing."""
        with self._lock:
            return self._alert_tag

    @property
    def evaluations(self) -> int:
        with self._lock:
            return self._evaluations

    def objective_names(self) -> List[str]:
        with self._lock:
            return list(self._objectives)

    def _publish(self, status: Dict[str, Any]) -> None:
        from ..metrics import names as mnames

        for name, s in status.items():
            tags = {mnames.TAG_OBJECTIVE: name}
            self._metrics.gauge(
                mnames.SLO_STATE, float(_STATE_RANK[s["state"]]), tags
            )
            if s["budgetRemaining"] is not None:
                self._metrics.gauge(
                    mnames.SLO_BUDGET_REMAINING, s["budgetRemaining"], tags
                )
            for window_name, w in s["windows"].items():
                for side in ("long", "short"):
                    rate = w[f"{side}BurnRate"]
                    if rate is None:
                        continue
                    self._metrics.gauge(
                        mnames.SLO_BURN_RATE,
                        rate,
                        {
                            mnames.TAG_OBJECTIVE: name,
                            mnames.TAG_WINDOW: f"{window_name}-{side}",
                        },
                    )


def _round(value: Optional[float], digits: int = 6) -> Optional[float]:
    if value is None:
        return None
    # clamp the zero-budget sentinel: scorecards must stay valid JSON
    value = min(float(value), 1e9)
    return round(value, digits)
