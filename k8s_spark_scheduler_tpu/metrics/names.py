"""Metric name catalog (reference internal/metrics/metrics.go:30-68)."""

REQUEST_COUNTER = "foundry.spark.scheduler.requests"
SCHEDULING_PROCESSING_TIME = "foundry.spark.scheduler.schedule.time"
RECONCILIATION_TIME = "foundry.spark.scheduler.reconciliation.time"
SCHEDULING_WAIT_TIME = "foundry.spark.scheduler.wait.time"
SCHEDULING_RETRY_TIME = "foundry.spark.scheduler.retry.time"
RESOURCE_USAGE_CPU = "foundry.spark.scheduler.resource.usage.cpu"
RESOURCE_USAGE_MEMORY = "foundry.spark.scheduler.resource.usage.memory"
RESOURCE_USAGE_NVIDIA_GPUS = "foundry.spark.scheduler.resource.usage.nvidia.com/gpu"
LIFECYCLE_AGE_MAX = "foundry.spark.scheduler.pod.lifecycle.max"
LIFECYCLE_AGE_P95 = "foundry.spark.scheduler.pod.lifecycle.p95"
LIFECYCLE_AGE_P50 = "foundry.spark.scheduler.pod.lifecycle.p50"
LIFECYCLE_COUNT = "foundry.spark.scheduler.pod.lifecycle.count"
SINGLE_AZ_DA_PACK_FAILURE_COUNT = (
    "foundry.spark.scheduler.singleazdynamicallocationpackfailure.count"
)
CROSS_AZ_TRAFFIC = "foundry.spark.scheduler.az.cross.traffic"
CROSS_AZ_TRAFFIC_MEAN = "foundry.spark.scheduler.az.cross.traffic.mean"
TOTAL_TRAFFIC = "foundry.spark.scheduler.total.traffic"
TOTAL_TRAFFIC_MEAN = "foundry.spark.scheduler.total.traffic.mean"
APPLICATION_ZONES_COUNT = "foundry.spark.scheduler.application.zones.count"
CLIENT_REQUEST_LATENCY = "foundry.spark.scheduler.client.request.latency"
CLIENT_REQUEST_RESULT = "foundry.spark.scheduler.client.request.result"
CACHED_OBJECT_COUNT = "foundry.spark.scheduler.cache.objects.count"
# cache-vs-API-server divergence (reporters.report_cache_drift)
CACHED_OBJECT_DRIFT = "foundry.spark.scheduler.cache.objects.count.drift"
INFLIGHT_REQUEST_COUNT = "foundry.spark.scheduler.cache.inflight.count"
UNBOUND_CPU_RESERVATIONS = "foundry.spark.scheduler.reservations.unbound.cpu"
UNBOUND_MEMORY_RESERVATIONS = "foundry.spark.scheduler.reservations.unbound.memory"
UNBOUND_NVIDIA_GPU_RESERVATIONS = "foundry.spark.scheduler.reservations.unbound.nvidiagpu"
TIME_TO_FIRST_BIND = "foundry.spark.scheduler.reservations.timetofirstbind"
TIME_TO_FIRST_BIND_MEDIAN = "foundry.spark.scheduler.reservations.timetofirstbind.median"
TIME_TO_FIRST_BIND_MEAN = "foundry.spark.scheduler.reservations.timetofirstbind.mean"
SOFT_RESERVATION_COUNT = "foundry.spark.scheduler.softreservation.count"
SOFT_RESERVATION_EXECUTOR_COUNT = "foundry.spark.scheduler.softreservation.executorcount"
EXECUTORS_WITH_NO_RESERVATION_COUNT = (
    "foundry.spark.scheduler.softreservation.executorswithnoreservations"
)
SOFT_RESERVATION_COMPACTION_TIME = "foundry.spark.scheduler.softreservation.compaction.time"
POD_INFORMER_DELAY = "foundry.spark.scheduler.informer.delay"
POD_INFORMER_DELAY_MAX = "foundry.spark.scheduler.informer.delay.max"
SCHEDULING_WASTE = "foundry.spark.scheduler.scheduling.waste"
SCHEDULING_WASTE_PER_INSTANCE_GROUP = (
    "foundry.spark.scheduler.scheduling.wasteperinstancegroup"
)
INITIAL_DRIVER_EXECUTOR_COLLOCATION = (
    "foundry.spark.scheduler.scheduling.initialdriverexecutorcollocation"
)
INITIAL_EXECUTORS_PER_NODE = "foundry.spark.scheduler.scheduling.initialexecutorspernode"
INITIAL_NODE_COUNT = "foundry.spark.scheduler.scheduling.initialnodecount"
PACKING_EFFICIENCY = "foundry.spark.scheduler.packing.efficiency"
ASYNC_CLIENT_REQUEST = "foundry.spark.scheduler.async.request.count"
ASYNC_CLIENT_RETRIES = "foundry.spark.scheduler.async.request.retries.count"
ASYNC_CLIENT_DROPPED = "foundry.spark.scheduler.async.request.dropped.count"

# kernel profiling (tracing/profiling.py): per-dispatch jit compile vs
# execute split for the solver kernels, tagged kernel= and lane=
KERNEL_COMPILE_TIME = "foundry.spark.scheduler.tpu.kernel.compile.time"
KERNEL_EXECUTE_TIME = "foundry.spark.scheduler.tpu.kernel.execute.time"
KERNEL_CACHE_HITS = "foundry.spark.scheduler.tpu.kernel.cache.hit.count"
KERNEL_CACHE_MISSES = "foundry.spark.scheduler.tpu.kernel.cache.miss.count"
KERNEL_JIT_CACHE_SIZE = "foundry.spark.scheduler.tpu.kernel.jit.cache.size"
# per-span duration distributions (tracing/spans.py), tagged span=
TRACE_SPAN_TIME = "foundry.spark.scheduler.trace.span.time"

# resilience layer (resilience/): overload protection + degraded mode
RESILIENCE_SHED_COUNT = "foundry.spark.scheduler.resilience.shed.count"
RESILIENCE_DEADLINE_EXPIRED_COUNT = (
    "foundry.spark.scheduler.resilience.deadline.expired.count"
)
RESILIENCE_BREAKER_STATE = "foundry.spark.scheduler.resilience.breaker.state"
RESILIENCE_BREAKER_TRANSITIONS = (
    "foundry.spark.scheduler.resilience.breaker.transitions.count"
)
RESILIENCE_JOURNAL_DEPTH = "foundry.spark.scheduler.resilience.journal.depth"
RESILIENCE_JOURNAL_APPENDED = (
    "foundry.spark.scheduler.resilience.journal.appended.count"
)
RESILIENCE_JOURNAL_REPLAYED = (
    "foundry.spark.scheduler.resilience.journal.replayed.count"
)
RESILIENCE_LANE_DEMOTIONS = "foundry.spark.scheduler.resilience.lane.demotion.count"
RESILIENCE_LANE_STATE = "foundry.spark.scheduler.resilience.lane.state"
RESILIENCE_HEALTH_STATE = "foundry.spark.scheduler.resilience.health.state"
RESILIENCE_GATE_INFLIGHT = "foundry.spark.scheduler.resilience.gate.inflight"

# delta-solve engine (ops/deltasolve.py): persistent native solver
# sessions + prefix-feasibility reuse for the earlier-drivers-fit loop
DELTASOLVE_WARM_HITS = "foundry.spark.scheduler.tpu.deltasolve.warm.hit.count"
DELTASOLVE_WARM_MISSES = "foundry.spark.scheduler.tpu.deltasolve.warm.miss.count"
DELTASOLVE_RESUME_DEPTH = "foundry.spark.scheduler.tpu.deltasolve.resume.depth"
DELTASOLVE_SESSIONS = "foundry.spark.scheduler.tpu.deltasolve.sessions"
DELTASOLVE_SESSION_BYTES = "foundry.spark.scheduler.tpu.deltasolve.session.bytes"

# node-name interning + uniform-failure response cache (types/serde.py)
SERDE_INTERN_HITS = "foundry.spark.scheduler.serde.names.intern.hit.count"
SERDE_INTERN_MISSES = "foundry.spark.scheduler.serde.names.intern.miss.count"

# decision provenance (provenance/): unschedulability explainer,
# shortfall telemetry, anomaly flight recorder
# per-dimension cluster shortfall (executors short when that dimension
# alone were the constraint), tagged dim=cpu|memory|nvidia.com/gpu
PROVENANCE_SHORTFALL = "foundry.spark.scheduler.tpu.provenance.shortfall"
# blocker-set size distribution of explained refusals
PROVENANCE_BLOCKERS = "foundry.spark.scheduler.tpu.provenance.blockers"
# explain invocations, tagged source=refusal|refusal-cached|http|debug
PROVENANCE_EXPLAIN_COUNT = (
    "foundry.spark.scheduler.tpu.provenance.explain.count"
)
# decision-record ring depth
PROVENANCE_RECORDS = "foundry.spark.scheduler.tpu.provenance.records"
# flight-recorder persists, tagged trigger=; bytes of the last bundle file
PROVENANCE_BUNDLE_PERSISTED = (
    "foundry.spark.scheduler.tpu.provenance.bundle.persisted.count"
)
PROVENANCE_BUNDLE_BYTES = (
    "foundry.spark.scheduler.tpu.provenance.bundle.bytes"
)
# warm≠cold parity guard outcomes, tagged result=ok|mismatch
PROVENANCE_PARITY_CHECKS = (
    "foundry.spark.scheduler.tpu.provenance.parity.check.count"
)

# extender-emitted placement / lane diagnostics (previously inline
# literals in scheduler/extender.py; declared here so the catalog drift
# check in tests/test_metric_names.py covers them)
TPU_FASTPATH = "foundry.spark.scheduler.tpu.fastpath"
SINGLEAZ_LANE = "foundry.spark.scheduler.tpu.singleaz.lane"
PACKING_EFFICIENCY_MAX = "foundry.spark.scheduler.packing.efficiency.max"
DRIVER_EXECUTOR_COLLOCATION = "foundry.spark.scheduler.driver.executor.collocation"
EXECUTOR_NODE_COUNT = "foundry.spark.scheduler.executor.node.count"
APP_CROSS_ZONE = "foundry.spark.scheduler.app.cross.zone"
# zone-tagged single-AZ DA pack-failure counter the reschedule path
# emits (distinct wire name from the reference's untagged
# SINGLE_AZ_DA_PACK_FAILURE_COUNT; both are pinned)
SINGLE_AZ_DA_PACK_FAILURE_ZONED = (
    "foundry.spark.scheduler.single.az.dynamic.allocation.pack.failure"
)

# capacity observatory (capacity/): native fragmentation/headroom
# analytics, queue-pressure forecasts, and the /state/capacity timeline
# per-dim total free capacity over schedulable nodes (base units)
CAPACITY_FREE = "foundry.spark.scheduler.tpu.capacity.free"
# per-dim largest single-node free chunk (base units)
CAPACITY_LARGEST_CHUNK = "foundry.spark.scheduler.tpu.capacity.largest.chunk"
# per-dim fragmentation index: 1 − largest-chunk/total-free
CAPACITY_FRAGMENTATION = "foundry.spark.scheduler.tpu.capacity.fragmentation"
# largest admissible gang per (shape, instance-group, zone); empty
# group/zone tags = cluster-wide
CAPACITY_HEADROOM = "foundry.spark.scheduler.tpu.capacity.headroom"
# per-instance-group max-dimension reserved/allocatable ratio
CAPACITY_UTILIZATION = "foundry.spark.scheduler.tpu.capacity.utilization"
# pending driver gangs / the subset that does not fit right now
CAPACITY_QUEUED_GANGS = "foundry.spark.scheduler.tpu.capacity.queued.gangs"
CAPACITY_QUEUE_PRESSURE = (
    "foundry.spark.scheduler.tpu.capacity.queue.pressure"
)
# forecast seconds until a fitting queued gang admits
CAPACITY_TIME_TO_ADMIT = "foundry.spark.scheduler.tpu.capacity.time.to.admit"
# sampler self-observability
CAPACITY_SAMPLE_COUNT = "foundry.spark.scheduler.tpu.capacity.sample.count"
CAPACITY_SAMPLE_TIME = "foundry.spark.scheduler.tpu.capacity.sample.time"
CAPACITY_PROBE_SOLVES = "foundry.spark.scheduler.tpu.capacity.probe.solves"

# contention observatory (contention/): lock wait/hold telemetry and
# per-request critical-path decomposition
# time blocked in acquire, per lock site (seconds; histogram)
LOCK_WAIT_TIME = "foundry.spark.scheduler.tpu.lock.wait.time"
# time the lock was held, tagged with the holder's span phase
LOCK_HOLD_TIME = "foundry.spark.scheduler.tpu.lock.hold.time"
# cumulative acquires / contended acquires per lock site (gauges)
LOCK_ACQUIRE_COUNT = "foundry.spark.scheduler.tpu.lock.acquire.count"
LOCK_CONTENDED_COUNT = "foundry.spark.scheduler.tpu.lock.contended.count"
# cumulative wait seconds charged to the phase that HELD the lock
# (tagged lock=, holder=): the top-blocker table as a metric
LOCK_BLOCKED_SECONDS = "foundry.spark.scheduler.tpu.lock.blocked.seconds"
# per-request latency attributed to one named segment (seconds,
# tagged segment=gate-queue|lock-wait|serde|solve|write-back|other)
CRITICALPATH_SEGMENT_TIME = (
    "foundry.spark.scheduler.tpu.criticalpath.segment.time"
)
# fraction of each request attributed to a named (non-other) segment
CRITICALPATH_COVERAGE = "foundry.spark.scheduler.tpu.criticalpath.coverage"
# requests whose largest segment was <segment>
CRITICALPATH_DOMINANT_COUNT = (
    "foundry.spark.scheduler.tpu.criticalpath.dominant.count"
)

# metrics-registry self-observability: per-metric label-set cardinality
# (tagged metric=<catalog name>) — catches label explosions before
# Prometheus does
METRICS_REGISTRY_SERIES = (
    "foundry.spark.scheduler.tpu.metrics.registry.series"
)

# HA failover fabric (ha/): lease-fenced multi-replica operation
# 1 while this replica holds the lease, 0 as follower
HA_LEADER_STATE = "foundry.spark.scheduler.tpu.ha.leader.state"
# the fencing epoch this replica holds (0 = never elected)
HA_EPOCH = "foundry.spark.scheduler.tpu.ha.epoch"
# leadership transitions, tagged to=leader|follower
HA_TRANSITIONS = "foundry.spark.scheduler.tpu.ha.transitions.count"
# fenced writes refused with StaleEpochError, tagged op=
HA_FENCE_REFUSALS = "foundry.spark.scheduler.tpu.ha.fence.refused.count"
# writes that committed while a newer epoch was observed — ALWAYS 0
# (the I-H3 invariant witness; any nonzero value is a split-brain bug)
HA_FENCE_STALE_COMMITS = (
    "foundry.spark.scheduler.tpu.ha.fence.stale.commit.count"
)
# takeover reconciliation wall time (seconds)
HA_RECONCILE_TIME = "foundry.spark.scheduler.tpu.ha.reconcile.time"
# repairs applied by the takeover reconciler, tagged class=
HA_RECONCILE_REPAIRS = (
    "foundry.spark.scheduler.tpu.ha.reconcile.repairs.count"
)

# kube write-conflict discipline (kube/conflict.py): 409s resolved by
# the unified get-refresh-resourceVersion-retry helper, tagged kind=
KUBE_CONFLICT_RETRIES = (
    "foundry.spark.scheduler.tpu.kube.conflict.retry.count"
)

# journal hardening (resilience/journal.py)
# background compactions triggered by the acked-fraction threshold
RESILIENCE_JOURNAL_COMPACTIONS = (
    "foundry.spark.scheduler.resilience.journal.compaction.count"
)
# torn tails truncated at recovery (bad CRC / partial final records)
RESILIENCE_JOURNAL_TORN_TAIL = (
    "foundry.spark.scheduler.resilience.journal.torn.tail.count"
)

# policy engine (policy/): priority ordering, backfill, gang-aware
# preemption, DRF fair share
# committed preemptions (one per validated victim plan)
POLICY_PREEMPTION_COUNT = "foundry.spark.scheduler.tpu.policy.preemption.count"
# whole applications evicted across all preemptions
POLICY_PREEMPTION_VICTIMS = (
    "foundry.spark.scheduler.tpu.policy.preemption.victims"
)
# victim-set what-if validation latency (milliseconds; histogram)
POLICY_WHATIF_MS = "foundry.spark.scheduler.tpu.policy.preemption.whatif.ms"
# per-tenant weighted dominant share (gauge, tagged tenant=)
POLICY_DRF_SHARE = "foundry.spark.scheduler.tpu.policy.drf.share"
# blocked queue heads safely skipped by the conservative backfill probe
POLICY_BACKFILL_SKIPS = "foundry.spark.scheduler.tpu.policy.backfill.skips"

# gang lifecycle ledger (lifecycle/ledger.py)
# phase transitions (counter, tagged phase=)
LIFECYCLE_TRANSITIONS = (
    "foundry.spark.scheduler.tpu.lifecycle.transitions.count"
)
# gangs currently in each phase (gauge, tagged phase=)
LIFECYCLE_GANGS = "foundry.spark.scheduler.tpu.lifecycle.gangs"
# gang queue wait submitted→bound (seconds; histogram)
LIFECYCLE_QUEUE_WAIT = (
    "foundry.spark.scheduler.tpu.lifecycle.queue.wait.time"
)
# per-request solver tenure attributed to a gang (seconds; histogram)
LIFECYCLE_SOLVE_TENURE = (
    "foundry.spark.scheduler.tpu.lifecycle.solve.tenure.time"
)
# gangs evicted, by coarse cause bucket (counter, tagged cause=)
LIFECYCLE_EVICTIONS = (
    "foundry.spark.scheduler.tpu.lifecycle.evictions.count"
)

# SLO engine (lifecycle/slo.py)
# good/bad samples per objective (counter, tagged objective=, outcome=)
SLO_EVENTS = "foundry.spark.scheduler.tpu.slo.events.count"
# burn rate per objective and alert window (gauge, tagged objective=,
# window=page-long|page-short|warn-long|warn-short)
SLO_BURN_RATE = "foundry.spark.scheduler.tpu.slo.burn.rate"
# error budget remaining over the long ticket window (gauge, 0..1)
SLO_BUDGET_REMAINING = "foundry.spark.scheduler.tpu.slo.budget.remaining"
# alert state per objective (gauge: 0 ok, 1 warn, 2 page)
SLO_STATE = "foundry.spark.scheduler.tpu.slo.state"

# sim runner decision instrumentation (sim/runner.py) — virtual-clock
# scenario metrics, namespaced so the catalog contract covers them
SIM_DECISION_LATENCY = "foundry.spark.scheduler.tpu.sim.decision.latency"
SIM_QUEUE_DEPTH = "foundry.spark.scheduler.tpu.sim.queue.depth"
# auditor coverage (sim/auditor.py): events audited / invariant hits
SIM_AUDIT_EVENTS = "foundry.spark.scheduler.tpu.sim.audit.events"
SIM_AUDIT_VIOLATIONS = (
    "foundry.spark.scheduler.tpu.sim.audit.violations.count"
)

# policy lab (lab/): trace synthesis + matrix evaluation harness
# apps emitted by one synthesizer invocation
LAB_TRACE_APPS = "foundry.spark.scheduler.tpu.lab.trace.apps"
# cells executed per matrix run
LAB_MATRIX_CELLS = "foundry.spark.scheduler.tpu.lab.matrix.cells"
# per-cell replay wall time (seconds; histogram, tagged cell=)
LAB_CELL_WALL_TIME = "foundry.spark.scheduler.tpu.lab.cell.wall.time"
# per-cell replay event count (gauge, tagged cell=)
LAB_CELL_EVENTS = "foundry.spark.scheduler.tpu.lab.cell.events.count"
# per-cell gang evictions (gauge, tagged cell=)
LAB_CELL_EVICTIONS = "foundry.spark.scheduler.tpu.lab.cell.evictions.count"

# concurrent admission engine (concurrent/): parallel speculative
# solves + FIFO-ordered commit gate
# speculation attempts, tagged outcome=solved|overlap|inflight-cap|
# replay|not-driver|policy-engine|... (every decline names its reason)
CONCURRENT_SPECULATION_COUNT = (
    "foundry.spark.scheduler.tpu.concurrent.speculation.count"
)
# speculative work abandoned because the request deadline expired,
# tagged phase=speculation-start|speculation-solved|commit-gate
CONCURRENT_SPECULATION_CANCELLED = (
    "foundry.spark.scheduler.tpu.concurrent.speculation.cancelled"
)
# commit-gate revalidation results, tagged result=seq-hit|memcmp-hit|
# conflict|queue-drift|skip-drift|candidate-drift|serial
CONCURRENT_COMMIT_RESULT = (
    "foundry.spark.scheduler.tpu.concurrent.commit.result"
)
# commits whose speculative verdict was invalidated (re-solved under
# the lock on the warm delta path) — the conflict-rate numerator
CONCURRENT_COMMIT_CONFLICTS = (
    "foundry.spark.scheduler.tpu.concurrent.commit.conflicts.count"
)
# time a request waited for its FIFO commit turn (seconds; histogram)
CONCURRENT_TICKET_WAIT_TIME = (
    "foundry.spark.scheduler.tpu.concurrent.ticket.wait.time"
)
# speculations currently in flight (gauge)
CONCURRENT_INFLIGHT = "foundry.spark.scheduler.tpu.concurrent.inflight.count"
# multi-active commit intents received, tagged result=committed|
# stale-epoch (stale intents are refused before reaching the gate)
CONCURRENT_INTENTS_FORWARDED = (
    "foundry.spark.scheduler.tpu.concurrent.intents.forwarded.count"
)

# equivalence-class aggregation (state/classindex.py + the native
# class-compressed solver): fleet shape diversity and compression health
# distinct node equivalence classes in the mirror (gauge)
CLASSES_COUNT = "foundry.spark.scheduler.tpu.classes.count"
# nodes per class — the compression the class-compressed solver enjoys
CLASSES_COMPRESSION_RATIO = (
    "foundry.spark.scheduler.tpu.classes.compression.ratio"
)
# native session partition rebuilds (overlay overflow / resume misses)
CLASSES_REBUILD_COUNT = "foundry.spark.scheduler.tpu.classes.rebuild.count"
# bind-time expansion latency: class placements → concrete node rows
# (milliseconds; histogram)
CLASSES_EXPAND_MS = "foundry.spark.scheduler.tpu.classes.expand.ms"

# tag keys (metrics.go:70-85)
TAG_SPARK_ROLE = "sparkrole"
TAG_COLLOCATION_TYPE = "collocation-type"
TAG_OUTCOME = "outcome"
TAG_INSTANCE_GROUP = "instance-group"
TAG_HOST = "nodename"
TAG_LIFECYCLE = "lifecycle"
TAG_QUEUE_INDEX = "queueIndex"
TAG_WASTE_TYPE = "wastetype"
TAG_ZONE = "zone"
TAG_KERNEL = "kernel"
TAG_LANE = "lane"
TAG_SPAN = "span"
TAG_LOCK = "lock"
TAG_PHASE = "phase"
TAG_HOLDER = "holder"
TAG_SEGMENT = "segment"
TAG_OBJECTIVE = "objective"
TAG_WINDOW = "window"
TAG_CAUSE = "cause"
TAG_CELL = "cell"

TICK_INTERVAL_SECONDS = 30.0
SLOW_LOG_THRESHOLD_SECONDS = 45.0
STUCK_POD_LOG_THRESHOLD_SECONDS = 12 * 3600.0
