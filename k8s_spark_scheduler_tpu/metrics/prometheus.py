"""Prometheus text exposition (format version 0.0.4) for the tagged
registry.

The JSON snapshot at ``GET /metrics`` stays the debugging surface; this
module renders the same registry contents in the exposition format a
Prometheus scraper (or ``promtool check metrics``) accepts:

- metric names sanitized to ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots and
  slashes in the reference's dotted names become underscores);
- tags become labels with proper value escaping (backslash, quote,
  newline);
- counters → ``counter``, gauges → ``gauge``, histograms → ``summary``
  with ``quantile`` labels plus ``_count``/``_sum`` series and an
  exact-tracked ``_max`` gauge.

Content type: ``text/plain; version=0.0.4; charset=utf-8``.

OpenMetrics flavour (``?format=openmetrics`` ONLY — never
Accept-negotiated): the same families, terminated with ``# EOF``, with
each histogram's ``_count`` line carrying a ``trace_id`` exemplar of
the most recent in-trace observation — the link from a latency series
back to the PR 1 span tree (``GET /traces`` /
``/debug/schedule/<pod>``).  Plain Prometheus text output is
byte-identical to before.  (Strict OpenMetrics attaches exemplars to
counters and histogram buckets and requires ``_total`` counter
samples; this flavour keeps the plain exposition's series names and
carries the exemplar on the counter-like summary ``_count``, so a
strict OpenMetrics parser — e.g. Prometheus with ``scrape_protocols:
[OpenMetricsText1.0.0]`` — would reject it and fail the whole scrape.
That is why Accept headers always get the plain 0.0.4 text —
server/http.py ``_metrics_format``.)
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
CONTENT_TYPE_OPENMETRICS = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_]")

TagSet = Tuple[Tuple[str, str], ...]


def sanitize_metric_name(name: str) -> str:
    out = _NAME_BAD_CHARS.sub("_", name)
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def sanitize_label_name(name: str) -> str:
    out = _LABEL_BAD_CHARS.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r'\"')
    )


def _fmt_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _label_str(tags: Iterable[Tuple[str, str]]) -> str:
    parts = [
        f'{sanitize_label_name(k)}="{escape_label_value(v)}"' for k, v in tags
    ]
    return "{" + ",".join(parts) + "}" if parts else ""


def _group(
    entries: Dict[Tuple[str, TagSet], object]
) -> Dict[str, List[Tuple[TagSet, object]]]:
    """Group (name, tags) keys by sanitized name, preserving insertion
    order, so the TYPE header is emitted once per family."""
    grouped: Dict[str, List[Tuple[TagSet, object]]] = {}
    for (name, tags), value in entries.items():
        grouped.setdefault(sanitize_metric_name(name), []).append((tags, value))
    return grouped


def _exemplar_suffix(snap: dict, openmetrics: bool) -> str:
    """OpenMetrics exemplar (`` # {trace_id="…"} value``) for a
    histogram's ``_count`` line; empty in plain mode or when no in-trace
    observation has been recorded."""
    if not openmetrics:
        return ""
    ex = snap.get("exemplar")
    if not ex:
        return ""
    trace_id, value = ex
    return (
        f' # {{trace_id="{escape_label_value(trace_id)}"}} {_fmt_value(value)}'
    )


def render(registry, openmetrics: bool = False) -> str:
    """Render a MetricsRegistry into Prometheus text format (or the
    OpenMetrics flavour with exemplars + ``# EOF`` when asked)."""
    collected = registry.collect()
    lines: List[str] = []

    for family, series in sorted(_group(collected["counters"]).items()):
        lines.append(f"# TYPE {family} counter")
        for tags, value in series:
            lines.append(f"{family}{_label_str(tags)} {_fmt_value(value)}")

    for family, series in sorted(_group(collected["gauges"]).items()):
        lines.append(f"# TYPE {family} gauge")
        for tags, value in series:
            lines.append(f"{family}{_label_str(tags)} {_fmt_value(value)}")

    for family, series in sorted(_group(collected["histograms"]).items()):
        lines.append(f"# TYPE {family} summary")
        max_lines: List[str] = []
        for tags, snap in series:
            for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                q_tags = tuple(tags) + (("quantile", q),)
                lines.append(
                    f"{family}{_label_str(q_tags)} {_fmt_value(snap[key])}"
                )
            lines.append(f"{family}_sum{_label_str(tags)} {_fmt_value(snap['sum'])}")
            lines.append(
                f"{family}_count{_label_str(tags)} {_fmt_value(snap['count'])}"
                f"{_exemplar_suffix(snap, openmetrics)}"
            )
            max_lines.append(f"{family}_max{_label_str(tags)} {_fmt_value(snap['max'])}")
        # exact stream max isn't part of the summary type — expose it as
        # a sibling gauge family
        lines.append(f"# TYPE {family}_max gauge")
        lines.extend(max_lines)

    if openmetrics:
        # the terminator is mandatory even for an empty exposition — a
        # scrape before the first recorded metric must still parse
        lines.append("# EOF")
    return "\n".join(lines) + "\n" if lines else ""
