"""Tagged metrics registry (palantir pkg/metrics analog).

Counters, gauges, and histograms keyed by (name, sorted tags).  The
reference's ~40 metric names (internal/metrics/metrics.go:30-68) are
declared in :mod:`.names`; periodic reporters live in
:mod:`.reporters`.
"""

from __future__ import annotations

import math
import random
import threading
import time
from collections import defaultdict
from typing import Dict, List, Tuple

from ..tracing.spans import current_trace_id
from ..analysis.guarded import guarded_by

TagSet = Tuple[Tuple[str, str], ...]

# seeded per-histogram for reproducible quantiles in tests; the seed is
# fixed (not time-derived) so two runs over the same stream agree
_RESERVOIR_SEED = 0x5EED


def _tags(tags: Dict[str, str] | None) -> TagSet:
    return tuple(sorted((tags or {}).items()))


class Histogram:
    """Decaying-free simple histogram: count/sum/max/p50/p95/p99 over a
    bounded reservoir.

    Once the reservoir is full, replacement is Vitter's Algorithm R:
    the i-th update survives with probability cap/i, giving every update
    an equal chance of being in the sample — so quantiles estimate the
    whole stream.  (The previous ``count % cap`` overwrite kept only an
    arbitrary recent window, biasing quantiles toward whatever the last
    ~cap updates happened to be.)  max is tracked exactly, not sampled.
    """

    __slots__ = ("values", "count", "total", "maximum", "_cap", "_rng", "exemplar")

    def __init__(self, cap: int = 2048):
        self.values: List[float] = []
        self.count = 0
        self.total = 0.0
        self.maximum = 0.0
        self._cap = cap
        self._rng = random.Random(_RESERVOIR_SEED)
        # (trace_id, observed value) of the most recent observation made
        # inside an active trace — the OpenMetrics exemplar linking PR 1
        # spans to this series (metrics/prometheus.py render_openmetrics)
        self.exemplar: Tuple[str, float] | None = None

    def update(self, v: float) -> None:
        self.count += 1
        self.total += v
        if self.count == 1 or v > self.maximum:
            self.maximum = v
        if len(self.values) < self._cap:
            self.values.append(v)
        else:  # Algorithm R: keep with probability cap/count
            j = self._rng.randrange(self.count)
            if j < self._cap:
                self.values[j] = v

    def quantile(self, q: float) -> float:
        if not self.values:
            return 0.0
        s = sorted(self.values)
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        return s[idx]

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "mean": (self.total / self.count) if self.count else 0.0,
            "p50": self.quantile(0.5),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "max": self.maximum if self.count else 0.0,
        }


@guarded_by("_lock", "_counters", "_gauges", "_histograms")
class MetricsRegistry:
    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[Tuple[str, TagSet], float] = defaultdict(float)
        self._gauges: Dict[Tuple[str, TagSet], float] = {}
        self._histograms: Dict[Tuple[str, TagSet], Histogram] = {}

    def counter(self, name: str, tags: Dict[str, str] | None = None, inc: float = 1.0) -> None:
        with self._lock:
            self._counters[(name, _tags(tags))] += inc

    def gauge(self, name: str, value: float, tags: Dict[str, str] | None = None) -> None:
        with self._lock:
            self._gauges[(name, _tags(tags))] = value

    def histogram(self, name: str, value: float, tags: Dict[str, str] | None = None) -> None:
        # trace correlation read OUTSIDE the registry lock (a contextvar
        # read — ~100ns; None whenever no span is active, e.g. direct
        # library use or background reporters)
        trace_id = current_trace_id()
        with self._lock:
            key = (name, _tags(tags))
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram()
            h.update(value)
            if trace_id is not None:
                h.exemplar = (trace_id, float(value))

    def timer(self, name: str, tags: Dict[str, str] | None = None):
        """Context manager recording elapsed seconds into a histogram."""
        registry = self

        class _Timer:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                registry.histogram(name, time.perf_counter() - self._t0, tags)
                return False

        return _Timer()

    # -- introspection -------------------------------------------------------

    def get_counter(self, name: str, tags: Dict[str, str] | None = None) -> float:
        with self._lock:
            return self._counters.get((name, _tags(tags)), 0.0)

    def get_gauge(self, name: str, tags: Dict[str, str] | None = None) -> float | None:
        with self._lock:
            return self._gauges.get((name, _tags(tags)))

    def get_histogram(self, name: str, tags: Dict[str, str] | None = None) -> dict:
        with self._lock:
            h = self._histograms.get((name, _tags(tags)))
            return h.snapshot() if h else Histogram().snapshot()

    def prune_gauges(self, name: str, keep: "set | None" = None) -> int:
        """Drop every gauge series under ``name`` whose tag dict is not
        in ``keep`` (an iterable of tag dicts; None = drop all).  For
        emitters whose label sets track external state — e.g. the
        capacity observatory's per-(shape, group, zone) headroom — so a
        vanished label combination stops exporting its last stale value
        and live cardinality stays bounded by the emitter's own caps."""
        keep_keys = {_tags(t) for t in keep} if keep is not None else set()
        with self._lock:
            dead = [
                k
                for k in self._gauges
                if k[0] == name and k[1] not in keep_keys
            ]
            for k in dead:
                del self._gauges[k]
            return len(dead)

    def series_stats(self) -> Dict[str, int]:
        """Per-metric-name label-set cardinality across counters,
        gauges, and histograms — the registry's own label-explosion
        canary (reported as …tpu.metrics.registry.series)."""
        with self._lock:
            counts: Dict[str, int] = {}
            for store in (self._counters, self._gauges, self._histograms):
                for name, _tags_key in store:
                    counts[name] = counts.get(name, 0) + 1
            return counts

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {self._fmt(k): v for k, v in self._counters.items()},
                "gauges": {self._fmt(k): v for k, v in self._gauges.items()},
                "histograms": {
                    self._fmt(k): h.snapshot() for k, h in self._histograms.items()
                },
            }

    def collect(self) -> dict:
        """Structured (name, tags) → value dump for exposition formats
        that need tags as labels, not baked into the name string
        (metrics/prometheus.py).  Histograms include the running sum so
        summaries can expose ``_sum``."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: dict(h.snapshot(), sum=h.total, exemplar=h.exemplar)
                    for k, h in self._histograms.items()
                },
            }

    @staticmethod
    def _fmt(key: Tuple[str, TagSet]) -> str:
        name, tags = key
        if not tags:
            return name
        return name + "[" + ",".join(f"{k}={v}" for k, v in tags) + "]"


default_registry = MetricsRegistry()
