"""Periodic metric reporters (reference internal/metrics/{usage,queue,
cache,resourcereservations,softreservations,informer}.go).

One background thread ticks every ``TICK_INTERVAL_SECONDS`` (30s,
metrics.go:89) and reports:
- per-node / per-instance-group reserved resource usage (usage.go:53-114)
- pending-pod lifecycle ages p50/p95/max per phase (queue.go:59-158),
  with stuck-pod logging past 12h (queue.go:160-172)
- cache vs API-server drift (cache.go:64-126)
- unbound reservation resource totals (resourcereservations.go:40-80)
- soft reservation counts + executors lacking reservations
  (softreservations.go:50-104)
- async write queue depths (inflight counts)
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from .. import timesource
from ..scheduler import labels as L
from ..types.resources import Resources
from . import names
from .registry import MetricsRegistry
from ..analysis.guarded import guarded_by

logger = logging.getLogger(__name__)


def _percentile(sorted_values: List[float], q: float) -> float:
    if not sorted_values:
        return 0.0
    idx = min(len(sorted_values) - 1, max(0, int(q * len(sorted_values))))
    return sorted_values[idx]


@guarded_by("_delay_lock", "_delays")
class ReporterSet:
    def __init__(self, server, tick_seconds: float = names.TICK_INTERVAL_SECONDS):
        self._server = server
        self._tick = tick_seconds
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # informer delay (informer.go:33-51): event-delivery lag of fresh
        # pod adds, sampled per tick
        self._delays: List[float] = []
        self._delay_lock = threading.Lock()
        server.pod_informer.add_event_handler(on_add=self._sample_informer_delay)

    def _sample_informer_delay(self, pod) -> None:
        created = pod.creation_timestamp
        if not created:
            return
        lag = max(timesource.now() - created, 0.0)
        if lag < 300.0:  # only fresh pods are a meaningful delay signal
            with self._delay_lock:
                self._delays.append(lag)
                if len(self._delays) > 4096:
                    del self._delays[:2048]

    @property
    def metrics(self) -> MetricsRegistry:
        return self._server.metrics

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True, name="metric-reporters")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self._tick):
            self.report_once()

    def report_once(self) -> None:
        waste = getattr(self._server, "waste_reporter", None)
        if waste is not None:
            try:
                waste.cleanup_metric_cache()
            except Exception:
                logger.exception("waste cache cleanup failed")
        for fn in (
            self.report_resource_usage,
            self.report_pod_lifecycle,
            self.report_cache_drift,
            self.report_unbound_reservations,
            self.report_soft_reservations,
            self.report_queue_depths,
            self.report_informer_delay,
            self.report_jit_cache_sizes,
            self.report_resilience,
            self.report_contention,
            self.report_registry_series,
        ):
            try:
                fn()
            except Exception:
                logger.exception("reporter %s failed", fn.__name__)

    # -- usage.go -----------------------------------------------------------

    def report_resource_usage(self) -> None:
        server = self._server
        usage = server.resource_reservation_manager.get_reserved_resources()
        nodes = {n.name: n for n in server.node_informer.list()}
        group_label = server.install.instance_group_label
        for node_name, res in usage.items():
            node = nodes.get(node_name)
            group = node.labels.get(group_label, "") if node else ""
            tags = {names.TAG_HOST: node_name, names.TAG_INSTANCE_GROUP: group}
            self.metrics.gauge(names.RESOURCE_USAGE_CPU, res.cpu.milli_value() / 1000.0, tags)
            self.metrics.gauge(names.RESOURCE_USAGE_MEMORY, float(res.memory.value()), tags)
            self.metrics.gauge(
                names.RESOURCE_USAGE_NVIDIA_GPUS, float(res.nvidia_gpu.value()), tags
            )

    # -- queue.go -----------------------------------------------------------

    def report_pod_lifecycle(self) -> None:
        server = self._server
        now = timesource.now()
        pending_ages: List[float] = []
        for pod in server.pod_informer.list():
            if not L.is_spark_scheduler_pod(pod):
                continue
            if pod.node_name == "" and pod.meta.deletion_timestamp is None:
                age = now - pod.creation_timestamp
                pending_ages.append(age)
                if age > names.STUCK_POD_LOG_THRESHOLD_SECONDS:
                    logger.warning(
                        "pod stuck in pending for over 12h: %s/%s",
                        pod.namespace,
                        pod.name,
                    )
        pending_ages.sort()
        tags = {names.TAG_LIFECYCLE: "queued"}
        self.metrics.gauge(names.LIFECYCLE_COUNT, float(len(pending_ages)), tags)
        self.metrics.gauge(names.LIFECYCLE_AGE_P50, _percentile(pending_ages, 0.5), tags)
        self.metrics.gauge(names.LIFECYCLE_AGE_P95, _percentile(pending_ages, 0.95), tags)
        self.metrics.gauge(
            names.LIFECYCLE_AGE_MAX, pending_ages[-1] if pending_ages else 0.0, tags
        )

    # -- cache.go drift -----------------------------------------------------

    def report_cache_drift(self) -> None:
        server = self._server
        cached = {(rr.namespace, rr.name) for rr in server.resource_reservation_cache.list()}
        stored = {
            (rr.namespace, rr.name) for rr in server.api.list("ResourceReservation")
        }
        self.metrics.gauge(names.CACHED_OBJECT_COUNT, float(len(cached)))
        drift = len(cached.symmetric_difference(stored))
        self.metrics.gauge(names.CACHED_OBJECT_DRIFT, float(drift))

    # -- resourcereservations.go (unbound totals) ---------------------------

    def report_unbound_reservations(self) -> None:
        server = self._server
        pods = {
            (p.namespace, p.name): p
            for p in server.pod_informer.list()
            if not L.is_pod_terminated(p)
        }
        unbound_total = Resources.zero()
        for rr in server.resource_reservation_cache.list():
            for reservation_name, reservation in rr.spec.reservations.items():
                pod_name = rr.status.pods.get(reservation_name)
                if pod_name is None or (rr.namespace, pod_name) not in pods:
                    unbound_total = unbound_total.add(reservation.resources_value())
        self.metrics.gauge(
            names.UNBOUND_CPU_RESERVATIONS, unbound_total.cpu.milli_value() / 1000.0
        )
        self.metrics.gauge(
            names.UNBOUND_MEMORY_RESERVATIONS, float(unbound_total.memory.value())
        )
        self.metrics.gauge(
            names.UNBOUND_NVIDIA_GPU_RESERVATIONS, float(unbound_total.nvidia_gpu.value())
        )

    # -- softreservations.go ------------------------------------------------

    def report_soft_reservations(self) -> None:
        server = self._server
        store = server.soft_reservation_store
        self.metrics.gauge(names.SOFT_RESERVATION_COUNT, float(store.get_application_count()))
        self.metrics.gauge(
            names.SOFT_RESERVATION_EXECUTOR_COUNT,
            float(store.get_active_extra_executor_count()),
        )
        # executors bound to nodes but absent from both hard and soft stores
        count = 0
        for pod in server.pod_informer.list():
            if (
                L.is_spark_scheduler_executor_pod(pod)
                and pod.node_name != ""
                and not L.is_pod_terminated(pod)
                and not server.resource_reservation_manager.pod_has_reservation(pod)
            ):
                count += 1
        self.metrics.gauge(names.EXECUTORS_WITH_NO_RESERVATION_COUNT, float(count))

    def report_informer_delay(self) -> None:
        with self._delay_lock:
            delays, self._delays = self._delays, []
        if delays:
            delays.sort()
            self.metrics.gauge(names.POD_INFORMER_DELAY, _percentile(delays, 0.5))
            self.metrics.gauge(names.POD_INFORMER_DELAY_MAX, delays[-1])

    # -- queue depths -------------------------------------------------------

    def report_queue_depths(self) -> None:
        server = self._server
        for i, depth in enumerate(server.resource_reservation_cache.inflight_queue_lengths()):
            self.metrics.gauge(
                names.INFLIGHT_REQUEST_COUNT,
                float(depth),
                {names.TAG_QUEUE_INDEX: str(i), "objectType": "resourcereservations"},
            )
        for i, depth in enumerate(server.demand_cache.inflight_queue_lengths()):
            self.metrics.gauge(
                names.INFLIGHT_REQUEST_COUNT,
                float(depth),
                {names.TAG_QUEUE_INDEX: str(i), "objectType": "demands"},
            )

    def report_jit_cache_sizes(self) -> None:
        """Per-kernel jit compilation-cache entry counts: growth in
        steady state = shape buckets leaking recompiles onto the
        request path (see ops/batch_solver.compilation_cache_stats)."""
        import sys

        # never force the JAX import from a metrics tick: if no solver
        # has run yet there is nothing to report
        if "k8s_spark_scheduler_tpu.ops.batch_solver" not in sys.modules:
            return
        from ..ops.batch_solver import compilation_cache_stats

        for kernel, size in compilation_cache_stats().items():
            self.metrics.gauge(
                names.KERNEL_JIT_CACHE_SIZE, float(size), {names.TAG_KERNEL: kernel}
            )

    # -- registry self-observability -----------------------------------------

    def report_registry_series(self) -> None:
        """Per-metric label-set cardinality (…tpu.metrics.registry.
        series, tagged metric=): the canary that catches a label
        explosion — e.g. a high-cardinality capacity tag — before the
        Prometheus scrape does.  One series per catalog name, so the
        canary itself stays O(#metric names)."""
        published = []
        for name, series in self.metrics.series_stats().items():
            if name == names.METRICS_REGISTRY_SERIES:
                continue  # never self-count: the gauge would ratchet
            tags = {"metric": name}
            published.append(tags)
            self.metrics.gauge(
                names.METRICS_REGISTRY_SERIES, float(series), tags
            )
        # a metric name that vanished from the registry (e.g. pruned
        # capacity gauges) must not keep exporting its last, too-high
        # series count — the canary tracks the registry, not history
        self.metrics.prune_gauges(names.METRICS_REGISTRY_SERIES, published)

    # -- contention -----------------------------------------------------------

    def report_contention(self) -> None:
        """Drain the lock-telemetry pending buffers into wait/hold
        histograms.  TimedLock never publishes from the lock path (the
        registry's own lock is a TimedLock — publishing there would
        recurse), so the reporter tick is the drain point."""
        from ..contention import locktime

        if locktime.active():
            locktime.publish(self.metrics)

    # -- resilience ----------------------------------------------------------

    def report_resilience(self) -> None:
        """Degraded-mode gauges + the periodic write-back recovery nudge:
        when journaled reservation intents exist and the breaker's probe
        window is due, put one back on the queue so recovery doesn't wait
        for organic write traffic.  Skipped under a virtual clock — the
        simulator drives recovery from its own (deterministic) events,
        and a wall-clock tick mutating state there would break digest
        reproducibility."""
        kit = getattr(self._server, "resilience", None)
        if kit is None:
            return
        self.metrics.gauge(names.RESILIENCE_GATE_INFLIGHT, float(kit.gate.in_flight))
        self.metrics.gauge(
            names.RESILIENCE_JOURNAL_DEPTH, float(kit.journal.depth())
        )
        # refresh the health-state gauge with the REAL serving state —
        # defaulting serving=True here would flap the gauge to "ready"
        # mid-boot between unready readiness-probe samples
        serving = (
            self._server.informer_factory.wait_for_cache_sync()
            and self._server.warmup_complete()
        )
        kit.health.state(serving=serving)
        if not timesource.is_virtual():
            self._server.resource_reservation_cache.nudge_recovery()
