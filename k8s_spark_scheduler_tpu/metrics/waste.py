"""WasteMetricsReporter (reference internal/metrics/waste.go:67-293).

Attributes a pod's time-to-schedule to phases around Demand creation and
fulfillment, so autoscaler-induced delays are visible:

- ``total-time-no-demand``: pod scheduled without ever needing a demand
- ``before-demand-creation``: pod creation → demand creation
- ``after-demand-fulfilled``: demand fulfilled → pod scheduled, plus the
  no-failures / since-last-failure / failure-<outcome> split depending on
  failed scheduling attempts after fulfillment

Best-effort in-memory state, cleaned up after 6h (waste.go:33-35).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .. import timesource
from ..demands.manager import pod_name_from_demand
from ..scheduler import labels as L
from ..types.objects import Demand, Pod
from . import names
from .registry import MetricsRegistry
from ..analysis.guarded import guarded_by

logger = logging.getLogger(__name__)

DEMAND_FULFILLED_AGE_CLEANUP_SECONDS = 6 * 3600.0
SLOW_WASTE_LOG_SECONDS = 60.0
SLOW_NO_DEMAND_LOG_SECONDS = 600.0


@dataclass
class _PodSchedulingInfo:
    demand_created_at: Optional[float] = None
    demand_fulfilled_at: Optional[float] = None
    last_failure_at: Optional[float] = None
    last_failure_outcome: str = ""
    created_at: float = field(default_factory=timesource.now)


@guarded_by("_lock", "_info")
class WasteMetricsReporter:
    def __init__(self, metrics: MetricsRegistry, instance_group_label: str):
        self._metrics = metrics
        self._instance_group_label = instance_group_label
        self._lock = threading.Lock()
        self._info: Dict[Tuple[str, str], _PodSchedulingInfo] = {}
        # SLO hook (server/wiring.py): ``slo_sink(waste_type, duration)``
        # forwards every waste sample to the eviction_waste objective —
        # this reporter is the single source of truth for waste, so the
        # SLO engine never re-derives it from raw informer events
        self.slo_sink = None

    # -- wiring (waste.go:88-120) -------------------------------------------

    def start(self, pod_informer, lazy_demand_informer) -> None:
        pod_informer.add_event_handler(
            on_update=self._on_pod_update,
            on_delete=self._on_pod_deleted,
            filter_func=L.is_spark_scheduler_pod,
        )

        def wire_demands() -> None:
            informer = lazy_demand_informer.informer()
            if informer is None:
                return
            informer.add_event_handler(
                on_add=self._on_demand_created,
                on_update=self._on_demand_update,
                filter_func=lambda d: L.SPARK_APP_ID_LABEL in d.labels,
            )

        lazy_demand_informer.on_ready(wire_demands)

    # -- events --------------------------------------------------------------

    def mark_failed_scheduling_attempt(self, pod: Pod, outcome: str) -> None:
        """waste.go:147-186 (channel replaced by a direct locked update)."""
        with self._lock:
            info = self._get_or_create(pod.namespace, pod.name)
            info.last_failure_at = timesource.now()
            info.last_failure_outcome = outcome

    def _on_demand_created(self, demand: Demand) -> None:
        pod_name = pod_name_from_demand(demand)
        with self._lock:
            info = self._get_or_create(demand.namespace, pod_name)
            # the demand's own creation timestamp, not delivery time
            # (waste.go:245-254) — synthetic informer replays after a
            # restart must not reset the phase boundary
            info.demand_created_at = demand.creation_timestamp or timesource.now()

    def _on_demand_update(self, old: Demand, new: Demand) -> None:
        from ..types.objects import DemandPhase

        old_fulfilled = old is not None and old.status.phase == DemandPhase.FULFILLED
        if not old_fulfilled and new.status.phase == DemandPhase.FULFILLED:
            pod_name = pod_name_from_demand(new)
            with self._lock:
                info = self._get_or_create(new.namespace, pod_name)
                info.demand_fulfilled_at = timesource.now()
                info.demand_created_at = new.creation_timestamp or info.demand_created_at

    def _on_pod_update(self, old: Optional[Pod], new: Pod) -> None:
        if not L.on_pod_scheduled(old, new):
            return
        self._on_pod_scheduled(new)

    def _on_pod_scheduled(self, pod: Pod) -> None:
        """waste.go:196-222."""
        now = timesource.now()
        with self._lock:
            info = self._info.pop((pod.namespace, pod.name), None)
        instance_group, _ = L.find_instance_group_from_pod_spec(pod, self._instance_group_label)

        if info is None or info.demand_created_at is None:
            created = pod.creation_timestamp or (info.created_at if info else now)
            self._mark(pod, instance_group, "total-time-no-demand", now - created,
                       SLOW_NO_DEMAND_LOG_SECONDS)
            return

        self._mark(
            pod,
            instance_group,
            "before-demand-creation",
            info.demand_created_at - (pod.creation_timestamp or info.created_at),
            SLOW_WASTE_LOG_SECONDS,
        )
        if info.demand_fulfilled_at is not None:
            self._mark(
                pod,
                instance_group,
                "after-demand-fulfilled",
                now - info.demand_fulfilled_at,
                SLOW_WASTE_LOG_SECONDS,
            )
            if info.last_failure_at is None or info.last_failure_at < info.demand_fulfilled_at:
                self._mark(
                    pod,
                    instance_group,
                    "after-demand-fulfilled-no-failures",
                    now - info.demand_fulfilled_at,
                    SLOW_WASTE_LOG_SECONDS,
                )
            else:
                # waste.go:211-215: the failure-<outcome> phase measures
                # fulfillment → last failed attempt; since-last-failure
                # measures last failed attempt → scheduled
                self._mark(
                    pod,
                    instance_group,
                    f"after-demand-fulfilled-failure-{info.last_failure_outcome}",
                    info.last_failure_at - info.demand_fulfilled_at,
                    SLOW_WASTE_LOG_SECONDS,
                )
                self._mark(
                    pod,
                    instance_group,
                    "after-demand-fulfilled-since-last-failure",
                    now - info.last_failure_at,
                    SLOW_WASTE_LOG_SECONDS,
                )

    def _on_pod_deleted(self, pod: Pod) -> None:
        with self._lock:
            self._info.pop((pod.namespace, pod.name), None)

    # -- internals -----------------------------------------------------------

    def _mark(self, pod: Pod, instance_group: str, waste_type: str, duration: float,
              slow_threshold: float) -> None:
        duration = max(duration, 0.0)
        self._metrics.histogram(
            names.SCHEDULING_WASTE, duration, {names.TAG_WASTE_TYPE: waste_type}
        )
        self._metrics.histogram(
            names.SCHEDULING_WASTE_PER_INSTANCE_GROUP,
            duration,
            {names.TAG_WASTE_TYPE: waste_type, names.TAG_INSTANCE_GROUP: instance_group},
        )
        if self.slo_sink is not None:
            try:
                self.slo_sink(waste_type, duration)
            except Exception:  # the sink must never break pod handling
                logger.exception("slo waste sink failed")
        if duration > slow_threshold:
            logger.warning(
                "scheduling waste above threshold: pod=%s/%s type=%s duration=%.1fs",
                pod.namespace,
                pod.name,
                waste_type,
                duration,
            )

    def _get_or_create(self, namespace: str, pod_name: str) -> _PodSchedulingInfo:
        info = self._info.get((namespace, pod_name))
        if info is None:
            info = self._info[(namespace, pod_name)] = _PodSchedulingInfo()  # schedlint: disable=LK001 -- private helper, every caller holds _lock (see callers)
        return info

    def scheduling_info(self, namespace: str, pod_name: str):
        """Read-only view of a pod's demand phase boundaries for the
        capacity observatory's time-to-admit forecast (None when the
        reporter has never seen the pod)."""
        with self._lock:
            info = self._info.get((namespace, pod_name))
            if info is None:
                return None
            return {
                "createdAt": info.created_at,
                "demandCreatedAt": info.demand_created_at,
                "demandFulfilledAt": info.demand_fulfilled_at,
                "lastFailureOutcome": info.last_failure_outcome or None,
            }

    def cleanup_metric_cache(self) -> None:
        """waste.go:160-172: drop entries older than 6h."""
        cutoff = timesource.now() - DEMAND_FULFILLED_AGE_CLEANUP_SECONDS
        with self._lock:
            stale = [k for k, v in self._info.items() if v.created_at < cutoff]
            for k in stale:
                logger.warning(
                    "deleting pod from scheduling waste reporter, not scheduled for 6 hours: %s/%s",
                    k[0],
                    k[1],
                )
                del self._info[k]
