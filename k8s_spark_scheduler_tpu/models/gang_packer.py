"""GangPacker — the flagship compiled program of this framework.

Bundles the batch gang-packing solver into a configured, reusable,
optionally mesh-sharded program: snapshot tensors in, whole-FIFO-queue
placement decisions out.  This is the ``binpack: tpu-batch`` data plane
(BASELINE.json north star): the control plane marshals cluster state
into `ClusterTensor`/`AppTensor` and reads back per-app decisions,
while everything inside `solve` is a single XLA program with the node
axis sharded over the device mesh.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.batch_solver import QueueSolve, solve_queue
from ..ops.tensorize import (
    AppTensor,
    ClusterTensor,
    ScaledProblem,
    scale_problem,
)
from ..parallel import mesh as meshlib


@dataclass(frozen=True)
class GangPackerConfig:
    assignment_policy: str = "tightly-pack"  # or "distribute-evenly"
    node_bucket: Optional[int] = None
    app_bucket: Optional[int] = None
    use_mesh: bool = False
    # "pallas": single-kernel VMEM-resident queue solve (fastest on one
    # chip); "xla": lax.scan program (mesh-shardable, CPU-testable)
    backend: str = "pallas"


class GangPacker:
    """Compiled whole-queue gang packer."""

    def __init__(self, config: GangPackerConfig = GangPackerConfig(), devices=None):
        self.config = config
        self._mesh = meshlib.make_mesh(devices) if config.use_mesh else None
        if self._mesh is not None:
            node_mat = meshlib.node_matrix_sharding(self._mesh)
            node_vec = meshlib.node_sharding(self._mesh)
            rep = meshlib.replicated(self._mesh)
            self._solve = jax.jit(
                functools.partial(
                    solve_queue, evenly=config.assignment_policy == "distribute-evenly"
                ),
                in_shardings=(node_mat, node_vec, node_vec, rep, rep, rep, rep),
                out_shardings=QueueSolve(
                    feasible=rep,
                    driver_idx=rep,
                    exec_counts=jax.sharding.NamedSharding(
                        self._mesh, jax.sharding.PartitionSpec(None, meshlib.NODE_AXIS)
                    ),
                    exec_capacity=jax.sharding.NamedSharding(
                        self._mesh, jax.sharding.PartitionSpec(None, meshlib.NODE_AXIS)
                    ),
                    avail_after=node_mat,
                ),
            )
        elif config.backend == "pallas" and jax.default_backend() == "tpu":
            from ..ops.pallas_queue import pallas_solve_queue

            evenly = config.assignment_policy == "distribute-evenly"

            def pallas_wrapped(*args):
                # decision-latency contract: per-app (feasible, driver)
                # plus the final availability.  Any single app's executor
                # placements are recovered with one O(N) solve_single on
                # the carried availability — exactly how TpuFifoSolver
                # decodes the current driver in production, and what the
                # bench measures as part of the headline op.  exec_counts
                # is therefore intentionally empty here (an [A, N]
                # placement matrix would be dead output for the FIFO
                # pass).
                feasible, driver_idx, avail_after = pallas_solve_queue(
                    *args, evenly=evenly
                )
                return QueueSolve(
                    feasible=feasible,
                    driver_idx=driver_idx,
                    exec_counts=jnp.zeros((0,), jnp.int32),
                    exec_capacity=jnp.zeros((0,), jnp.int32),
                    avail_after=avail_after,
                )

            self._solve = pallas_wrapped
        else:
            self._solve = functools.partial(
                solve_queue, evenly=config.assignment_policy == "distribute-evenly"
            )

    @property
    def mesh(self):
        return self._mesh

    def scale(self, cluster: ClusterTensor, apps: AppTensor) -> ScaledProblem:
        node_bucket = self.config.node_bucket
        if self._mesh is not None:
            from ..ops.tensorize import bucket_size

            n_devices = len(self._mesh.devices.reshape(-1))
            base = node_bucket or bucket_size(cluster.avail.shape[0])
            node_bucket = meshlib.pad_to_multiple(base, n_devices)
        return scale_problem(
            cluster, apps, node_bucket=node_bucket, app_bucket=self.config.app_bucket
        )

    def device_args(self, problem: ScaledProblem):
        args = (
            jnp.asarray(problem.avail),
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver),
            jnp.asarray(problem.executor),
            jnp.asarray(problem.count),
            jnp.asarray(problem.app_valid),
        )
        if self._mesh is not None:
            node_mat = meshlib.node_matrix_sharding(self._mesh)
            node_vec = meshlib.node_sharding(self._mesh)
            rep = meshlib.replicated(self._mesh)
            shardings = (node_mat, node_vec, node_vec, rep, rep, rep, rep)
            args = tuple(jax.device_put(a, s) for a, s in zip(args, shardings))
        return args

    def solve(self, problem: ScaledProblem) -> QueueSolve:
        """Run the compiled program.  problem.ok must be True.

        Profiled: compile vs execute time and cache hit/miss land in
        the kernel metrics (tracing/profiling.py) tagged with the
        configured backend lane."""
        if not problem.ok:
            raise ValueError("problem is not exactly tensorizable; use the host oracle")
        from ..tracing.profiling import default_profiler

        lane = "mesh" if self._mesh is not None else self.config.backend
        with default_profiler.profile(
            "gang_packer.solve_queue",
            lane=lane,
            fn=self._solve if hasattr(self._solve, "_cache_size") else None,
            shape_key=(problem.avail.shape, problem.driver.shape),
        ) as rec:
            out = self._solve(*self.device_args(problem))
            rec.sync(out.avail_after)
        return out

    def solve_fn(self):
        """(fn, sharding-prepared) — the raw jittable callable for
        compile checks and AOT tooling."""
        return self._solve
