"""ctypes binding for the native snapshot maintainer (native/snapshot.cpp).

Builds the shared library on first import with g++ (cached beside the
source); degrades gracefully to a pure-numpy implementation when no
compiler is available, so the framework never hard-depends on the
toolchain.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "snapshot.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "_build", "libsnapshot.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def build_native_lib(src: str, lib_path: str, flags: list[str]) -> ctypes.CDLL:
    """Shared compile-on-first-use machinery for the native libraries:
    rebuild when the source is newer (a present prebuilt .so with no
    source alongside is used as-is), always via an atomic tmp+rename so
    concurrent processes never CDLL-load a partially written file.
    Raises on failure — callers wrap with their own degrade policy."""
    stale = not os.path.exists(lib_path) or (
        os.path.exists(src) and os.path.getmtime(lib_path) < os.path.getmtime(src)
    )
    if stale:
        os.makedirs(os.path.dirname(lib_path), exist_ok=True)
        tmp = lib_path + f".tmp.{os.getpid()}"
        subprocess.run(
            ["g++", *flags, "-shared", "-fPIC", "-std=c++17", src, "-o", tmp],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, lib_path)
    return ctypes.CDLL(lib_path)


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            lib = build_native_lib(_SRC, _LIB, ["-O2"])
            lib.snap_create.restype = ctypes.c_void_p
            lib.snap_create.argtypes = [ctypes.c_int64]
            lib.snap_destroy.argtypes = [ctypes.c_void_p]
            lib.snap_size.restype = ctypes.c_int64
            lib.snap_size.argtypes = [ctypes.c_void_p]
            lib.snap_load.restype = ctypes.c_int
            lib.snap_load.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
            lib.snap_apply_deltas.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_int64,
            ]
            lib.snap_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.snap_scale_int32.restype = ctypes.c_int
            lib.snap_scale_int32.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            lib.snap_scale_rows.restype = ctypes.c_int
            lib.snap_scale_rows.argtypes = [
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_void_p,
                ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            try:
                # optional (older prebuilt .so may lack it; rows_equal
                # then uses the numpy fallback)
                lib.snap_rows_diff.restype = ctypes.c_int64
                lib.snap_rows_diff.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_void_p,
                    ctypes.c_int64,
                ]
            except AttributeError:
                pass
            try:
                # optional: equivalence-class grouping (ROADMAP 2)
                lib.snap_group_rows.restype = ctypes.c_int64
                lib.snap_group_rows.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_void_p,
                    ctypes.c_int64,
                    ctypes.c_void_p,
                ]
            except AttributeError:
                pass
            _lib = lib
        except Exception:
            logger.warning("native snapshot library unavailable; using numpy fallback",
                           exc_info=True)
            _lib_failed = True
    return _lib


def native_available() -> bool:
    return _build_and_load() is not None


class SnapshotMaintainer:
    """Incrementally-maintained availability tensor with int32 scaling.

    The per-request marshal path uses the stateless
    :func:`scale_rows_int32` below; this class adds the steady-state mode
    (load once, apply reservation deltas as pods bind/die, scale per
    request) for event-driven snapshot maintenance.
    """

    def __init__(self, avail_rows: np.ndarray):
        avail_rows = np.ascontiguousarray(avail_rows, dtype=np.int64)
        self._n = avail_rows.shape[0]
        self._lib = _build_and_load()
        self._handle = None
        if self._lib is not None:
            handle = self._lib.snap_create(self._n)
            if handle and self._lib.snap_load(
                ctypes.c_void_p(handle), avail_rows.ctypes.data_as(ctypes.c_void_p), self._n
            ):
                self._handle = ctypes.c_void_p(handle)
            elif handle:
                self._lib.snap_destroy(ctypes.c_void_p(handle))
        if self._handle is None:
            self._np = avail_rows.copy()

    def __del__(self):
        if getattr(self, "_handle", None) is not None and self._lib is not None:
            self._lib.snap_destroy(self._handle)
            self._handle = None

    @property
    def backend(self) -> str:
        return "native" if self._handle is not None else "numpy"

    @property
    def n_nodes(self) -> int:
        return self._n

    def apply_deltas(self, node_idx: np.ndarray, deltas: np.ndarray) -> None:
        """avail[idx] -= delta (use negative deltas to release)."""
        node_idx = np.ascontiguousarray(node_idx, dtype=np.int32)
        deltas = np.ascontiguousarray(deltas, dtype=np.int64)
        if self._handle is not None:
            self._lib.snap_apply_deltas(
                self._handle,
                node_idx.ctypes.data_as(ctypes.c_void_p),
                deltas.ctypes.data_as(ctypes.c_void_p),
                len(node_idx),
            )
        else:
            valid = (node_idx >= 0) & (node_idx < self._n)
            np.subtract.at(self._np, node_idx[valid], deltas[valid])

    def read(self) -> np.ndarray:
        if self._handle is not None:
            out = np.empty((self._n, 3), dtype=np.int64)
            self._lib.snap_read(self._handle, out.ctypes.data_as(ctypes.c_void_p))
            return out
        return self._np.copy()

    def scale_int32(
        self, demand_rows: np.ndarray, node_bucket: int
    ) -> Tuple[bool, np.ndarray, np.ndarray, np.ndarray]:
        """(ok, scaled_avail[node_bucket,3] int32, scaled_demands, scale[3])."""
        demand_rows = np.ascontiguousarray(demand_rows, dtype=np.int64)
        n_demands = demand_rows.shape[0]
        if self._handle is not None:
            out_avail = np.zeros((node_bucket, 3), dtype=np.int32)
            out_demands = np.zeros((max(n_demands, 1), 3), dtype=np.int32)
            out_scale = np.ones(3, dtype=np.int64)
            ok = self._lib.snap_scale_int32(
                self._handle,
                demand_rows.ctypes.data_as(ctypes.c_void_p),
                n_demands,
                node_bucket,
                out_avail.ctypes.data_as(ctypes.c_void_p),
                out_demands.ctypes.data_as(ctypes.c_void_p),
                out_scale.ctypes.data_as(ctypes.c_void_p),
            )
            return bool(ok), out_avail, out_demands[:n_demands], out_scale
        return _numpy_scale_int32(self._np, demand_rows, node_bucket)


def rows_equal(a: np.ndarray, b: np.ndarray) -> bool:
    """Exact equality of two [n, 3] int64 row blocks — the delta-solve
    engine's warm-basis check.  Native memcmp when the library carries
    snap_rows_diff, numpy otherwise; both are exact."""
    a = np.ascontiguousarray(a, dtype=np.int64)
    b = np.ascontiguousarray(b, dtype=np.int64)
    if a.shape != b.shape:
        return False
    n = a.shape[0]
    if n == 0:
        return True
    lib = _build_and_load()
    if lib is not None and hasattr(lib, "snap_rows_diff"):
        diff = lib.snap_rows_diff(
            a.ctypes.data_as(ctypes.c_void_p),
            b.ctypes.data_as(ctypes.c_void_p),
            n,
        )
        return diff < 0
    return bool(np.array_equal(a, b))


def group_rows(rows: np.ndarray, flags: Optional[np.ndarray] = None
               ) -> Tuple[int, np.ndarray]:
    """Equivalence-class grouping of [n, 3] int64 rows (plus an optional
    per-row uint8 flag, e.g. schedulability): returns (class count,
    class id per row in first-occurrence order).  The capacity
    observatory's per-class headroom/frag lanes use it to collapse a
    100k-node scan to a few dozen class probes.  Native one-pass hash
    when the library carries snap_group_rows, numpy otherwise; the class
    id assignment is identical (first-occurrence order) either way."""
    rows = np.ascontiguousarray(rows, dtype=np.int64)
    n = rows.shape[0]
    out = np.zeros(n, dtype=np.int32)
    if n == 0:
        return 0, out
    if flags is not None:
        flags = np.ascontiguousarray(flags, dtype=np.uint8)
    lib = _build_and_load()
    if lib is not None and hasattr(lib, "snap_group_rows"):
        n_classes = lib.snap_group_rows(
            rows.ctypes.data_as(ctypes.c_void_p),
            flags.ctypes.data_as(ctypes.c_void_p) if flags is not None else None,
            n,
            out.ctypes.data_as(ctypes.c_void_p),
        )
        return int(n_classes), out
    seen: dict = {}
    for i in range(n):
        key = (int(rows[i, 0]), int(rows[i, 1]), int(rows[i, 2]),
               int(flags[i]) if flags is not None else 0)
        cid = seen.get(key)
        if cid is None:
            cid = len(seen)
            seen[key] = cid
        out[i] = cid
    return len(seen), out


def scale_rows_int32(avail_rows: np.ndarray, demand_rows: np.ndarray, node_bucket: int):
    """Stateless per-request scaling (no handle allocation): the marshal
    path's entry point.  Native-backed when available."""
    avail_rows = np.ascontiguousarray(avail_rows, dtype=np.int64)
    demand_rows = np.ascontiguousarray(demand_rows, dtype=np.int64)
    lib = _build_and_load()
    if lib is None:
        return _numpy_scale_int32(avail_rows, demand_rows, node_bucket)
    n = avail_rows.shape[0]
    n_demands = demand_rows.shape[0]
    out_avail = np.zeros((node_bucket, 3), dtype=np.int32)
    out_demands = np.zeros((max(n_demands, 1), 3), dtype=np.int32)
    out_scale = np.ones(3, dtype=np.int64)
    ok = lib.snap_scale_rows(
        avail_rows.ctypes.data_as(ctypes.c_void_p),
        n,
        demand_rows.ctypes.data_as(ctypes.c_void_p),
        n_demands,
        node_bucket,
        out_avail.ctypes.data_as(ctypes.c_void_p),
        out_demands.ctypes.data_as(ctypes.c_void_p),
        out_scale.ctypes.data_as(ctypes.c_void_p),
    )
    return bool(ok), out_avail, out_demands[:n_demands], out_scale


def _numpy_scale_int32(avail: np.ndarray, demand_rows: np.ndarray, node_bucket: int):
    INT32_SAFE = 2**31 - 1
    n = avail.shape[0]
    out_avail = np.zeros((max(node_bucket, 0), 3), dtype=np.int32)
    out_demands = np.zeros((demand_rows.shape[0], 3), dtype=np.int32)
    scale = np.ones(3, dtype=np.int64)
    if node_bucket < n:  # same contract as snapshot.cpp:101
        return False, out_avail, out_demands, scale
    for d in range(3):
        values = np.concatenate([avail[:, d], demand_rows[:, d]])
        g = int(np.gcd.reduce(np.abs(values))) if len(values) else 1
        g = max(g, 1)
        scale[d] = g
        sa = avail[:, d] // g
        sd = demand_rows[:, d] // g
        if (np.abs(sa) > INT32_SAFE).any() or (len(sd) and (np.abs(sd) > INT32_SAFE).any()):
            return False, out_avail, out_demands, scale
        out_avail[:n, d] = sa
        out_demands[:, d] = sd
    return True, out_avail, out_demands, scale
