"""ctypes binding for the native FIFO queue solver (native/fifo_solver.cpp).

The host-CPU lane of the batch solver: bit-exact decisions vs
ops/batch_solver.solve_queue (tightly-pack / distribute-evenly), at
native speed for deployments without an accelerator.  Build-on-first-use
with graceful degradation, same pattern as the snapshot maintainer.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SRC = os.path.join(_REPO_ROOT, "native", "fifo_solver.cpp")
_LIB = os.path.join(_REPO_ROOT, "native", "_build", "libfifosolver.so")

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False

_P = ctypes.c_void_p


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            from . import build_native_lib

            lib = build_native_lib(
                _SRC,
                _LIB,
                [
                    "-O3", "-march=native", "-funroll-loops",
                    # IEEE semantics preserved; only errno/trap
                    # bookkeeping dropped so divpd vectorizes cleanly
                    "-fno-math-errno", "-fno-trapping-math",
                    # the delta-solve session's sharded cold pass runs a
                    # small std::thread pool
                    "-pthread",
                ],
            )
            lib.fifo_solve_queue.restype = ctypes.c_int
            lib.fifo_solve_queue.argtypes = [
                ctypes.c_int64, ctypes.c_int64, _P, _P, _P, _P, _P, _P, _P,
                ctypes.c_int, _P, _P,
            ]
            lib.fifo_solve_app.restype = ctypes.c_int
            lib.fifo_solve_app.argtypes = [
                ctypes.c_int64, _P, _P, _P, _P, _P, ctypes.c_int32,
                _P, _P, _P, _P,
            ]
            lib.fifo_solve_queue_minfrag.restype = ctypes.c_int
            lib.fifo_solve_queue_minfrag.argtypes = [
                ctypes.c_int64, ctypes.c_int64, _P, _P, _P, _P, _P, _P, _P,
                _P, _P,
            ]
            try:
                # optional helper: a prebuilt library from an older
                # source may lack it — that must not disable the lane
                lib.seq_sum_f64.restype = ctypes.c_double
                lib.seq_sum_f64.argtypes = [_P, ctypes.c_int64]
            except AttributeError:
                pass
            try:
                lib.seq_sum_f64_plain.restype = ctypes.c_double
                lib.seq_sum_f64_plain.argtypes = [_P, ctypes.c_int64]
            except AttributeError:
                pass
            lib.fifo_solve_queue_single_az.restype = ctypes.c_int
            lib.fifo_solve_queue_single_az.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, _P, _P, _P,
                _P, _P, _P, _P, _P, _P, _P, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, _P, _P, _P,
            ]
            try:
                # delta-solve session API (PR 5) — optional for the same
                # prebuilt-library reason as seq_sum_f64
                lib.fifo_sess_create.restype = _P
                lib.fifo_sess_create.argtypes = []
                lib.fifo_sess_destroy.restype = None
                lib.fifo_sess_destroy.argtypes = [_P]
                lib.fifo_sess_load.restype = ctypes.c_int
                lib.fifo_sess_load.argtypes = [
                    _P, ctypes.c_int64, _P, _P, _P, ctypes.c_int,
                    ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
                ]
                lib.fifo_sess_solve.restype = ctypes.c_int64
                lib.fifo_sess_solve.argtypes = [
                    _P, ctypes.c_int64, _P, _P, _P, _P,
                ]
                lib.fifo_sess_mem_bytes.restype = ctypes.c_int64
                lib.fifo_sess_mem_bytes.argtypes = [_P]
            except AttributeError:
                pass
            try:
                # equivalence-class compressed lanes (ROADMAP 2) —
                # optional for the same prebuilt-library reason
                lib.fifo_solve_queue_classes.restype = ctypes.c_int
                lib.fifo_solve_queue_classes.argtypes = [
                    ctypes.c_int64, ctypes.c_int64, _P, _P, _P, _P,
                    ctypes.c_int, _P, _P, _P,
                ]
                lib.fifo_sess_set_classes.restype = None
                lib.fifo_sess_set_classes.argtypes = [_P, ctypes.c_int]
                lib.fifo_sess_class_stats.restype = None
                lib.fifo_sess_class_stats.argtypes = [_P, _P]
            except AttributeError:
                pass
            try:
                # decision-provenance explainer (PR 6) — optional for the
                # same prebuilt-library reason as the session API
                lib.fifo_explain_queue.restype = ctypes.c_int
                lib.fifo_explain_queue.argtypes = [
                    ctypes.c_int64, ctypes.c_int64, _P, _P, _P, _P,
                    ctypes.c_int, ctypes.c_int64, _P, _P,
                ]
            except AttributeError:
                pass
            try:
                # capacity-observatory probes (PR 7) — optional for the
                # same prebuilt-library reason
                lib.fifo_probe_headroom.restype = ctypes.c_int
                lib.fifo_probe_headroom.argtypes = [
                    ctypes.c_int64, _P, _P, _P, ctypes.c_int64, _P,
                    ctypes.c_int32, _P, _P, _P,
                ]
                lib.fifo_frag_report.restype = ctypes.c_int
                lib.fifo_frag_report.argtypes = [ctypes.c_int64, _P, _P, _P]
            except AttributeError:
                pass
            _lib = lib
        except Exception:
            logger.warning(
                "native fifo solver unavailable; device/XLA lanes only",
                exc_info=True,
            )
            _lib_failed = True
    return _lib


def native_fifo_available() -> bool:
    return _build_and_load() is not None


def _c(arr: np.ndarray) -> ctypes.c_void_p:
    return arr.ctypes.data_as(_P)


def solve_queue_native(
    avail: np.ndarray,        # [N, 3] int32 (not mutated)
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    drivers: np.ndarray,      # [A, 3] int32
    executors: np.ndarray,    # [A, 3] int32
    counts: np.ndarray,       # [A] int32
    app_valid: np.ndarray,    # [A] bool
    evenly: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(feasible[A] bool, driver_idx[A] int32, avail_after[N,3] int32) —
    decision-identical to solve_queue(..., with_placements=False)."""
    lib = _build_and_load()
    if lib is None:
        raise RuntimeError("native fifo solver not available")
    avail_io = np.ascontiguousarray(avail, dtype=np.int32).copy()
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    drv = np.ascontiguousarray(drivers, dtype=np.int32)
    exe = np.ascontiguousarray(executors, dtype=np.int32)
    cnt = np.ascontiguousarray(counts, dtype=np.int32)
    val = np.ascontiguousarray(app_valid, dtype=np.uint8)
    nb, na = avail_io.shape[0], drv.shape[0]
    feas = np.zeros(na, dtype=np.uint8)
    didx = np.zeros(na, dtype=np.int32)
    lib.fifo_solve_queue(
        nb, na, _c(avail_io), _c(rank), _c(eok), _c(drv), _c(exe), _c(cnt),
        _c(val), int(evenly), _c(feas), _c(didx),
    )
    return feas.astype(bool), didx, avail_io


def solve_queue_min_frag_native(
    avail: np.ndarray,        # [N, 3] int32 (not mutated)
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    drivers: np.ndarray,      # [A, 3] int32
    executors: np.ndarray,    # [A, 3] int32
    counts: np.ndarray,       # [A] int32
    app_valid: np.ndarray,    # [A] bool
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(feasible[A] bool, driver_idx[A] int32, avail_after[N,3] int32) —
    decision-identical to batch_solver.solve_queue_min_frag(...,
    with_placements=False) on MF-sentinel-safe inputs (the same guard the
    device lanes hold, batch_solver.mf_sentinel_safe)."""
    lib = _build_and_load()
    if lib is None:
        raise RuntimeError("native fifo solver not available")
    avail_io = np.ascontiguousarray(avail, dtype=np.int32).copy()
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    drv = np.ascontiguousarray(drivers, dtype=np.int32)
    exe = np.ascontiguousarray(executors, dtype=np.int32)
    cnt = np.ascontiguousarray(counts, dtype=np.int32)
    val = np.ascontiguousarray(app_valid, dtype=np.uint8)
    nb, na = avail_io.shape[0], drv.shape[0]
    feas = np.zeros(na, dtype=np.uint8)
    didx = np.zeros(na, dtype=np.int32)
    lib.fifo_solve_queue_minfrag(
        nb, na, _c(avail_io), _c(rank), _c(eok), _c(drv), _c(exe), _c(cnt),
        _c(val), _c(feas), _c(didx),
    )
    return feas.astype(bool), didx, avail_io


def solve_queue_single_az_native(
    avail: np.ndarray,        # [N, 3] int32 (not mutated)
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    zone_id: np.ndarray,      # [N] int32, -1 = in no candidate zone
    drivers: np.ndarray,      # [A, 3] int32
    executors: np.ndarray,    # [A, 3] int32
    counts: np.ndarray,       # [A] int32
    app_valid: np.ndarray,    # [A] bool
    sched_base: np.ndarray,   # [N, 3] int64 base-unit schedulable rows
    scale: np.ndarray,        # [3] int64 tensorize scale vector
    n_zones: int,
    az_aware: bool = False,
    minfrag: bool = False,
    strict: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """(feasible[A] bool, zone_idx[A] int32, driver_idx[A] int32,
    avail_after[N,3] int32) — the single-AZ FIFO pass with the zone
    chosen by EXACT float64 average packing efficiency: decision-
    identical to TpuSingleAzFifoSolver's host lane (pack_one +
    _choose_best_result), with no fixed-point uncertainty valve.
    zone_idx: chosen zone, n_zones = cross-zone fallback, -1 = none."""
    lib = _build_and_load()
    if lib is None:
        raise RuntimeError("native fifo solver not available")
    avail_io = np.ascontiguousarray(avail, dtype=np.int32).copy()
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    zid = np.ascontiguousarray(zone_id, dtype=np.int32)
    drv = np.ascontiguousarray(drivers, dtype=np.int32)
    exe = np.ascontiguousarray(executors, dtype=np.int32)
    cnt = np.ascontiguousarray(counts, dtype=np.int32)
    val = np.ascontiguousarray(app_valid, dtype=np.uint8)
    nb, na = avail_io.shape[0], drv.shape[0]
    sched = np.zeros((nb, 3), dtype=np.int64)
    sb = np.asarray(sched_base, dtype=np.int64)
    sched[: sb.shape[0]] = sb[:nb]
    scl = np.ascontiguousarray(scale, dtype=np.int64)
    feas = np.zeros(na, dtype=np.uint8)
    zone = np.zeros(na, dtype=np.int32)
    didx = np.zeros(na, dtype=np.int32)
    lib.fifo_solve_queue_single_az(
        nb, na, int(n_zones), _c(avail_io), _c(rank), _c(eok), _c(zid),
        _c(drv), _c(exe), _c(cnt), _c(val), _c(sched), _c(scl),
        int(az_aware), int(minfrag), int(strict), _c(feas), _c(zone),
        _c(didx),
    )
    return feas.astype(bool), zone, didx, avail_io


def seq_sum_f64_native(values: np.ndarray) -> Optional[float]:
    """CPython-sum-compatible float64 reduction — bit-identical to
    builtin sum() of the list on THIS interpreter (Neumaier-compensated
    since 3.12, plain left-to-right before), or None when the lib (or
    the needed symbol, in an older prebuilt) is unavailable.

    The gauge path now uses :func:`neumaier_sum_f64_native` instead
    (its contract is cross-lane order-robustness, not builtin parity);
    this wrapper remains the drop-in for any host loop of the form
    ``sum(list)`` a lane wants to move to C without changing a bit."""
    import sys

    lib = _build_and_load()
    if lib is None:
        return None
    symbol = "seq_sum_f64" if sys.version_info >= (3, 12) else "seq_sum_f64_plain"
    if not hasattr(lib, symbol):
        return None
    v = np.ascontiguousarray(values, dtype=np.float64)
    return float(getattr(lib, symbol)(_c(v), v.shape[0]))


def neumaier_sum_f64_native(values: np.ndarray) -> Optional[float]:
    """Neumaier-compensated float64 sum (the seq_sum_f64 symbol,
    interpreter-independent): the packing-efficiency gauge uses this
    because its cross-lane bit-equality contract needs an order-robust
    sum — the host lane accumulates the same per-node maxes in metadata
    order, the tensor lanes in node-priority order, and compensation
    recovers the same rounded value where plain sequential addition
    diverges by an ulp.  None when unavailable."""
    lib = _build_and_load()
    if lib is None or not hasattr(lib, "seq_sum_f64"):
        return None
    v = np.ascontiguousarray(values, dtype=np.float64)
    return float(lib.seq_sum_f64(_c(v), v.shape[0]))


# queue policy codes shared with native/fifo_solver.cpp::FifoSession
POLICY_TIGHTLY = 0
POLICY_EVENLY = 1
POLICY_MINFRAG = 2


def solve_packed_cold(
    policy_code: int,
    avail: np.ndarray,        # [N, 3] int32 basis (not mutated)
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    apps_packed: np.ndarray,  # [A, 8] int32: d0..2 e0..2 count valid
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stateless cold solve of a session-format packed queue under the
    given policy code — ONE dispatch shared by the delta-solve engine's
    warm≠cold parity guard and the flight-recorder bundle replay, so the
    policy-code → solver mapping can never diverge between the two
    mechanisms whose job is proving solver equivalence."""
    drv = apps_packed[:, 0:3]
    exe = apps_packed[:, 3:6]
    cnt = apps_packed[:, 6]
    val = apps_packed[:, 7].astype(bool)
    if policy_code == POLICY_MINFRAG:
        return solve_queue_min_frag_native(
            avail, driver_rank, exec_ok, drv, exe, cnt, val
        )
    return solve_queue_native(
        avail, driver_rank, exec_ok, drv, exe, cnt, val,
        evenly=(policy_code == POLICY_EVENLY),
    )


def native_classes_available() -> bool:
    lib = _build_and_load()
    return lib is not None and hasattr(lib, "fifo_solve_queue_classes")


def solve_packed_classes(
    policy_code: int,
    avail: np.ndarray,        # [N, 3] int32 basis (not mutated)
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    apps_packed: np.ndarray,  # [A, 8] int32: d0..2 e0..2 count valid
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, dict]:
    """Stateless class-compressed solve of a session-format packed queue
    (fifo_solver.cpp ``fifo_solve_queue_classes``): byte-identical
    verdicts and post-queue availability to :func:`solve_packed_cold` at
    the same inputs, with per-app cost O(classes + diverged overlay)
    instead of O(nodes).  The fourth element is the compression evidence:
    ``{"classes_initial", "rebuilds", "overlay_peak", "classes_last"}``."""
    lib = _build_and_load()
    if lib is None or not hasattr(lib, "fifo_solve_queue_classes"):
        raise RuntimeError("native class-compressed solver not available")
    avail_io = np.ascontiguousarray(avail, dtype=np.int32).copy()
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    apps = np.ascontiguousarray(apps_packed, dtype=np.int32)
    nb, na = avail_io.shape[0], apps.shape[0]
    feas = np.zeros(max(na, 1), dtype=np.uint8)
    didx = np.zeros(max(na, 1), dtype=np.int32)
    stats = np.zeros(4, dtype=np.int64)
    lib.fifo_solve_queue_classes(
        nb, na, _c(avail_io), _c(rank), _c(eok), _c(apps),
        int(policy_code), _c(feas), _c(didx), _c(stats),
    )
    evidence = {
        "classes_initial": int(stats[0]),
        "rebuilds": int(stats[1]),
        "overlay_peak": int(stats[2]),
        "classes_last": int(stats[3]),
    }
    return feas[:na].astype(bool), didx[:na], avail_io, evidence


def native_session_available() -> bool:
    lib = _build_and_load()
    return lib is not None and hasattr(lib, "fifo_sess_create")


class NativeFifoSession:
    """Persistent native solver session: the scaled availability basis,
    the rank-sorted driver candidates, and the prefix-feasibility
    checkpoints stay resident in the C++ extension between Filter
    requests (fifo_solver.cpp ``fifo_sess_*``).

    ``solve`` self-verifies the queue prefix byte-for-byte inside the
    extension, so callers may pass whatever they believe the queue is —
    a wrong belief costs a deeper re-solve, never a wrong decision.
    Not thread-safe; the owning engine serializes access."""

    def __init__(self, threads: int = 0, min_pool_nodes: int = 8192):
        lib = _build_and_load()
        if lib is None or not hasattr(lib, "fifo_sess_create"):
            raise RuntimeError("native fifo session not available")
        self._lib = lib
        self._handle = ctypes.c_void_p(lib.fifo_sess_create())
        if not self._handle:
            raise RuntimeError("fifo_sess_create failed")
        self._threads = int(threads)
        self._min_pool_nodes = int(min_pool_nodes)
        self.nb = 0

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.fifo_sess_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def load(
        self,
        avail: np.ndarray,        # [Nb, 3] int32 scaled basis
        driver_rank: np.ndarray,  # [Nb] int32
        exec_ok: np.ndarray,      # [Nb] bool
        policy: int,
        stride: int = 64,
    ) -> None:
        av = np.ascontiguousarray(avail, dtype=np.int32)
        rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
        eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
        nb = av.shape[0]
        ok = self._lib.fifo_sess_load(
            self._handle, nb, _c(av), _c(rank), _c(eok), int(policy),
            int(stride), self._threads, self._min_pool_nodes,
        )
        if not ok:
            raise RuntimeError("fifo_sess_load failed")
        self.nb = int(nb)

    def solve(
        self, apps_packed: np.ndarray  # [A, 8] int32: d0..2 e0..2 count valid
    ) -> Tuple[int, np.ndarray, np.ndarray, np.ndarray]:
        """(resume_index, feasible[A] bool, driver_idx[A] int32,
        avail_after[Nb, 3] int32)."""
        apps = np.ascontiguousarray(apps_packed, dtype=np.int32)
        na = apps.shape[0]
        feas = np.zeros(max(na, 1), dtype=np.uint8)
        didx = np.zeros(max(na, 1), dtype=np.int32)
        avail_after = np.zeros((self.nb, 3), dtype=np.int32)
        resume = self._lib.fifo_sess_solve(
            self._handle, na, _c(apps), _c(feas), _c(didx), _c(avail_after)
        )
        if resume < 0:
            raise RuntimeError("fifo_sess_solve on an unloaded session")
        return int(resume), feas[:na].astype(bool), didx[:na], avail_after

    def mem_bytes(self) -> int:
        if not getattr(self, "_handle", None):
            return 0
        return int(self._lib.fifo_sess_mem_bytes(self._handle))

    def set_classes(self, enable: bool) -> bool:
        """Toggle equivalence-class compressed stepping (ROADMAP 2).
        Verdicts and planes stay byte-identical either way; returns
        whether the loaded extension supports the mode (older prebuilt
        libraries silently stay row-level)."""
        if not hasattr(self._lib, "fifo_sess_set_classes"):
            return False
        self._lib.fifo_sess_set_classes(self._handle, int(bool(enable)))
        return True

    def class_stats(self) -> dict:
        """Compression evidence of the session's class partition:
        ``{"classes_last", "rebuilds", "overlay_peak", "overlay_now"}``
        (zeros until class mode has stepped, or when unsupported)."""
        out = np.zeros(4, dtype=np.int64)
        if getattr(self, "_handle", None) and hasattr(
            self._lib, "fifo_sess_class_stats"
        ):
            self._lib.fifo_sess_class_stats(self._handle, _c(out))
        return {
            "classes_last": int(out[0]),
            "rebuilds": int(out[1]),
            "overlay_peak": int(out[2]),
            "overlay_now": int(out[3]),
        }


def native_explain_available() -> bool:
    lib = _build_and_load()
    return lib is not None and hasattr(lib, "fifo_explain_queue")


class ExplainResult:
    """Decoded ``fifo_explain_queue`` output (provenance/explain.py).

    ``flip`` is the queue position whose step turned the target
    infeasible (-1 = feasible at its own position, -2 = infeasible even
    against the empty basis); ``blockers`` is the per-position blocker
    mask; the rest decompose the target-position probe (see the C++
    entry-point comment for exact semantics)."""

    __slots__ = (
        "flip", "feasible", "cap_total", "dim_totals", "max_cap",
        "max_node", "driver_fit", "tightest_dim", "shortfall_execs",
        "blockers",
    )

    def __init__(self, info: np.ndarray, blockers: np.ndarray):
        self.flip = int(info[0])
        self.feasible = bool(info[1])
        self.cap_total = int(info[2])
        self.dim_totals = (int(info[3]), int(info[4]), int(info[5]))
        self.max_cap = int(info[6])
        self.max_node = int(info[7])
        self.driver_fit = int(info[8])
        self.tightest_dim = int(info[9])
        self.shortfall_execs = int(info[10])
        self.blockers = blockers

    @property
    def blocker_count(self) -> int:
        return int(self.blockers.sum())


def explain_queue_native(
    avail: np.ndarray,        # [N, 3] int32 basis (queue position 0)
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    apps_packed: np.ndarray,  # [A, 8] int32: d0..2 e0..2 count valid
    policy: int,
    target: int,
) -> Optional[ExplainResult]:
    """Shortfall vector + blocker set for the app at queue position
    ``target`` (see fifo_solver.cpp fifo_explain_queue), or None when
    the library (or the symbol, in an older prebuilt) is unavailable or
    the inputs are degenerate.  Diagnostic only — never a decision
    input."""
    lib = _build_and_load()
    if lib is None or not hasattr(lib, "fifo_explain_queue"):
        return None
    av = np.ascontiguousarray(avail, dtype=np.int32)
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    apps = np.ascontiguousarray(apps_packed, dtype=np.int32)
    nb, na = av.shape[0], apps.shape[0]
    if nb <= 0 or na <= 0 or not (0 <= target < na):
        return None
    blockers = np.zeros(na, dtype=np.uint8)
    info = np.zeros(12, dtype=np.int64)
    ok = lib.fifo_explain_queue(
        nb, na, _c(av), _c(rank), _c(eok), _c(apps),
        int(policy), int(target), _c(blockers), _c(info),
    )
    if not ok:
        return None
    return ExplainResult(info, blockers.astype(bool))


def native_probe_available() -> bool:
    lib = _build_and_load()
    return lib is not None and hasattr(lib, "fifo_probe_headroom")


def probe_headroom_native(
    avail: np.ndarray,        # [N, 3] int32 scaled availability basis
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    shapes: np.ndarray,       # [S, 6] int32: d0..2 e0..2 (scaled units)
    k_max: int,
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """(headroom[S] int64, usable[S,3] int64, probes[S] int64) — per
    shape, the largest gang size the solver would admit at queue
    position 0 against this basis (fifo_probe_headroom), or None when
    the library (or symbol) is unavailable.  Read-only diagnostic —
    never a decision input."""
    lib = _build_and_load()
    if lib is None or not hasattr(lib, "fifo_probe_headroom"):
        return None
    av = np.ascontiguousarray(avail, dtype=np.int32)
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    shp = np.ascontiguousarray(shapes, dtype=np.int32)
    nb, ns = av.shape[0], shp.shape[0]
    if nb <= 0 or ns <= 0 or k_max <= 0:
        return None
    headroom = np.zeros(ns, dtype=np.int64)
    usable = np.zeros((ns, 3), dtype=np.int64)
    probes = np.zeros(ns, dtype=np.int64)
    ok = lib.fifo_probe_headroom(
        nb, _c(av), _c(rank), _c(eok), ns, _c(shp),
        ctypes.c_int32(int(k_max)), _c(headroom), _c(usable), _c(probes),
    )
    if not ok:
        return None
    return headroom, usable, probes


def frag_report_native(
    avail: np.ndarray,   # [N, 3] int32 scaled availability
    exec_ok: np.ndarray, # [N] bool
) -> Optional[np.ndarray]:
    """[3, 4] int64 per-dimension (total free, largest chunk, free
    nodes, overdrawn nodes) over the eligible rows, or None when the
    library (or symbol) is unavailable."""
    lib = _build_and_load()
    if lib is None or not hasattr(lib, "fifo_frag_report"):
        return None
    av = np.ascontiguousarray(avail, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    out = np.zeros(12, dtype=np.int64)
    if not lib.fifo_frag_report(av.shape[0], _c(av), _c(eok), _c(out)):
        return None
    return out.reshape(3, 4)


def solve_app_native(
    avail: np.ndarray,        # [N, 3] int32
    driver_rank: np.ndarray,  # [N] int32
    exec_ok: np.ndarray,      # [N] bool
    driver: np.ndarray,       # [3] int32
    executor: np.ndarray,     # [3] int32
    k: int,
) -> Tuple[bool, int, np.ndarray, np.ndarray]:
    """(feasible, driver_idx, exec_counts[N], exec_capacity[N]) —
    decision-identical to batch_solver.solve_app (tightly-pack fill
    counts + post-driver-placement capacities)."""
    lib = _build_and_load()
    if lib is None:
        raise RuntimeError("native fifo solver not available")
    av = np.ascontiguousarray(avail, dtype=np.int32)
    rank = np.ascontiguousarray(driver_rank, dtype=np.int32)
    eok = np.ascontiguousarray(exec_ok, dtype=np.uint8)
    drv = np.ascontiguousarray(driver, dtype=np.int32)
    exe = np.ascontiguousarray(executor, dtype=np.int32)
    nb = av.shape[0]
    feas = np.zeros(1, dtype=np.uint8)
    didx = np.zeros(1, dtype=np.int32)
    counts = np.zeros(nb, dtype=np.int32)
    caps = np.zeros(nb, dtype=np.int32)
    lib.fifo_solve_app(
        nb, _c(av), _c(rank), _c(eok), _c(drv), _c(exe),
        ctypes.c_int32(int(k)), _c(feas), _c(didx), _c(counts), _c(caps),
    )
    return bool(feas[0]), int(didx[0]), counts, caps
