"""Bridge between the scheduler's SparkBinPackFunction interface and the
JAX batch solver: marshals snapshots to tensors, runs the jitted kernel,
and decodes device results into the reference's exact placement lists.

Safety net: any problem that can't be represented exactly in scaled
int32 (tensorize.scale_problem.ok == False) falls back to the host
oracle, so `binpack: tpu-batch` can never produce a wrong decision from
numeric representation.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import numpy as np

from .. import compat
from ..types.resources import NodeGroupSchedulingMetadata, Resources
from . import packers
from .efficiency import compute_packing_efficiencies
from .packers import PackingResult, empty_packing_result
from .registry import Binpacker, TPU_BATCH
from .tensorize import (
    ClusterTensor,
    ScaledProblem,
    scale_problem,
    tensorize_apps,
    tensorize_cluster,
)

logger = logging.getLogger(__name__)


def evenly_counts(cap: np.ndarray, k: int) -> np.ndarray:
    """Exact distribute-evenly per-node counts from per-node capacities
    (distribute_evenly.go:34-73): t complete round-robin sweeps plus a
    partial sweep over the first r capacity-remaining nodes in priority
    order."""
    cap = cap.astype(np.int64)
    if k <= 0:
        return np.zeros_like(cap)
    total = int(cap.sum())
    assert total >= k, "evenly_counts called on infeasible problem"

    # S(t) = Σ min(cap, t) is monotone; find t_full = max{t : S(t) ≤ k}
    lo, hi = 0, int(cap.max())
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if int(np.minimum(cap, mid).sum()) <= k:
            lo = mid
        else:
            hi = mid - 1
    t_full = lo
    counts = np.minimum(cap, t_full)
    r = k - int(counts.sum())
    if r > 0:
        open_nodes = np.flatnonzero(cap > t_full)[:r]
        counts[open_nodes] += 1
    return counts


def build_reserved(
    names: List[str],
    counts: np.ndarray,
    driver_node: str,
    driver_resources: Resources,
    executor_resources: Resources,
) -> dict:
    """Per-node reserved map for efficiency computation, identical to the
    oracle's mutation of `reserved` (driver + count x executor per node),
    in O(#hosting-nodes) exact arithmetic."""
    from ..utils.quantity import Quantity

    reserved = {driver_node: driver_resources}
    for name, c in zip(names, counts):
        if c > 0:
            total = Resources(
                Quantity(executor_resources.cpu.exact * int(c)),
                Quantity(executor_resources.memory.exact * int(c)),
                Quantity(executor_resources.nvidia_gpu.exact * int(c)),
            )
            reserved[name] = reserved.get(name, Resources.zero()).add(total)
    return reserved


def min_frag_unclamped_caps(
    avail: np.ndarray, exec_row: np.ndarray, exec_ok: np.ndarray, driver_idx: int,
    driver_row: np.ndarray,
) -> np.ndarray:
    """Exact UNCLAMPED per-node capacities (int64) for the min-frag
    decode, from scaled integer availability rows with the driver
    subtracted on its node (capacity.go:36-75; negative dims are 0 even
    under a zero requirement — the reserved>available short-circuit)."""
    avail = avail.astype(np.int64).copy()
    avail[driver_idx] -= driver_row.astype(np.int64)
    exec_row = exec_row.astype(np.int64)
    per_dim = np.where(
        exec_row[None, :] == 0,
        np.where(avail >= 0, np.int64(2**62), np.int64(0)),
        np.floor_divide(avail, np.maximum(exec_row[None, :], 1)),
    )
    cap = np.clip(per_dim.min(axis=1), 0, None)
    return np.where(exec_ok, cap, 0)


def minimal_fragmentation_assignment(
    names: List[str], cap: np.ndarray, k: int
) -> Optional[List[str]]:
    """Exact minimal-fragmentation placement from per-node integer
    capacities (minimal_fragmentation.go:59-137): the capacities the
    device returns equal the oracle's Fraction floor divisions, so the
    host-side bisect algorithm reproduces the oracle list exactly."""
    from .capacity import NodeAndExecutorCapacity
    from .packers import minimal_fragmentation_from_capacities

    if k == 0:
        return []
    capacities = [
        NodeAndExecutorCapacity(name, int(c)) for name, c in zip(names, cap) if c > 0
    ]
    nodes, ok = minimal_fragmentation_from_capacities(k, capacities)
    return nodes if ok else None


def min_frag_zone_decode(
    names: List[str],
    avail_rows: np.ndarray,
    exec_row: np.ndarray,
    zone_exec_ok: np.ndarray,
    d_idx: int,
    driver_row: np.ndarray,
    k: int,
    strict_reference_parity: bool,
):
    """Per-zone minimal-fragmentation decode shared by the single-AZ
    single-app adapter and the FIFO solver's zone-choice lane: exact
    bisect placements on device-equal capacities, the true per-node
    counts (for the usage carry), and the efficiency-side counts —
    zeroed under strict parity, where the reference's no-write-back
    quirk makes the zone choice see only the driver's reservation.
    Returns (executor_nodes, counts, eff_counts) or None (infeasible)."""
    zcap = min_frag_unclamped_caps(avail_rows, exec_row, zone_exec_ok, d_idx, driver_row)
    executor_nodes = minimal_fragmentation_assignment(names, zcap, k)
    if executor_nodes is None:
        return None
    counts = np.zeros(len(names), dtype=np.int64)
    pos = {name: i for i, name in enumerate(names)}
    for node in executor_nodes:
        counts[pos[node]] += 1
    eff_counts = np.zeros_like(counts) if strict_reference_parity else counts
    return executor_nodes, counts, eff_counts


def counts_to_tightly_list(names: List[str], counts: np.ndarray) -> List[str]:
    out: List[str] = []
    for name, c in zip(names, counts):
        if c > 0:
            out.extend([name] * int(c))
    return out


def counts_to_evenly_list(names: List[str], counts: np.ndarray) -> List[str]:
    """Round-robin visit order: sweep t emits every node with count > t,
    in priority order (matches the Go loop's append order)."""
    counts = counts.astype(np.int64)
    k = int(counts.sum())
    if k == 0:
        return []
    idx = np.flatnonzero(counts)
    # (sweep, priority position) pairs for each emitted executor
    sweeps = np.concatenate([np.arange(counts[i]) for i in idx])
    positions = np.repeat(idx, counts[idx])
    order = np.lexsort((positions, sweeps))
    return [names[positions[j]] for j in order]


class TpuBatchBinpacker:
    """A drop-in SparkBinPackFunction backed by the JAX solver.

    assignment_policy: 'tightly-pack' or 'distribute-evenly' — controls
    the executor placement list (feasibility and driver choice are
    policy-invariant, see batch_solver docstring).
    """

    def __init__(
        self,
        assignment_policy: str = "tightly-pack",
        verify_against_oracle: bool = False,
        strict_reference_parity: bool = compat.DEFAULT_STRICT,
    ):
        self.assignment_policy = assignment_policy
        self.verify_against_oracle = verify_against_oracle
        self.strict_reference_parity = strict_reference_parity

    def __call__(
        self,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_node_priority_order: Sequence[str],
        executor_node_priority_order: Sequence[str],
        metadata: NodeGroupSchedulingMetadata,
    ) -> PackingResult:
        from .sparkapp import app_resources_of  # lazy tiny helper

        cluster = tensorize_cluster(
            metadata, driver_node_priority_order, executor_node_priority_order
        )
        apps = tensorize_apps(
            [app_resources_of(driver_resources, executor_resources, executor_count)]
        )
        problem = scale_problem(cluster, apps)
        oracle = {
            "tightly-pack": packers.tightly_pack,
            "minimal-fragmentation": packers.make_minimal_fragmentation_pack(
                self.strict_reference_parity
            ),
        }.get(self.assignment_policy, packers.distribute_evenly)
        if not problem.ok:
            logger.warning("snapshot not exactly tensorizable; using host oracle")
            return oracle(
                driver_resources,
                executor_resources,
                executor_count,
                driver_node_priority_order,
                executor_node_priority_order,
                metadata,
            )

        result = self._solve_and_decode(cluster, problem, executor_count, metadata)

        if self.verify_against_oracle:
            expected = oracle(
                driver_resources,
                executor_resources,
                executor_count,
                driver_node_priority_order,
                executor_node_priority_order,
                metadata,
            )
            if (
                expected.has_capacity != result.has_capacity
                or expected.driver_node != result.driver_node
                or expected.executor_nodes != result.executor_nodes
            ):
                logger.error(
                    "tpu-batch solver disagreed with oracle (solver %s@%s vs oracle %s@%s); "
                    "using oracle",
                    result.has_capacity,
                    result.driver_node,
                    expected.has_capacity,
                    expected.driver_node,
                )
                return expected
        return result

    def _solve_and_decode(
        self,
        cluster: ClusterTensor,
        problem: ScaledProblem,
        executor_count: int,
        metadata: NodeGroupSchedulingMetadata,
    ) -> PackingResult:
        import jax.numpy as jnp

        from .batch_solver import solve_single

        solve = solve_single(
            jnp.asarray(problem.avail),
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(problem.driver[0]),
            jnp.asarray(problem.executor[0]),
            jnp.asarray(problem.count[0]),
        )
        feasible = bool(solve.feasible)
        if not feasible:
            return empty_packing_result()

        driver_idx = int(solve.driver_idx)
        names = cluster.node_names
        driver_node = names[driver_idx]

        if self.assignment_policy == "tightly-pack":
            counts = np.asarray(solve.exec_counts)[: len(names)]
            executor_nodes = counts_to_tightly_list(names, counts)
        elif self.assignment_policy == "minimal-fragmentation":
            # min-frag's (k+max)/2 subset threshold needs UNCLAMPED
            # capacities (the device clamps to k for overflow safety):
            # recompute exactly from the scaled integer rows, with the
            # driver subtracted on its node
            cap = min_frag_unclamped_caps(
                problem.avail[: len(names)],
                problem.executor[0],
                np.asarray(problem.exec_ok[: len(names)]),
                driver_idx,
                problem.driver[0],
            )
            executor_nodes = minimal_fragmentation_assignment(names, cap, executor_count)
            if executor_nodes is None:
                return empty_packing_result()
            # the reference's min-frag does NOT fold executor placements
            # into reserved for efficiency (packers.minimal_fragmentation
            # QUIRK, switchable) — under strict parity efficiency
            # accounting sees only the driver; corrected mode folds the
            # placements in, mirroring the oracle's write-back
            counts = np.zeros(len(names), dtype=np.int64)
            if not self.strict_reference_parity:
                pos = {name: i for i, name in enumerate(names)}
                for node in executor_nodes:
                    counts[pos[node]] += 1
        else:
            cap = np.asarray(solve.exec_capacity)[: len(names)]
            counts = evenly_counts(cap, executor_count)
            executor_nodes = counts_to_evenly_list(names, counts)

        # efficiencies as the reference computes them: driver + per-node
        # executor reservations folded into `reserved`
        reserved = {driver_node: Resources.zero()}
        # build reserved the same way the oracle mutates it
        dr = metadata[driver_node]  # noqa: F841 (existence check)
        reserved[driver_node] = self._scale_back(problem, problem.driver[0])
        for name, c in zip(names, counts):
            if c > 0:
                add = self._scale_back(problem, problem.executor[0] * int(c))
                reserved[name] = reserved.get(name, Resources.zero()).add(add)
        return PackingResult(
            driver_node=driver_node,
            executor_nodes=executor_nodes,
            has_capacity=True,
            packing_efficiencies=compute_packing_efficiencies(metadata, reserved),
        )

    @staticmethod
    def _scale_back(problem: ScaledProblem, row: np.ndarray) -> Resources:
        from fractions import Fraction

        from ..utils.quantity import Quantity

        cpu_m, mem_b, gpu_m = (
            int(row[0]) * int(problem.scale[0]),
            int(row[1]) * int(problem.scale[1]),
            int(row[2]) * int(problem.scale[2]),
        )
        return Resources(
            Quantity(Fraction(cpu_m, 1000)),
            Quantity(mem_b),
            Quantity(Fraction(gpu_m, 1000)),
        )


def tpu_batch_binpacker() -> Binpacker:
    from .fifo_solver import TpuFifoSolver

    return Binpacker(
        name=TPU_BATCH,
        binpack_func=TpuBatchBinpacker(assignment_policy="tightly-pack"),
        is_single_az=False,
        queue_solver=TpuFifoSolver(assignment_policy="tightly-pack"),
    )


def tpu_batch_evenly_binpacker() -> Binpacker:
    from .fifo_solver import TpuFifoSolver

    return Binpacker(
        name="tpu-batch-distribute-evenly",
        binpack_func=TpuBatchBinpacker(assignment_policy="distribute-evenly"),
        is_single_az=False,
        queue_solver=TpuFifoSolver(assignment_policy="distribute-evenly"),
    )


def tpu_batch_min_frag_binpacker(
    strict_reference_parity: bool = compat.DEFAULT_STRICT,
) -> Binpacker:
    from .fifo_solver import TpuFifoSolver

    return Binpacker(
        name="tpu-batch-minimal-fragmentation",
        binpack_func=TpuBatchBinpacker(
            assignment_policy="minimal-fragmentation",
            strict_reference_parity=strict_reference_parity,
        ),
        is_single_az=False,
        queue_solver=TpuFifoSolver(
            assignment_policy="minimal-fragmentation",
            strict_reference_parity=strict_reference_parity,
        ),
    )


def candidate_zone_masks(driver_order, executor_order, metadata, names, nb):
    """Zone ordering + per-zone node masks shared by the single-AZ gang
    and FIFO device paths (single_az.go:30-45 first-appearance order;
    zones without executor candidates are dropped)."""
    driver_zones_in_order, _ = packers.group_nodes_by_zone(driver_order, metadata)
    _, executor_by_zone = packers.group_nodes_by_zone(executor_order, metadata)
    candidate_zones = [z for z in driver_zones_in_order if z in executor_by_zone]
    zone_of = {name: metadata[name].zone_label for name in names}
    zone_masks = np.zeros((max(len(candidate_zones), 1), nb), dtype=bool)
    for zi, zone in enumerate(candidate_zones):
        for i, name in enumerate(names):
            zone_masks[zi, i] = zone_of[name] == zone
    return candidate_zones, zone_masks


class TpuSingleAzBinpacker:
    """Single-AZ combinator on device (single_az.go:23-55): all zones
    solved in one vmapped call, zone chosen on host with the oracle's
    exact efficiency math (_choose_best_result).  az_aware=True adds the
    cross-zone fallback (az_aware_pack_tightly.go:27-38).

    inner_policy selects the per-zone distribution: "tightly-pack"
    (device counts) or "minimal-fragmentation"
    (single_az_minimal_fragmentation semantics: zone feasibility and
    driver choice are policy-invariant, so the vmapped zone solves are
    shared; placements come from the exact host bisect on device-equal
    capacities, and under strict parity the reference's
    no-efficiency-write-back quirk makes the zone choice see only the
    driver's reservation)."""

    def __init__(
        self,
        az_aware: bool = False,
        inner_policy: str = "tightly-pack",
        strict_reference_parity: bool = compat.DEFAULT_STRICT,
    ):
        self.az_aware = az_aware
        self.inner_policy = inner_policy
        self.strict_reference_parity = strict_reference_parity

    def __call__(
        self,
        driver_resources: Resources,
        executor_resources: Resources,
        executor_count: int,
        driver_node_priority_order: Sequence[str],
        executor_node_priority_order: Sequence[str],
        metadata: NodeGroupSchedulingMetadata,
    ) -> PackingResult:
        import jax.numpy as jnp

        from .batch_solver import solve_single, solve_zones_jit
        from .sparkapp import app_resources_of

        cluster = tensorize_cluster(
            metadata, driver_node_priority_order, executor_node_priority_order
        )
        apps = tensorize_apps(
            [app_resources_of(driver_resources, executor_resources, executor_count)]
        )
        problem = scale_problem(cluster, apps)
        if self.inner_policy == "minimal-fragmentation":
            oracle = packers.make_single_az_minimal_fragmentation(
                self.strict_reference_parity
            )
        else:
            oracle = (
                packers.az_aware_tightly_pack
                if self.az_aware
                else packers.single_az_tightly_pack
            )
        if not problem.ok:
            logger.warning("snapshot not exactly tensorizable; using host oracle")
            return oracle(
                driver_resources,
                executor_resources,
                executor_count,
                driver_node_priority_order,
                executor_node_priority_order,
                metadata,
            )

        names = cluster.node_names
        n = len(names)
        nb = problem.avail.shape[0]
        candidate_zones, zone_masks = candidate_zone_masks(
            driver_node_priority_order, executor_node_priority_order, metadata, names, nb
        )

        solves = solve_zones_jit(
            jnp.asarray(problem.avail),
            jnp.asarray(problem.driver_rank),
            jnp.asarray(problem.exec_ok),
            jnp.asarray(zone_masks),
            jnp.asarray(problem.driver[0]),
            jnp.asarray(problem.executor[0]),
            jnp.asarray(problem.count[0]),
        )
        feasible = np.asarray(solves.feasible)
        driver_idx = np.asarray(solves.driver_idx)
        counts = np.asarray(solves.exec_counts)

        results = []
        exec_ok_arr = np.asarray(problem.exec_ok[:n])
        for zi, zone in enumerate(candidate_zones):
            if not feasible[zi]:
                continue
            d_idx = int(driver_idx[zi])
            driver_node = names[d_idx]
            if self.inner_policy == "minimal-fragmentation":
                decoded = min_frag_zone_decode(
                    names,
                    problem.avail[:n],
                    problem.executor[0],
                    exec_ok_arr & zone_masks[zi][:n],
                    d_idx,
                    problem.driver[0],
                    executor_count,
                    self.strict_reference_parity,
                )
                if decoded is None:  # unreachable: zone feasibility proven
                    continue
                executor_nodes, _counts, eff_counts = decoded
            else:
                zone_counts = counts[zi][:n]
                executor_nodes = counts_to_tightly_list(names, zone_counts)
                eff_counts = zone_counts
            results.append(
                PackingResult(
                    driver_node=driver_node,
                    executor_nodes=executor_nodes,
                    has_capacity=True,
                    packing_efficiencies=compute_packing_efficiencies(
                        metadata,
                        build_reserved(
                            names, eff_counts, driver_node, driver_resources, executor_resources
                        ),
                    ),
                )
            )

        if results:
            best = packers._choose_best_result(metadata, results)
            # _choose_best_result can return the empty result when every
            # candidate has zero avg efficiency (the documented quirk) —
            # az-aware must then still take the cross-zone fallback, like
            # az_aware_pack_tightly.go:34-37's has_capacity check
            if best.has_capacity or not self.az_aware:
                return best
        if self.az_aware:
            # cross-zone fallback: plain tightly-pack on device
            return TpuBatchBinpacker(assignment_policy="tightly-pack")(
                driver_resources,
                executor_resources,
                executor_count,
                driver_node_priority_order,
                executor_node_priority_order,
                metadata,
            )
        return empty_packing_result()


def tpu_batch_single_az_binpacker() -> Binpacker:
    from .fifo_solver import TpuSingleAzFifoSolver

    return Binpacker(
        name="tpu-batch-single-az",
        binpack_func=TpuSingleAzBinpacker(az_aware=False),
        is_single_az=True,
        queue_solver=TpuSingleAzFifoSolver(az_aware=False),
    )


def tpu_batch_single_az_min_frag_binpacker(
    strict_reference_parity: bool = compat.DEFAULT_STRICT,
) -> Binpacker:
    from .fifo_solver import TpuSingleAzFifoSolver

    return Binpacker(
        name="tpu-batch-single-az-minimal-fragmentation",
        binpack_func=TpuSingleAzBinpacker(
            az_aware=False,
            inner_policy="minimal-fragmentation",
            strict_reference_parity=strict_reference_parity,
        ),
        is_single_az=True,
        queue_solver=TpuSingleAzFifoSolver(
            az_aware=False,
            inner_policy="minimal-fragmentation",
            strict_reference_parity=strict_reference_parity,
        ),
    )


def tpu_batch_az_aware_binpacker() -> Binpacker:
    from .fifo_solver import TpuSingleAzFifoSolver

    return Binpacker(
        name="tpu-batch-az-aware",
        binpack_func=TpuSingleAzBinpacker(az_aware=True),
        is_single_az=True,
        queue_solver=TpuSingleAzFifoSolver(az_aware=True),
    )
