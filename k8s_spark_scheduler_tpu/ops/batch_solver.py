"""JAX batch gang-packing solver — the TPU-native replacement for the
reference's first-fit loops (SURVEY §3.2 hot loops; BASELINE.json north
star).

The key identity making the O(driver-candidates × nodes) Go loop an
O(nodes) vector program: for the tightly-pack / distribute-evenly
policies, executor distribution over a candidate set succeeds iff the
total per-node executor capacity is ≥ k (both fill every node to its
capacity in the limit), and placing the driver on node d only changes
node d's capacity.  So

    T_d = S − cap_d + cap'_d          (S = Σ min(cap_n, k))

for every driver candidate d at once, and the chosen driver is the
first-priority d with (driver fits d) ∧ (T_d ≥ k) — bit-identical to
``SparkBinPack`` + ``tightlyPackExecutors`` / ``distributeExecutorsEvenly``
(reference lib/pkg/binpack/binpack.go:60-87, pack_tightly.go:34-63,
distribute_evenly.go:34-73), proven by the parity suite in
tests/test_batch_parity.py.

The FIFO earlier-drivers pass (resource.go:224-262) is a ``lax.scan``
over apps carrying availability, reproducing the reference's
usage-subtraction quirk (one executor's worth per hosting node,
driver overwritten — sparkpods.go:139-146).

All arrays are int32 (see tensorize.scale_problem for the exactness
guarantee); everything here is shape-static and jit/vmap/shard_map
compatible, with the node axis shardable over a device mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

# plain int (not a jnp scalar): creating a device array at import time
# would initialize the JAX backend as a side effect of merely importing
# this module; int32 ops promote it correctly
BIG = 2**31 - 1


class AppSolve(NamedTuple):
    """Per-app gang decision."""

    feasible: jnp.ndarray      # [] bool
    driver_idx: jnp.ndarray    # [] int32 (index into node axis; N if infeasible)
    exec_counts: jnp.ndarray   # [N] int32 tightly-pack fill counts
    exec_capacity: jnp.ndarray  # [N] int32 per-node capacity after driver placement


def node_capacity(avail: jnp.ndarray, executor: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Per-node executor capacity clamped to [0, k]
    (capacity.go:36-75: floor division per dim, zero-requirement → ∞ —
    but a dimension whose availability is already negative is 0 even
    when the requirement is 0: reserved(0) > available short-circuits
    before the zero-requirement check, capacity.go:37-44)."""
    safe = jnp.maximum(executor, 1)
    per_dim = jnp.where(
        executor[None, :] == 0,
        jnp.where(avail >= 0, BIG, 0),
        jnp.floor_divide(avail, safe[None, :]),
    )
    cap = jnp.min(per_dim, axis=1)
    return jnp.clip(cap, 0, k)


def solve_app(
    avail: jnp.ndarray,        # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32 — driver priority position, BIG if not a candidate
    exec_ok: jnp.ndarray,      # [N] bool — in executor priority list (array order = that list)
    driver: jnp.ndarray,       # [3] int32
    executor: jnp.ndarray,     # [3] int32
    k: jnp.ndarray,            # [] int32
) -> AppSolve:
    """One gang decision, O(N) vector ops."""
    n = avail.shape[0]

    # driver fit mask (Resources.GreaterThan: any-dim; fits = all dims ≤)
    driver_fits = jnp.all(avail >= driver[None, :], axis=1) & (driver_rank < BIG)

    # capacities without / with the driver on the node
    base_cap = jnp.where(exec_ok, node_capacity(avail, executor, k), 0)
    cap_with_driver = jnp.where(
        exec_ok, node_capacity(avail - driver[None, :], executor, k), 0
    )

    total = jnp.sum(base_cap)
    # total capacity if driver lands on d (only node d's capacity changes)
    total_d = total - base_cap + cap_with_driver

    feasible_d = driver_fits & (total_d >= k)
    # first feasible node in DRIVER priority order (ranks are unique)
    masked_rank = jnp.where(feasible_d, driver_rank, BIG)
    driver_idx = jnp.argmin(masked_rank).astype(jnp.int32)
    feasible = masked_rank[driver_idx] < BIG
    driver_idx = jnp.where(feasible, driver_idx, jnp.int32(n))

    safe_idx = jnp.minimum(driver_idx, n - 1)
    cap = jnp.where(
        jnp.arange(n, dtype=jnp.int32) == safe_idx, cap_with_driver, base_cap
    )
    cap = jnp.where(feasible, cap, jnp.zeros_like(cap))

    # tightly-pack greedy fill: x_n = clip(k − Σ_{m<n} cap_m, 0, cap_n)
    cum_excl = jnp.cumsum(cap) - cap
    exec_counts = jnp.clip(k - cum_excl, 0, cap)
    exec_counts = jnp.where(feasible, exec_counts, jnp.zeros_like(exec_counts))

    return AppSolve(
        feasible=feasible,
        driver_idx=jnp.where(feasible, driver_idx, jnp.int32(n)),
        exec_counts=exec_counts,
        exec_capacity=cap,
    )


def evenly_exec_mask(cap: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Which nodes receive ≥1 executor under distribute-evenly: the first
    min(k, #nodes-with-capacity) capacity-bearing nodes in priority order
    (sweep 0 of the round-robin)."""
    has = (cap > 0).astype(jnp.int32)
    rank_excl = jnp.cumsum(has) - has
    return (cap > 0) & (rank_excl < k)


def usage_delta(
    solve: AppSolve,
    driver: jnp.ndarray,
    executor: jnp.ndarray,
    n: int,
    evenly: bool,
) -> jnp.ndarray:
    """The reference's post-placement subtraction QUIRK
    (sparkpods.go:139-146 + resources.go:129-135): nodes hosting ≥1
    executor lose ONE executor's worth; the driver node loses the driver —
    unless it also hosts executors, in which case the executor entry
    overwrites the driver's."""
    if evenly:
        exec_mask = evenly_exec_mask(solve.exec_capacity, jnp.sum(solve.exec_counts))
        exec_mask = exec_mask & solve.feasible
    else:
        exec_mask = solve.exec_counts > 0
    is_driver = jnp.arange(n, dtype=jnp.int32) == solve.driver_idx
    delta = jnp.where(
        exec_mask[:, None],
        executor[None, :],
        jnp.where(is_driver[:, None], driver[None, :], jnp.zeros_like(driver)[None, :]),
    )
    return jnp.where(solve.feasible, delta, jnp.zeros_like(delta))


class QueueSolve(NamedTuple):
    feasible: jnp.ndarray     # [A] bool
    driver_idx: jnp.ndarray   # [A] int32
    exec_counts: jnp.ndarray  # [A, N] int32 (tightly-pack counts)
    exec_capacity: jnp.ndarray  # [A, N] int32
    avail_after: jnp.ndarray  # [N, 3] int32


@functools.partial(jax.jit, static_argnames=("evenly", "with_placements"))
def solve_queue(
    avail: jnp.ndarray,      # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,    # [N]
    drivers: jnp.ndarray,    # [A, 3] int32
    executors: jnp.ndarray,  # [A, 3] int32
    counts: jnp.ndarray,     # [A] int32
    app_valid: jnp.ndarray,  # [A] bool
    evenly: bool = False,
    with_placements: bool = True,
) -> QueueSolve:
    """Whole-FIFO-queue gang solve: scan apps in order, carrying
    availability.  Infeasible apps are skipped (no subtraction), exactly
    like a queue of Filter calls draining one by one.

    with_placements=False returns only the per-app decisions (feasible,
    driver_idx) and the final availability — the decision-latency path;
    any single app's placement is recomputable via solve_single.
    """
    n = avail.shape[0]

    def step(carry_avail, app):
        driver, executor, k, valid = app
        solve = solve_app(carry_avail, driver_rank, exec_ok, driver, executor, k)
        feasible = solve.feasible & valid
        solve = AppSolve(
            feasible=feasible,
            driver_idx=jnp.where(feasible, solve.driver_idx, jnp.int32(n)),
            exec_counts=jnp.where(feasible, solve.exec_counts, jnp.zeros_like(solve.exec_counts)),
            exec_capacity=solve.exec_capacity,
        )
        delta = usage_delta(solve, driver, executor, n, evenly)
        if with_placements:
            out = solve
        else:
            out = (feasible, solve.driver_idx)
        return carry_avail - delta, out

    avail_after, outs = lax.scan(step, avail, (drivers, executors, counts, app_valid))
    if with_placements:
        return QueueSolve(
            feasible=outs.feasible,
            driver_idx=outs.driver_idx,
            exec_counts=outs.exec_counts,
            exec_capacity=outs.exec_capacity,
            avail_after=avail_after,
        )
    feasible, driver_idx = outs
    return QueueSolve(
        feasible=feasible,
        driver_idx=driver_idx,
        exec_counts=jnp.zeros((0,), jnp.int32),
        exec_capacity=jnp.zeros((0,), jnp.int32),
        avail_after=avail_after,
    )


# Unbounded-capacity stand-in for the min-frag kernel (host uses
# 2^63-1, capacity.go:45-48).  Capacities here must stay UNCLAMPED for
# the (k+max)/2 subset threshold, so the sentinel lives just above any
# real capacity: callers guard max(avail) ≤ 2^31-3 (tensorize's GCD
# scaling makes this essentially always true) so a real capacity can
# never collide with it.
MF_SENT = 2**31 - 2


def min_frag_capacity(
    avail: jnp.ndarray, executor: jnp.ndarray, exec_ok: jnp.ndarray
) -> jnp.ndarray:
    """UNCLAMPED per-node executor capacity (capacity.go:36-75) for the
    minimal-fragmentation kernel; MF_SENT marks unbounded nodes."""
    safe = jnp.maximum(executor, 1)
    per_dim = jnp.where(
        executor[None, :] == 0,
        jnp.where(avail >= 0, MF_SENT, 0),
        jnp.floor_divide(avail, safe[None, :]),
    )
    cap = jnp.min(per_dim, axis=1)
    return jnp.where(exec_ok, jnp.clip(cap, 0, MF_SENT), 0)


def min_frag_counts(cap: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Minimal-fragmentation per-node executor counts from unclamped
    capacities — the whole of minimal_fragmentation.go:59-137 as
    sort-free vector ops, no data-dependent loop.

    The drain loop linearizes over capacity *value classes*: with
    T(v) = Σ_{cap ≥ v} cap, a class v is fully drained iff T(v) < k, so
    the stop class v* = max{v : T(v) ≥ k} (binary-searched in 31
    probes).  Entering v* with R = k − Σ_{cap > v*} cap remaining,
    t* = ⌈R/v*⌉ − 1 of its nodes (earliest in priority order) drain
    fully and the final k* = R − t*·v* executors go to the smallest
    remaining capacity ≥ k* (earliest priority among equals) — exactly
    the host's ascending bisect.  Probe sums clamp per-term to k so
    everything stays int32 (Σ min(cap,k) ≤ N·k, the scale_problem
    guard); drained classes all have cap < k so the exact prefix sum
    Σ_{cap > v*} cap < k needs no widening.  The (k+max)/2
    "avoid mostly-empty nodes" subset attempt
    (minimal_fragmentation.go:71-87) is the same computation under a
    tighter eligibility mask.  Only valid when Σ min(cap, k) ≥ k (the
    caller's solve_app feasibility); returns zeros otherwise and for
    k = 0."""
    n = cap.shape[0]
    elig = cap > 0
    d = jnp.where(elig, cap, 0)
    iota = jnp.arange(n, dtype=jnp.int32)

    def run(sub):
        """One _internal_minimal_fragmentation pass over the eligibility
        mask `sub`.  Returns (ok, counts-by-node)."""
        dd = jnp.where(sub, d, 0)
        dc = jnp.minimum(dd, k)  # probe terms, int32-safe to sum
        ok = (jnp.sum(dc) >= k) & (k > 0)

        def body(_, lohi):
            lo, hi = lohi
            mid = lo + (hi - lo + 1) // 2
            good = jnp.sum(jnp.where(dd >= mid, dc, 0)) >= k
            return (jnp.where(good, mid, lo), jnp.where(good, hi, mid - 1))

        # fixed 31 probes cover the full int32 capacity domain; this is
        # the variant measured at 123ms/queue (10k×1k) on TPU.  A
        # lax.while_loop bounded by max(dd) (~7 probes for real
        # capacities) is a candidate speedup but is unmeasured on
        # hardware — an earlier "pathological compile" diagnosis against
        # it was traced to a wedged TPU relay plus the sitecustomize
        # env-override trap, not the loop construct.
        vstar, _ = lax.fori_loop(
            0, 31, body, (jnp.int32(1), jnp.int32(MF_SENT))
        )
        s = jnp.sum(jnp.where(dd > vstar, dd, 0))  # drained classes, < k
        r = k - s
        tstar = jnp.maximum(r - 1, 0) // vstar
        kstar = r - tstar * vstar
        at = sub & (dd == vstar)
        at_i = at.astype(jnp.int32)
        at_rank = jnp.cumsum(at_i) - at_i  # class position in priority order
        drained = (sub & (dd > vstar)) | (at & (at_rank < tstar))
        # final placement: smallest capacity ≥ k* among the not-drained,
        # ties to the earliest priority index (the ascending bisect)
        cand = sub & ~drained & (dd >= kstar)
        vp = jnp.min(jnp.where(cand, dd, BIG))
        partial = jnp.argmax(cand & (dd == vp)).astype(jnp.int32)
        counts = jnp.where(drained, dd, 0)
        counts = counts + jnp.where((iota == partial) & ok, kstar, 0)
        return ok, jnp.where(ok, counts, jnp.zeros_like(counts))

    max_cap = jnp.max(d)
    has_sent = jnp.any(elig & (d == MF_SENT))
    # exact (k + max)//2 without int32 overflow; with an unbounded node
    # the host threshold (k + 2^63-1)//2 admits every bounded capacity
    target = (k // 2) + (max_cap // 2) + (((k & 1) + (max_cap & 1)) // 2)
    subset = elig & jnp.where(has_sent, d < MF_SENT, d < target)
    attempt = has_sent | (k < max_cap)
    sub_ok, sub_counts = run(subset & attempt)
    full_ok, full_counts = run(elig)
    counts = jnp.where(attempt & sub_ok, sub_counts, full_counts)
    return jnp.where(full_ok, counts, jnp.zeros_like(counts))


def min_frag_step_counts(carry_avail, feasible, driver_idx, driver, executor, exec_ok, k):
    """Shared per-step min-frag placement: subtract the driver on its
    chosen node, run the capacity + drain kernels over the eligible
    mask, zero when infeasible.  Used by both the plain min-frag queue
    scan and the single-AZ scan's per-zone solves so capacity-semantics
    fixes can never diverge between lanes."""
    n = carry_avail.shape[0]
    is_drv = (jnp.arange(n, dtype=jnp.int32) == driver_idx) & feasible
    avail_eff = carry_avail - jnp.where(is_drv[:, None], driver[None, :], 0)
    mf = min_frag_counts(min_frag_capacity(avail_eff, executor, exec_ok), k)
    return jnp.where(feasible, mf, jnp.zeros_like(mf))


def mf_sentinel_safe(avail) -> bool:
    """Host-side guard shared by the fused min-frag lanes: every scaled
    availability value must stay below MF_SENT − 1 so a real capacity
    can never collide with the unbounded-capacity sentinel."""
    import numpy as _np

    a = _np.asarray(avail)
    return a.size == 0 or int(a.max()) <= MF_SENT - 1


# queue-scan assignment policies every whole-queue lane implements (the
# XLA scan, the pallas kernel, the native C++ solver, and the native
# delta-solve session — native/fifo_solver.cpp::FifoSession uses these
# exact integer codes); single-AZ policies are a separate solver family
QUEUE_POLICY_CODES = {
    "tightly-pack": 0,
    "distribute-evenly": 1,
    "minimal-fragmentation": 2,
}


def queue_policy_code(assignment_policy: str):
    """Native session policy code for a TpuFifoSolver assignment policy,
    or None when no whole-queue session lane serves it."""
    return QUEUE_POLICY_CODES.get(assignment_policy)


@functools.partial(jax.jit, static_argnames=("with_placements",))
def solve_queue_min_frag(
    avail: jnp.ndarray,      # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,    # [N]
    drivers: jnp.ndarray,    # [A, 3] int32
    executors: jnp.ndarray,  # [A, 3] int32
    counts: jnp.ndarray,     # [A] int32
    app_valid: jnp.ndarray,  # [A] bool
    with_placements: bool = True,
) -> QueueSolve:
    """Whole-FIFO-queue solve under the minimal-fragmentation policy in
    ONE dispatch (minimal_fragmentation.go:59-137 × resource.go:224-262).
    Feasibility and driver choice equal tightly-pack's (the drain is
    work-conserving, so distribution succeeds iff Σ capacity ≥ k); only
    the placement — and therefore the carried usage subtraction — needs
    the min-frag kernel."""
    n = avail.shape[0]

    def step(carry_avail, app):
        driver, executor, k, valid = app
        solve = solve_app(carry_avail, driver_rank, exec_ok, driver, executor, k)
        feasible = solve.feasible & valid
        didx = jnp.where(feasible, solve.driver_idx, jnp.int32(n))
        mf = min_frag_step_counts(
            carry_avail, feasible, didx, driver, executor, exec_ok, k
        )
        mf_solve = AppSolve(
            feasible=feasible, driver_idx=didx, exec_counts=mf, exec_capacity=mf
        )
        delta = usage_delta(mf_solve, driver, executor, n, evenly=False)
        out = (feasible, didx, mf) if with_placements else (feasible, didx)
        return carry_avail - delta, out

    avail_after, outs = lax.scan(step, avail, (drivers, executors, counts, app_valid))
    if with_placements:
        feasible, didx, mf = outs
        return QueueSolve(
            feasible=feasible,
            driver_idx=didx,
            exec_counts=mf,
            exec_capacity=jnp.zeros((0,), jnp.int32),
            avail_after=avail_after,
        )
    feasible, didx = outs
    return QueueSolve(
        feasible=feasible,
        driver_idx=didx,
        exec_counts=jnp.zeros((0,), jnp.int32),
        exec_capacity=jnp.zeros((0,), jnp.int32),
        avail_after=avail_after,
    )


@jax.jit
def solve_single(
    avail: jnp.ndarray,
    driver_rank: jnp.ndarray,
    exec_ok: jnp.ndarray,
    driver: jnp.ndarray,
    executor: jnp.ndarray,
    k: jnp.ndarray,
) -> AppSolve:
    """Single-app entry point for the Filter hot path."""
    return solve_app(avail, driver_rank, exec_ok, driver, executor, k)


class ZoneQueueSolve(NamedTuple):
    """Per-app outcome of the fused single-AZ FIFO scan."""

    feasible: jnp.ndarray    # [A] bool
    zone_idx: jnp.ndarray    # [A] int32 — chosen zone; Z = cross-zone fallback, -1 = none
    driver_idx: jnp.ndarray  # [A] int32
    uncertain: jnp.ndarray   # [A] bool — zone choice within the fixed-point margin
    avail_after: jnp.ndarray  # [N, 3] int32


# Fixed-point bits for the on-device zone-efficiency score.  The zone
# choice (single_az.go:75-97: highest average of per-occurrence max
# packing efficiency, strict improvement in zone order) is computed as
# Q_z = Σ_n w_n · round(2^EFF_SHIFT · maxEff_n) with integer weights
# w_n = executor count + driver indicator.  Because every feasible zone
# places exactly k executors + 1 driver, comparing averages equals
# comparing these sums.  Per-term quantization error is < 0.6 fixed-point
# ulps, so |Q_a − Q_b| > 2(k+1)+2 certifies that the float64 oracle
# orders the true sums the same way; equal Q keeps the earlier zone
# (identical to Go for mathematically equal scores), and distinct-but-
# closer scores raise `uncertain` and the caller re-solves on the exact
# host path.  See docs/design.md § "Single-AZ zone choice on device".
EFF_SHIFT = 18


def _zone_score(
    carry_avail: jnp.ndarray,  # [N, 3] int32 scaled
    solve: AppSolve,
    driver: jnp.ndarray,
    executor: jnp.ndarray,
    s_cpu_milli: jnp.ndarray,  # [N] int32 schedulable cpu, base milli units
    s_gpu_milli: jnp.ndarray,  # [N] int32
    inv_mem: jnp.ndarray,      # [N] f32 = scale_mem / schedulable_mem_bytes
    th_mem: jnp.ndarray,       # [N] int32 = ceil(sched_mem_bytes / scale_mem)
    scale_cpu: jnp.ndarray,    # [] int32
    scale_gpu: jnp.ndarray,    # [] int32
    eff_counts: jnp.ndarray | None = None,  # [N] int32 — reservation-side
    # counts for the efficiency numerators when they differ from the
    # occurrence weights (min-frag strict parity: the no-write-back
    # quirk makes efficiencies see only the driver, while occurrences
    # still weight every executor placement)
):
    """(Q, nonzero): the fixed-point zone score for one zone's packing and
    the exact S > 0 indicator (efficiency.go:80-156 semantics: value()
    ceil to cores for cpu/gpu, bytes for memory; gpu efficiency 0 on
    gpu-less nodes; per-node max over dims; occurrence-weighted sum)."""
    n = carry_avail.shape[0]
    is_driver = (jnp.arange(n, dtype=jnp.int32) == solve.driver_idx) & solve.feasible
    counts = solve.exec_counts
    w = counts + is_driver.astype(jnp.int32)
    res_counts = counts if eff_counts is None else eff_counts
    new = res_counts[:, None] * executor[None, :] + jnp.where(
        is_driver[:, None], driver[None, :], 0
    )
    m = carry_avail - new  # scaled availability net of this packing; ≥ 0 where w > 0

    # reserved numerators in exact base units (bounded int32 by the
    # caller's guards): r_dim = sched_base − m·scale
    num_cq = s_cpu_milli - m[:, 0] * scale_cpu
    num_gq = s_gpu_milli - m[:, 2] * scale_gpu
    num_cores = lax.div(num_cq + 999, jnp.int32(1000))
    num_gcores = lax.div(num_gq + 999, jnp.int32(1000))
    den_cores = jnp.maximum(lax.div(s_cpu_milli + 999, jnp.int32(1000)), 1)
    den_gcores = jnp.maximum(lax.div(s_gpu_milli + 999, jnp.int32(1000)), 1)
    has_gpu = s_gpu_milli > 0

    ratio_c = num_cores.astype(jnp.float32) / den_cores.astype(jnp.float32)
    ratio_g = jnp.where(
        has_gpu, num_gcores.astype(jnp.float32) / den_gcores.astype(jnp.float32), 0.0
    )
    ratio_m = jnp.maximum(1.0 - m[:, 1].astype(jnp.float32) * inv_mem, 0.0)
    eff = jnp.maximum(jnp.maximum(ratio_c, ratio_m), ratio_g)
    q = jnp.floor(eff * jnp.float32(2**EFF_SHIFT) + 0.5).astype(jnp.int32)
    score = jnp.sum(jnp.where(w > 0, w * q, 0))
    # exact S > 0: some occupied node has a strictly positive reserved
    # quantity in a dimension that counts (the all-zero-efficiency quirk)
    nonzero = jnp.any(
        (w > 0) & ((num_cq > 0) | (m[:, 1] < th_mem) | (has_gpu & (num_gq > 0)))
    )
    return score, nonzero


@functools.partial(jax.jit, static_argnames=("az_aware", "minfrag", "strict"))
def solve_queue_single_az(
    avail: jnp.ndarray,        # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,      # [N] bool
    zone_masks: jnp.ndarray,   # [Z, N] bool
    drivers: jnp.ndarray,      # [A, 3] int32
    executors: jnp.ndarray,    # [A, 3] int32
    counts: jnp.ndarray,       # [A] int32
    app_valid: jnp.ndarray,    # [A] bool
    s_cpu_milli: jnp.ndarray,  # [N] int32
    s_gpu_milli: jnp.ndarray,  # [N] int32
    inv_mem: jnp.ndarray,      # [N] f32
    th_mem: jnp.ndarray,       # [N] int32
    scale_cpu: jnp.ndarray,    # [] int32
    scale_gpu: jnp.ndarray,    # [] int32
    az_aware: bool = False,
    minfrag: bool = False,
    strict: bool = True,
) -> ZoneQueueSolve:
    """Whole-FIFO-queue single-AZ gang solve in ONE dispatch
    (single_az.go:23-97 × resource.go:224-262): scan apps in order; each
    step solves every zone (inner tightly-pack, or the min-frag kernel
    when minfrag=True — single-az-minimal-fragmentation semantics, with
    driver-only efficiency numerators under strict parity), scores
    feasible zones with the fixed-point efficiency comparator (see
    EFF_SHIFT), applies the strict-improvement choice in zone order,
    optionally falls back to a cross-zone pack
    (az_aware_pack_tightly.go:27-38; no min-frag variant), and carries
    availability with the reference's subtraction quirk."""
    assert not (az_aware and minfrag)
    n = avail.shape[0]
    z_count = zone_masks.shape[0]

    def step(carry_avail, app):
        driver, executor, k, valid = app
        band = 2 * (k + 1) + 2

        def zone_solve(mask):
            """One zone's packing + fixed-point score.  vmapped over
            zones so the scan body holds exactly ONE fori_loop — several
            per step (an unrolled zone loop around the min-frag kernel)
            sends XLA compile time pathological, like the while_loop
            note on min_frag_counts."""
            solve = solve_app(
                carry_avail,
                jnp.where(mask, driver_rank, BIG),
                exec_ok & mask,
                driver,
                executor,
                k,
            )
            if minfrag:
                mf = min_frag_step_counts(
                    carry_avail, solve.feasible, solve.driver_idx,
                    driver, executor, exec_ok & mask, k,
                )
                solve = AppSolve(
                    feasible=solve.feasible,
                    driver_idx=solve.driver_idx,
                    exec_counts=mf,
                    exec_capacity=solve.exec_capacity,
                )
                eff_counts = jnp.zeros_like(mf) if strict else mf
            else:
                eff_counts = None
            score, nz = _zone_score(
                carry_avail, solve, driver, executor,
                s_cpu_milli, s_gpu_milli, inv_mem, th_mem, scale_cpu, scale_gpu,
                eff_counts=eff_counts,
            )
            return solve.feasible, solve.driver_idx, solve.exec_counts, score, nz

        zf, zdidx, zcounts, zscore, znz = jax.vmap(zone_solve)(zone_masks)

        best_q = jnp.int32(0)
        best_zone = jnp.int32(-1)
        uncertain = jnp.zeros((), bool)
        chosen_counts = jnp.zeros((n,), jnp.int32)
        chosen_didx = jnp.int32(n)

        for z in range(z_count):
            f, score, nz = zf[z], zscore[z], znz[z]
            first = best_zone < 0
            better = f & jnp.where(first, nz, score > best_q)
            uncertain = uncertain | (
                f & ~first & (score != best_q) & (jnp.abs(score - best_q) <= band)
            )
            best_q = jnp.where(better, score, best_q)
            best_zone = jnp.where(better, jnp.int32(z), best_zone)
            chosen_counts = jnp.where(better, zcounts[z], chosen_counts)
            chosen_didx = jnp.where(better, zdidx[z], chosen_didx)

        if az_aware:
            cross = solve_app(carry_avail, driver_rank, exec_ok, driver, executor, k)
            use_cross = (best_zone < 0) & cross.feasible
            best_zone = jnp.where(use_cross, jnp.int32(z_count), best_zone)
            chosen_counts = jnp.where(use_cross, cross.exec_counts, chosen_counts)
            chosen_didx = jnp.where(use_cross, cross.driver_idx, chosen_didx)

        placed = (best_zone >= 0) & valid
        chosen_counts = jnp.where(placed, chosen_counts, jnp.zeros_like(chosen_counts))
        chosen_didx = jnp.where(placed, chosen_didx, jnp.int32(n))

        # the reference's usage-subtraction quirk: one executor's worth on
        # hosting nodes, executor entry overwriting the driver's
        exec_mask = chosen_counts > 0
        is_driver = jnp.arange(n, dtype=jnp.int32) == chosen_didx
        delta = jnp.where(
            exec_mask[:, None],
            executor[None, :],
            jnp.where(is_driver[:, None], driver[None, :], jnp.zeros_like(driver)[None, :]),
        )
        delta = jnp.where(placed, delta, jnp.zeros_like(delta))
        out = (placed, jnp.where(placed, best_zone, jnp.int32(-1)), chosen_didx, uncertain)
        return carry_avail - delta, out

    avail_after, outs = lax.scan(step, avail, (drivers, executors, counts, app_valid))
    placed, zone_idx, chosen_didx, uncertain = outs
    return ZoneQueueSolve(
        feasible=placed,
        zone_idx=zone_idx,
        driver_idx=chosen_didx,
        uncertain=uncertain,
        avail_after=avail_after,
    )


def solve_zones(
    avail: jnp.ndarray,        # [N, 3] int32
    driver_rank: jnp.ndarray,  # [N] int32
    exec_ok: jnp.ndarray,      # [N] bool
    zone_masks: jnp.ndarray,   # [Z, N] bool — node membership per zone
    driver: jnp.ndarray,       # [3] int32
    executor: jnp.ndarray,     # [3] int32
    k: jnp.ndarray,            # [] int32
) -> AppSolve:
    """Per-zone gang solves in one shot (the single-AZ combinator's inner
    loop, single_az.go:23-55): restrict driver candidates and executor
    capacity to each zone and solve every zone at once via vmap.  Zone
    selection (best avg packing efficiency) happens on host with the
    oracle's float64 math for exact parity."""

    def one_zone(mask):
        return solve_app(
            avail,
            jnp.where(mask, driver_rank, BIG),
            exec_ok & mask,
            driver,
            executor,
            k,
        )

    return jax.vmap(one_zone)(zone_masks)


solve_zones_jit = jax.jit(solve_zones)


def compilation_cache_stats() -> dict:
    """Entry counts of each jitted solver kernel's compilation cache —
    the profiling hook behind the kernel cache-hit metrics
    (tracing/profiling.py) and the periodic jit-cache gauge
    (metrics/reporters.py).  A steadily growing count in steady state
    means shape buckets are leaking recompiles onto the request path."""
    out = {}
    for name, fn in (
        ("solve_queue", solve_queue),
        ("solve_queue_min_frag", solve_queue_min_frag),
        ("solve_single", solve_single),
        ("solve_queue_single_az", solve_queue_single_az),
        ("solve_zones", solve_zones_jit),
    ):
        try:
            out[name] = fn._cache_size()
        except Exception:
            continue
    return out
