"""Node executor-capacity math (reference ``lib/pkg/capacity/capacity.go``).

Exact floor division over Fractions reproduces the reference's
``inf.Dec`` arithmetic (capacity.go:36-54) bit-for-bit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Sequence

from ..types.resources import (
    NodeGroupResources,
    NodeGroupSchedulingMetadata,
    Resources,
)
from ..utils.quantity import Quantity

# stand-in for Go's math.MaxInt (capacity.go:45-48): an unbounded dimension
MAX_CAPACITY = 2**63 - 1


@dataclass
class NodeAndExecutorCapacity:
    node_name: str
    capacity: int


def capacity_against_single_dimension(
    available: Quantity, reserved: Quantity, required: Quantity
) -> int:
    """floor((available - reserved) / required); 0 if reserved > available;
    MAX if required is zero (capacity.go:36-54)."""
    if reserved.cmp(available) == 1:
        return 0
    if required.is_zero():
        return MAX_CAPACITY
    q = (available.exact - reserved.exact) / required.exact
    return int(q.numerator // q.denominator)  # Fraction floor division


def get_node_capacity(available: Resources, reserved: Resources, single_executor: Resources) -> int:
    """min over cpu/memory/gpu dimensions (capacity.go:57-75)."""
    return min(
        capacity_against_single_dimension(available.cpu, reserved.cpu, single_executor.cpu),
        capacity_against_single_dimension(available.memory, reserved.memory, single_executor.memory),
        capacity_against_single_dimension(
            available.nvidia_gpu, reserved.nvidia_gpu, single_executor.nvidia_gpu
        ),
    )


def get_node_capacities(
    node_priority_order: Sequence[str],
    metadata: NodeGroupSchedulingMetadata,
    reserved_resources: NodeGroupResources,
    single_executor: Resources,
) -> List[NodeAndExecutorCapacity]:
    """Capacity per node, ordered by node_priority_order (capacity.go:78-102);
    nodes missing from metadata are skipped."""
    capacities: List[NodeAndExecutorCapacity] = []
    for node_name in node_priority_order:
        md = metadata.get(node_name)
        if md is None:
            continue
        reserved = reserved_resources.get(node_name, Resources.zero())
        capacities.append(
            NodeAndExecutorCapacity(node_name, get_node_capacity(md.available, reserved, single_executor))
        )
    return capacities


def filter_out_nodes_without_capacity(
    capacities: List[NodeAndExecutorCapacity],
) -> List[NodeAndExecutorCapacity]:
    return [c for c in capacities if c.capacity > 0]
