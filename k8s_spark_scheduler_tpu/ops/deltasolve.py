"""Incremental delta-solve engine: persistent native solver sessions +
prefix-feasibility reuse for the earlier-drivers-fit loop.

The paper's core guarantee — a driver schedules only if the whole gang
fits and every earlier driver fits first — was re-proved from scratch on
every Filter request: a full snapshot marshal, the AZ-aware sorts, GCD
scaling, and an O(queue × nodes) native queue solve (~17-21 ms at
10k × 1k per NOTES_ROUND5).  Between consecutive decisions almost
nothing changes (the Firmament observation), so the warm path here costs
O(what changed):

- **Persistent native session** (``native/fifo_solver.cpp`` FifoSession
  via :class:`..native.fifo.NativeFifoSession`): the scaled availability
  basis, rank-sorted driver candidates, and the last-solved queue stay
  resident in the C++ extension, keyed by the snapshot *structure
  revision* plus the request's affinity/candidate identity (the same
  exact key the fast-path prep cache uses — ``fast_path.build_prep_keyed``).
- **Prefix-feasibility cache**: the session checkpoints the post-prefix
  availability carry every ``stride`` queue positions; the next request
  resumes from the nearest checkpoint at or below the first changed
  queue index.  The prefix match is verified byte-for-byte inside the
  extension — Python-side bookkeeping is an optimization, never a
  correctness input.
- **Sharded cold-solve fallback**: when the session is cold or
  invalidated (failover, journal replay, content change, inexact
  snapshot), the dim-at-a-time capacity sweeps can shard over node
  ranges on a small native thread pool (``DELTASOLVE_THREADS``); on
  small hosts the pool stays off and the cold solve is the plain serial
  native pass.

Invalidation rules (docs/design.md has the operator-facing version):

1. *Structure* — the session key embeds ``snap.structure_key`` and the
   candidate-list tuple; any node add/remove/relabel/cordon or a
   different candidate set simply misses the session map.
2. *Content* — a warm hit requires the idx-selected availability AND
   schedulable rows to equal the session basis exactly.  The O(1) fast
   path is the change-feed sequence (``snap.content_key``): unchanged
   sequence ⟹ unchanged world.  A changed sequence falls back to an
   exact memcmp (``native.rows_equal``) — churn that cancelled out (a
   probe reservation created then released) still warms.
3. *Scale* — warm reuse requires every demand row to divide the cached
   scale vector exactly and fit int32 after division; decisions are
   scale-invariant (capacities are exact integer quotients), so solving
   in the cached units is bit-identical to a fresh GCD rescale.
4. *Failover / journal replay* — replayed reservation intents flow
   through the store observers into the tensor mirror, bumping the feed
   and changing content, so rule 2 invalidates; a fresh process starts
   with an empty session map by construction.

Every miss reason is counted (``…tpu.deltasolve.warm.miss.count``) and
warm resumes record their depth (``…tpu.deltasolve.resume.depth``).
"""

from __future__ import annotations

import logging
import os
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..analysis import racecheck
from ..analysis.guarded import guarded_by
from ..metrics import names as mnames
from ..tracing import spans as tracing
from ..tracing.profiling import default_profiler
from .fifo_solver import FifoOutcome
from .tensorize import INT32_SAFE, ScaledProblem

logger = logging.getLogger(__name__)

# checkpoint stride: 1k-app queues keep ~16 live checkpoints (the C++
# side doubles the stride past 24, so memory stays bounded either way)
_DEFAULT_STRIDE = 64
# sharded cold pass: below this node count the per-pass dispatch
# round-trip exceeds the sweep itself (see fifo_solver.cpp SweepPool)
_POOL_MIN_NODES = 8192


def _default_threads() -> int:
    env = os.environ.get("DELTASOLVE_THREADS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            return 0
    return min(4, os.cpu_count() or 1)


@dataclass
class _Session:
    """One resident (cluster basis, policy) problem."""

    native: object            # NativeFifoSession
    policy_code: int
    avail64: np.ndarray       # [M, 3] int64 idx-selected availability basis
    sched64: np.ndarray       # [M, 3] int64 idx-selected schedulable basis
    cluster: object           # ClusterTensor built against the basis
    zones: Dict[str, str]
    scale: np.ndarray         # [3] int64
    scaled_avail: np.ndarray  # [Nb, 3] int32 (pre-queue, padded)
    driver_rank: np.ndarray   # [Nb] int32
    exec_ok: np.ndarray       # [Nb] bool
    nb: int
    content_key: tuple        # snapshot content sequence last verified
    # class-digest warm tier (state/classindex.py): the XOR content
    # digest + class-structure revision of the snapshot this basis was
    # built from.  (-1, -1) = snapshot didn't carry a digest (tests
    # building bare TensorSnapshots); the tier then stands aside.
    class_digest: tuple = (-1, -1)
    class_rev: int = -1
    # class-compressed solve mode: on for big fleets only (min_nodes);
    # last_rebuilds tracks the native partition-rebuild counter so the
    # tpu.classes.rebuild.count metric gets deltas, not running totals
    use_classes: bool = False
    last_rebuilds: int = 0


@guarded_by("_lock", "_sessions", "_stats", "_resume_depths", "_parity_count")
class DeltaSolveEngine:
    """Serves the whole FIFO driver decision from resident native state
    when it can, falling back (``solve`` → None) to the per-request
    build + cold solve otherwise.  Decisions are bit-identical to the
    cold path — the per-app queue step is literally the same C++
    function (tests/test_deltasolve.py replays random delta streams
    against cold solves to prove it)."""

    MAX_SESSIONS = 4

    def __init__(self, metrics=None, threads: Optional[int] = None,
                 stride: int = _DEFAULT_STRIDE):
        self._metrics = metrics
        self._threads = _default_threads() if threads is None else threads
        self._stride = stride
        self._lock = threading.Lock()
        self._sessions: OrderedDict = OrderedDict()
        self._stats = {"warm_hits": 0, "cold_solves": 0, "misses": {}}
        self._resume_depths = deque(maxlen=1024)
        self._native_ok: Optional[bool] = None
        # decision provenance (provenance/tracker.py): wiring points the
        # sink at ProvenanceTracker.capture when provenance is enabled.
        # None (the default) keeps the warm path entirely free of
        # capture work.  All three are set before serving starts and
        # only read here — no lock needed.
        self.capture_sink = None
        # warm≠cold parity guard: every Nth warm hit re-runs the queue
        # through the stateless cold solver and fires the flight
        # recorder on divergence.  0 = off (a full cold solve per check).
        self.parity_interval = 0
        self.parity_hooks = None  # (on_ok, on_mismatch) callables
        self._parity_count = 0
        # equivalence-class aggregation (Install.classes): the O(1)
        # digest warm tier below and the native session's class-
        # compressed solve mode.  Set at wiring before serving starts,
        # only read here — no lock needed.
        self.classes_enabled = True
        self.classes_min_nodes = 20000

    # -- availability --------------------------------------------------------

    def _native_available(self) -> bool:
        if self._native_ok is None:
            try:
                from ..native.fifo import native_session_available

                self._native_ok = native_session_available()
            except Exception:
                self._native_ok = False
        return self._native_ok

    def _solver_supported(self, solver) -> bool:
        """The session lane serves the plain-FIFO solver's native host
        lane: on accelerator-backed deployments the pallas queue kernel
        keeps the carry VMEM-resident and this engine stands aside."""
        from .fifo_solver import _native_selected, _pallas_selected

        backend = getattr(solver, "backend", None)
        if backend is None or not hasattr(solver, "_tensorize_with_cache"):
            return False
        if _pallas_selected(backend):
            return False
        try:
            return _native_selected(backend)
        except RuntimeError:
            return False

    # -- bookkeeping ---------------------------------------------------------

    def _miss(self, reason: str) -> None:
        with self._lock:
            racecheck.note_access(self, "_stats")
            self._stats["misses"][reason] = (
                self._stats["misses"].get(reason, 0) + 1
            )
        if self._metrics is not None:
            self._metrics.counter(
                mnames.DELTASOLVE_WARM_MISSES, {"reason": reason}
            )

    def _record_warm(self, resume: int) -> None:
        with self._lock:
            racecheck.note_access(self, "_stats")
            self._stats["warm_hits"] += 1
            self._resume_depths.append(int(resume))
        if self._metrics is not None:
            self._metrics.counter(mnames.DELTASOLVE_WARM_HITS)
            self._metrics.histogram(
                mnames.DELTASOLVE_RESUME_DEPTH, float(resume)
            )

    def _record_cold(self) -> None:
        with self._lock:
            racecheck.note_access(self, "_stats")
            self._stats["cold_solves"] += 1

    def stats(self) -> dict:
        with self._lock:
            depths = sorted(self._resume_depths)
            hits = self._stats["warm_hits"]
            cold = self._stats["cold_solves"]
            digest_hits = self._stats.get("digest_hits", 0)
            misses = dict(self._stats["misses"])
            sessions = len(self._sessions)
            session_bytes = sum(
                s.native.mem_bytes() for s in self._sessions.values()
            )
        total = hits + cold + sum(misses.values())
        return {
            "warm_hits": hits,
            "cold_solves": cold,
            "digest_hits": digest_hits,
            "misses": misses,
            "warm_hit_rate": (hits / total) if total else 0.0,
            "resume_depth_p50": (
                float(depths[len(depths) // 2]) if depths else None
            ),
            "sessions": sessions,
            "session_bytes": session_bytes,
        }

    def latest_basis(self):
        """(node_names, avail64 [N,3] int64, exec_ok [N] bool,
        driver_rank [N] int64) of the most recently used session's
        cluster view, or None when no session is resident.  The policy
        engine's what-if victim validation rides this warm basis — the
        post-build availability the last solve actually ran against —
        instead of re-deriving one from the raw snapshot."""
        with self._lock:
            racecheck.note_access(self, "_sessions")
            if not self._sessions:
                return None
            sess = next(reversed(self._sessions.values()))
        c = sess.cluster
        return (
            list(c.node_names),
            np.asarray(c.avail, dtype=np.int64),
            np.asarray(c.exec_ok, dtype=bool),
            np.asarray(c.driver_rank, dtype=np.int64),
        )

    def invalidate(self) -> None:
        """Drop every session (tests / explicit failover hooks; organic
        invalidation flows through the content rules in the docstring).
        Native handles are NOT destroyed here: a Filter request may hold
        a dropped session mid-solve (solve() runs outside the engine
        lock), so handles retire via refcounting — NativeFifoSession.
        __del__ frees the C++ state once the last reference drops."""
        with self._lock:
            racecheck.note_access(self, "_sessions")
            self._sessions.clear()

    def _publish_gauges(self) -> None:
        if self._metrics is None:
            return
        with self._lock:
            n = len(self._sessions)
            b = sum(s.native.mem_bytes() for s in self._sessions.values())
        self._metrics.gauge(mnames.DELTASOLVE_SESSIONS, float(n))
        self._metrics.gauge(mnames.DELTASOLVE_SESSION_BYTES, float(b))

    # -- the solve -----------------------------------------------------------

    def solve(
        self,
        snap,
        driver_pod,
        candidate_names,
        node_sorter,
        earlier_apps: List,
        earlier_skip_allowed: List[bool],
        current_app,
        solver,
    ) -> Optional[Tuple[FifoOutcome, Dict[str, str]]]:
        """(FifoOutcome, node→zone map) or None when this lane cannot
        serve the request exactly (the caller then runs the per-request
        build + solve path)."""
        from .batch_solver import queue_policy_code

        policy_code = queue_policy_code(solver.assignment_policy)
        if policy_code is None or not self._solver_supported(solver):
            self._miss("unsupported")
            return None
        if not self._native_available():
            self._miss("no-native")
            return None
        if not snap.exact:
            self._miss("inexact")
            return None

        from .fast_path import build_prep_keyed

        # candidate_names passes through verbatim: on the HTTP path it is
        # the interned tuple (serde.intern_node_names), so the prep/session
        # key shares ONE string set across requests instead of pinning a
        # fresh 10k-string copy per cache entry (the r5 soak's RSS churn)
        prep, key = build_prep_keyed(
            snap,
            driver_pod,
            candidate_names,
            node_sorter.driver_label_priority,
            node_sorter.executor_label_priority,
        )
        if key is None:
            self._miss("affinity-shape")
            return None
        skey = (key, policy_code)

        apps = solver._tensorize_with_cache(list(earlier_apps), current_app)
        if not apps.exact:
            self._miss("apps-inexact")
            return None
        n_earlier = len(earlier_apps)

        with self._lock:
            racecheck.note_access(self, "_sessions")
            sess = self._sessions.get(skey)
            if sess is not None:
                self._sessions.move_to_end(skey)

        warm = False
        scaled = None
        if sess is not None:
            snap_digest = getattr(snap, "class_digest", (-1, -1))
            if sess.content_key == snap.content_key:
                warm = True
            elif (
                self.classes_enabled
                and sess.class_digest != (-1, -1)
                and snap_digest == sess.class_digest
            ):
                # O(1) class-digest tier (state/classindex.py): the XOR
                # node-content digest cancelled back to the session's —
                # same-class node churn (create/release, cordon/uncordon
                # round trips) warms without the O(N) row compare.  The
                # digest hashes a superset of what rows_equal checks, so
                # equality ⟹ equal rows up to 64-bit XOR collisions;
                # the warm≠cold parity guard audits the conclusion.
                warm = True
                sess.content_key = snap.content_key
                sess.class_rev = getattr(snap, "class_rev", -1)
                with self._lock:
                    racecheck.note_access(self, "_stats")
                    self._stats["digest_hits"] = (
                        self._stats.get("digest_hits", 0) + 1
                    )
            else:
                from ..native import rows_equal

                avail64 = snap.avail[prep.idx]
                sched64 = snap.schedulable[prep.idx]
                if rows_equal(avail64, sess.avail64) and rows_equal(
                    sched64, sess.sched64
                ):
                    # churn cancelled out (e.g. a reservation created
                    # then released): the basis is still exact
                    warm = True
                    sess.content_key = snap.content_key
                    sess.class_digest = snap_digest
                    sess.class_rev = getattr(snap, "class_rev", -1)
        if warm:
            scaled = self._scale_apps(apps, sess.scale, sess.nb)
            if scaled is None:
                # the cached units no longer represent these demands
                # exactly — rebuild with a fresh GCD
                warm = False

        if not warm:
            sess, scaled = self._cold_build(
                snap, driver_pod, candidate_names, node_sorter, prep, skey,
                policy_code, apps,
            )
            if sess is None:
                return None
            self._record_cold()

        driver_s, executor_s, count_s = scaled
        packed = np.empty((n_earlier, 8), dtype=np.int32)
        packed[:, 0:3] = driver_s[:n_earlier]
        packed[:, 3:6] = executor_s[:n_earlier]
        packed[:, 6] = count_s[:n_earlier]
        packed[:, 7] = 1

        solver.last_queue_lane = "native-session"
        with tracing.child_span(
            "fifo_gate",
            {"lane": "native-session", "earlierApps": n_earlier},
        ) as gate_span:
            with default_profiler.profile(
                "fifo_queue", lane="native-session", jit=False
            ):
                resume, feasible, didx, avail_after = sess.native.solve(
                    packed
                )
            gate_span.tag("resumeFrom", int(resume))
            gate_span.tag("warm", warm)
            if sess.use_classes and self._metrics is not None:
                try:
                    st = sess.native.class_stats()
                    delta = st["rebuilds"] - sess.last_rebuilds
                    if delta > 0:
                        sess.last_rebuilds = st["rebuilds"]
                        self._metrics.counter(
                            mnames.CLASSES_REBUILD_COUNT, inc=float(delta)
                        )
                except Exception:
                    pass
            if warm:
                self._record_warm(resume)
                if self.parity_interval:
                    # counted under the engine lock: solve() already runs
                    # concurrently in tests and will for real once the
                    # extender lock splits (ROADMAP-1) — an unguarded
                    # += here was the PR 9 vector-clock detector's first
                    # real finding
                    with self._lock:
                        racecheck.note_access(self, "_parity_count")
                        self._parity_count += 1
                        parity_due = (
                            self._parity_count % self.parity_interval == 0
                        )
                    if parity_due:
                        self._verify_parity(
                            sess, packed, feasible, didx, avail_after
                        )
            if self.capture_sink is not None:
                self._capture(
                    sess, snap, policy_code, packed, driver_s, executor_s,
                    count_s, n_earlier, feasible, didx, resume,
                    avail_after, earlier_skip_allowed,
                )
            if n_earlier:
                blocked = ~feasible & ~np.asarray(
                    earlier_skip_allowed, dtype=bool
                )
                if blocked.any():
                    gate_span.tag("earlierOk", False)
                    return (
                        FifoOutcome(supported=True, earlier_ok=False),
                        sess.zones,
                    )
            gate_span.tag("earlierOk", True)

        problem = ScaledProblem(
            avail=sess.scaled_avail,
            driver_rank=sess.driver_rank,
            exec_ok=sess.exec_ok,
            driver=driver_s,
            executor=executor_s,
            count=count_s,
            app_valid=np.ones(len(count_s), dtype=bool),
            scale=sess.scale,
            ok=True,
        )
        outcome = solver._pack_current(
            sess.cluster, problem, avail_after, n_earlier, current_app,
            metadata=None, use_native=True,
        )
        return outcome, sess.zones

    # -- internals -----------------------------------------------------------

    @staticmethod
    def _session_artifacts(
        sess, packed, n_earlier, feasible, didx, resume, avail_after,
        lane, skip_allowed=(), content_key=None, feed_seq=None,
    ):
        """One SolveArtifacts construction from session fields, shared
        by the capture sink and the parity guard so the two bundles the
        subsystem emits can never drift apart field-by-field.  Arrays
        are referenced, not copied — the session's basis arrays are
        replaced on rebuild, never mutated in place."""
        from ..provenance.tracker import SolveArtifacts

        return SolveArtifacts(
            policy_code=sess.policy_code,
            lane=lane,
            basis=sess.scaled_avail,
            driver_rank=sess.driver_rank,
            exec_ok=sess.exec_ok,
            packed=packed,
            n_earlier=n_earlier,
            feasible=np.asarray(feasible, dtype=bool),
            didx=np.asarray(didx, dtype=np.int32),
            resume=int(resume),
            avail_after=np.asarray(avail_after, dtype=np.int32),
            scale=sess.scale,
            node_names=sess.cluster.node_names,
            zone_names=sess.cluster.zone_names,
            zone_id=sess.cluster.zone_id,
            skip_allowed=list(skip_allowed),
            content_key=content_key,
            feed_seq=feed_seq,
        )

    def _capture(
        self, sess, snap, policy_code, packed, driver_s, executor_s,
        count_s, n_earlier, feasible, didx, resume, avail_after,
        earlier_skip_allowed,
    ) -> None:
        """Hand the decision's full native inputs + verdicts to the
        provenance sink."""
        try:
            packed_full = np.empty((n_earlier + 1, 8), dtype=np.int32)
            packed_full[:n_earlier] = packed
            packed_full[n_earlier, 0:3] = driver_s[n_earlier]
            packed_full[n_earlier, 3:6] = executor_s[n_earlier]
            packed_full[n_earlier, 6] = count_s[n_earlier]
            packed_full[n_earlier, 7] = 1
            self.capture_sink(self._session_artifacts(
                sess, packed_full, n_earlier, feasible, didx, resume,
                avail_after, lane="native-session",
                skip_allowed=earlier_skip_allowed,
                content_key=snap.content_key,
                feed_seq=int(snap.content_key[1]),
            ))
        except Exception:
            logger.exception("provenance capture failed (diagnostic only)")

    def _verify_parity(
        self, sess, packed, feasible, didx, avail_after
    ) -> None:
        """Warm≠cold parity guard: the stateless cold solver run on the
        same basis + queue must reproduce the session's verdicts
        byte-for-byte (the PR 5 shared-step-function guarantee, now
        checked in the wild).  Divergence fires the flight recorder."""
        try:
            from ..native.fifo import solve_packed_cold

            cold_f, cold_d, cold_after = solve_packed_cold(
                sess.policy_code, sess.scaled_avail, sess.driver_rank,
                sess.exec_ok, packed,
            )
            ok = (
                cold_f.tobytes() == np.asarray(feasible, dtype=bool).tobytes()
                and cold_d.tobytes() == np.asarray(didx, np.int32).tobytes()
                and cold_after.tobytes()
                == np.asarray(avail_after, np.int32).tobytes()
            )
            hooks = self.parity_hooks
            if ok:
                if hooks is not None and hooks[0] is not None:
                    hooks[0]()
                return
            detail = {
                "policy": sess.policy_code,
                "n_apps": int(packed.shape[0]),
                "feasible_equal": bool(
                    cold_f.tobytes()
                    == np.asarray(feasible, dtype=bool).tobytes()
                ),
            }
            logger.error("deltasolve warm/cold parity mismatch: %s", detail)
            if hooks is not None and hooks[1] is not None:
                # ship the DIVERGING solve itself: the persisted bundle
                # must contain the anomaly, not just the decisions that
                # preceded it (the tracker notes these artifacts into
                # the recorder ring before persisting)
                try:
                    detail["artifacts"] = self._session_artifacts(
                        sess, packed, int(packed.shape[0]), feasible,
                        didx, 0, avail_after, lane="native-session-parity",
                    )
                except Exception:
                    pass
                hooks[1](detail)
        except Exception:
            logger.exception("parity guard failed to run (diagnostic only)")

    @staticmethod
    def _scale_apps(apps, scale: np.ndarray, nb: int):
        """(driver_s, executor_s, count_s) int32 in the session's units,
        or None when the cached scale cannot represent these demands
        exactly inside the session's numeric bounds.  Decisions are
        scale-invariant, so any exact representation matches the cold
        solve bit-for-bit."""
        d = apps.driver
        e = apps.executor
        if (d % scale).any() or (e % scale).any():
            return None
        ds = d // scale
        es = e // scale
        if (np.abs(ds) > INT32_SAFE).any() or (np.abs(es) > INT32_SAFE).any():
            return None
        counts = apps.count
        max_k = int(counts.max()) if counts.size else 0
        if max_k > INT32_SAFE or (max_k > 0 and nb * max_k > INT32_SAFE):
            # same int32 sum-overflow guard scale_problem applies
            return None
        return (
            ds.astype(np.int32),
            es.astype(np.int32),
            np.minimum(counts, INT32_SAFE).astype(np.int32),
        )

    def _cold_build(
        self, snap, driver_pod, candidate_names, node_sorter, prep, skey,
        policy_code, apps,
    ):
        """Build + load a fresh session (the full per-request path, plus
        one basis upload).  Returns (session, scaled apps) or (None, _)
        when the request can't be represented natively at all."""
        from ..native.fifo import NativeFifoSession
        from .batch_solver import mf_sentinel_safe
        from .fast_path import build_cluster_tensor
        from .tensorize import scale_problem

        built = build_cluster_tensor(
            snap,
            driver_pod,
            candidate_names,
            driver_label_priority=node_sorter.driver_label_priority,
            executor_label_priority=node_sorter.executor_label_priority,
        )
        if built is None:
            self._miss("inexact")
            return None, None
        cluster, zones = built
        problem = scale_problem(cluster, apps)
        if not problem.ok:
            self._miss("scale")
            return None, None
        if policy_code == 2 and not mf_sentinel_safe(problem.avail):
            self._miss("mf-sentinel")
            return None, None

        # reuse the evictee's native handle when this key is being
        # rebuilt: load() replaces all resident state, and an unchanged
        # worker count keeps the sharded pool's threads alive instead of
        # churning a pool per rebuild.  The stale entry is POPPED before
        # its handle is reloaded — if anything below raises, no mapping
        # survives whose Python-side basis disagrees with the basis now
        # resident in the shared handle (the next request cold-builds).
        with self._lock:
            racecheck.note_access(self, "_sessions")
            prior = self._sessions.pop(skey, None)
        if prior is not None:
            native = prior.native
        else:
            native = NativeFifoSession(
                threads=self._threads, min_pool_nodes=_POOL_MIN_NODES
            )
        native.load(
            problem.avail, problem.driver_rank, problem.exec_ok,
            policy_code, stride=self._stride,
        )
        # class-compressed solve mode at scale: partition upkeep only
        # pays for itself on big fleets, so small clusters (and the 10k
        # perf-gate lanes) keep the row-level step functions verbatim.
        # Decisions are byte-identical either way (PR 20 parity suite).
        use_classes = False
        if hasattr(native, "set_classes"):
            want = (
                self.classes_enabled
                and int(problem.avail.shape[0]) >= self.classes_min_nodes
            )
            # always called (even want=False): a reused evictee handle
            # must not carry the previous build's class mode
            supported = native.set_classes(want)
            use_classes = want and supported
        na = apps.driver.shape[0]
        sess = _Session(
            native=native,
            policy_code=policy_code,
            avail64=snap.avail[prep.idx],
            sched64=snap.schedulable[prep.idx],
            cluster=cluster,
            zones=zones,
            scale=problem.scale.astype(np.int64),
            scaled_avail=problem.avail,
            driver_rank=problem.driver_rank,
            exec_ok=problem.exec_ok,
            nb=int(problem.avail.shape[0]),
            content_key=snap.content_key,
            class_digest=getattr(snap, "class_digest", (-1, -1)),
            class_rev=getattr(snap, "class_rev", -1),
            use_classes=use_classes,
        )
        with self._lock:
            racecheck.note_access(self, "_sessions")
            self._sessions[skey] = sess  # stale entry already popped above
            while len(self._sessions) > self.MAX_SESSIONS:
                # evictees are dropped, not closed: another thread's
                # in-flight solve may still hold one (solve() runs
                # outside this lock); the native buffers free via
                # NativeFifoSession.__del__ when the last ref drops
                self._sessions.popitem(last=False)
        self._publish_gauges()
        # the scaled app block comes straight from the cold scaling
        scaled = (
            problem.driver[:na],
            problem.executor[:na],
            problem.count[:na],
        )
        return sess, scaled
