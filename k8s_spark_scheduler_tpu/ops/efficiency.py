"""Packing-efficiency math (reference ``lib/pkg/binpack/efficiency.go``).

Efficiency is reporting/selection metadata (used to pick the best AZ in
the single-AZ combinator and for metrics), so float math is acceptable
here exactly as in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

from ..types.resources import (
    NodeGroupResources,
    NodeGroupSchedulingMetadata,
    NodeSchedulingMetadata,
)


@dataclass
class PackingEfficiency:
    """Per-node reserved/schedulable ratios (efficiency.go:53-63)."""

    node_name: str
    cpu: float
    memory: float
    gpu: float

    def max(self) -> float:
        return max(self.gpu, self.cpu, self.memory)


@dataclass
class AvgPackingEfficiency:
    """Average over nodes (efficiency.go:25-30)."""

    cpu: float
    memory: float
    gpu: float
    max: float

    def less_than(self, other: "AvgPackingEfficiency") -> bool:
        return self.max < other.max


def worst_avg_packing_efficiency() -> AvgPackingEfficiency:
    return AvgPackingEfficiency(0.0, 0.0, 0.0, 0.0)


def _normalize(v: int) -> int:
    return 1 if v == 0 else v


def compute_packing_efficiency(
    node_name: str,
    md: NodeSchedulingMetadata,
    reserved_resources: NodeGroupResources,
) -> PackingEfficiency:
    """(schedulable - available + newly_reserved) / schedulable per dim
    (efficiency.go:80-105)."""
    node_reserved = md.schedulable.sub(md.available)
    extra = reserved_resources.get(node_name)
    if extra is not None:
        node_reserved = node_reserved.add(extra)
    schedulable = md.schedulable

    gpu_eff = 0.0
    if schedulable.nvidia_gpu.value() != 0:
        gpu_eff = float(node_reserved.nvidia_gpu.value()) / float(
            _normalize(schedulable.nvidia_gpu.value())
        )

    return PackingEfficiency(
        node_name=node_name,
        cpu=float(node_reserved.cpu.value()) / float(_normalize(schedulable.cpu.value())),
        memory=float(node_reserved.memory.value()) / float(_normalize(schedulable.memory.value())),
        gpu=gpu_eff,
    )


def compute_packing_efficiencies(
    metadata: NodeGroupSchedulingMetadata,
    reserved_resources: NodeGroupResources,
) -> Dict[str, PackingEfficiency]:
    """Efficiency for every node in the snapshot (efficiency.go:66-77)."""
    return {
        node_name: compute_packing_efficiency(node_name, md, reserved_resources)
        for node_name, md in metadata.items()
    }


def compute_avg_packing_efficiency(
    metadata: NodeGroupSchedulingMetadata,
    packing_efficiencies: List[PackingEfficiency],
) -> AvgPackingEfficiency:
    """Average of per-node efficiencies; GPU averaged only over GPU nodes,
    defaulting to 1.0 when none (efficiency.go:114-156).

    Note: callers may pass duplicate entries (one per executor occurrence);
    the average intentionally weights by occurrences, matching
    single_az.go:75-97's use.
    """
    if not packing_efficiencies:
        return worst_avg_packing_efficiency()

    cpu_sum = memory_sum = gpu_sum = max_sum = 0.0
    nodes_with_gpu = 0
    for eff in packing_efficiencies:
        md = metadata[eff.node_name]
        cpu_sum += eff.cpu
        memory_sum += eff.memory
        if md.schedulable.nvidia_gpu.value() != 0:
            gpu_sum += eff.gpu
            nodes_with_gpu += 1
        max_sum += max(eff.gpu, eff.cpu, eff.memory)

    length = max(float(len(packing_efficiencies)), 1.0)
    gpu_eff = 1.0 if nodes_with_gpu == 0 else gpu_sum / float(nodes_with_gpu)
    return AvgPackingEfficiency(
        cpu=cpu_sum / length,
        memory=memory_sum / length,
        gpu=gpu_eff,
        max=max_sum / length,
    )
